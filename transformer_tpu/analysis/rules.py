"""JAX-aware AST lint rules (TPA001–TPA006).

Static analysis over the package source for the silent-bug classes that
jit-heavy code grows (SURVEY.md territory; Mesh-TensorFlow's thesis in
PAPERS.md — compile-time checking is what keeps a supercomputer-scale stack
maintainable). Every rule reports a :class:`Finding` with a stable
fingerprint, honours inline ``# tpa: disable=CODE`` suppressions, and can be
grandfathered through a checked-in baseline file (``analysis/baseline.json``).

Rule catalogue (docs/ANALYSIS.md has the long-form version):

- **TPA001** — Python ``if``/``while`` whose condition involves a traced
  value inside a jitted function. Under trace these either raise a
  ConcretizationTypeError or, worse, bake one branch into the compiled
  program. Conditions on static arguments, on shape/dtype/ndim metadata, and
  ``x is None`` / ``x is not None`` identity tests are concrete and allowed.
- **TPA002** — a ``numpy`` function applied to a traced value inside a
  jitted function: NumPy either materializes the tracer (host sync /
  TracerArrayConversionError) or silently computes at trace time.
- **TPA003** — a jitted function reading module-level *mutable* state
  (module dicts/lists, ``global``-rebound names): jit captures the value at
  trace time, so later mutation is silently ignored (or forces retraces).
- **TPA004** — ``static_argnames`` naming a parameter that does not exist in
  the decorated signature (jax only validates lazily, and only sometimes),
  or ``static_argnums``/``donate_argnums`` out of the positional range.
- **TPA005** — reuse of a donated argument after the donating call: donated
  buffers are invalidated by XLA; the next dereference dies at runtime with
  a buffer-deleted error only on the devices that donated.
- **TPA006** — broad ``except Exception:`` (or bare ``except:``) in a
  LIBRARY module (anything outside ``cli/`` and ``__main__`` entry points).
  Handlers that unconditionally re-raise (cleanup handlers ending in bare
  ``raise``) are structural pass-throughs and exempt.
- **TPA007** — retry loop without backoff or attempt bound: a constant-true
  ``while`` whose except handler just ``continue``s, with no sleep/backoff
  call and no ``raise``/``break`` escape in the handler. Under a persistent
  fault this spins hot forever — the failure shape the serving tier's
  bounded-retry-with-jittered-backoff policy exists to prevent
  (docs/ROBUSTNESS.md). Handlers that sleep/back off, re-raise, or break
  are exempt; bounded loops (``for``, condition-tested ``while``) are
  never flagged.

The taint analysis is deliberately conservative-but-simple: values derived
from non-static parameters of a jitted function are traced; ``.shape`` /
``.dtype`` / ``.ndim`` / ``.size`` reads and ``len()`` launder taint (those
are concrete under trace). False negatives are acceptable; false positives
on the shipped tree are not — ``python -m transformer_tpu.analysis rules``
must exit 0 (tests/test_analysis.py pins both directions per rule).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable

from transformer_tpu.analysis.baselines import (  # noqa: F401  (re-exports:
    # Finding/RulesReport/load_baseline/write_baseline/_SUPPRESS_RE/
    # _iter_py_files/_package_root are this module's historical public
    # surface — concurrency.py and the tests import them from here)
    Finding,
    RulesReport,
    _SUPPRESS_RE,
    _iter_py_files,
    _package_root,
    line_suppressed,
    load_baseline,
    write_baseline,
)

RULES: dict[str, str] = {
    "TPA001": "Python if/while on a traced value inside a jitted function",
    "TPA002": "numpy op applied to a traced value inside a jitted function",
    "TPA003": "jitted function closes over mutable module state",
    "TPA004": "static/donate argnames/argnums do not match the jitted signature",
    "TPA005": "donated argument reused after the donating call",
    "TPA006": "broad `except Exception` in a library (non-CLI) module",
    "TPA007": "retry loop without backoff or attempt bound (while True + "
              "except-and-continue)",
}

# Call names (last dotted component) that count as backoff inside a retry
# handler: sleeping, waiting on a condition/event, or an explicit backoff
# helper all bound the retry rate.
_BACKOFF_CALLS = frozenset({"sleep", "wait", "backoff", "backoff_ms"})

# Attribute reads that are concrete (host-side) even on a tracer.
_LAUNDER_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding", "aval"})
# Calls whose result is concrete regardless of argument taint.
_LAUNDER_CALLS = frozenset({"len", "isinstance", "type", "id", "repr", "str"})

# --------------------------------------------------------------------------
# small AST helpers


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _literal_strs(node: ast.AST | None) -> list[str] | None:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
                return None
            out.append(elt.value)
        return out
    return None


def _literal_ints(node: ast.AST | None) -> list[int] | None:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return out
    return None


@dataclasses.dataclass
class JitSpec:
    """What one jit declaration pinned statically (literal values only;
    non-literal expressions leave the field None = unknown)."""

    node: ast.AST  # the decorator / call node, for line reporting
    static_argnames: list[str] | None = None
    static_argnums: list[int] | None = None
    donate_argnums: list[int] | None = None
    donate_argnames: list[str] | None = None
    has_static_argnames_kw: bool = False
    has_static_argnums_kw: bool = False
    has_donate_kw: bool = False


_JIT_NAMES = frozenset({"jax.jit", "jit", "pjit", "jax.pjit"})
_PARTIAL_NAMES = frozenset({"partial", "functools.partial"})


def _jit_call_spec(call: ast.Call) -> JitSpec:
    spec = JitSpec(node=call)
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            spec.has_static_argnames_kw = True
            spec.static_argnames = _literal_strs(kw.value)
        elif kw.arg == "static_argnums":
            spec.has_static_argnums_kw = True
            spec.static_argnums = _literal_ints(kw.value)
        elif kw.arg == "donate_argnums":
            spec.has_donate_kw = True
            spec.donate_argnums = _literal_ints(kw.value)
        elif kw.arg == "donate_argnames":
            spec.has_donate_kw = True
            spec.donate_argnames = _literal_strs(kw.value)
    return spec


def _decorator_jit_spec(dec: ast.AST) -> JitSpec | None:
    """JitSpec when the decorator jits the function: ``@jax.jit`` or
    ``@partial(jax.jit, ...)``."""
    if _dotted(dec) in _JIT_NAMES:
        return JitSpec(node=dec)
    if isinstance(dec, ast.Call):
        fname = _dotted(dec.func)
        if fname in _JIT_NAMES:
            return _jit_call_spec(dec)
        if fname in _PARTIAL_NAMES and dec.args:
            if _dotted(dec.args[0]) in _JIT_NAMES:
                return _jit_call_spec(dec)
    return None


def _positional_params(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _all_params(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    a = fn.args
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


# --------------------------------------------------------------------------
# taint


def _is_none_compare(node: ast.Compare) -> bool:
    return all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and all(
        isinstance(c, ast.Constant) and c.value is None for c in node.comparators
    )


def _tainted(node: ast.AST | None, tainted: set[str]) -> bool:
    """Does ``node`` (an expression) derive from a traced value? Laundered
    subtrees (shape/dtype metadata, ``len``, ``is None`` identity tests) are
    concrete under trace and never propagate taint."""
    if node is None:
        return False
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in _LAUNDER_ATTRS:
            return False
        return _tainted(node.value, tainted)
    if isinstance(node, ast.Compare) and _is_none_compare(node):
        return False
    if isinstance(node, ast.Call):
        fname = _dotted(node.func)
        if fname in _LAUNDER_CALLS:
            return False
        return any(_tainted(a, tainted) for a in node.args) or any(
            _tainted(kw.value, tainted) for kw in node.keywords
        )
    if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
        return False  # defining a closure is not itself a traced use
    return any(_tainted(child, tainted) for child in ast.iter_child_nodes(node))


def _target_names(target: ast.AST) -> list[str]:
    """Plain names bound by an assignment target (tuple/star unpack included)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    return []


class _JitBodyScanner:
    """TPA001/TPA002 over one jitted function: an ordered statement walk
    propagating a taint set seeded with the non-static parameters."""

    def __init__(self, module: "_Module", fn: ast.FunctionDef, static: set[str]):
        self.module = module
        self.fn = fn
        self.tainted: set[str] = {
            p for p in _all_params(fn) if p not in static and p != "self"
        }
        self.findings: list[Finding] = []

    def run(self) -> list[Finding]:
        self._stmts(self.fn.body)
        return self.findings

    # -- statement dispatch, in source order
    def _stmts(self, body: Iterable[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        # TPA002 scans each statement's own expressions (compound bodies are
        # recursed as statements below, so taint state is current for them).
        self._scan_numpy_calls(stmt)
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign([stmt.target], stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            if _tainted(stmt.value, self.tainted):
                self.tainted.update(_target_names(stmt.target))
        elif isinstance(stmt, (ast.If, ast.While)):
            if _tainted(stmt.test, self.tainted):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self.findings.append(
                    self.module.finding(
                        "TPA001",
                        stmt,
                        self.fn.name,
                        f"Python `{kind}` on a traced value — use jnp.where/"
                        "lax.cond/lax.while_loop (or mark the argument static)",
                    )
                )
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.For):
            if _tainted(stmt.iter, self.tainted):
                self.tainted.update(_target_names(stmt.target))
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.With):
            self._stmts(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs trace as part of the jitted program; their
            # parameters are traced values too (lax.while_loop carries,
            # vmapped bodies). Shadowing is handled by seeding a fresh
            # scanner whose taint is the outer set plus the inner params.
            inner = _JitBodyScanner(self.module, stmt, static=set())
            inner.tainted |= {t for t in self.tainted if t not in _all_params(stmt)}
            self.findings.extend(inner.run())

    def _assign(self, targets: list[ast.AST], value: ast.AST) -> None:
        names: list[str] = []
        for t in targets:
            names.extend(_target_names(t))
        if _tainted(value, self.tainted):
            self.tainted.update(names)
        else:
            self.tainted.difference_update(names)

    def _scan_numpy_calls(self, stmt: ast.stmt) -> None:
        """Scan the statement's HEADER expressions for numpy-on-tracer calls
        (compound-statement bodies are recursed via ``_stmt``, so each call
        site is scanned exactly once, with the taint state current)."""
        roots: list[ast.AST]
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs get their own scanner
        if isinstance(stmt, ast.Assign):
            roots = [stmt.value]
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign, ast.Return)):
            roots = [stmt.value] if stmt.value is not None else []
        elif isinstance(stmt, (ast.If, ast.While)):
            roots = [stmt.test]
        elif isinstance(stmt, ast.For):
            roots = [stmt.iter]
        elif isinstance(stmt, ast.With):
            roots = [item.context_expr for item in stmt.items]
        elif isinstance(stmt, ast.Try):
            roots = []
        else:
            roots = [stmt]  # simple statement: walk it whole
        for root in roots:
            self._scan_numpy_exprs(root)

    def _scan_numpy_exprs(self, root: ast.AST) -> None:
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            if not fname:
                continue
            base = fname.split(".", 1)[0]
            if base not in self.module.numpy_aliases:
                continue
            args_tainted = any(_tainted(a, self.tainted) for a in node.args) or any(
                _tainted(kw.value, self.tainted) for kw in node.keywords
            )
            if args_tainted:
                self.findings.append(
                    self.module.finding(
                        "TPA002",
                        node,
                        self.fn.name,
                        f"`{fname}` applied to a traced value — numpy "
                        "materializes tracers; use jax.numpy",
                    )
                )


# --------------------------------------------------------------------------
# per-module analysis


class _Module:
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.numpy_aliases = self._numpy_aliases()
        self.is_cli = self._is_cli()
        # (fn node, JitSpec) for decorator-form and resolvable
        # assignment-form (``name = jax.jit(local_def, ...)``) jits.
        self.jitted: list[tuple[ast.FunctionDef, JitSpec]] = []
        self._collect_jits()

    def _is_cli(self) -> bool:
        parts = self.rel.replace(os.sep, "/").split("/")
        return "cli" in parts or parts[-1] == "__main__.py"

    def _numpy_aliases(self) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "numpy":
                        out.add(alias.asname or "numpy")
        return out

    def _collect_jits(self) -> None:
        defs = {
            s.name: s for s in self.tree.body if isinstance(s, ast.FunctionDef)
        }
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    spec = _decorator_jit_spec(dec)
                    if spec is not None:
                        self.jitted.append((node, spec))
            elif isinstance(node, ast.Call) and _dotted(node.func) in _JIT_NAMES:
                # assignment-form jax.jit(f, ...): analyzable when f is a
                # module-level def in this file.
                if node.args and isinstance(node.args[0], ast.Name):
                    target = defs.get(node.args[0].id)
                    if target is not None:
                        self.jitted.append((target, _jit_call_spec(node)))

    def finding(
        self, code: str, node: ast.AST, symbol: str, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        snippet = (
            self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        )
        return Finding(
            code=code,
            path=self.rel,
            line=line,
            symbol=symbol,
            message=message,
            snippet=snippet,
        )

    def suppressed(self, f: Finding) -> bool:
        return line_suppressed(self.lines, f)

    # -- the rules ---------------------------------------------------------

    def static_names_for(self, fn: ast.FunctionDef, spec: JitSpec) -> set[str]:
        static = set(spec.static_argnames or ())
        pos = _positional_params(fn)
        for i in spec.static_argnums or ():
            if 0 <= i < len(pos):
                static.add(pos[i])
        return static

    def rule_tpa001_002(self) -> list[Finding]:
        out: list[Finding] = []
        for fn, spec in self.jitted:
            static = self.static_names_for(fn, spec)
            out.extend(_JitBodyScanner(self, fn, static).run())
        return out

    def rule_tpa003(self) -> list[Finding]:
        mutable = self._mutable_module_names()
        if not mutable:
            return []
        out: list[Finding] = []
        for fn, _spec in self.jitted:
            bound = set(_all_params(fn))
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign) else [node.target]
                    )
                    for t in targets:
                        bound.update(_target_names(t))
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    bound.update(_all_params(node))
                    bound.add(node.name)
                elif isinstance(node, ast.For):
                    bound.update(_target_names(node.target))
                elif isinstance(node, ast.comprehension):
                    bound.update(_target_names(node.target))
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in mutable
                    and node.id not in bound
                ):
                    out.append(
                        self.finding(
                            "TPA003",
                            node,
                            fn.name,
                            f"jitted function reads mutable module state "
                            f"`{node.id}` — jit captures the value at trace "
                            "time; pass it as an argument",
                        )
                    )
        return out

    def _mutable_module_names(self) -> set[str]:
        mutable: set[str] = set()
        assigned: dict[str, int] = {}
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    for name in _target_names(t):
                        assigned[name] = assigned.get(name, 0) + 1
                        if isinstance(
                            stmt.value,
                            (
                                ast.List,
                                ast.Dict,
                                ast.Set,
                                ast.ListComp,
                                ast.DictComp,
                                ast.SetComp,
                            ),
                        ):
                            mutable.add(name)
                        elif isinstance(stmt.value, ast.Call) and _dotted(
                            stmt.value.func
                        ) in (
                            "list",
                            "dict",
                            "set",
                            "bytearray",
                            "collections.defaultdict",
                            "collections.deque",
                            "collections.OrderedDict",
                            "collections.Counter",
                        ):
                            mutable.add(name)
        mutable.update(n for n, c in assigned.items() if c > 1)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Global):
                mutable.update(node.names)
        return mutable

    def rule_tpa004(self) -> list[Finding]:
        out: list[Finding] = []
        for fn, spec in self.jitted:
            params = set(_all_params(fn))
            pos = _positional_params(fn)
            has_varargs = fn.args.vararg is not None
            for name in spec.static_argnames or ():
                if name not in params:
                    out.append(
                        self.finding(
                            "TPA004",
                            spec.node,
                            fn.name,
                            f"static_argnames names {name!r}, which is not a "
                            f"parameter of `{fn.name}` — the jit silently "
                            "ignores it (or dies at call time)",
                        )
                    )
            for label, nums in (
                ("static_argnums", spec.static_argnums),
                ("donate_argnums", spec.donate_argnums),
            ):
                for i in nums or ():
                    if not has_varargs and not -len(pos) <= i < len(pos):
                        out.append(
                            self.finding(
                                "TPA004",
                                spec.node,
                                fn.name,
                                f"{label} index {i} is out of range for "
                                f"`{fn.name}`'s {len(pos)} positional "
                                "parameters",
                            )
                        )
            for name in spec.donate_argnames or ():
                if name not in params:
                    out.append(
                        self.finding(
                            "TPA004",
                            spec.node,
                            fn.name,
                            f"donate_argnames names {name!r}, which is not a "
                            f"parameter of `{fn.name}`",
                        )
                    )
        return out

    def donating_registry(self) -> dict[str, set[int]]:
        """bare function name -> donated positional indices (this module)."""
        out: dict[str, set[int]] = {}
        for fn, spec in self.jitted:
            donated: set[int] = set(spec.donate_argnums or ())
            pos = _positional_params(fn)
            for name in spec.donate_argnames or ():
                if name in pos:
                    donated.add(pos.index(name))
            if donated:
                out[fn.name] = out.get(fn.name, set()) | donated
        return out

    def rule_tpa005(self, registry: dict[str, set[int]]) -> list[Finding]:
        if not registry:
            return []
        out: list[Finding] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_scan_donation_reuse(self, node, registry))
        return out

    def rule_tpa006(self) -> list[Finding]:
        if self.is_cli:
            return []
        out: list[Finding] = []
        enclosing = _enclosing_symbols(self.tree)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or _dotted(node.type) in (
                "Exception",
                "BaseException",
            )
            if not broad:
                continue
            # Cleanup handlers that unconditionally re-raise are structural
            # pass-throughs, not swallowers.
            if node.body and isinstance(node.body[-1], ast.Raise) and node.body[-1].exc is None:
                continue
            caught = "bare except" if node.type is None else f"except {_dotted(node.type)}"
            out.append(
                self.finding(
                    "TPA006",
                    node,
                    enclosing.get(id(node), "<module>"),
                    f"{caught} in a library module swallows unrelated "
                    "failures — catch specific exception types (CLI "
                    "answer-and-continue loops are exempt by location)",
                )
            )
        return out


    def rule_tpa007(self) -> list[Finding]:
        if self.is_cli:
            return []
        out: list[Finding] = []
        enclosing = _enclosing_symbols(self.tree)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.While):
                continue
            test = node.test
            if not (isinstance(test, ast.Constant) and test.value):
                continue  # condition-tested loops are bounded by their test
            for handler in _loop_retry_handlers(node):
                out.append(
                    self.finding(
                        "TPA007",
                        handler,
                        enclosing.get(id(node), "<module>"),
                        "unbounded retry: `while True` whose handler "
                        "continues without a sleep/backoff or attempt "
                        "bound spins hot under a persistent fault — add "
                        "jittered backoff and re-raise after N attempts",
                    )
                )
        return out


def _loop_retry_handlers(loop: ast.While) -> list[ast.ExceptHandler]:
    """Except handlers that retry ``loop`` unboundedly: the handler's last
    statement is ``continue`` and nothing in its body backs off (a
    sleep/wait/backoff call), escapes (``raise``/``break``/``return``), or
    re-raises. Only ``try`` statements whose ``continue`` actually binds
    THIS loop are considered — nested loops and function defs are skipped
    (their retry shapes are judged when their own loop is visited)."""
    trys: list[ast.Try] = []
    stack: list[ast.stmt] = list(loop.body)
    while stack:
        stmt = stack.pop()
        if isinstance(
            stmt,
            (ast.While, ast.For, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            continue  # continue/break inside bind the inner construct
        if isinstance(stmt, ast.Try):
            trys.append(stmt)
            stack.extend(stmt.body)
            stack.extend(stmt.orelse)
            stack.extend(stmt.finalbody)
        elif isinstance(stmt, ast.If):
            stack.extend(stmt.body)
            stack.extend(stmt.orelse)
        elif isinstance(stmt, ast.With):
            stack.extend(stmt.body)
    out: list[ast.ExceptHandler] = []
    for t in trys:
        for handler in t.handlers:
            if not (handler.body and isinstance(handler.body[-1], ast.Continue)):
                continue
            bounded = False
            for inner in ast.walk(handler):
                if isinstance(inner, (ast.Raise, ast.Break, ast.Return)):
                    bounded = True
                    break
                if isinstance(inner, ast.Call):
                    fname = _dotted(inner.func)
                    if fname and fname.split(".")[-1] in _BACKOFF_CALLS:
                        bounded = True
                        break
            if not bounded:
                out.append(handler)
    return out


def _enclosing_symbols(tree: ast.Module) -> dict[int, str]:
    """Map id(node) -> nearest enclosing function/class name, for reporting."""
    out: dict[int, str] = {}

    def visit(node: ast.AST, symbol: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_symbol = symbol
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                child_symbol = child.name if symbol == "<module>" else f"{symbol}.{child.name}"
            out[id(child)] = child_symbol
            visit(child, child_symbol)

    visit(tree, "<module>")
    return out


def _chain_prefixes(chain: str) -> list[str]:
    parts = chain.split(".")
    return [".".join(parts[: i + 1]) for i in range(len(parts))]


def _scan_donation_reuse(
    module: _Module,
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
    registry: dict[str, set[int]],
) -> list[Finding]:
    """Linear (statement-order) scan for loads of a donated buffer after the
    donating call. Loop bodies run twice so next-iteration reuse is seen.
    Only bare-name calls (``f(...)``, not ``obj.f(...)``) resolve against
    the registry — conservative, no false positives on bound methods."""
    findings: list[Finding] = []
    dead: dict[str, int] = {}  # chain -> donating call line
    reported: set[tuple[str, int]] = set()

    def loads_in(stmt: ast.stmt) -> list[tuple[str, ast.AST]]:
        out = []
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                chain = _dotted(node)
                if chain:
                    out.append((chain, node))
        return out

    def rebinds_in(stmt: ast.stmt) -> list[str]:
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.For):
            targets = [stmt.target]
        chains: list[str] = []

        def collect(t: ast.AST) -> None:
            if isinstance(t, (ast.Tuple, ast.List)):
                for elt in t.elts:
                    collect(elt)
            elif isinstance(t, ast.Starred):
                collect(t.value)
            else:
                chain = _dotted(t)
                if chain:
                    chains.append(chain)

        for t in targets:
            collect(t)
        return chains

    def donations_in(stmt: ast.stmt) -> list[tuple[str, int]]:
        out = []
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
                continue
            donated = registry.get(node.func.id)
            if not donated:
                continue
            for i in donated:
                if i < len(node.args):
                    chain = _dotted(node.args[i])
                    if chain:
                        out.append((chain, node.lineno))
        return out

    def process(body: Iterable[ast.stmt]) -> None:
        for stmt in body:
            # 1) loads of already-dead chains are reuse-after-donation
            for chain, node in loads_in(stmt):
                for prefix in _chain_prefixes(chain):
                    if prefix in dead and (prefix, node.lineno) not in reported:
                        reported.add((prefix, node.lineno))
                        findings.append(
                            module.finding(
                                "TPA005",
                                node,
                                fn.name,
                                f"`{chain}` was donated at line "
                                f"{dead[prefix]} — the buffer is invalidated; "
                                "rebind it from the call result before reuse",
                            )
                        )
            # 2) this statement's donating calls kill their buffer args
            for chain, lineno in donations_in(stmt):
                dead[chain] = lineno
            # 3) rebinding resurrects the name
            for chain in rebinds_in(stmt):
                for k in [k for k in dead if k == chain or k.startswith(chain + ".")]:
                    del dead[k]
            # recurse
            if isinstance(stmt, (ast.For, ast.While)):
                process(stmt.body)
                process(stmt.body)  # second pass: cross-iteration reuse
                process(stmt.orelse)
            elif isinstance(stmt, ast.If):
                process(stmt.body)
                process(stmt.orelse)
            elif isinstance(stmt, ast.With):
                process(stmt.body)
            elif isinstance(stmt, ast.Try):
                process(stmt.body)
                for h in stmt.handlers:
                    process(h.body)
                process(stmt.orelse)
                process(stmt.finalbody)

    process(fn.body)
    return findings


# --------------------------------------------------------------------------
# driver


def default_baseline_path() -> str:
    return os.path.join(_package_root(), "analysis", "baseline.json")


def run_rules(
    paths: list[str] | None = None,
    baseline_path: str | None = None,
    rules: Iterable[str] | None = None,
) -> RulesReport:
    """Run the lint rules over ``paths`` (default: the installed
    ``transformer_tpu`` package). Findings suppressed inline or matched by
    the baseline are split out; the remainder are actionable."""
    if paths is None:
        paths = [_package_root()]
        if baseline_path is None:
            baseline_path = default_baseline_path()
    baseline = load_baseline(baseline_path)
    active = set(rules) if rules is not None else set(RULES)

    modules: list[_Module] = []
    for full, rel in _iter_py_files(paths):
        with open(full, encoding="utf-8") as f:
            source = f.read()
        try:
            modules.append(_Module(full, rel, source))
        except SyntaxError as e:
            raise SyntaxError(f"cannot lint {full}: {e}") from e

    # Cross-module donation registry: a donating jit in one module can be
    # imported and called by name elsewhere.
    registry: dict[str, set[int]] = {}
    for m in modules:
        for name, donated in m.donating_registry().items():
            registry[name] = registry.get(name, set()) | donated

    findings: list[Finding] = []
    baselined: list[Finding] = []
    for m in modules:
        raw: list[Finding] = []
        if active & {"TPA001", "TPA002"}:
            raw.extend(
                f for f in m.rule_tpa001_002() if f.code in active
            )
        if "TPA003" in active:
            raw.extend(m.rule_tpa003())
        if "TPA004" in active:
            raw.extend(m.rule_tpa004())
        if "TPA005" in active:
            raw.extend(m.rule_tpa005(registry))
        if "TPA006" in active:
            raw.extend(m.rule_tpa006())
        if "TPA007" in active:
            raw.extend(m.rule_tpa007())
        for f in raw:
            if m.suppressed(f):
                continue
            if f.fingerprint in baseline:
                baselined.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return RulesReport(
        findings=findings, baselined=baselined, files_checked=len(modules)
    )


