"""Retrace sentinel: catch recompilation regressions before a TPU does.

A jitted hot path that silently retraces — a config knob that stopped being
hashable, a shape that stopped bucketing, a weak-typed scalar flipping per
call — costs seconds of XLA compile per occurrence and shows up only as
mysterious step-time jitter. With the bench relay often down (ROADMAP), a
retrace regression could ship unmeasured for rounds; this module turns "the
steady-state decode path compiles exactly N programs" into an assertable
budget.

Mechanics: every ``jax.jit`` callable exposes ``_cache_size()`` — the number
of compiled executables its cache holds. :class:`RetraceSentinel` snapshots
the watched functions' cache sizes, the caller drives the hot path, and
``check()`` fails if any function compiled more NEW programs than its
declared budget (0 for a steady-state path). This is jit-cache accounting,
not wall-clock sampling, so it is exact and CPU-safe.

``leak_checking()`` wires ``jax.checking_leaks`` around a block: tracer
leaks (the cousin failure mode — a traced value smuggled out through module
state) raise at the source instead of exploding later.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable, Iterator

import jax


def _cache_size(fn: Any) -> int:
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        raise ValueError(
            f"{fn!r} exposes no _cache_size — pass the jax.jit-wrapped "
            "callable itself (not the underlying Python function)"
        )
    return int(probe())


@dataclasses.dataclass
class WatchDelta:
    name: str
    budget: int
    before: int
    after: int

    @property
    def compiles(self) -> int:
        return self.after - self.before

    @property
    def within_budget(self) -> bool:
        return self.compiles <= self.budget

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "budget": self.budget,
            "compiles": self.compiles,
            "cache_before": self.before,
            "cache_after": self.after,
            "ok": self.within_budget,
        }


class RetraceSentinel:
    """Budgeted compile-count accounting over a set of jitted functions.

    >>> sentinel = RetraceSentinel()
    >>> sentinel.watch("decode_step", _pool_step, budget=0)
    >>> sentinel.snapshot()          # after warmup
    >>> ...drive the steady-state hot path...
    >>> sentinel.assert_within_budget()
    """

    def __init__(self) -> None:
        self._fns: dict[str, tuple[Any, int]] = {}
        self._before: dict[str, int] = {}

    def watch(self, name: str, fn: Any, budget: int = 0) -> None:
        _cache_size(fn)  # validate now, not at snapshot time
        self._fns[name] = (fn, budget)

    def snapshot(self) -> dict[str, int]:
        self._before = {
            name: _cache_size(fn) for name, (fn, _) in self._fns.items()
        }
        return dict(self._before)

    def deltas(self) -> list[WatchDelta]:
        if not self._fns:
            return []
        if not self._before:
            raise RuntimeError("snapshot() was never taken — nothing to diff")
        return [
            WatchDelta(
                name=name,
                budget=budget,
                before=self._before[name],
                after=_cache_size(fn),
            )
            for name, (fn, budget) in self._fns.items()
        ]

    def violations(self) -> list[WatchDelta]:
        return [d for d in self.deltas() if not d.within_budget]

    def assert_within_budget(self) -> None:
        bad = self.violations()
        if bad:
            raise AssertionError(
                "retrace budget exceeded: "
                + "; ".join(
                    f"{d.name} compiled {d.compiles} new program(s), "
                    f"budget {d.budget}"
                    for d in bad
                )
            )


@contextlib.contextmanager
def leak_checking() -> Iterator[None]:
    """``jax.checking_leaks`` as a composable context: tracer leaks raise
    where they escape. Trace-heavy (re-traces watched functions), so this is
    a debugging/CI tool, not a production wrapper."""
    with jax.checking_leaks():
        yield


# --------------------------------------------------------------------------
# canned steady-state scenarios (CLI `retrace` + tests)


def _tiny_lm_setup():
    from transformer_tpu.analysis.configs import FAST_MATRIX
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.models.transformer import transformer_init

    cfg = FAST_MATRIX["lm_bf16"]
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    tok = SubwordTokenizer.build_from_corpus(
        ["the quick brown fox jumps over the lazy dog"] * 4,
        target_vocab_size=cfg.input_vocab_size - 2,
    )
    return cfg, params, tok


def decode_retrace_report(steps: int = 3) -> list[WatchDelta]:
    """Steady-state serving: warm the slot-pool scheduler up on one request,
    snapshot, then serve ``steps`` more same-shaped requests. The hot paths
    (``_pool_step`` = decode step, ``_slot_prefill``, ``_pick_pool``) must
    compile ZERO new programs — admission bucketing (``prefill_len_for``)
    and the fixed-shape pool exist precisely to guarantee this."""
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import ContinuousScheduler

    cfg, params, tok = _tiny_lm_setup()

    def serve(reqs):
        s = ContinuousScheduler(
            params, cfg, tok, num_slots=2, max_total=32, default_max_new=4
        )
        return s.run(reqs)

    serve([{"prompt": "the quick brown fox"}])  # warmup compile
    sentinel = RetraceSentinel()
    sentinel.watch("decode_step(_pool_step)", sched._pool_step, budget=0)
    sentinel.watch("_slot_prefill", sched._slot_prefill, budget=0)
    sentinel.watch("pick(_pick_pool)", sched._pick_pool, budget=0)
    sentinel.snapshot()
    for _ in range(steps):
        out = serve([{"prompt": "the quick brown fox"}])
        assert "continuation" in out[0], out
    return sentinel.deltas()


def speculative_retrace_report(steps: int = 3) -> list[WatchDelta]:
    """Steady-state SPECULATIVE serving: accept lengths vary per request
    (a self-repeating prompt lands long n-gram accepts; an irregular one
    mostly misses), yet the hot paths — ``_pool_verify`` (the W-wide
    verify forward), ``_pick_pool_verify``, ``_slot_prefill``, and
    ``_pool_rollback`` — must compile ZERO new programs after warmup:
    rows are padded to the static width k + 1 and rollback is index
    arithmetic, so no accept length may mint a fresh shape."""
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import ContinuousScheduler

    cfg, params, tok = _tiny_lm_setup()

    # Mixed acceptance shapes on purpose: repetitive text drafts well,
    # irregular text rejects early, short prompts exercise the boundary.
    waves = [
        [{"prompt": "the quick brown fox"}, {"prompt": "dog dog dog dog"}],
        [{"prompt": "the the the the the"}, {"prompt": "lazy fox"}],
        [{"prompt": "quick quick brown"}, {"prompt": "the lazy dog"}],
    ]

    def serve(reqs):
        s = ContinuousScheduler(
            params, cfg, tok, num_slots=2, max_total=32, default_max_new=6,
            speculate_k=3,
        )
        return s.run(reqs)

    for wave in waves:
        # Warmup covers every prefill bucket the waves touch: bucketed
        # prefill widths (prefill_len_for) are a bounded compile set, not
        # steady-state retraces — the budget guards the per-STEP paths.
        serve([dict(r) for r in wave])
    sentinel = RetraceSentinel()
    sentinel.watch("verify(_pool_verify)", sched._pool_verify, budget=0)
    sentinel.watch("pick(_pick_pool_verify)", sched._pick_pool_verify, budget=0)
    sentinel.watch("_slot_prefill", sched._slot_prefill, budget=0)
    sentinel.watch("rollback(_pool_rollback)", sched._pool_rollback, budget=0)
    sentinel.snapshot()
    for i in range(steps):
        out = serve([dict(r) for r in waves[i % len(waves)]])
        assert all("continuation" in r for r in out), out
    return sentinel.deltas()


def prefix_cache_retrace_report(steps: int = 3) -> list[WatchDelta]:
    """Steady-state serving WITH the cross-request prefix cache: hits,
    misses, and partial hits all flow through admission, yet the hot paths
    — ``_pool_step``, ``_slot_prefill`` (suffix prefill at a traced start),
    ``_slot_restore`` (block restore at power-of-two padded widths),
    ``_slot_read_blocks`` (retirement export, one static block width), and
    ``_pick_pool`` — must compile ZERO new programs after warmup: hit
    lengths bucket by block count exactly as prompt lengths bucket by
    ``prefill_len_for``, so no admission outcome may mint a fresh shape."""
    from transformer_tpu.serve import PrefixCache
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import ContinuousScheduler

    cfg, params, tok = _tiny_lm_setup()
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)

    # One shared long prefix plus divergent tails: replays are full hits,
    # tail variants are partial hits, and the short prompt is a clean miss
    # — every admission outcome the trie can produce, every round.
    waves = [
        [{"prompt": "the quick brown fox jumps"}],
        [{"prompt": "the quick brown fox jumps"},        # full hit
         {"prompt": "the quick brown dog"}],             # partial hit
        [{"prompt": "lazy"},                             # miss
         {"prompt": "the quick brown fox jumps"}],
    ]

    def serve(reqs):
        s = ContinuousScheduler(
            params, cfg, tok, num_slots=2, max_total=48, default_max_new=4,
            prefix_cache=cache,
        )
        return s.run(reqs)

    for wave in waves + waves:
        # TWO warmup passes: the first populates the trie (every wave-0
        # admission is a miss), the second re-serves the same prompts as
        # hits/partial hits — covering every restore-pad bucket and
        # suffix-prefill bucket steady state will see (bounded compile
        # sets, not steady-state retraces — the budget guards the
        # per-admission/per-step paths).
        serve([dict(r) for r in wave])
    sentinel = RetraceSentinel()
    sentinel.watch("decode_step(_pool_step)", sched._pool_step, budget=0)
    sentinel.watch("_slot_prefill", sched._slot_prefill, budget=0)
    sentinel.watch("restore(_slot_restore)", sched._slot_restore, budget=0)
    sentinel.watch("export(_slot_read_blocks)", sched._slot_read_blocks, budget=0)
    sentinel.watch("pick(_pick_pool)", sched._pick_pool, budget=0)
    sentinel.snapshot()
    for i in range(steps):
        out = serve([dict(r) for r in waves[i % len(waves)]])
        assert all("continuation" in r for r in out), out
    return sentinel.deltas()


def paged_retrace_report(steps: int = 3) -> list[WatchDelta]:
    """Steady-state serving on the PAGED KV layout (``--kv_layout paged``)
    across every admission outcome the block pool can produce — fresh
    allocations, frees at retirement, device-tier ALIAS hits, spill-to-host
    followed by host-restore (re-adopted back into the device tier), and a
    copy-on-write block split — while the hot paths
    (``_pool_step_paged``, ``_slot_prefill_paged``, ``_pool_write_blocks``,
    ``_pool_read_block``, ``_pool_copy_blocks``, ``_pick_pool``) compile
    ZERO new programs after warmup: table/index shapes are static, host
    restores pad to power-of-two block counts, and per-slot indices are
    host-derived, so no pool state may mint a fresh shape. Greedy answers
    are asserted byte-identical round over round."""
    from transformer_tpu.serve import PrefixCache
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import ContinuousScheduler

    cfg, params, tok = _tiny_lm_setup()
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=4,
        prefix_cache=cache, kv_layout="paged",
    )
    wave = [
        {"prompt": "the quick brown fox jumps"},
        {"prompt": "the quick brown dog"},
    ]

    def one_round():
        out = s.run([dict(r) for r in wave])       # miss / alias / partial
        # Spill rung: push every device-tier block to the host trie (the
        # wire format), then re-serve — hits now restore through the
        # batched host write and are re-adopted, so the NEXT round
        # aliases again. Exercises _pool_read_block + _pool_write_blocks.
        s.stats["kv_spilled_blocks"] += cache.release_device_blocks(1 << 30)
        out2 = s.run([dict(r) for r in wave])
        # CoW rung: alias a device-tier block into a free slot's table
        # (refcount 2) and write-guard it — the pool splits the block and
        # copies it on device (_pool_copy_blocks), the fork a
        # parallel-sampling tier drives per step. The row is returned
        # before any admission can see it.
        bid = None
        with cache._lock:
            stack = [cache._root]
            while stack:
                n = stack.pop()
                stack.extend(n.children.values())
                if n.device_block is not None:
                    bid = n.device_block
                    break
        if bid is not None:
            slot = s._free[-1]
            s.pool.alloc.extend(slot, bid=bid)
            s._paged_cow(slot, 0, cache.block_tokens)
            s.pool.alloc.free_slot(slot)
        s.pool.alloc.check_consistency()
        return [r.get("continuation") for r in out + out2]

    # ONE warmup round compiles every shape steady state sees: the round
    # itself covers miss -> spill -> host-restore -> re-adopt -> CoW, and
    # the first steady round's alias hits reuse the restore-round's
    # suffix buckets (aliasing is a host-side table op).
    want = one_round()
    sentinel = RetraceSentinel()
    sentinel.watch("decode(_pool_step_paged)", sched._pool_step_paged, budget=0)
    sentinel.watch("_slot_prefill_paged", sched._slot_prefill_paged, budget=0)
    sentinel.watch("restore(_pool_write_blocks)", sched._pool_write_blocks, budget=0)
    sentinel.watch("spill(_pool_read_block)", sched._pool_read_block, budget=0)
    sentinel.watch("cow(_pool_copy_blocks)", sched._pool_copy_blocks, budget=0)
    sentinel.watch("pick(_pick_pool)", sched._pick_pool, budget=0)
    sentinel.snapshot()
    for i in range(steps):
        got = one_round()
        assert got == want, f"paged round {i} changed greedy answers"
    return sentinel.deltas()


def resilience_retrace_report(steps: int = 3) -> list[WatchDelta]:
    """Steady-state serving WHILE circuit breakers flip: injected drafter
    and prefix-cache faults open the breakers mid-run, requests keep
    answering through the degraded path, the fault plane disarms, and
    half-open probes close the breakers — all on ONE scheduler whose hot
    paths (``_pool_verify``, ``_pick_pool_verify``, ``_slot_prefill``,
    ``_slot_restore``, ``_slot_read_blocks``, ``_pool_rollback``) must
    compile ZERO new programs after warmup. Degradation is a row-content /
    admission-path change, never a shape change: breaker-open rows still
    ride the static W-wide verify program and breaker-open admissions use
    the same bucketed full-prefill widths a cache miss uses. Greedy
    answers are asserted byte-identical before, during, and after the
    breaker transitions (docs/ROBUSTNESS.md)."""
    from transformer_tpu.serve import PrefixCache, resilience
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.resilience import FaultPlane
    from transformer_tpu.serve.scheduler import ContinuousScheduler

    cfg, params, tok = _tiny_lm_setup()
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=4,
        speculate_k=2, prefix_cache=cache,
        breaker_threshold=2, breaker_cooldown_s=0.0, retry_backoff_ms=1.0,
    )
    wave = [
        {"prompt": "the quick brown fox jumps"},
        {"prompt": "the quick brown dog"},
        {"prompt": "lazy"},
    ]
    # Warmup: two passes cover misses (full prefill buckets) AND
    # hits/partial hits (restore pads + suffix buckets) — breaker-open
    # admissions reuse the miss path's programs, so warmup covers the
    # degraded mode too.
    want = s.run([dict(r) for r in wave])
    want2 = s.run([dict(r) for r in wave])
    assert [r.get("continuation") for r in want] == [
        r.get("continuation") for r in want2
    ], "prefix-cache replay changed greedy answers"
    sentinel = RetraceSentinel()
    sentinel.watch("verify(_pool_verify)", sched._pool_verify, budget=0)
    sentinel.watch("pick(_pick_pool_verify)", sched._pick_pool_verify, budget=0)
    sentinel.watch("_slot_prefill", sched._slot_prefill, budget=0)
    sentinel.watch("restore(_slot_restore)", sched._slot_restore, budget=0)
    sentinel.watch("export(_slot_read_blocks)", sched._slot_read_blocks, budget=0)
    sentinel.watch("rollback(_pool_rollback)", sched._pool_rollback, budget=0)
    sentinel.snapshot()
    for i in range(steps):
        with resilience.active(
            FaultPlane.parse("draft.propose:p=1,times=4;prefix.match:p=1,times=4")
        ):
            out = s.run([dict(r) for r in wave])  # breakers open mid-run
        assert [r.get("continuation") for r in out] == [
            r.get("continuation") for r in want
        ], f"degraded round {i} changed greedy answers"
        out = s.run([dict(r) for r in wave])      # probes close the breakers
        assert [r.get("continuation") for r in out] == [
            r.get("continuation") for r in want
        ], f"recovered round {i} changed greedy answers"
        assert s.breakers["speculative"].state == "closed"
        assert s.breakers["prefix_cache"].state == "closed"
    return sentinel.deltas()


def upgrade_retrace_report(steps: int = 3) -> list[WatchDelta]:
    """Steady-state serving ACROSS live-weight swaps: requests are
    admitted, a structural-twin weight set is staged mid-flight (the
    quiesce), the pool drains on the admission-time weights, the flip
    lands at a drained step boundary, new traffic serves the new weights,
    and a rollback re-stages the resident old pair — and through the
    whole quiesce/swap/rollback ladder the hot paths (``_pool_step``,
    ``_slot_prefill``, ``_pick_pool``) must compile ZERO new programs:
    params are traced operands of the same executables, so a verified
    twin only changes VALUES (docs/SERVING.md "Live-weights rollout").
    Answers are asserted byte-stable per weight_version tag."""
    from transformer_tpu.models.transformer import transformer_init
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import ContinuousScheduler

    cfg, params, tok = _tiny_lm_setup()
    params_new = transformer_init(jax.random.PRNGKey(1), cfg)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=32, default_max_new=4,
        weight_version="v0",
    )
    wave = [
        {"prompt": "the quick brown fox"}, {"prompt": "the lazy dog"},
    ]
    want_old = s.run([dict(r) for r in wave])  # warmup compile on v0
    sentinel = RetraceSentinel()
    sentinel.watch("decode_step(_pool_step)", sched._pool_step, budget=0)
    sentinel.watch("_slot_prefill", sched._slot_prefill, budget=0)
    sentinel.watch("pick(_pick_pool)", sched._pick_pool, budget=0)
    sentinel.snapshot()
    want_new = None
    for i in range(steps):
        # Straddle the boundary: admit the wave on v0, THEN stage v1 —
        # the in-flight requests must finish on their admission-time
        # weights while admission quiesces.
        for r in wave:
            s.submit(dict(r))
        s.admit()
        assert s.active_count == len(wave), "wave not admitted pre-stage"
        s.stage_params(params_new, "v1")
        while s.busy:
            s.admit()
            s.step()
        out = s.drain_ready()
        assert [r["continuation"] for r in out] == [
            r["continuation"] for r in want_old
        ], f"round {i}: straddling requests left their admission weights"
        assert all(r["weight_version"] == "v0" for r in out)
        s.step()  # the drained boundary: the flip lands here
        assert s.weight_version == "v1", "swap did not land"
        out = s.run([dict(r) for r in wave])
        assert all(r["weight_version"] == "v1" for r in out)
        if want_new is None:
            want_new = out
        else:
            assert [r["continuation"] for r in out] == [
                r["continuation"] for r in want_new
            ], f"round {i}: v1 answers drifted"
        s.stage_rollback()
        s.step()
        assert s.weight_version == "v0", "rollback did not land"
        out = s.run([dict(r) for r in wave])
        assert [r["continuation"] for r in out] == [
            r["continuation"] for r in want_old
        ], f"round {i}: rollback changed v0 answers"
    return sentinel.deltas()


def train_retrace_report(steps: int = 3) -> list[WatchDelta]:
    """Steady-state training: one warmup step compiles; ``steps`` more
    same-shaped steps must not."""
    import numpy as np

    from transformer_tpu.analysis.configs import TINY_TRAIN
    from transformer_tpu.train.state import TrainState, make_optimizer
    from transformer_tpu.train.trainer import make_train_step

    cfg, params, _ = _tiny_lm_setup()
    train_cfg = TINY_TRAIN
    tx = make_optimizer(cfg, train_cfg)
    state = TrainState(
        step=jax.numpy.int32(0), params=params, opt_state=tx.init(params)
    )
    step = jax.jit(make_train_step(cfg, train_cfg, tx=tx))
    B, L = train_cfg.batch_size, train_cfg.sequence_length
    rng = np.random.default_rng(0)

    def batch():
        ids = rng.integers(1, cfg.input_vocab_size, size=(B, L)).astype(np.int32)
        return ids, ids

    src, tgt = batch()
    state, _ = step(state, src, tgt, jax.random.PRNGKey(0))  # warmup
    sentinel = RetraceSentinel()
    sentinel.watch("train_step", step, budget=0)
    sentinel.snapshot()
    for i in range(steps):
        src, tgt = batch()
        state, _ = step(state, src, tgt, jax.random.PRNGKey(i))
    return sentinel.deltas()


def sharded_retrace_report(steps: int = 3) -> list[WatchDelta]:
    """Steady-state SHARDED serving (``--mesh``, serve/sharded.py): one
    LONG-LIVED scheduler whose canned programs are per-instance pjit twins
    over a 2-device mesh — the twins live on the instance, so the watched
    jit objects must be the scheduler's own, not the module-level ones.
    Same bucketing contract as the unsharded scenarios: after warmup, the
    sharded decode step, verify, prefill, and the shared pick programs
    must compile ZERO new programs. A resharding leak — an operand whose
    committed sharding drifts between calls, re-keying the pjit cache —
    shows up here as a steady-state retrace."""
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import ContinuousScheduler

    if len(jax.devices()) < 2:
        # The CLI forces 8 virtual CPU devices before importing jax
        # (_ensure_cpu_devices); a bare interpreter without them cannot
        # build the mesh, so the scenario reports nothing rather than
        # failing for a reason that is not a retrace.
        return []
    cfg, params, tok = _tiny_lm_setup()
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=32, default_max_new=4,
        mesh=2, speculate_k=2,
    )
    # Greedy only: the tiny bf16 analysis model NaNs under sampled
    # residual draws regardless of mesh (a numeric quirk of the canned
    # config, not a serving property); sampled-request parity is
    # tests/test_sharded.py's statement, over float32 models.
    waves = [
        [{"prompt": "the quick brown fox"}, {"prompt": "dog dog dog dog"}],
        [{"prompt": "the the the the the"}, {"prompt": "the lazy dog"}],
    ]
    for wave in waves:  # warmup covers every prefill bucket the waves touch
        out = s.run([dict(r) for r in wave])
        assert all("continuation" in r for r in out), out
    sentinel = RetraceSentinel()
    sentinel.watch("sharded decode(pool_step)", s._sharded.pool_step, budget=0)
    sentinel.watch("sharded verify(pool_verify)", s._sharded.pool_verify,
                   budget=0)
    sentinel.watch("sharded rollback(pool_rollback)", s._sharded.pool_rollback,
                   budget=0)
    sentinel.watch("sharded prefill(slot_prefill)", s._sharded.slot_prefill,
                   budget=0)
    sentinel.watch("pick(_pick_pool_verify) on sharded logits",
                   sched._pick_pool_verify, budget=0)
    sentinel.snapshot()
    for i in range(steps):
        out = s.run([dict(r) for r in waves[i % len(waves)]])
        assert all("continuation" in r for r in out), out
    return sentinel.deltas()
