"""Concurrency static analysis (TPA101–TPA105) for the serving tier.

The repo's host side already runs threads in four places (the obs scrape
thread, the serve CLI's stdin reader, the prefetch double-buffer, event-log
writers), and the next ROADMAP tier — multi-replica router, disaggregated
prefill/decode, hot checkpoint swap — multiplies the mutable state those
threads share (``PrefixCache`` refcounts, slot pools, metric registries).
Code review is the only thing guarding lock discipline today; this module
gives it the same machine-checked safety net TPA001–006 gave the compile
path.

Rule catalogue (docs/ANALYSIS.md has the long-form version):

- **TPA101** — unguarded access to shared state: a write (or mutating call)
  to state reachable from more than one thread root made outside any lock
  region, or a read outside a lock of state that IS lock-guarded elsewhere.
- **TPA102** — inconsistent guard choice: the same shared state accessed
  under two different locks with no lock common to all guarded accesses
  (two threads can then hold "the" lock simultaneously).
- **TPA103** — lock-order cycle: nested acquisitions establish a partial
  order between locks; a cycle in that order is a deadlock waiting for the
  right interleaving.
- **TPA104** — non-atomic read-modify-write on shared state outside a lock
  (``self.refs += 1``, ``self.nbytes = self.nbytes - n``): two threads can
  both read the old value and one increment is lost.
- **TPA105** — a blocking call made while holding a lock: jitted dispatch,
  ``jax.device_put``/``device_get``, file ``open``, ``queue.get/put``,
  ``thread.join``, ``time.sleep``, ``subprocess.*`` — every other thread
  that wants the lock now waits on the device/disk/peer too.

**Thread roots** are inferred from the AST: functions (module-level or
nested) passed as ``threading.Thread(target=...)``, bound methods passed
the same way (``target=self.loop``), and ``do_*`` methods of
``*RequestHandler`` subclasses (each request runs on a server thread).
**Shared state** is then the module-global / ``self``-attribute / closure
state reachable both from a thread root and from code outside it.

Deliberately conservative, like TPA001–006: aliasing is not tracked (a
local that points into a shared structure is invisible), parameters are
not followed across calls, and initialization writes that happen before
the thread starts (``__init__`` bodies; statements above the first
``Thread(...)`` in a closure scope) are exempt — they happen-before the
race. False negatives are acceptable; false positives on the shipped tree
are rule bugs. Suppress decisions inline with ``# tpa: disable=TPA10x —
reason`` and grandfather the rest in ``analysis/concurrency_baseline.json``
(same fingerprint workflow as the TPA001–006 baseline).

The dynamic counterpart — a deterministic interleaving explorer that RUNS
the interesting schedules instead of approximating them — lives in
:mod:`transformer_tpu.analysis.schedules`.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable

from transformer_tpu.analysis.baselines import (
    Finding,
    RulesReport,
    _iter_py_files,
    _package_root,
    line_suppressed,
    load_baseline,
)
from transformer_tpu.analysis.rules import _dotted

CONCURRENCY_RULES: dict[str, str] = {
    "TPA101": "unguarded access to state shared between thread roots",
    "TPA102": "shared state guarded by two different locks",
    "TPA103": "lock-order cycle across nested acquisitions",
    "TPA104": "non-atomic read-modify-write on shared state outside a lock",
    "TPA105": "blocking call made while holding a lock",
}

# Constructors whose results are lock objects (guard a `with` region).
_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
})
# Constructors whose results are internally synchronized (or immutable
# handshake primitives): accessing them from several threads is their job.
_SYNC_CTORS = _LOCK_CTORS | frozenset({
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "threading.local",
    "collections.deque",  # append/popleft are atomic under the GIL
})
_THREAD_CTORS = frozenset({"threading.Thread", "Thread"})
_QUEUE_CTORS = frozenset({
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
})

# Container/object methods that mutate their receiver.
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "appendleft", "popleft",
    "sort", "reverse",
})

# Calls that block the calling thread (flagged under a held lock). Dotted
# names match exactly; bare final attributes match the listed method names
# only when the receiver is a known queue/thread object.
_BLOCKING_DOTTED = frozenset({
    "time.sleep", "open", "os.replace", "os.rename",
    "jax.device_put", "jax.device_get", "jax.block_until_ready",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "socket.create_connection",
})
_BLOCKING_QUEUE_METHODS = frozenset({"get", "put", "join"})
_BLOCKING_ANY_RECEIVER = frozenset({"block_until_ready"})

_JIT_DECOS = frozenset({"jax.jit", "jit", "pjit", "jax.pjit"})


# --------------------------------------------------------------------------
# access bookkeeping


@dataclasses.dataclass
class _Access:
    state: str                # normalized state id ("self.x", "name")
    kind: str                 # "read" | "write" | "rmw" | "mutate"
    node: ast.AST
    symbol: str               # enclosing function, for reporting
    held: frozenset[str]      # lock names held at the access


def _call_name(node: ast.Call) -> str | None:
    return _dotted(node.func)


def _is_ctor(value: ast.AST, ctors: frozenset[str]) -> bool:
    return isinstance(value, ast.Call) and _call_name(value) in ctors


def _bound_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    """Names bound inside ``fn`` (params, assignments, imports, for/with
    targets, nested defs) — used to separate closure reads from locals."""
    a = fn.args
    out = {p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)}
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
        elif isinstance(node, ast.For):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        elif isinstance(node, ast.withitem) and node.optional_vars is not None:
            for sub in ast.walk(node.optional_vars):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                out.add((alias.asname or alias.name).split(".", 1)[0])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if node is not fn:
                out.add(node.name)
        elif isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


class _AccessCollector:
    """Walk one function body in statement order, tracking the held-lock
    stack (``with <lock>:`` regions plus linear ``.acquire()``/``.release()``
    pairs) and recording every access to the state ids in ``states``.

    ``resolve(expr) -> state id | None`` maps an expression to a state id
    (class scope: ``self.X``; closure/module scope: bare names).
    """

    def __init__(
        self,
        module: "_ConcModule",
        symbol: str,
        states: set[str],
        resolve,
        skip_defs: set[int] | None = None,
        track_locks: bool = False,
    ):
        self.module = module
        self.symbol = symbol
        self.states = states
        self.resolve = resolve
        self.skip_defs = skip_defs or set()
        self.track_locks = track_locks
        self.accesses: list[_Access] = []
        self.blocking: list[tuple[ast.Call, str, frozenset[str]]] = []
        self.order_edges: list[tuple[str, str, ast.AST]] = []

    # -- lock resolution
    def _lock_name(self, expr: ast.AST) -> str | None:
        chain = _dotted(expr)
        if chain is None:
            return None
        leaf = chain.rsplit(".", 1)[-1]
        return leaf if leaf in self.module.lock_names else None

    # -- the walk
    def walk(self, body: Iterable[ast.stmt], held: list[str]) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: list[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if id(stmt) in self.skip_defs:
                return
            # Nested defs (closures run later, possibly on another thread's
            # schedule — but from THIS scope's perspective they see the same
            # state): scan with the current lock stack cleared; a closure
            # body does not inherit the definer's held locks at call time.
            self.walk(stmt.body, [])
            return
        if isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                self._stmt(sub, held)
            return
        if isinstance(stmt, ast.With):
            entered: list[str] = []
            for item in stmt.items:
                self._exprs(item.context_expr, held)
                lock = self._lock_name(item.context_expr)
                if lock is not None:
                    if self.track_locks:
                        for outer in held:
                            if outer != lock:
                                self.order_edges.append((outer, lock, stmt))
                    entered.append(lock)
            self.walk(stmt.body, held + entered)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._exprs(stmt.test, held)
            self.walk(stmt.body, list(held))
            self.walk(stmt.orelse, list(held))
            return
        if isinstance(stmt, ast.For):
            self._exprs(stmt.iter, held)
            # Iterating shared state reads it.
            self._record(stmt.iter, "read", held)
            self.walk(stmt.body, list(held))
            self.walk(stmt.orelse, list(held))
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body, list(held))
            for h in stmt.handlers:
                self.walk(h.body, list(held))
            self.walk(stmt.orelse, list(held))
            self.walk(stmt.finalbody, list(held))
            return
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            # lock.acquire() / lock.release() as bare statements toggle the
            # linear lock stack for the REST of this block.
            call = stmt.value
            if isinstance(call.func, ast.Attribute):
                lock = self._lock_name(call.func.value)
                if lock is not None and call.func.attr == "acquire":
                    if self.track_locks:
                        for outer in held:
                            if outer != lock:
                                self.order_edges.append((outer, lock, stmt))
                    self._exprs(call, held)
                    held.append(lock)
                    return
                if lock is not None and call.func.attr == "release":
                    self._exprs(call, held)
                    if lock in held:
                        held.remove(lock)
                    return
            self._exprs(stmt.value, held)
            return
        if isinstance(stmt, ast.Assign):
            self._exprs(stmt.value, held)
            rmw = self._value_reads(stmt.value, stmt.targets)
            for t in stmt.targets:
                self._target(t, held, rmw)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._exprs(stmt.value, held)
            self._target(stmt.target, held, rmw=False)
            return
        if isinstance(stmt, ast.AugAssign):
            self._exprs(stmt.value, held)
            sid = self.resolve(stmt.target)
            if sid in self.states:
                self.accesses.append(
                    _Access(sid, "rmw", stmt, self.symbol, frozenset(held))
                )
            else:
                self._target(stmt.target, held, rmw=False)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                sid = self.resolve(base)
                if sid in self.states:
                    self.accesses.append(
                        _Access(sid, "mutate", stmt, self.symbol, frozenset(held))
                    )
            return
        if isinstance(stmt, (ast.Return, ast.Raise)):
            for child in ast.iter_child_nodes(stmt):
                self._exprs(child, held)
            return
        # Anything else: scan its expressions generically.
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._exprs(child, held)

    def _value_reads(self, value: ast.AST, targets: list[ast.AST]) -> bool:
        """``x = x + 1`` is the same lost-update RMW as ``x += 1``."""
        target_ids = {self.resolve(t) for t in targets} - {None}
        if not target_ids:
            return False
        for node in ast.walk(value):
            if self.resolve(node) in target_ids:
                return True
        return False

    def _target(self, target: ast.AST, held: list[str], rmw: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._target(elt, held, rmw)
            return
        if isinstance(target, ast.Starred):
            self._target(target.value, held, rmw)
            return
        if isinstance(target, ast.Subscript):
            sid = self.resolve(target.value)
            if sid in self.states:
                self.accesses.append(
                    _Access(sid, "mutate", target, self.symbol, frozenset(held))
                )
            self._exprs(target.slice, held)
            return
        sid = self.resolve(target)
        if sid in self.states:
            self.accesses.append(
                _Access(
                    sid, "rmw" if rmw else "write", target, self.symbol,
                    frozenset(held),
                )
            )

    def _record(self, expr: ast.AST, kind: str, held: list[str]) -> None:
        sid = self.resolve(expr)
        if sid in self.states:
            self.accesses.append(
                _Access(sid, kind, expr, self.symbol, frozenset(held))
            )

    def _exprs(self, root: ast.AST, held: list[str]) -> None:
        """Scan an expression tree for state reads, mutating calls, and
        blocking calls under a held lock."""
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                self._call(node, held)
            elif isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                sid = self.resolve(node)
                if sid in self.states and not self._is_mutator_receiver(node):
                    self.accesses.append(
                        _Access(sid, "read", node, self.symbol, frozenset(held))
                    )

    def _is_mutator_receiver(self, node: ast.AST) -> bool:
        # The receiver load inside `x.append(...)` is reported as the
        # mutate access by _call, not double-counted as a read here.
        parent = getattr(node, "_tpa_parent", None)
        return (
            isinstance(parent, ast.Attribute)
            and parent.attr in _MUTATORS
        )

    def _call(self, node: ast.Call, held: list[str]) -> None:
        fname = _call_name(node)
        # mutating method on shared state: x.append(...), self.stats.update()
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            sid = self.resolve(node.func.value)
            if sid in self.states:
                self.accesses.append(
                    _Access(sid, "mutate", node, self.symbol, frozenset(held))
                )
        if not held or not self.track_locks:
            return
        # blocking call while holding a lock?
        reason = None
        if fname in _BLOCKING_DOTTED:
            reason = f"`{fname}` blocks"
        elif fname in self.module.jitted_names:
            reason = f"`{fname}` dispatches a jitted computation"
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _BLOCKING_ANY_RECEIVER:
                reason = f"`.{attr}()` blocks on device completion"
            elif attr in _BLOCKING_QUEUE_METHODS:
                recv = _dotted(node.func.value)
                leaf = recv.rsplit(".", 1)[-1] if recv else None
                if leaf in self.module.queue_names:
                    reason = f"`{recv}.{attr}()` can block on the queue"
                elif leaf in self.module.thread_obj_names and attr == "join":
                    reason = f"`{recv}.join()` blocks until the thread exits"
        if reason is not None:
            self.blocking.append((node, reason, frozenset(held)))


# --------------------------------------------------------------------------
# per-module analysis


class _ConcModule:
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._annotate_parents()
        self.lock_names = self._collect_lock_names()
        self.queue_names = self._collect_ctor_names(_QUEUE_CTORS)
        self.thread_obj_names = self._collect_ctor_names(_THREAD_CTORS)
        self.sync_names = self._collect_ctor_names(_SYNC_CTORS)
        self.jitted_names = self._collect_jitted_names()
        self.findings: list[Finding] = []
        self.order_edges: list[tuple[str, str, ast.AST, str]] = []

    def _annotate_parents(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._tpa_parent = node  # type: ignore[attr-defined]

    # -- name collections --------------------------------------------------

    def _collect_lock_names(self) -> set[str]:
        """Bare attribute/global names assigned a Lock/RLock/Condition
        anywhere in the module. Identity is the leaf name — `self._lock`
        in one class and `sched._lock` seen from another resolve to the
        same guard, which is how the code actually uses them."""
        out: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and _is_ctor(node.value, _LOCK_CTORS):
                for t in node.targets:
                    chain = _dotted(t)
                    if chain:
                        out.add(chain.rsplit(".", 1)[-1])
        return out

    def _collect_ctor_names(self, ctors: frozenset[str]) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and _is_ctor(node.value, ctors):
                for t in node.targets:
                    chain = _dotted(t)
                    if chain:
                        out.add(chain.rsplit(".", 1)[-1])
        return out

    def _collect_jitted_names(self) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    name = _dotted(d)
                    if name in _JIT_DECOS:
                        out.add(node.name)
                    elif (
                        isinstance(dec, ast.Call)
                        and name in ("partial", "functools.partial")
                        and dec.args
                        and _dotted(dec.args[0]) in _JIT_DECOS
                    ):
                        out.add(node.name)
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _dotted(node.value.func) in _JIT_DECOS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
        return out

    # -- reporting helpers --------------------------------------------------

    def finding(self, code: str, node: ast.AST, symbol: str, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        snippet = self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        return Finding(
            code=code, path=self.rel, line=line, symbol=symbol,
            message=message, snippet=snippet,
        )

    def suppressed(self, f: Finding) -> bool:
        return line_suppressed(self.lines, f)

    # -- thread roots -------------------------------------------------------

    @staticmethod
    def _thread_targets(scope: ast.AST) -> list[ast.AST]:
        """Expressions passed as ``target=`` to ``threading.Thread(...)``
        within ``scope``."""
        out = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and _call_name(node) in _THREAD_CTORS:
                for kw in node.keywords:
                    if kw.arg == "target":
                        out.append(kw.value)
        return out

    # -- the three scopes ---------------------------------------------------

    def analyze(self) -> list[Finding]:
        # Lock-discipline pass first (TPA103/TPA105 need lock regions, not
        # shared-state discovery): every outermost function exactly once —
        # _AccessCollector recurses into nested defs itself.
        self._lock_pass()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._analyze_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._analyze_closure_scope(node)
        self._analyze_module_scope()
        self._lock_order_findings()
        return self.findings

    def _lock_pass(self) -> None:
        if not self.lock_names:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            parent = getattr(node, "_tpa_parent", None)
            enclosing = None
            while parent is not None:
                if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    enclosing = parent
                    break
                parent = getattr(parent, "_tpa_parent", None)
            if enclosing is not None:
                continue  # nested def: walked by its outermost ancestor
            symbol = node.name
            p = getattr(node, "_tpa_parent", None)
            if isinstance(p, ast.ClassDef):
                symbol = f"{p.name}.{node.name}"
            col = _AccessCollector(
                self, symbol, set(), lambda e: None, track_locks=True
            )
            col.walk(node.body, [])
            for call, reason, held in col.blocking:
                self._blocking_finding(call, reason, held, symbol)
            for a, b, edge_node in col.order_edges:
                self.order_edges.append((a, b, edge_node, symbol))

    # .. class scope: self-attribute state

    def _analyze_class(self, cls: ast.ClassDef) -> None:
        methods = {
            s.name: s
            for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if not methods:
            return
        is_handler = any(
            (_dotted(b) or "").endswith("RequestHandler") for b in cls.bases
        )
        roots: set[str] = set()
        if is_handler:
            roots.update(n for n in methods if n.startswith("do_"))
        for target in self._thread_targets(cls):
            chain = _dotted(target)
            if chain is None:
                continue
            leaf = chain.rsplit(".", 1)[-1]
            if (chain.startswith("self.") or chain.startswith(cls.name + ".")) \
                    and leaf in methods:
                roots.add(leaf)
        if not roots:
            return

        # Intra-class call graph: reachability from the thread roots.
        calls: dict[str, set[str]] = {}
        for name, fn in methods.items():
            callees = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    chain = _dotted(node.func)
                    if chain and chain.startswith("self."):
                        leaf = chain.split(".", 1)[1]
                        if leaf in methods:
                            callees.add(leaf)
            calls[name] = callees
        reach = set(roots)
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            for callee in calls.get(m, ()):
                if callee not in reach:
                    reach.add(callee)
                    frontier.append(callee)

        def resolve(expr: ast.AST):
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                return f"self.{expr.attr}"
            return None

        # All self-attrs, to find which are accessed on both sides.
        per_method: dict[str, set[str]] = {}
        for name, fn in methods.items():
            attrs = set()
            for node in ast.walk(fn):
                sid = resolve(node)
                if sid is not None:
                    leaf = sid.split(".", 1)[1]
                    if leaf not in self.sync_names:
                        attrs.add(sid)
            per_method[name] = attrs
        root_side = set().union(*(per_method[m] for m in reach)) if reach else set()
        other_methods = [
            m for m in methods
            if m not in reach and m not in ("__init__", "__post_init__", "__del__")
        ]
        other_side = (
            set().union(*(per_method[m] for m in other_methods))
            if other_methods else set()
        )
        shared = root_side & other_side
        if not shared:
            return
        symbol_prefix = cls.name
        accesses: list[_Access] = []
        for name, fn in methods.items():
            if name in ("__init__", "__post_init__"):
                continue  # happens-before thread start
            col = _AccessCollector(
                self, f"{symbol_prefix}.{name}", shared, resolve
            )
            col.walk(fn.body, [])
            accesses.extend(col.accesses)
        self._shared_state_findings(accesses)

    # .. closure scope: Thread(target=<nested def>) sharing enclosing locals

    def _analyze_closure_scope(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        nested = {
            s.name: s
            for s in fn.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if not nested:
            return
        roots: list[ast.FunctionDef | ast.AsyncFunctionDef] = []
        first_thread_line = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _call_name(node) in _THREAD_CTORS:
                if first_thread_line is None or node.lineno < first_thread_line:
                    first_thread_line = node.lineno
                for kw in node.keywords:
                    if kw.arg == "target" and isinstance(kw.value, ast.Name):
                        w = nested.get(kw.value.id)
                        if w is not None and w not in roots:
                            roots.append(w)
        if not roots:
            return
        fn_bound = _bound_names(fn)
        import_bound: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    import_bound.add((alias.asname or alias.name).split(".", 1)[0])

        def resolve(expr: ast.AST):
            if isinstance(expr, ast.Name):
                return expr.id
            return None

        shared_all: set[str] = set()
        root_ids = {id(w) for w in roots}
        per_root_free: dict[int, set[str]] = {}
        for w in roots:
            w_bound = _bound_names(w)
            free = set()
            for node in ast.walk(w):
                if isinstance(node, ast.Name) and node.id in fn_bound \
                        and node.id not in w_bound:
                    free.add(node.id)
            free -= import_bound
            free -= {n.name for n in nested.values() if hasattr(n, "name")}
            free -= self.sync_names
            per_root_free[id(w)] = free
        # outside accesses: names used in fn AFTER the first Thread(...)
        # construction, outside the root defs (statements before it
        # happen-before the thread starts).
        outside: set[str] = set()
        root_nodes = {id(n) for w in roots for n in ast.walk(w)}
        for node in ast.walk(fn):
            if id(node) in root_nodes or not isinstance(node, ast.Name):
                continue
            if first_thread_line is not None and node.lineno <= first_thread_line:
                continue
            outside.add(node.id)
        for w in roots:
            others = outside | set().union(
                *(f for i, f in per_root_free.items() if i != id(w)), set()
            )
            shared_all |= per_root_free[id(w)] & others
        shared_all -= self.sync_names
        if not shared_all:
            return
        accesses: list[_Access] = []
        # Collect accesses inside each root (full body) ...
        for w in roots:
            col = _AccessCollector(
                self, f"{fn.name}.{w.name}", shared_all, resolve
            )
            col.walk(w.body, [])
            accesses.extend(col.accesses)
        # ... and in the enclosing body after thread start, skipping roots.
        col = _AccessCollector(
            self, fn.name, shared_all, resolve, skip_defs=root_ids
        )
        col.walk(fn.body, [])
        accesses.extend(
            a for a in col.accesses
            if first_thread_line is None
            or getattr(a.node, "lineno", 0) > first_thread_line
        )
        self._shared_state_findings(accesses)

    # .. module scope: globals shared with module-level thread targets

    def _analyze_module_scope(self) -> None:
        top_defs = {
            s.name: s
            for s in self.tree.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        roots: set[str] = set()
        for target in self._thread_targets(self.tree):
            if isinstance(target, ast.Name) and target.id in top_defs:
                roots.add(target.id)
        if not roots:
            return
        module_globals = set()
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        module_globals.add(t.id)
        module_globals -= self.sync_names
        if not module_globals:
            return

        def resolve(expr: ast.AST):
            if isinstance(expr, ast.Name):
                return expr.id
            return None

        # call-graph closure over module-level defs
        calls: dict[str, set[str]] = {}
        for name, fn in top_defs.items():
            callees = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    if node.func.id in top_defs:
                        callees.add(node.func.id)
            calls[name] = callees
        reach = set(roots)
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            for callee in calls.get(m, ()):
                if callee not in reach:
                    reach.add(callee)
                    frontier.append(callee)

        def fn_accessed(fn) -> set[str]:
            out = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and node.id in module_globals:
                    bound = _bound_names(fn)
                    if node.id not in bound:
                        out.add(node.id)
            return out

        root_side = set().union(*(fn_accessed(top_defs[m]) for m in reach))
        other = [m for m in top_defs if m not in reach]
        other_side = (
            set().union(*(fn_accessed(top_defs[m]) for m in other))
            if other else set()
        )
        shared = root_side & other_side
        if not shared:
            return
        accesses: list[_Access] = []
        for name, fn in top_defs.items():
            col = _AccessCollector(self, name, shared, resolve)
            col.walk(fn.body, [])
            accesses.extend(col.accesses)
        self._shared_state_findings(accesses)

    # -- findings from collected accesses -----------------------------------

    def _shared_state_findings(self, accesses: list[_Access]) -> None:
        by_state: dict[str, list[_Access]] = {}
        for a in accesses:
            by_state.setdefault(a.state, []).append(a)
        for state, acc in by_state.items():
            guarded = [a for a in acc if a.held]
            guard_locks = set().union(*(a.held for a in guarded)) if guarded else set()
            common = (
                frozenset.intersection(*(a.held for a in guarded))
                if guarded else frozenset()
            )
            # TPA102: two different locks, none common to all guarded uses.
            if len(guard_locks) >= 2 and not common:
                a = guarded[0]
                self.findings.append(
                    self.finding(
                        "TPA102", a.node, a.symbol,
                        f"`{state}` is guarded by {len(guard_locks)} different "
                        f"locks ({', '.join(sorted(guard_locks))}) — two "
                        "threads can each hold 'the' lock; pick one guard",
                    )
                )
            for a in acc:
                if a.held:
                    continue
                if a.kind == "rmw":
                    self.findings.append(
                        self.finding(
                            "TPA104", a.node, a.symbol,
                            f"non-atomic read-modify-write on shared "
                            f"`{state}` outside a lock — two threads can "
                            "both read the old value and one update is lost",
                        )
                    )
                elif a.kind in ("write", "mutate"):
                    self.findings.append(
                        self.finding(
                            "TPA101", a.node, a.symbol,
                            f"unguarded write to `{state}`, which is shared "
                            "with a thread root — wrap it in the owning lock "
                            "(or document the happens-before edge inline)",
                        )
                    )
                elif guarded:
                    self.findings.append(
                        self.finding(
                            "TPA101", a.node, a.symbol,
                            f"unguarded read of `{state}`, which is "
                            "lock-guarded elsewhere — a torn/stale read; "
                            "take the same lock",
                        )
                    )

    def _lock_order_findings(self) -> None:
        graph: dict[str, dict[str, tuple[ast.AST, str]]] = {}
        for a, b, node, symbol in self.order_edges:
            graph.setdefault(a, {}).setdefault(b, (node, symbol))
        # DFS cycle detection; each distinct cycle (as a lock set) is
        # reported once, at the edge that closes it.
        reported: set[frozenset[str]] = set()

        def dfs(start: str, cur: str, path: list[str]) -> None:
            for nxt in graph.get(cur, {}):
                if nxt == start:
                    cyc = [*path, cur]
                    key = frozenset(cyc)
                    if key in reported:
                        continue
                    reported.add(key)
                    node, symbol = graph[cur][nxt]
                    order = " -> ".join([*cyc, start])
                    self.findings.append(
                        self.finding(
                            "TPA103", node, symbol,
                            f"lock-order cycle {order}: another thread "
                            "acquiring in the opposite order deadlocks both "
                            "— impose one global acquisition order",
                        )
                    )
                elif nxt not in path and nxt != cur:
                    dfs(start, nxt, [*path, cur])

        for start in sorted(graph):
            dfs(start, start, [])

    def _blocking_finding(
        self, node: ast.Call, reason: str, held: frozenset[str], symbol: str
    ) -> None:
        self.findings.append(
            self.finding(
                "TPA105", node, symbol,
                f"{reason} while holding {', '.join(sorted(held))} — every "
                "thread contending for the lock now waits on this call too; "
                "move it outside the critical section",
            )
        )


# --------------------------------------------------------------------------
# driver


def default_concurrency_baseline_path() -> str:
    return os.path.join(_package_root(), "analysis", "concurrency_baseline.json")


def run_concurrency(
    paths: list[str] | None = None,
    baseline_path: str | None = None,
) -> RulesReport:
    """Run the TPA101–105 concurrency rules over ``paths`` (default: the
    installed ``transformer_tpu`` package + its concurrency baseline)."""
    if paths is None:
        paths = [_package_root()]
        if baseline_path is None:
            baseline_path = default_concurrency_baseline_path()
    baseline = load_baseline(baseline_path)
    findings: list[Finding] = []
    baselined: list[Finding] = []
    n_files = 0
    for full, rel in _iter_py_files(paths):
        with open(full, encoding="utf-8") as f:
            source = f.read()
        try:
            mod = _ConcModule(full, rel, source)
        except SyntaxError as e:
            raise SyntaxError(f"cannot analyze {full}: {e}") from e
        n_files += 1
        raw = mod.analyze()
        # Nested ast.walk scopes can visit a class twice (module walk +
        # enclosing-function walk); dedupe by (code, path, line, message).
        seen: set[tuple] = set()
        for f in raw:
            key = (f.code, f.path, f.line, f.message)
            if key in seen:
                continue
            seen.add(key)
            if mod.suppressed(f):
                continue
            if f.fingerprint in baseline:
                baselined.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return RulesReport(findings=findings, baselined=baselined, files_checked=n_files)
