"""The config matrix the contract checker traces.

Tiny on purpose: contracts run under ``jax.eval_shape`` / ``jax.make_jaxpr``
— no device execution — so the cost is trace time, which scales with layer
COUNT, not width. ``FAST_MATRIX`` is the tier-1 set (every cache variant the
acceptance criteria name); ``FULL_MATRIX`` adds the architectural spread
(pre-LN, RoPE, tied weights, gated FFN, fp32) and runs under ``-m slow`` /
``contracts --matrix full``.
"""

from __future__ import annotations

import dataclasses

from transformer_tpu.config import ModelConfig, TrainConfig

_TINY = dict(
    num_layers=2,
    d_model=16,
    num_heads=2,
    dff=32,
    input_vocab_size=64,
    target_vocab_size=64,
    max_position=64,
    dropout_rate=0.0,
    dtype="bfloat16",
)


def _cfg(**over) -> ModelConfig:
    return ModelConfig(**{**_TINY, **over})


# name -> ModelConfig. Names are stable identifiers (baseline-able, and the
# CLI/json output keys results by them).
FAST_MATRIX: dict[str, ModelConfig] = {
    "seq2seq_bf16": _cfg(),
    "lm_bf16": _cfg(decoder_only=True),
    "lm_int8_cache": _cfg(decoder_only=True, kv_cache_int8=True),
    "lm_window": _cfg(decoder_only=True, attention_window=8),
    "lm_gqa": _cfg(decoder_only=True, num_kv_heads=1),
}

FULL_MATRIX: dict[str, ModelConfig] = {
    **FAST_MATRIX,
    "seq2seq_fp32": _cfg(dtype="float32"),
    "seq2seq_prenorm": _cfg(norm_scheme="pre"),
    "seq2seq_tied": _cfg(tie_embeddings=True, tie_output=True),
    "lm_rope": _cfg(decoder_only=True, position_scheme="rope"),
    "lm_gqa_int8": _cfg(decoder_only=True, num_kv_heads=1, kv_cache_int8=True),
    "lm_window_int8": _cfg(
        decoder_only=True, attention_window=8, kv_cache_int8=True
    ),
    "lm_swiglu": _cfg(decoder_only=True, ffn_activation="swiglu"),
    "mlm_bf16": _cfg(encoder_only=True),
}

TINY_TRAIN = TrainConfig(
    batch_size=2,
    sequence_length=8,
    epochs=1,
    warmup_steps=10,
    label_smoothing=0.1,
)


def matrix(name: str) -> dict[str, ModelConfig]:
    if name == "fast":
        return dict(FAST_MATRIX)
    if name == "full":
        return dict(FULL_MATRIX)
    raise ValueError(f"unknown config matrix {name!r} (fast|full)")


def describe(cfg: ModelConfig) -> str:
    """Short human label: the non-default knobs only."""
    base = ModelConfig()
    diffs = []
    for f in dataclasses.fields(ModelConfig):
        v = getattr(cfg, f.name)
        if v != getattr(base, f.name):
            diffs.append(f"{f.name}={v}")
    return ", ".join(diffs) or "defaults"
