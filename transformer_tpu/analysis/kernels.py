"""TPA300 — abstract Pallas kernel verifier (zero device execution).

Every ``pl.pallas_call`` site in the package is discovered two ways at
once and cross-checked:

* **trace capture** — the canned programs from :mod:`.costs` (plus a few
  kernel-direct entries) are traced under a monkeypatched
  ``pallas.pallas_call`` that records grids, BlockSpecs, scratch shapes,
  operand avals and concrete scalar-prefetch values, then matched
  against the ``pallas_call`` equations in the resulting jaxprs;
* **AST discovery** — ``kernels/`` and ``ops/`` are scanned for
  ``pallas_call`` call expressions so a kernel that silently fell out of
  the canned coverage is a finding (TPA300), not a blind spot.

Three analyses run on each captured site, all on the host with no
device work:

1. **grid/BlockSpec conformance** — each index-map lambda is enumerated
   over its full grid (they are pure host Python); every block index
   must land in-bounds, block shapes must tile the array (implicit
   padding is noted), and an out-spec revisited by several grid steps
   must use ``arbitrary`` dimension semantics and guard its writes.
2. **VMEM footprint** — per grid step the in/out/scratch block bytes
   are summed (double-buffered for grid-varying specs) against a
   per-generation budget, banked per kernel in
   ``kernels_baseline.json`` with the costs-style fail-on-growth /
   ``--update-baseline`` workflow.
3. **kernel-safety lints** TPA301-305 (see docs/ANALYSIS.md) riding the
   shared :mod:`.baselines` fingerprint/suppression machinery.

The per-kernel FLOPs reported here are priced by
:func:`.costs.pallas_call_flops` — the same walk ``jaxpr_costs`` uses —
so the two families cannot drift (tests assert equality).
"""

from __future__ import annotations

import ast
import contextlib
import dataclasses
import functools
import itertools
import json
import math
import os
import sys
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from .baselines import Finding, _package_root, line_suppressed

# ---------------------------------------------------------------------------
# Budgets
# ---------------------------------------------------------------------------

_MIB = 1024 * 1024

#: Usable VMEM per TensorCore by TPU generation (conservative: the
#: compiler reserves a slice of the architectural 16/32 MiB for spills).
VMEM_BUDGETS: dict[str, int] = {
    "v4": 16 * _MIB,
    "v5e": 16 * _MIB,
    "v5p": 16 * _MIB,
    "v6e": 32 * _MIB,
}

#: ROADMAP bench target is "TPU v5 lite".
DEFAULT_GENERATION = "v5e"

#: Native (sublane, lane) tile by element byte-width: fp32 (8,128),
#: bf16 (16,128), int8/fp8 (32,128).
_SUBLANE_BY_ITEMSIZE = {8: 8, 4: 8, 2: 16, 1: 32}
_LANE = 128

#: Full-grid index-map enumeration cap; larger grids are corner-sampled.
_MAX_ENUM = 4096

#: Primitives whose interpret-mode semantics diverge from compiled Mosaic
#: (TPA305).
_DIVERGENT_PRIMS = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "callback",
        "threefry2x32",
        "random_seed",
        "random_bits",
        "random_wrap",
        "random_unwrap",
        "random_fold_in",
        "rng_bit_generator",
    }
)

#: Ops that carry a masked-exp taint through (element-wise reshapes of the
#: same values); anything else drops the ("mexp", k) tag.
_MEXP_CARRIERS = frozenset(
    {
        "convert_element_type",
        "broadcast_in_dim",
        "reshape",
        "transpose",
        "squeeze",
        "copy",
    }
)

#: Reductions / contractions kill the "masked" taint: their output is a
#: statistic, not the masked lanes themselves (e.g. a running max of
#: ``_MASKED``-filled scores is a plain finite value afterwards).
_MASK_BARRIERS = frozenset(
    {
        "reduce_max",
        "reduce_min",
        "reduce_sum",
        "reduce_prod",
        "reduce_and",
        "reduce_or",
        "argmax",
        "argmin",
        "dot_general",
        "conv_general_dilated",
    }
)

_NEG_CONST_THRESHOLD = -1e20


# ---------------------------------------------------------------------------
# Capture
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _SpecView:
    """Normalized view of one BlockSpec against its operand aval."""

    role: str  # "in" | "out"
    index: int
    array_shape: tuple[int, ...]
    dtype: Any
    block_shape: tuple[int, ...]
    index_map: Callable | None
    grid_varying: bool = False  # filled by conformance


@dataclasses.dataclass
class _Capture:
    """One pallas_call site captured at trace time."""

    kernel_name: str
    kernel_file: str
    kernel_line: int
    call_path: str
    call_line: int
    grid: tuple[int, ...]
    in_specs: list[Any]
    out_specs: list[Any]
    out_shapes: list[Any]  # ShapeDtypeStruct-likes
    scratch: list[dict]  # {"shape","dtype","space"}
    num_scalar_prefetch: int
    dimension_semantics: tuple[str, ...] | None
    input_output_aliases: dict[int, int]
    interpret: Any
    in_avals: list[tuple[tuple[int, ...], Any]] = dataclasses.field(default_factory=list)
    scalar_values: list[Any] = dataclasses.field(default_factory=list)
    calls: int = 1

    def site_key(self):
        return (
            self.kernel_name,
            self.grid,
            tuple(tuple(s["shape"]) for s in self.scratch),
            tuple(self.in_avals),
        )


def _unwrap_fn(fn):
    while isinstance(fn, functools.partial):
        fn = fn.func
    return getattr(fn, "__wrapped__", fn)


def _normalize_specs(specs) -> list[Any]:
    if specs is None:
        return []
    if isinstance(specs, (list, tuple)):
        out = []
        for s in specs:
            if isinstance(s, (list, tuple)):
                out.extend(_normalize_specs(s))
            else:
                out.append(s)
        return out
    return [specs]


def _scratch_views(scratch_shapes) -> list[dict]:
    out = []
    for s in _normalize_specs(scratch_shapes):
        shape = tuple(getattr(s, "shape", ()))
        try:
            dt = np.dtype(getattr(s, "dtype", np.float32))
        except TypeError:
            dt = np.dtype(np.float32)
        space = str(getattr(s, "memory_space", "vmem")).lower()
        out.append({"shape": shape, "dtype": dt, "space": space})
    return out


@contextlib.contextmanager
def _capture_pallas(records: list[_Capture]):
    """Monkeypatch ``pallas.pallas_call`` on the shared module object.

    Every kernel module in the package imports ``pallas as pl`` from the
    same module, so one patch point sees all call sites at trace time.
    """
    import jax
    from jax.experimental import pallas as _pallas

    # A previous trace of the same program (e.g. the costs family, or a
    # bench's own program_costs call) leaves cached sub-traces that skip
    # re-executing the Python that calls pallas_call — flush them so the
    # capture always sees every site.
    jax.clear_caches()

    real = _pallas.pallas_call

    def patched(kernel, *pargs, **kw):
        caller = sys._getframe(1)
        fn = _unwrap_fn(kernel)
        code = getattr(fn, "__code__", None)
        grid_spec = kw.get("grid_spec")
        if grid_spec is not None:
            grid = tuple(getattr(grid_spec, "grid", ()) or ())
            in_specs = _normalize_specs(getattr(grid_spec, "in_specs", None))
            out_specs = _normalize_specs(getattr(grid_spec, "out_specs", None))
            scratch = _scratch_views(getattr(grid_spec, "scratch_shapes", None))
            nsp = int(getattr(grid_spec, "num_scalar_prefetch", 0) or 0)
        else:
            g = kw.get("grid", ())
            grid = tuple(g) if isinstance(g, (tuple, list)) else ((g,) if g else ())
            in_specs = _normalize_specs(kw.get("in_specs"))
            out_specs = _normalize_specs(kw.get("out_specs"))
            scratch = _scratch_views(kw.get("scratch_shapes"))
            nsp = 0
        cp = kw.get("compiler_params")
        sem = getattr(cp, "dimension_semantics", None)
        if sem is None and isinstance(cp, dict):
            sem = (cp.get("mosaic") or {}).get("dimension_semantics")
        sem = tuple(sem) if sem else None
        aliases = dict(kw.get("input_output_aliases") or {})
        base = _Capture(
            kernel_name=getattr(fn, "__name__", str(fn)),
            kernel_file=getattr(code, "co_filename", "<unknown>"),
            kernel_line=getattr(code, "co_firstlineno", 0),
            call_path=caller.f_code.co_filename,
            call_line=caller.f_lineno,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shapes=_normalize_specs(kw.get("out_shape")),
            scratch=scratch,
            num_scalar_prefetch=nsp,
            dimension_semantics=sem,
            input_output_aliases=aliases,
            interpret=kw.get("interpret"),
        )
        inner = real(kernel, *pargs, **kw)

        def wrapped(*operands):
            rec = dataclasses.replace(base)
            flat = []
            for op in operands:
                if isinstance(op, (list, tuple)):
                    flat.extend(op)
                else:
                    flat.append(op)
            rec.in_avals = [
                (tuple(np.shape(o)), np.dtype(getattr(o, "dtype", type(o))))
                for o in flat
            ]
            svals = []
            for o in flat[: rec.num_scalar_prefetch]:
                try:
                    svals.append(np.asarray(o))
                except Exception:  # tpa: disable=TPA006
                    svals.append(None)
            rec.scalar_values = svals
            records.append(rec)
            return inner(*operands)

        return wrapped

    _pallas.pallas_call = patched
    try:
        yield
    finally:
        _pallas.pallas_call = real


# ---------------------------------------------------------------------------
# Spec views + index-map enumeration
# ---------------------------------------------------------------------------


def _spec_views(cap: _Capture) -> list[_SpecView]:
    """Pair each in/out BlockSpec with its operand aval."""
    views: list[_SpecView] = []
    data_avals = cap.in_avals[cap.num_scalar_prefetch :]
    for i, spec in enumerate(cap.in_specs):
        if i < len(data_avals):
            shape, dt = data_avals[i]
        else:
            shape, dt = (), np.dtype(np.float32)
        views.append(_make_view("in", i, shape, dt, spec))
    for i, spec in enumerate(cap.out_specs):
        if i < len(cap.out_shapes):
            o = cap.out_shapes[i]
            shape = tuple(getattr(o, "shape", ()))
            dt = np.dtype(getattr(o, "dtype", np.float32))
        else:
            shape, dt = (), np.dtype(np.float32)
        views.append(_make_view("out", i, shape, dt, spec))
    return views


def _make_view(role, index, array_shape, dtype, spec) -> _SpecView:
    block = getattr(spec, "block_shape", None)
    imap = getattr(spec, "index_map", None)
    if block is None:
        block = array_shape
    else:
        block = tuple(
            array_shape[d] if b is None else int(b) for d, b in enumerate(block)
        )
    return _SpecView(
        role=role,
        index=index,
        array_shape=tuple(int(d) for d in array_shape),
        dtype=np.dtype(dtype),
        block_shape=block,
        index_map=imap,
    )


def _grid_points(grid: tuple[int, ...]):
    """Full grid if small, else the corner/midpoint sample lattice."""
    size = int(np.prod(grid)) if grid else 1
    if not grid:
        return [()], False
    if size <= _MAX_ENUM:
        return list(itertools.product(*(range(d) for d in grid))), False
    axes = [sorted({0, d // 2, d - 1}) for d in grid]
    return list(itertools.product(*axes)), True


def _synth_scalar_args(cap: _Capture) -> list[np.ndarray]:
    """Stand-in scalar-prefetch operands when tracing gave us tracers.

    Values are kept in ``[0, lead)`` where ``lead`` is the largest
    leading dim over the data operands — for a paged block table that is
    ``num_blocks``, so synthesized ids are always legal block ids.
    """
    data_avals = cap.in_avals[cap.num_scalar_prefetch :]
    lead = max((s[0] for s, _ in data_avals if s), default=1)
    out = []
    for k in range(cap.num_scalar_prefetch):
        if k < len(cap.scalar_values) and cap.scalar_values[k] is not None:
            out.append(np.asarray(cap.scalar_values[k]))
            continue
        shape, dt = cap.in_avals[k]
        n = int(np.prod(shape)) if shape else 1
        flat = (np.arange(n) % max(lead, 1)).astype(np.dtype(dt))
        if n:
            flat[0] = max(lead - 1, 0)
        out.append(flat.reshape(shape))
    return out


@dataclasses.dataclass
class _Conformance:
    checked_points: int = 0
    sampled: bool = False
    violations: list[str] = dataclasses.field(default_factory=list)
    padding: list[str] = dataclasses.field(default_factory=list)
    revisited_out: dict[int, tuple[int, ...]] = dataclasses.field(default_factory=dict)
    # per (role, index): map from grid point -> block index (for aliases)
    maps: dict[tuple[str, int], dict] = dataclasses.field(default_factory=dict)


def _check_conformance(cap: _Capture, views: list[_SpecView]) -> _Conformance:
    res = _Conformance()
    points, sampled = _grid_points(cap.grid)
    res.sampled = sampled
    res.checked_points = len(points)
    scalars = _synth_scalar_args(cap)
    for v in views:
        tag = f"{v.role}_specs[{v.index}]"
        res.maps[(v.role, v.index)] = {}
        nblocks = [
            -(-a // b) if b else 1 for a, b in zip(v.array_shape, v.block_shape)
        ]
        for a, b in zip(v.array_shape, v.block_shape):
            if b and a % b:
                res.padding.append(
                    f"{tag}: block {v.block_shape} pads array {v.array_shape}"
                )
                break
        seen_axes_vary = [False] * max(len(cap.grid), 1)
        baseline_idx = None
        oob = 0
        for pt in points:
            try:
                idx = v.index_map(*pt, *scalars) if v.index_map else tuple(
                    0 for _ in v.array_shape
                )
            except Exception as e:  # noqa: BLE001 — report, don't crash the pass  # tpa: disable=TPA006
                res.violations.append(f"{tag}: index map raised {type(e).__name__}: {e}")
                break
            try:
                idx = tuple(int(np.asarray(d)) for d in (
                    idx if isinstance(idx, (tuple, list)) else (idx,)
                ))
            except Exception:  # tpa: disable=TPA006
                res.violations.append(f"{tag}: index map not host-evaluable at {pt}")
                break
            if len(idx) != len(v.array_shape):
                res.violations.append(
                    f"{tag}: index map rank {len(idx)} != operand rank "
                    f"{len(v.array_shape)}"
                )
                break
            for d, (i_d, n_d) in enumerate(zip(idx, nblocks)):
                if not 0 <= i_d < n_d:
                    oob += 1
                    if oob <= 3:
                        res.violations.append(
                            f"{tag}: grid point {pt} -> block index {idx} "
                            f"out of bounds in dim {d} "
                            f"(array {v.array_shape}, block {v.block_shape})"
                        )
            res.maps[(v.role, v.index)][pt] = idx
            if baseline_idx is None:
                baseline_idx = idx
            elif idx != baseline_idx:
                for a in range(len(cap.grid)):
                    ref = tuple(0 if ax == a else p for ax, p in enumerate(pt))
                    prev = res.maps[(v.role, v.index)].get(ref)
                    if prev is not None and prev != idx:
                        seen_axes_vary[a] = True
        if oob > 3:
            res.violations.append(f"{tag}: ... {oob - 3} more out-of-bounds points")
        # grid-varying = double-buffered pipelining; also drives revisit check
        varies = any(seen_axes_vary[: len(cap.grid)])
        v.grid_varying = varies and bool(cap.grid)
        if v.role == "out" and cap.grid:
            const_axes = tuple(
                a
                for a in range(len(cap.grid))
                if cap.grid[a] > 1 and not _axis_varies(res.maps[(v.role, v.index)], a)
            )
            if const_axes:
                res.revisited_out[v.index] = const_axes
    return res


def _axis_varies(mapping: dict, axis: int) -> bool:
    """True if the block index depends on grid axis ``axis``."""
    groups: dict[tuple, set] = {}
    for pt, idx in mapping.items():
        key = tuple(p for a, p in enumerate(pt) if a != axis)
        groups.setdefault(key, set()).add(idx)
    return any(len(v) > 1 for v in groups.values())


def _check_aliases(cap: _Capture, views: list[_SpecView], res: _Conformance):
    ins = {v.index: v for v in views if v.role == "in"}
    outs = {v.index: v for v in views if v.role == "out"}
    for k, val in cap.input_output_aliases.items():
        i = int(k) - cap.num_scalar_prefetch
        vi, vo = ins.get(i), outs.get(int(val))
        if vi is None or vo is None:
            continue
        if vi.block_shape != vo.block_shape:
            res.violations.append(
                f"alias in[{i}]->out[{val}]: block shapes differ "
                f"({vi.block_shape} vs {vo.block_shape})"
            )
            continue
        mi = res.maps.get(("in", i), {})
        mo = res.maps.get(("out", int(val)), {})
        for pt, idx in mi.items():
            if pt in mo and mo[pt] != idx:
                res.violations.append(
                    f"alias in[{i}]->out[{val}]: index maps diverge at {pt} "
                    f"({idx} vs {mo[pt]})"
                )
                break


# ---------------------------------------------------------------------------
# VMEM model
# ---------------------------------------------------------------------------


def _vmem_footprint(cap: _Capture, views: list[_SpecView]) -> tuple[int, dict]:
    """Per-grid-step VMEM bytes: blocks (x2 when pipelined) + scratch."""
    grid_size = int(np.prod(cap.grid)) if cap.grid else 1
    breakdown: dict[str, int] = {}
    total = 0
    for v in views:
        bytes_ = int(np.prod(v.block_shape)) * v.dtype.itemsize if v.block_shape else (
            v.dtype.itemsize
        )
        mult = 2 if (v.grid_varying and grid_size > 1) else 1
        breakdown[f"{v.role}[{v.index}]"] = bytes_ * mult
        total += bytes_ * mult
    for i, s in enumerate(cap.scratch):
        space = s["space"]
        if "smem" in space or "sem" in space:
            continue
        bytes_ = int(np.prod(s["shape"])) * s["dtype"].itemsize if s["shape"] else s[
            "dtype"
        ].itemsize
        breakdown[f"scratch[{i}]"] = bytes_
        total += bytes_
    return total, breakdown


# ---------------------------------------------------------------------------
# Body provenance engine (jaxpr walk of the kernel body)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Write:
    ref: int
    rmw: bool
    pid_guard: bool
    conditional: bool
    line: int


@dataclasses.dataclass
class _BodyFacts:
    writes: list[_Write] = dataclasses.field(default_factory=list)
    reads: set[int] = dataclasses.field(default_factory=set)
    masked_exps: dict[int, dict] = dataclasses.field(default_factory=dict)
    divergent: list[tuple[str, int]] = dataclasses.field(default_factory=list)
    _mexp_counter: int = 0


def _eqn_line(eqn) -> int:
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        return int(frame.start_line) if frame else 0
    except Exception:  # noqa: BLE001  # tpa: disable=TPA006
        return 0


def _literal_taint(var) -> frozenset:
    val = getattr(var, "val", None)
    if val is None:
        return frozenset()
    try:
        arr = np.asarray(val)
        if arr.dtype.kind == "f" and arr.size and float(arr.min()) <= (
            _NEG_CONST_THRESHOLD
        ):
            return frozenset({"negconst"})
    except Exception:  # noqa: BLE001  # tpa: disable=TPA006
        pass
    return frozenset()


def _taint_of(env, var) -> frozenset:
    if hasattr(var, "val"):  # Literal
        return _literal_taint(var)
    return env.get(var, frozenset())


def _propagate(prim_name: str, taint: frozenset) -> frozenset:
    out = set()
    for t in taint:
        if isinstance(t, tuple) and t and t[0] == "ref":
            continue  # ref identity never flows through values
        if isinstance(t, tuple) and t and t[0] == "mexp":
            if prim_name in _MEXP_CARRIERS:
                out.add(t)
            continue
        if t in ("masked", "negconst") and prim_name in _MASK_BARRIERS:
            continue
        out.add(t)
    return frozenset(out)


def _ref_ids(taint: frozenset) -> set[int]:
    return {t[1] for t in taint if isinstance(t, tuple) and t and t[0] == "ref"}


def _read_ids(taint: frozenset) -> set[int]:
    return {t[1] for t in taint if isinstance(t, tuple) and t and t[0] == "read"}


def _sub_call_jaxprs(eqn):
    """Sub-jaxprs of call-like primitives, via the shared costs helper."""
    from .costs import _sub_jaxprs

    subs = []
    for value in eqn.params.values():
        subs.extend(_sub_jaxprs(value))
    return subs


def _walk_body(jaxpr, env: dict, preds: list, facts: _BodyFacts, depth: int = 0):
    if depth > 16:
        return
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        in_taints = [_taint_of(env, v) for v in eqn.invars]
        union = frozenset().union(*in_taints) if in_taints else frozenset()
        if name == "program_id":
            for ov in eqn.outvars:
                env[ov] = frozenset({"pid"})
        elif name == "get":
            ref_ids = _ref_ids(in_taints[0])
            facts.reads |= ref_ids
            out = frozenset(("read", r) for r in ref_ids) | _propagate(name, union)
            for ov in eqn.outvars:
                env[ov] = out
        elif name in ("swap", "addupdate"):
            ref_ids = _ref_ids(in_taints[0])
            val_taint = in_taints[1] if len(in_taints) > 1 else frozenset()
            pid_guard = any("pid" in p for p in preds)
            for r in ref_ids:
                facts.writes.append(
                    _Write(
                        ref=r,
                        rmw=(name == "addupdate") or (("read", r) in val_taint),
                        pid_guard=pid_guard,
                        conditional=bool(preds),
                        line=_eqn_line(eqn),
                    )
                )
            out = frozenset(("read", r) for r in ref_ids) | _propagate(name, union)
            for ov in eqn.outvars:
                env[ov] = out
        elif name == "cond":
            pred_taint = in_taints[0]
            branches = eqn.params.get("branches", ())
            out_taints = None
            for br in branches:
                bj = getattr(br, "jaxpr", br)
                env2 = dict(env)
                for bv, ov in zip(bj.invars, eqn.invars[1:]):
                    env2[bv] = _taint_of(env, ov)
                _walk_body(bj, env2, preds + [pred_taint], facts, depth + 1)
                branch_outs = [_taint_of(env2, v) for v in bj.outvars]
                if out_taints is None:
                    out_taints = branch_outs
                else:
                    out_taints = [
                        a | b for a, b in zip(out_taints, branch_outs)
                    ]
            for ov, t in zip(eqn.outvars, out_taints or []):
                env[ov] = _propagate(name, t)
        elif name == "select_n":
            data = in_taints[1:]
            data_union = frozenset().union(*data) if data else frozenset()
            out = _propagate(name, data_union)
            if any("negconst" in d for d in data):
                out = out | frozenset({"masked"})
            for d in data:
                for t in d:
                    if isinstance(t, tuple) and t and t[0] == "mexp":
                        k = t[1]
                        if k in facts.masked_exps:
                            facts.masked_exps[k]["guarded"] = True
            for ov in eqn.outvars:
                env[ov] = out
        elif name == "exp":
            out = _propagate(name, union)
            if "masked" in union:
                k = facts._mexp_counter
                facts._mexp_counter += 1
                facts.masked_exps[k] = {"guarded": False, "line": _eqn_line(eqn)}
                out = out | frozenset({("mexp", k)})
            for ov in eqn.outvars:
                env[ov] = out
        else:
            if name in _DIVERGENT_PRIMS:
                facts.divergent.append((name, _eqn_line(eqn)))
            subs = _sub_call_jaxprs(eqn)
            walked = False
            for sub in subs:
                sj = getattr(sub, "jaxpr", sub)
                if len(sj.invars) == len(eqn.invars):
                    env2 = dict(env)
                    for bv, ov in zip(sj.invars, eqn.invars):
                        env2[bv] = _taint_of(env, ov)
                    _walk_body(sj, env2, preds, facts, depth + 1)
                    outs = [_taint_of(env2, v) for v in sj.outvars]
                    for ov, t in zip(eqn.outvars, outs):
                        env[ov] = _propagate(name, t)
                    walked = True
                    break
            if not walked:
                if subs:
                    for sub in subs:
                        sj = getattr(sub, "jaxpr", sub)
                        _walk_body(sj, {}, preds, facts, depth + 1)
                out = _propagate(name, union)
                for ov in eqn.outvars:
                    env[ov] = out


def _body_facts(body_jaxpr, gm) -> tuple[_BodyFacts, dict[int, str], dict[int, Any]]:
    """Walk a kernel body; return facts + ref-slot roles and dtypes.

    ``gm`` is the eqn's GridMapping: invars after the scalar operands are
    ordered [inputs, outputs, scratch].
    """
    n_scalar = int(getattr(gm, "num_index_operands", 0) or 0)
    n_in = int(getattr(gm, "num_inputs", 0) or 0)
    n_out = int(getattr(gm, "num_outputs", 0) or 0)
    roles: dict[int, str] = {}
    dtypes: dict[int, Any] = {}
    env: dict = {}
    for slot, var in enumerate(body_jaxpr.invars):
        env[var] = frozenset({("ref", slot)})
        if slot < n_scalar:
            roles[slot] = "scalar"
        elif slot < n_scalar + n_in:
            roles[slot] = "in"
        elif slot < n_scalar + n_in + n_out:
            roles[slot] = "out"
        else:
            roles[slot] = "scratch"
        aval = getattr(var, "aval", None)
        inner = getattr(aval, "inner_aval", aval)
        dtypes[slot] = getattr(inner, "dtype", None)
    facts = _BodyFacts()
    _walk_body(body_jaxpr, env, [], facts)
    return facts, roles, dtypes


# ---------------------------------------------------------------------------
# Lints (TPA301-305)
# ---------------------------------------------------------------------------


def _is_float(dt) -> bool:
    """Float check that also recognizes ml_dtypes (bf16 has numpy kind 'V')."""
    d = np.dtype(dt)
    if d.kind == "f":
        return True
    return "float" in d.name or d.name in ("bfloat16", "e4m3", "e5m2")


def _display_path(abs_path: str) -> str:
    base = os.path.dirname(_package_root())
    try:
        rel = os.path.relpath(abs_path, base)
    except ValueError:
        return os.path.basename(abs_path)
    if rel.startswith(".."):
        return os.path.basename(abs_path)
    return rel


def _lint_site(cap: _Capture, facts: _BodyFacts | None, roles, dtypes) -> list[Finding]:
    findings: list[Finding] = []
    path = _display_path(cap.kernel_file)
    sym = cap.kernel_name

    def add(code, line, snippet, message):
        findings.append(
            Finding(
                code=code,
                path=path,
                line=line or cap.kernel_line,
                symbol=sym,
                message=message,
                snippet=snippet,
            )
        )

    if facts is not None:
        rmw_refs = {w.ref for w in facts.writes if w.rmw}
        n_data = len(roles)
        # TPA301: read-modify-write accumulator in a sub-fp32 float scratch.
        for r in sorted(rmw_refs):
            if roles.get(r) != "scratch":
                continue
            dt = dtypes.get(r)
            if dt is not None and _is_float(dt) and np.dtype(dt).itemsize < 4:
                add(
                    "TPA301",
                    cap.kernel_line,
                    f"{sym}:scratch{r}",
                    f"accumulator scratch slot {r} is {np.dtype(dt).name}; "
                    "running softmax stats / accumulators must be float32 "
                    "to avoid catastrophic cancellation across grid steps",
                )
        # TPA302: RMW accumulator with no guarded (or unconditional) init.
        for r in sorted(rmw_refs):
            if roles.get(r) not in ("scratch", "out"):
                continue
            inits = [
                w
                for w in facts.writes
                if w.ref == r and not w.rmw and (w.pid_guard or not w.conditional)
            ]
            if not inits:
                add(
                    "TPA302",
                    cap.kernel_line,
                    f"{sym}:init{r}",
                    f"ref slot {r} is accumulated (read-modify-write) but no "
                    "initializing write is guarded by a first-grid-step "
                    "`@pl.when` (or unconditional) — carries garbage from "
                    "the previous grid iteration",
                )
        # TPA303: exp() of mask-selected scores without a guard clamp.
        for k, info in sorted(facts.masked_exps.items()):
            if not info["guarded"]:
                add(
                    "TPA303",
                    info["line"],
                    f"{sym}:exp@{k}",
                    "exp() of masked scores flows to output unguarded — "
                    "clamp with a `_MASK_GUARD` select (jnp.where(s > "
                    "_MASK_GUARD, exp(...), 0)) so -1e30 lanes cannot "
                    "produce spurious non-zero weight",
                )
        # TPA305: interpret-divergent primitives in the body.
        seen_prims = set()
        for prim, line in facts.divergent:
            if prim in seen_prims:
                continue
            seen_prims.add(prim)
            add(
                "TPA305",
                line,
                f"{sym}:{prim}",
                f"primitive `{prim}` behaves differently under "
                "`interpret=True` (CPU CI) than compiled Mosaic — parity "
                "tests cannot vouch for the TPU build",
            )
    # TPA304: last-two-dims block misaligned with the dtype's native tile.
    for v in _spec_views(cap):
        if len(v.block_shape) < 2:
            continue
        sub = _SUBLANE_BY_ITEMSIZE.get(v.dtype.itemsize, 8)
        b2, b1 = v.block_shape[-2], v.block_shape[-1]
        a2, a1 = v.array_shape[-2], v.array_shape[-1]
        bad2 = (b2 % sub != 0) and (b2 != a2)
        bad1 = (b1 % _LANE != 0) and (b1 != a1)
        if bad2 or bad1:
            add(
                "TPA304",
                cap.kernel_line,
                f"{sym}:{v.role}{v.index}",
                f"{v.role}_specs[{v.index}] block {v.block_shape} misaligned "
                f"with native ({sub},{_LANE}) tile for {v.dtype.name} "
                f"(array {v.array_shape}) — forces a Mosaic relayout",
            )
    return findings


def _check_out_race(
    cap: _Capture, conf: _Conformance, facts: _BodyFacts | None, roles
) -> list[str]:
    """Out-spec revisited across grid steps needs arbitrary semantics and
    guarded/accumulated writes."""
    violations = []
    if not conf.revisited_out:
        return violations
    for out_idx, axes in conf.revisited_out.items():
        for a in axes:
            sem = None
            if cap.dimension_semantics and a < len(cap.dimension_semantics):
                sem = str(cap.dimension_semantics[a])
            if sem is not None and "arbitrary" not in sem:
                violations.append(
                    f"out_specs[{out_idx}]: revisited across grid axis {a} "
                    f"(extent {cap.grid[a]}) but dimension_semantics[{a}] is "
                    f"{sem!r} — write race under parallel execution"
                )
        if facts is not None:
            out_slots = [s for s, role in roles.items() if role == "out"]
            out_slots.sort()
            if out_idx < len(out_slots):
                slot = out_slots[out_idx]
                writes = [w for w in facts.writes if w.ref == slot]
                unguarded = [
                    w for w in writes if not w.rmw and not w.pid_guard
                ]
                if writes and unguarded:
                    violations.append(
                        f"out_specs[{out_idx}]: revisited block is written "
                        "unconditionally (no first/last-step `@pl.when` "
                        "guard, not an accumulation) — earlier grid steps' "
                        "results are overwritten"
                    )
    return violations


# ---------------------------------------------------------------------------
# Eqn discovery + matching
# ---------------------------------------------------------------------------


def _iter_pallas_eqns(jaxpr, depth: int = 0):
    from .costs import _sub_jaxprs

    if depth > 24:
        return
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            yield eqn
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                yield from _iter_pallas_eqns(getattr(sub, "jaxpr", sub), depth + 1)


def _eqn_kernel_name(eqn) -> str:
    nsi = eqn.params.get("name_and_src_info")
    name = getattr(nsi, "name", None) or str(nsi or "")
    return name.split(" at ")[0].strip()


def _eqn_key(eqn):
    gm = eqn.params.get("grid_mapping")
    grid = tuple(getattr(gm, "grid", ()) or ())
    return (_eqn_kernel_name(eqn), grid)


def _match_sites(caps: list[_Capture], eqns: list):
    """Dedupe captures, pair each with an unclaimed eqn of the same key."""
    deduped: dict = {}
    for cap in caps:
        key = cap.site_key()
        if key in deduped:
            deduped[key].calls += 1
        else:
            deduped[key] = cap
    pool: dict = {}
    for eqn in eqns:
        pool.setdefault(_eqn_key(eqn), []).append(eqn)
    pairs = []
    for cap in deduped.values():
        key = (cap.kernel_name, cap.grid)
        bucket = pool.get(key)
        pairs.append((cap, bucket.pop(0) if bucket else None))
    return pairs


# ---------------------------------------------------------------------------
# AST discovery (TPA300)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _AstSite:
    path: str
    display: str
    line: int
    end_line: int
    symbol: str


def _ast_pallas_sites(py_path: str, display: str) -> list[_AstSite]:
    try:
        with open(py_path, "r", encoding="utf-8") as fh:
            src = fh.read()
        tree = ast.parse(src)
    except (OSError, SyntaxError):
        return []
    sites = []
    func_stack: list[tuple[str, int, int]] = []

    def visit(node, enclosing):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            enclosing = node.name
        if isinstance(node, ast.Call):
            fn = node.func
            name = None
            if isinstance(fn, ast.Attribute):
                name = fn.attr
            elif isinstance(fn, ast.Name):
                name = fn.id
            if name == "pallas_call":
                sites.append(
                    _AstSite(
                        path=py_path,
                        display=display,
                        line=node.lineno,
                        end_line=getattr(node, "end_lineno", node.lineno),
                        symbol=enclosing or "<module>",
                    )
                )
        for child in ast.iter_child_nodes(node):
            visit(child, enclosing)

    visit(tree, None)
    return sites


def _default_ast_targets() -> list[tuple[str, str]]:
    root = _package_root()
    out = []
    for sub in ("kernels", "ops"):
        d = os.path.join(root, sub)
        if not os.path.isdir(d):
            continue
        for fname in sorted(os.listdir(d)):
            if fname.endswith(".py"):
                p = os.path.join(d, fname)
                out.append((p, _display_path(p)))
    return out


# ---------------------------------------------------------------------------
# Canned entries (the package's shipped kernels, smallest honest shapes)
# ---------------------------------------------------------------------------


def _canned_entries() -> dict[str, Callable[[], tuple[Callable, tuple]]]:
    """name -> zero-arg factory returning ``(fn, args)`` to trace.

    Shapes are the smallest that exercise multi-step grids in every axis
    (so index maps and revisit/guard discipline are actually checked) and
    respect the dtype's native sublane tiling (block 8 for fp32, 16 for
    bf16) so the shipped package stays at zero TPA304 findings.
    """
    import jax
    import jax.numpy as jnp

    from transformer_tpu.analysis.configs import FAST_MATRIX
    from transformer_tpu.kernels.flash_attention import (
        _FlashConfig,
        flash_attention,
        flash_ring_step,
    )
    from transformer_tpu.kernels.paged_flash import paged_flash_attention
    from transformer_tpu.ops.ffn import fused_ln_ffn

    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    bf16 = lambda *s: jax.ShapeDtypeStruct(s, jnp.bfloat16)  # noqa: E731
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731

    def flash_fwd_causal():
        fn = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, block_q=8, block_k=8, interpret=True
        )
        return fn, (f32(1, 16, 2, 8), f32(1, 16, 2, 8), f32(1, 16, 2, 8))

    def flash_fwd_mask_bf16():
        fn = lambda q, k, v, m: flash_attention(  # noqa: E731
            q, k, v, kv_mask=m, block_q=16, block_k=16, interpret=True
        )
        return fn, (
            bf16(1, 32, 2, 8),
            bf16(1, 32, 2, 8),
            bf16(1, 32, 2, 8),
            jax.ShapeDtypeStruct((1, 32), jnp.bool_),
        )

    def flash_grad_causal():
        def loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=True, block_q=8, block_k=8, interpret=True
                ).astype(jnp.float32)
            )

        fn = jax.grad(loss, argnums=(0, 1, 2))
        return fn, (f32(1, 16, 2, 8), f32(1, 16, 2, 8), f32(1, 16, 2, 8))

    def flash_grad_gqa():
        def loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=True, block_q=8, block_k=8, interpret=True
                ).astype(jnp.float32)
            )

        fn = jax.grad(loss, argnums=(0, 1, 2))
        return fn, (f32(1, 16, 4, 8), f32(1, 16, 2, 8), f32(1, 16, 2, 8))

    def flash_ring():
        cfg = _FlashConfig(
            causal=False,
            has_mask=False,
            block_q=8,
            block_k=8,
            num_heads=2,
            scale=8**-0.5,
            interpret=True,
        )
        fn = lambda q, k, v, m, l, acc: flash_ring_step(  # noqa: E731
            cfg, q, k, v, None, m, l, acc
        )
        return fn, (
            f32(2, 8, 8),
            f32(2, 8, 8),
            f32(2, 8, 8),
            f32(2, 1, 8, 1),
            f32(2, 1, 8, 1),
            f32(2, 8, 8),
        )

    def _paged_table():
        # Concrete block table/lengths (closure constants): ops on them
        # stay concrete through tracing, so the capture records real block
        # ids and the index-map enumeration runs over genuine table rows —
        # including the last pool block and repeated sink-0 entries.
        table = np.array([[0, 1, 8, 0], [2, 0, 3, 4]], dtype=np.int32)
        lengths = np.array([18, 27], dtype=np.int32)
        return jnp.asarray(table), jnp.asarray(lengths)

    def paged_bf16():
        table, lengths = _paged_table()
        fn = lambda q, kp, vp: paged_flash_attention(  # noqa: E731
            q, kp, vp, table, lengths, interpret=True
        )
        return fn, (bf16(2, 1, 2, 8), bf16(9, 8, 2, 8), bf16(9, 8, 2, 8))

    def paged_int8():
        table, lengths = _paged_table()
        fn = lambda q, kp, vp, ks, vs: paged_flash_attention(  # noqa: E731
            q, kp, vp, table, lengths, k_scale=ks, v_scale=vs, interpret=True
        )
        return fn, (
            bf16(2, 1, 2, 8),
            jax.ShapeDtypeStruct((9, 8, 2, 8), jnp.int8),
            jax.ShapeDtypeStruct((9, 8, 2, 8), jnp.int8),
            f32(9, 8, 2, 1),
            f32(9, 8, 2, 1),
        )

    def paged_gqa_verify():
        table, lengths = _paged_table()
        fn = lambda q, kp, vp: paged_flash_attention(  # noqa: E731
            q, kp, vp, table, lengths, interpret=True
        )
        return fn, (bf16(2, 3, 4, 8), bf16(9, 8, 2, 8), bf16(9, 8, 2, 8))

    def _ffn_params(d, dff, dtype, gated):
        ffn = {
            "in": {"kernel": jax.ShapeDtypeStruct((d, dff), dtype),
                   "bias": jax.ShapeDtypeStruct((dff,), dtype)},
            "out": {"kernel": jax.ShapeDtypeStruct((dff, d), dtype),
                    "bias": jax.ShapeDtypeStruct((d,), dtype)},
        }
        if gated:
            ffn["gate"] = {"kernel": jax.ShapeDtypeStruct((d, dff), dtype),
                           "bias": jax.ShapeDtypeStruct((dff,), dtype)}
        ln = {"scale": jax.ShapeDtypeStruct((d,), dtype),
              "bias": jax.ShapeDtypeStruct((d,), dtype)}
        return ln, ffn

    def ffn_relu():
        ln, ffn = _ffn_params(8, 256, jnp.bfloat16, gated=False)
        fn = lambda lp, fp, x: fused_ln_ffn(  # noqa: E731
            lp, fp, x, activation="relu", block_dff=128, interpret=True
        )
        return fn, (ln, ffn, bf16(2, 8))

    def ffn_swiglu():
        ln, ffn = _ffn_params(8, 256, jnp.bfloat16, gated=True)
        fn = lambda lp, fp, x: fused_ln_ffn(  # noqa: E731
            lp, fp, x, activation="swiglu", block_dff=128, interpret=True
        )
        return fn, (ln, ffn, bf16(2, 8))

    def _serve_entry(variant):
        # Mirror costs.canned_cost_reports()'s fused paged serve program
        # exactly — the kernels verified here are the ones costs prices.
        from transformer_tpu.analysis.costs import (
            _PAGED_BLOCK,
            _PAGED_POOL_BLOCKS,
            _SERVE_SLOTS,
            _SERVE_TOTAL,
            _abstract_model,
        )
        from transformer_tpu.serve import scheduler as sched
        from transformer_tpu.serve.scheduler import abstract_paged_pool

        cfg = FAST_MATRIX[variant]
        params = _abstract_model(cfg)
        pool, table, index = abstract_paged_pool(
            cfg, _SERVE_SLOTS, _SERVE_TOTAL, _PAGED_POOL_BLOCKS, _PAGED_BLOCK
        )
        flash_raw = sched._pool_step_paged_flash.__wrapped__
        fn = lambda p, c, tb, ix, t: flash_raw(  # noqa: E731
            p, c, tb, ix, t, cfg, _PAGED_BLOCK, False
        )
        return fn, (params, pool, table, index, i32(_SERVE_SLOTS))

    entries = {
        "flash.fwd[causal,fp32]": flash_fwd_causal,
        "flash.fwd[mask,bf16]": flash_fwd_mask_bf16,
        "flash.grad[causal,fp32]": flash_grad_causal,
        "flash.grad[gqa,fp32]": flash_grad_gqa,
        "flash.ring_step[fp32]": flash_ring,
        "paged_flash[bf16]": paged_bf16,
        "paged_flash[int8]": paged_int8,
        "paged_flash[gqa,verify]": paged_gqa_verify,
        "ffn.fused[relu,bf16]": ffn_relu,
        "ffn.fused[swiglu,bf16]": ffn_swiglu,
    }
    for variant in ("lm_bf16", "lm_int8_cache", "lm_gqa"):
        entries[f"serve.pool_step_paged_flash[{variant}]"] = functools.partial(
            _serve_entry, variant
        )
    return entries


# ---------------------------------------------------------------------------
# Reports + analysis driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class KernelReport:
    """One verified pallas_call site."""

    name: str  # "<entry>/<kernel fn>"
    entry: str
    kernel: str
    src: str  # "path:line" of the kernel fn
    grid: tuple[int, ...]
    grid_size: int
    calls: int
    predicted_vmem_bytes: int
    vmem_breakdown: dict[str, int]
    budget_bytes: int
    fits_budget: bool
    flops_per_call: int
    checked_points: int
    sampled: bool
    padding: list[str]
    notes: list[str]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["grid"] = list(self.grid)
        return d


@dataclasses.dataclass
class KernelsResult:
    generation: str
    reports: list[KernelReport]
    findings: list[Finding]  # unbaselined, unsuppressed lints
    baselined: int
    violations: list[str]  # conformance/race/budget — never baselineable
    regressions: list[str]  # vmem growth / coverage loss vs baseline
    notes: list[str]
    files_checked: int
    ast_sites: int

    @property
    def ok(self) -> bool:
        return not (self.findings or self.violations or self.regressions)

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "budget_bytes": VMEM_BUDGETS[self.generation],
            "kernels": [r.to_dict() for r in self.reports],
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "baselined": self.baselined,
            "violations": list(self.violations),
            "regressions": list(self.regressions),
            "notes": list(self.notes),
            "files_checked": self.files_checked,
            "ast_sites": self.ast_sites,
            "ok": self.ok,
        }


def _trace_entry(name: str, factory) -> tuple[list[_Capture], Any]:
    import jax

    records: list[_Capture] = []
    fn, args = factory()
    with _capture_pallas(records):
        closed = jax.make_jaxpr(fn)(*args)
    return records, closed


def _module_lines(path: str) -> list[str]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return fh.read().splitlines()
    except OSError:
        return []


def analyze_entries(
    entries: dict[str, Callable],
    generation: str | None = None,
    ast_targets: list[tuple[str, str]] | None = None,
) -> KernelsResult:
    """Trace every entry under capture, verify each pallas_call site, and
    cross-check coverage against AST-discovered sites."""
    generation = generation or DEFAULT_GENERATION
    budget = VMEM_BUDGETS[generation]
    reports: list[KernelReport] = []
    findings: list[Finding] = []
    violations: list[str] = []
    notes: list[str] = []
    covered: list[tuple[str, int]] = []  # (abs call path, call line)
    src_cache: dict[str, list[str]] = {}

    for ename, factory in entries.items():
        try:
            caps, closed = _trace_entry(ename, factory)
        except Exception as e:  # noqa: BLE001  # tpa: disable=TPA006
            violations.append(f"{ename}: entry failed to trace: {e!r}")
            continue
        if not caps:
            notes.append(f"{ename}: no pallas_call captured")
            continue
        eqns = list(_iter_pallas_eqns(closed.jaxpr))
        for cap, eqn in _match_sites(caps, eqns):
            covered.append((os.path.abspath(cap.call_path), cap.call_line))
            views = _spec_views(cap)
            conf = _check_conformance(cap, views)
            _check_aliases(cap, views, conf)
            facts = roles = dtypes = None
            flops = 0
            if eqn is not None:
                gm = eqn.params.get("grid_mapping")
                body = eqn.params.get("jaxpr")
                if body is not None and gm is not None:
                    facts, roles, dtypes = _body_facts(body, gm)
                from .costs import pallas_call_flops

                flops = pallas_call_flops(eqn)
            else:
                notes.append(
                    f"{ename}/{cap.kernel_name}: no matching pallas_call eqn "
                    "(body lints and FLOPs skipped)"
                )
            vmem, breakdown = _vmem_footprint(cap, views)
            race = _check_out_race(cap, conf, facts, roles or {})
            site = f"{ename}/{cap.kernel_name}"
            for msg in conf.violations + race:
                violations.append(f"{site}: {msg}")
            if vmem > budget:
                violations.append(
                    f"{site}: predicted_vmem_bytes {vmem} exceeds {generation} "
                    f"budget {budget}"
                )
            lints = _lint_site(cap, facts, roles or {}, dtypes or {})
            kpath = os.path.abspath(cap.kernel_file)
            if kpath not in src_cache:
                src_cache[kpath] = _module_lines(kpath)
            for f in lints:
                if not line_suppressed(src_cache[kpath], f):
                    findings.append(f)
            reports.append(
                KernelReport(
                    name=site,
                    entry=ename,
                    kernel=cap.kernel_name,
                    src=f"{_display_path(cap.kernel_file)}:{cap.kernel_line}",
                    grid=cap.grid,
                    grid_size=int(np.prod(cap.grid)) if cap.grid else 1,
                    calls=cap.calls,
                    predicted_vmem_bytes=vmem,
                    vmem_breakdown=breakdown,
                    budget_bytes=budget,
                    fits_budget=vmem <= budget,
                    flops_per_call=flops,
                    checked_points=conf.checked_points,
                    sampled=conf.sampled,
                    padding=conf.padding,
                    notes=[],
                )
            )

    # TPA300: AST sites with no captured call covering them.
    ast_targets = ast_targets if ast_targets is not None else _default_ast_targets()
    ast_sites: list[_AstSite] = []
    for p, display in ast_targets:
        ast_sites.extend(_ast_pallas_sites(p, display))
    for site in ast_sites:
        hit = any(
            os.path.abspath(site.path) == cp and site.line <= cl <= site.end_line
            for cp, cl in covered
        )
        if not hit:
            f = Finding(
                code="TPA300",
                path=site.display,
                line=site.line,
                symbol=site.symbol,
                message=(
                    f"pallas_call in `{site.symbol}` is not exercised by any "
                    "canned verifier entry — grid/BlockSpec conformance, VMEM "
                    "footprint and safety lints are all blind to it; add an "
                    "entry (see docs/ANALYSIS.md)"
                ),
                snippet=f"{site.symbol}:pallas_call",
            )
            if not line_suppressed(
                src_cache.setdefault(site.path, _module_lines(site.path)), f
            ):
                findings.append(f)

    return KernelsResult(
        generation=generation,
        reports=sorted(reports, key=lambda r: r.name),
        findings=findings,
        baselined=0,
        violations=violations,
        regressions=[],
        notes=notes,
        files_checked=len(ast_targets),
        ast_sites=len(ast_sites),
    )


# ---------------------------------------------------------------------------
# Baseline workflow (costs-style fail-on-growth)
# ---------------------------------------------------------------------------


def default_kernels_baseline_path() -> str:
    return os.path.join(_package_root(), "analysis", "kernels_baseline.json")


def load_kernels_baseline(path: str) -> dict:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return {"findings": {}, "kernels": {}}
    grand = {
        f["fingerprint"]: f.get("reason", "baselined")
        for f in data.get("findings", [])
    }
    return {"findings": grand, "kernels": data.get("kernels", {})}


def write_kernels_baseline(result: KernelsResult, path: str) -> None:
    payload = {
        "findings": [
            {
                "fingerprint": f.fingerprint,
                "reason": "grandfathered by --update-baseline",
                "line": f.line,
            }
            for f in sorted(result.findings, key=lambda f: f.fingerprint)
        ],
        "kernels": {
            r.name: {
                "predicted_vmem_bytes": r.predicted_vmem_bytes,
                "flops_per_call": r.flops_per_call,
                "grid_size": r.grid_size,
            }
            for r in result.reports
        },
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def compare_kernels_to_baseline(result: KernelsResult, path: str) -> KernelsResult:
    """Split findings into baselined/new and gate VMEM against the bank.

    Growth in any kernel's ``predicted_vmem_bytes`` is a regression (run
    ``--update-baseline`` to accept deliberate changes); a banked kernel
    disappearing from the report is lost coverage and also fails.
    FLOPs drift is advisory (a note): it usually means shapes changed.
    """
    bank = load_kernels_baseline(path)
    keep: list[Finding] = []
    baselined = 0
    for f in result.findings:
        if f.fingerprint in bank["findings"]:
            baselined += 1
        else:
            keep.append(f)
    result.findings = keep
    result.baselined = baselined
    banked = bank["kernels"]
    if not banked:
        result.notes.append(f"no kernel baseline at {path} (run --update-baseline)")
        return result
    current = {r.name: r for r in result.reports}
    for name, r in current.items():
        b = banked.get(name)
        if b is None:
            result.regressions.append(
                f"{name}: not in baseline (new kernel or renamed entry — "
                "run --update-baseline to bank it)"
            )
            continue
        if r.predicted_vmem_bytes > int(b.get("predicted_vmem_bytes", 0)):
            result.regressions.append(
                f"{name}: predicted_vmem_bytes grew "
                f"{int(b['predicted_vmem_bytes'])} -> {r.predicted_vmem_bytes}"
            )
        elif r.predicted_vmem_bytes < int(b.get("predicted_vmem_bytes", 0)):
            result.notes.append(
                f"{name}: predicted_vmem_bytes improved "
                f"{int(b['predicted_vmem_bytes'])} -> {r.predicted_vmem_bytes} "
                "(run --update-baseline to bank the win)"
            )
        if r.flops_per_call != int(b.get("flops_per_call", r.flops_per_call)):
            result.notes.append(
                f"{name}: flops_per_call drifted "
                f"{int(b['flops_per_call'])} -> {r.flops_per_call}"
            )
    for name in banked:
        if name not in current:
            result.regressions.append(
                f"{name}: banked kernel missing from report (coverage lost)"
            )
    return result


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def _load_path_entries(paths: Sequence[str]) -> tuple[dict, list[tuple[str, str]]]:
    """User-supplied modules: each may export ``ANALYSIS_KERNEL_ENTRIES``
    (name -> zero-arg factory); all are AST-scanned."""
    import importlib.util

    entries: dict[str, Callable] = {}
    targets: list[tuple[str, str]] = []
    for i, p in enumerate(paths):
        absp = os.path.abspath(p)
        display = os.path.basename(absp)
        targets.append((absp, display))
        spec = importlib.util.spec_from_file_location(f"_tpa_kernel_mod{i}", absp)
        if spec is None or spec.loader is None:
            continue
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
        except Exception:  # noqa: BLE001 — AST scan still applies  # tpa: disable=TPA006
            continue
        for name, factory in (getattr(mod, "ANALYSIS_KERNEL_ENTRIES", {}) or {}).items():
            entries[f"{display}:{name}"] = factory
    return entries, targets


def run_kernels(
    paths: Sequence[str] | None = None,
    baseline_path: str | None = None,
    compare: bool = True,
    generation: str | None = None,
) -> KernelsResult:
    """Package mode (no paths): canned entries + kernels//ops AST scan +
    the checked-in baseline. Paths mode: the given modules' declared
    ``ANALYSIS_KERNEL_ENTRIES`` with those files as the AST universe."""
    if paths:
        entries, targets = _load_path_entries(paths)
        result = analyze_entries(entries, generation, ast_targets=targets)
    else:
        result = analyze_entries(_canned_entries(), generation)
        if baseline_path is None:
            baseline_path = default_kernels_baseline_path()
    if compare and baseline_path is not None:
        result = compare_kernels_to_baseline(result, baseline_path)
    return result


def program_kernel_vmem(fn: Callable, *args, generation: str | None = None) -> dict:
    """Per-kernel predicted VMEM for one traceable program (decode_bench
    hook): {kernel name -> predicted_vmem_bytes}, no lints, no baseline."""
    import jax

    records: list[_Capture] = []
    with _capture_pallas(records):
        jax.make_jaxpr(fn)(*args)
    out: dict[str, int] = {}
    deduped: dict = {}
    for cap in records:
        deduped.setdefault(cap.site_key(), cap)
    for cap in deduped.values():
        views = _spec_views(cap)
        _check_conformance(cap, views)  # fills grid_varying
        vmem, _ = _vmem_footprint(cap, views)
        key = cap.kernel_name
        if key in out:
            out[key] = max(out[key], vmem)
        else:
            out[key] = vmem
    return out


def summarize_kernels(result: KernelsResult) -> str:
    from .costs import _fmt_bytes

    lines = [
        f"kernels: {len(result.reports)} site(s) verified "
        f"[{result.generation}, budget {_fmt_bytes(VMEM_BUDGETS[result.generation])}], "
        f"{result.ast_sites} AST site(s) in {result.files_checked} file(s)"
    ]
    for r in result.reports:
        mark = "ok" if r.fits_budget else "OVER"
        extra = " (sampled)" if r.sampled else ""
        lines.append(
            f"  {r.name}: grid {r.grid} x{r.calls} call(s), "
            f"vmem {_fmt_bytes(r.predicted_vmem_bytes)} [{mark}], "
            f"{r.checked_points} index points{extra}"
        )
    for v in result.violations:
        lines.append(f"  VIOLATION: {v}")
    for g in result.regressions:
        lines.append(f"  REGRESSION: {g}")
    for f in result.findings:
        lines.append(f"  {f.code} {f.path}:{f.line} {f.symbol}: {f.message}")
    if result.baselined:
        lines.append(f"  ({result.baselined} baselined finding(s) suppressed)")
    for n in result.notes:
        lines.append(f"  note: {n}")
    lines.append("kernels: OK" if result.ok else "kernels: FAIL")
    return "\n".join(lines)
