"""Sharding static analysis: collective inventory + TPA201–205 lints.

Mesh-TensorFlow's framing (PAPERS.md) is that a sharded program IS its
per-axis layouts plus the collectives those layouts force — and that both
are checkable at compile time. This module gives the repo that check, on
CPU, with zero device execution:

**Collective inventory** — walk a traced jaxpr (``jax.make_jaxpr``) for the
explicit collective equations ``shard_map`` bodies carry (``psum`` /
``all_gather`` / ``all_to_all`` / ``ppermute`` / ``pmin`` / ``pmax`` /
``reduce_scatter``), attribute each to its mesh axis, weight static counts
by enclosing ``scan`` trip counts (a ring's per-hop permute counts P-1
times, not once), and estimate per-step communication bytes from operand
sizes and the axis size (ring-algorithm factors: an all-reduce moves
``2·(n-1)/n`` of the buffer, a gather ``(n-1)/n`` of its output, a permute
one full shard per hop). GSPMD-inserted collectives (plain ``pjit`` with
``NamedSharding``) are invisible at jaxpr level by construction — the
inventory covers the manual (``shard_map``) programs, which is where this
repo's seq/pipe/expert traffic lives, and the *absence* of collectives in
single-device serving programs, which is what the decode-hot-loop budget
pins (``analysis/costs_baseline.json``).

**Sharding lints (TPA201–205)** — AST rules over the package with the same
fingerprint / ``# tpa: disable`` / baseline workflow as TPA001–007
(``analysis/baselines.py``; separate ``analysis/sharding_baseline.json``,
shipped empty):

- **TPA201** — a jit/pjit call passing ``in_shardings`` without
  ``out_shardings``: the program's boundary activations are left to GSPMD
  propagation, so the layout handed to the NEXT program (or donated back
  into the same buffer) can silently change per compile.
- **TPA202** — a mesh-axis name (in a ``PartitionSpec``/``P`` literal or an
  ``axis_name=`` argument) that is not in the declared mesh vocabulary
  collected from the analyzed files (``Mesh(..., names)``, ``axis_names``
  declarations). A typo'd axis silently means "replicated" in a spec — the
  array is simply not sharded, and nothing fails until HBM fills.
- **TPA203** — a donated argument whose literal ``in_shardings`` and
  ``out_shardings`` entries disagree: XLA cannot alias a buffer across a
  layout change, so the donation silently degrades to a copy (plus a
  resharding collective).
- **TPA204** — a collective call inside a serving-hot-loop jitted function
  (modules under ``serve/`` or the ``_pool_*``/``_slot_*``/``_pick_*``
  naming idiom): the decode loop is one-token latency-bound work; a
  collective there serializes every step on the slowest chip. The runtime
  complement is the empty per-program collective set pinned in
  ``costs_baseline.json``.
- **TPA205** — a partition-rule entry that fully replicates a
  large-parameter path (``embedding``/``table``/``kernel`` patterns mapped
  to an axis-free spec): every chip then holds the whole matrix — the
  "accidental full replication" memory cliff. Deliberately replicated
  small tensors (biases, norms, routers) are out of scope or suppressed
  inline where the decision lives.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Callable, Iterable

from transformer_tpu.analysis.baselines import (
    Finding,
    RulesReport,
    _iter_py_files,
    _package_root,
    line_suppressed,
    load_baseline,
)
from transformer_tpu.analysis.rules import (
    _JIT_NAMES,
    _decorator_jit_spec,
    _dotted,
    _literal_ints,
)

SHARDING_RULES: dict[str, str] = {
    "TPA201": "in_shardings without out_shardings leaves boundary "
              "activations unconstrained",
    "TPA202": "mesh-axis name not in the declared mesh vocabulary",
    "TPA203": "donated argument's in/out shardings disagree (donation "
              "degrades to a copy)",
    "TPA204": "collective op inside a serving-hot-loop jitted function",
    "TPA205": "partition rule fully replicates a large parameter",
}

# Collective jaxpr primitives (and the user-facing call names TPA204 scans
# for). pmean lowers to psum+div; axis_index is not a transfer.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pgather",
})
_COLLECTIVE_CALLS = COLLECTIVE_PRIMITIVES | frozenset({"pmean", "pshuffle"})

# Spec constructors whose string arguments are mesh-axis uses.
_SPEC_CTORS = frozenset({"P", "PartitionSpec"})


# ==========================================================================
# collective inventory (jaxpr side)


def _sub_jaxprs(value: Any) -> Iterable[Any]:
    """Yield raw Jaxprs nested in an eqn param value (ClosedJaxpr, Jaxpr,
    or lists/tuples of either)."""
    import jax

    if isinstance(value, jax.core.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, jax.core.Jaxpr):
        yield value
    elif isinstance(value, (list, tuple)):
        for item in value:
            yield from _sub_jaxprs(item)


def walk_eqns_weighted(jaxpr, weight: int = 1):
    """Yield ``(eqn, weight)`` over every equation, recursing through
    pjit/shard_map/scan/while/cond sub-jaxprs. ``scan`` multiplies the
    weight by its trip count (a collective inside a ring scan runs per
    hop); ``while`` trip counts are unknowable statically and keep weight
    ×1 (documented undercount — budgets pin the *set*, counts are advisory
    there)."""
    for eqn in jaxpr.eqns:
        yield eqn, weight
        mult = weight
        if eqn.primitive.name == "scan":
            mult = weight * int(eqn.params.get("length", 1))
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                yield from walk_eqns_weighted(sub, mult)


def _aval_bytes(aval) -> int:
    import numpy as np

    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # Extended dtypes (PRNG key arrays) aren't numpy dtypes but do
        # carry their own itemsize (key<fry> = 2 x uint32 = 8 bytes).
        itemsize = int(getattr(dtype, "itemsize", 4))
    return n * itemsize


def _eqn_axes(eqn) -> tuple[str, ...]:
    """The named mesh axes a collective equation runs over."""
    for key in ("axis_name", "axes"):
        v = eqn.params.get(key)
        if v is None:
            continue
        if isinstance(v, str):
            return (v,)
        return tuple(str(a) for a in v if isinstance(a, (str,)))
    return ()


def _comm_bytes(kind: str, in_bytes: int, out_bytes: int, n: int) -> int:
    """Ring-algorithm per-step byte estimate for one call of a collective
    over an axis of size ``n``. n=1 (or unknown axes) transfers nothing."""
    if n <= 1:
        return 0
    if kind == "all_gather":
        return out_bytes * (n - 1) // n
    if kind in ("psum", "pmax", "pmin", "pbroadcast"):
        return 2 * in_bytes * (n - 1) // n
    if kind in ("reduce_scatter", "psum_scatter", "all_to_all", "pgather"):
        return in_bytes * (n - 1) // n
    if kind == "ppermute":
        return in_bytes
    return in_bytes


def collective_inventory(
    closed_jaxpr, axis_sizes: dict[str, int] | None = None
) -> dict[str, dict[str, int]]:
    """Aggregate the collective equations of a traced program.

    Returns ``{"kind[axis,...]": {"count": N, "bytes": B}}`` where ``count``
    is the scan-weighted static occurrence count and ``bytes`` the estimated
    per-step communication volume (see :func:`_comm_bytes`)."""
    axis_sizes = axis_sizes or {}
    out: dict[str, dict[str, int]] = {}
    for eqn, weight in walk_eqns_weighted(closed_jaxpr.jaxpr):
        kind = eqn.primitive.name
        if kind not in COLLECTIVE_PRIMITIVES:
            continue
        axes = _eqn_axes(eqn)
        n = 1
        for a in axes:
            n *= int(axis_sizes.get(a, 1))
        in_bytes = sum(
            _aval_bytes(v.aval) for v in eqn.invars if hasattr(v, "aval")
        )
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        key = f"{kind}[{','.join(axes) or '?'}]"
        slot = out.setdefault(key, {"count": 0, "bytes": 0})
        slot["count"] += weight
        slot["bytes"] += weight * _comm_bytes(kind, in_bytes, out_bytes, n)
    return out


# ==========================================================================
# canned sharded programs (the collective sets costs_baseline.json pins)


def _mesh_1d(axis: str, size: int):
    import numpy as np

    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if len(devices) < size:
        return None
    return Mesh(np.asarray(devices[:size]).reshape(size), (axis,))


def canned_sharded_programs() -> tuple[dict[str, tuple], list[str]]:
    """name -> (traceable_fn, abstract_args, axis_sizes), plus the list of
    programs skipped on this host. Mesh shapes are FIXED (seq=2, model=2,
    fsdp=2) so the traced shapes — and therefore the baselined numbers —
    are identical on every host with >= 2 devices (tests force 8 virtual
    CPU devices via conftest; the CLI forces the same before importing
    jax)."""
    import functools

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from transformer_tpu.parallel.compat import shard_map
    from transformer_tpu.parallel.ring_attention import (
        ring_attention,
        ulysses_attention,
    )

    programs: dict[str, tuple] = {}
    skipped: list[str] = []
    B, S, H, D = 1, 16, 2, 8
    act = jax.ShapeDtypeStruct((B, S, H, D), np.float32)

    # -- sequence parallelism: the repo's real per-shard attention bodies --
    mesh = _mesh_1d("seq", 2)
    if mesh is None:
        skipped.extend(
            ["parallel.ring_attention[seq=2]", "parallel.ulysses_attention[seq=2]"]
        )
    else:
        spec = P(None, "seq", None, None)
        for name, impl in (
            ("parallel.ring_attention[seq=2]", ring_attention),
            ("parallel.ulysses_attention[seq=2]", ulysses_attention),
        ):
            body = functools.partial(
                impl, axis_name="seq", axis_size=2, causal=True
            )
            fn = shard_map(
                lambda q, k, v, body=body: body(q, k, v),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False,
            )
            programs[name] = (fn, (act, act, act), {"seq": 2})

    # -- tensor parallelism: the parallel/sharding.py FFN layout (column-
    # then row-sharded matmul pair, one psum — the Mesh-TF claim made
    # checkable) --
    mesh = _mesh_1d("model", 2)
    M, F = 32, 64
    if mesh is None:
        skipped.append("parallel.tp_ffn[model=2]")
    else:
        def tp_ffn(h, w_in, w_out):
            mid = jax.nn.relu(h @ w_in)        # (B, F/model) per shard
            part = mid @ w_out                 # partial (B, M) per shard
            return jax.lax.psum(part, "model")

        fn = shard_map(
            tp_ffn, mesh=mesh,
            in_specs=(P(), P(None, "model"), P("model", None)),
            out_specs=P(),
            check_vma=False,
        )
        programs["parallel.tp_ffn[model=2]"] = (
            fn,
            (
                jax.ShapeDtypeStruct((4, M), np.float32),
                jax.ShapeDtypeStruct((M, F), np.float32),
                jax.ShapeDtypeStruct((F, M), np.float32),
            ),
            {"model": 2},
        )

    # -- fsdp: the ZeRO-3 per-layer gather (pipeline._gather_layer shape:
    # all_gather the shard, use it, drop it) --
    mesh = _mesh_1d("fsdp", 2)
    if mesh is None:
        skipped.append("parallel.fsdp_gather[fsdp=2]")
    else:
        def fsdp_layer(h, w_shard):
            w = jax.lax.all_gather(w_shard, "fsdp", axis=0, tiled=True)
            return h @ w

        fn = shard_map(
            fsdp_layer, mesh=mesh,
            in_specs=(P(), P("fsdp", None)),
            out_specs=P(),
            check_vma=False,
        )
        programs["parallel.fsdp_gather[fsdp=2]"] = (
            fn,
            (
                jax.ShapeDtypeStruct((4, M), np.float32),
                jax.ShapeDtypeStruct((M, M), np.float32),
            ),
            {"fsdp": 2},
        )

    # -- the SHARDED serving hot loop (serve/sharded.py, --mesh): the
    # programs a --mesh 2 replica jits as pjit twins. At trace level they
    # carry ZERO explicit collectives (params replicate; the pool shards on
    # a batch-like storage axis; cross-shard traffic is GSPMD data
    # movement) — banking them at mesh 2 makes ANY explicit collective that
    # sneaks into the decode/verify/prefill path a hard "stray collective"
    # failure against costs_baseline.json. GSPMD-INSERTED collectives are
    # invisible to a trace; serving_hlo_collectives() below gates those on
    # the compiled HLO.
    mesh = _mesh_1d("data", 2)
    _serve_names = [
        "serve.pool_step[lm_bf16,mesh=2]",
        "serve.pool_verify[lm_bf16,W=4,mesh=2]",
        "serve.slot_prefill[lm_bf16,n=8,mesh=2]",
    ]
    if mesh is None:
        skipped.extend(_serve_names)
    else:
        from transformer_tpu.analysis.configs import FAST_MATRIX
        from transformer_tpu.models.transformer import transformer_init
        from transformer_tpu.serve import scheduler as sched
        from transformer_tpu.serve.scheduler import abstract_pool_caches

        cfg = FAST_MATRIX["lm_bf16"]
        key = jax.ShapeDtypeStruct((2,), np.uint32)
        params = jax.eval_shape(lambda k: transformer_init(k, cfg), key)
        pool = abstract_pool_caches(cfg, 2, 32)
        i32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.int32)  # noqa: E731
        step_raw = sched._pool_step.__wrapped__
        verify_raw = sched._pool_verify.__wrapped__
        prefill_raw = sched._slot_prefill.__wrapped__
        programs[_serve_names[0]] = (
            lambda p, c, t: step_raw(p, c, t, cfg),
            (params, pool, i32(2)),
            {"data": 2},
        )
        programs[_serve_names[1]] = (
            lambda p, c, t: verify_raw(p, c, t, cfg),
            (params, pool, i32(2, 4)),
            {"data": 2},
        )
        programs[_serve_names[2]] = (
            lambda p, c, s, pr, st: prefill_raw(p, c, s, pr, st, cfg, 0),
            (params, pool, i32(), i32(1, 8), i32()),
            {"data": 2},
        )
    del jnp
    return programs, skipped


# ==========================================================================
# compiled-HLO collective gate for the sharded serving decode step

# HLO op spellings of the cross-device collectives (sync + async start
# forms share these prefixes).
_HLO_COLLECTIVE_RE = (
    r"\b(all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast)"
)


def serving_hlo_collectives() -> tuple[dict[str, dict[str, int]], list[str]]:
    """Compile the DENSE sharded decode-step twins at mesh 2 and inventory
    collectives in the compiled HLO — the layer a jaxpr trace cannot see
    (GSPMD inserts collectives at partitioning time, after tracing).

    The serving layout (serve/sharded.py) makes the dense decode step
    embarrassingly parallel: params fully replicated, pool KV + step
    tokens + logits all sharded on the slot axis — so its compiled HLO
    must contain ZERO collectives, and ``analysis costs`` fails hard on
    any. Prefill and the paged programs legitimately move data across
    shards (replicated prompt rows into a sharded slot, block-row gathers
    through the table) — that traffic is deterministic data movement, not
    a reduction, so it is not gated here.

    Returns ``(inventory, skipped)`` where inventory maps program name ->
    {hlo_op: count} (empty dict = clean)."""
    import re

    import jax
    import numpy as np

    from transformer_tpu.analysis.configs import FAST_MATRIX
    from transformer_tpu.models.transformer import transformer_init
    from transformer_tpu.serve.scheduler import abstract_pool_caches
    from transformer_tpu.serve.sharded import ShardedPrograms, serving_mesh

    names = [
        "serve.pool_step[lm_bf16,mesh=2]",
        "serve.pool_verify[lm_bf16,W=4,mesh=2]",
    ]
    if len(jax.devices()) < 2:
        return {}, names
    cfg = FAST_MATRIX["lm_bf16"]
    key = jax.ShapeDtypeStruct((2,), np.uint32)
    params = jax.eval_shape(lambda k: transformer_init(k, cfg), key)
    pool = abstract_pool_caches(cfg, 2, 32)
    sp = ShardedPrograms(serving_mesh(2), params)
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.int32)  # noqa: E731
    out: dict[str, dict[str, int]] = {}
    for name, fn, args in (
        (names[0], sp.pool_step, (params, pool, i32(2), cfg)),
        (names[1], sp.pool_verify, (params, pool, i32(2, 4), cfg)),
    ):
        text = fn.lower(*args).compile().as_text()
        found: dict[str, int] = {}
        for m in re.finditer(_HLO_COLLECTIVE_RE, text):
            found[m.group(1)] = found.get(m.group(1), 0) + 1
        out[name] = found
    return out, []


# ==========================================================================
# TPA201–205 (AST side)


class _ShardModule:
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)

    # -- shared helpers ----------------------------------------------------

    def finding(self, code: str, node: ast.AST, symbol: str, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        snippet = (
            self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        )
        return Finding(
            code=code, path=self.rel, line=line, symbol=symbol,
            message=message, snippet=snippet,
        )

    def suppressed(self, f: Finding) -> bool:
        return line_suppressed(self.lines, f)

    def _enclosing(self) -> dict[int, str]:
        out: dict[int, str] = {}

        def visit(node: ast.AST, symbol: str) -> None:
            for child in ast.iter_child_nodes(node):
                child_symbol = symbol
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    child_symbol = (
                        child.name
                        if symbol == "<module>"
                        else f"{symbol}.{child.name}"
                    )
                out[id(child)] = child_symbol
                visit(child, child_symbol)

        visit(self.tree, "<module>")
        return out

    # -- axis vocabulary ---------------------------------------------------

    def declared_axes(self) -> set[str]:
        """Mesh-axis names this module DECLARES: ``Mesh(..., (names))``
        literals, ``axis_names`` assignments, and tuples returned from
        ``axis_names`` functions/properties."""
        axes: set[str] = set()

        def strs(node: ast.AST | None) -> list[str]:
            if isinstance(node, (ast.Tuple, ast.List)):
                out = []
                for e in node.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.append(e.value)
                return out
            return []

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and _dotted(node.func) in (
                "Mesh", "jax.sharding.Mesh",
            ):
                if len(node.args) >= 2:
                    axes.update(strs(node.args[1]))
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes.update(strs(kw.value))
            elif isinstance(node, ast.Assign):
                names = []
                for t in node.targets:
                    d = _dotted(t)
                    if d:
                        names.append(d.rsplit(".", 1)[-1])
                if any("axis_names" in n for n in names):
                    axes.update(strs(node.value))
            elif isinstance(node, ast.FunctionDef) and "axis_names" in node.name:
                for inner in ast.walk(node):
                    if isinstance(inner, ast.Return):
                        axes.update(strs(inner.value))
        return axes

    def axis_uses(self) -> list[tuple[str, ast.AST, str]]:
        """(axis_name, node, symbol) for every literal mesh-axis reference:
        strings inside ``P(...)``/``PartitionSpec(...)`` (including tuple
        elements) and ``axis_name=``/collective-call axis arguments."""
        uses: list[tuple[str, ast.AST, str]] = []
        enclosing = self._enclosing()

        def spec_strs(node: ast.AST) -> list[str]:
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                return [node.value]
            if isinstance(node, (ast.Tuple, ast.List)):
                out: list[str] = []
                for e in node.elts:
                    out.extend(spec_strs(e))
                return out
            return []

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            if not fname:
                continue
            base = fname.rsplit(".", 1)[-1]
            symbol = enclosing.get(id(node), "<module>")
            if base in _SPEC_CTORS:
                for a in node.args:
                    for s in spec_strs(a):
                        uses.append((s, node, symbol))
            if base in _COLLECTIVE_CALLS:
                # jax.lax.psum(x, 'axis') / ppermute(x, 'axis', perm)
                if len(node.args) >= 2:
                    for s in spec_strs(node.args[1]):
                        uses.append((s, node, symbol))
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    for s in spec_strs(kw.value):
                        uses.append((s, node, symbol))
        return uses

    # -- rules -------------------------------------------------------------

    def _jit_calls(self) -> list[tuple[ast.Call, str]]:
        out = []
        enclosing = self._enclosing()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and _dotted(node.func) in _JIT_NAMES:
                out.append((node, enclosing.get(id(node), "<module>")))
        return out

    def rule_tpa201(self) -> list[Finding]:
        out = []
        for call, symbol in self._jit_calls():
            kwargs = {kw.arg for kw in call.keywords}
            if "in_shardings" in kwargs and "out_shardings" not in kwargs:
                out.append(
                    self.finding(
                        "TPA201", call, symbol,
                        "jit with in_shardings but no out_shardings — the "
                        "output layout is left to GSPMD propagation and can "
                        "change per compile; pin the boundary activations",
                    )
                )
        return out

    def rule_tpa202(self, universe: set[str]) -> list[Finding]:
        if not universe:
            return []  # nothing declared anywhere in the analyzed set
        out = []
        for axis, node, symbol in self.axis_uses():
            if axis not in universe:
                out.append(
                    self.finding(
                        "TPA202", node, symbol,
                        f"mesh axis {axis!r} is not in the declared mesh "
                        f"vocabulary {sorted(universe)} — a typo'd axis "
                        "silently means 'replicated'",
                    )
                )
        return out

    def rule_tpa203(self) -> list[Finding]:
        out = []
        for call, symbol in self._jit_calls():
            kws = {kw.arg: kw.value for kw in call.keywords}
            donate = _literal_ints(kws.get("donate_argnums"))
            ins, outs = kws.get("in_shardings"), kws.get("out_shardings")
            if not donate or ins is None or outs is None:
                continue
            if not isinstance(ins, (ast.Tuple, ast.List)) or not isinstance(
                outs, (ast.Tuple, ast.List)
            ):
                continue  # non-literal: not judgeable from the AST
            for i in donate:
                if 0 <= i < len(ins.elts) and i < len(outs.elts):
                    if ast.dump(ins.elts[i]) != ast.dump(outs.elts[i]):
                        out.append(
                            self.finding(
                                "TPA203", call, symbol,
                                f"donated argument {i} has in_sharding "
                                f"{ast.unparse(ins.elts[i])} but out_sharding "
                                f"{ast.unparse(outs.elts[i])} — XLA cannot "
                                "alias across layouts, so donation degrades "
                                "to a copy plus a reshard",
                            )
                        )
        return out

    def _is_serving_hot(self, fn: ast.FunctionDef) -> bool:
        parts = self.rel.replace(os.sep, "/").split("/")
        in_serve = "serve" in parts[:-1] or parts[-1].startswith("serve")
        hot_name = fn.name.startswith(("_pool_", "_slot_", "_pick_"))
        return in_serve or hot_name

    def rule_tpa204(self) -> list[Finding]:
        out = []
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if not any(
                _decorator_jit_spec(d) is not None for d in node.decorator_list
            ):
                continue
            if not self._is_serving_hot(node):
                continue
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call):
                    fname = _dotted(inner.func)
                    if fname and fname.rsplit(".", 1)[-1] in _COLLECTIVE_CALLS:
                        out.append(
                            self.finding(
                                "TPA204", inner, node.name,
                                f"collective `{fname}` inside the serving "
                                "hot loop — every decode step now "
                                "serializes on the slowest chip; keep "
                                "decode single-chip (or move the collective "
                                "out of the per-token path)",
                            )
                        )
        return out

    _LARGE_PARAM = ("embedding", "table", "kernel")
    _SMALL_PARAM = ("bias", "scale", "ln", "norm")

    def rule_tpa205(self) -> list[Finding]:
        out = []
        enclosing = self._enclosing()
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.Tuple, ast.List)) or len(node.elts) != 2:
                continue
            pat, spec = node.elts
            if not (isinstance(pat, ast.Constant) and isinstance(pat.value, str)):
                continue
            text = pat.value.lower()
            if not any(m in text for m in self._LARGE_PARAM):
                continue
            if any(m in text for m in self._SMALL_PARAM):
                continue
            if not (
                isinstance(spec, ast.Call)
                and _dotted(spec.func)
                and _dotted(spec.func).rsplit(".", 1)[-1] in _SPEC_CTORS
            ):
                continue
            axes = [
                a for a in spec.args
                if not (isinstance(a, ast.Constant) and a.value is None)
            ]
            if axes:
                continue  # something is sharded
            out.append(
                self.finding(
                    "TPA205", node, enclosing.get(id(node), "<module>"),
                    f"partition rule {pat.value!r} maps a large-parameter "
                    "path to a fully replicated spec — every chip holds the "
                    "whole matrix; shard it (or justify inline if the "
                    "tensor is genuinely small)",
                )
            )
        return out


# ==========================================================================
# driver


def default_sharding_baseline_path() -> str:
    return os.path.join(_package_root(), "analysis", "sharding_baseline.json")


def run_sharding(
    paths: list[str] | None = None,
    baseline_path: str | None = None,
) -> RulesReport:
    """Run TPA201–205 over ``paths`` (default: the installed
    ``transformer_tpu`` package + its sharding baseline). The TPA202 axis
    vocabulary is collected across the WHOLE analyzed file set first, so a
    mesh declared in ``config.py`` covers specs written in ``parallel/``."""
    if paths is None:
        paths = [_package_root()]
        if baseline_path is None:
            baseline_path = default_sharding_baseline_path()
    baseline = load_baseline(baseline_path)

    modules: list[_ShardModule] = []
    for full, rel in _iter_py_files(paths):
        with open(full, encoding="utf-8") as f:
            source = f.read()
        try:
            modules.append(_ShardModule(full, rel, source))
        except SyntaxError as e:
            raise SyntaxError(f"cannot analyze {full}: {e}") from e

    universe: set[str] = set()
    for m in modules:
        universe |= m.declared_axes()

    findings: list[Finding] = []
    baselined: list[Finding] = []
    for m in modules:
        raw = (
            m.rule_tpa201()
            + m.rule_tpa202(universe)
            + m.rule_tpa203()
            + m.rule_tpa204()
            + m.rule_tpa205()
        )
        for f in raw:
            if m.suppressed(f):
                continue
            if f.fingerprint in baseline:
                baselined.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return RulesReport(
        findings=findings, baselined=baselined, files_checked=len(modules)
    )
