"""Deterministic interleaving checker for the serving tier's host threads.

The static rules (:mod:`.concurrency`, TPA101–105) approximate what COULD
race; this module RUNS the schedules. A cooperative scheduler takes over
``threading.Lock``/``Thread``/``Condition``/``Event`` and ``queue.Queue``
inside the modules under test (their module-level ``threading``/``queue``
names are swapped for scheduler-aware shims), serializes every thread onto
one token, and yields at each line of instrumented package code — so a
"schedule" is an explicit, replayable sequence of which-thread-runs-next
decisions instead of whatever the OS felt like. Exploration is

- **bounded-exhaustive** for the canned 2-thread scenarios: a DFS over the
  decision tree with replay (run a prefix of decisions, then default to
  INERTIA — keep running the thread that ran last — and queue every
  untaken branch), breadth-first, so the cap is spent on low-preemption
  schedules first: every single-context-switch schedule, then every
  two-switch one, and so on. Most real races need only one or two
  preemptions (the CHESS observation), which is why the revert-the-lock
  canaries are found within a 64-schedule budget;
- **seeded-random** beyond 2 threads (the tree is too wide): distinct
  decision traces under a seeded RNG, deduped.

Every explored schedule must terminate (a blocked-forever thread set is
reported as a deadlock, a runaway one as non-termination) and must uphold
the scenario's invariants — refcounts never negative, byte accounting
exact, JSONL lines never torn, the scrape never observes a half-built
registry. The canned scenarios cover the four places this repo already
runs threads: ``PrefixCache`` admission/retirement vs. eviction, registry
scrape vs. lazy metric creation, prefetch producer vs. consumer shutdown,
and concurrent ``EventLog`` writers. ``python -m transformer_tpu.analysis
schedules`` runs them all; ``tests/test_analysis.py`` pins ≥ 200 explored
interleavings with zero violations, and the revert-the-lock canary proves
the explorer actually catches the bug class the PR 3 registry lock fixed.

Timeouts are modeled deterministically: a timed wait may only give up when
no other thread can run — the schedule space stays finite and replayable,
while liveness bugs (a producer that spins forever because its consumer
left) still surface as non-termination.
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import queue as _queue
import random
import sys
import threading
from collections import deque
from typing import Callable, Iterable

_STEP_CAP = 200_000  # driver iterations per schedule: non-termination guard


class _SchedulerAbort(BaseException):
    """Raised inside a controlled thread to unwind it during teardown.
    BaseException so scenario code's ``except Exception`` cannot eat it."""


@dataclasses.dataclass
class Violation:
    kind: str              # "exception" | "invariant" | "deadlock" | "nontermination"
    detail: str
    # Branch-point decision trace that reproduces it: exactly the indices
    # run() consumes as a replay prefix (forced single-runnable points are
    # NOT recorded — the prefix is indexed by multi-choice count).
    schedule: list[int]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ScenarioResult:
    name: str
    schedules: int         # distinct interleavings fully explored
    deadlocks: int
    violations: list[Violation]
    max_decisions: int     # longest decision trace seen (tree depth bound)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.deadlocks

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "schedules": self.schedules,
            "deadlocks": self.deadlocks,
            "max_decisions": self.max_decisions,
            "violations": [v.to_dict() for v in self.violations],
        }


# --------------------------------------------------------------------------
# the cooperative scheduler


class _DetThread:
    """One controlled thread: a real daemon thread that only runs while it
    holds the scheduler's token."""

    def __init__(self, sched: "DetScheduler", target, name, args=(), kwargs=None,
                 daemon=None):
        self.sched = sched
        self.target = target
        self.name = name
        self.args = args
        self.kwargs = kwargs or {}
        self.tid = sched._register(self)
        self.started = False
        self.finished = False
        self.pred: Callable[[], bool] | None = None
        self.timeout_ok = False     # pred-wait may give up when nothing else runs
        self.timed_out = False
        self._sem = threading.Semaphore(0)
        self._thread = threading.Thread(
            target=self._bootstrap, name=f"det-{name}", daemon=True
        )

    # threading.Thread API surface the shims expose
    def start(self) -> None:
        if self.started:
            raise RuntimeError(f"thread {self.name} already started")
        self.started = True
        self._thread.start()
        # Give the driver a chance to interleave right after spawn, matching
        # real threading where the child may run before start() returns.
        self.sched.switch_point()

    def is_alive(self) -> bool:
        return self.started and not self.finished

    def join(self, timeout: float | None = None) -> None:
        if not self.started:
            return
        if timeout is None:
            self.sched.block_until(lambda: self.finished)
        else:
            self.sched.timeout_wait(lambda: self.finished)

    @property
    def daemon(self) -> bool:  # shim compatibility
        return True

    def _bootstrap(self) -> None:
        sys.settrace(self.sched._trace)
        self._sem.acquire()  # wait to be scheduled the first time
        try:
            if self.sched._abort:
                raise _SchedulerAbort
            self.target(*self.args, **self.kwargs)
        except _SchedulerAbort:
            pass
        except BaseException as e:  # tpa: disable=TPA006 — the whole point: ANY scenario failure is recorded as a schedule violation with its reproducing decision trace, then teardown continues
            self.sched._record_exception(self, e)
        finally:
            sys.settrace(None)
            self.finished = True  # tpa: disable=TPA101 — scheduler handoff: the driver reads `finished` only after this thread releases the control token on the next line, and controlled threads only while holding it
            self.sched._control.release()


class DetScheduler:
    """Serializes controlled threads onto one token and records/replays the
    which-thread-next decisions. One instance per explored schedule."""

    def __init__(self, instrument_files: Iterable[str] = ()):
        self._instrument = {str(f) for f in instrument_files}
        self.threads: list[_DetThread] = []
        self._control = threading.Semaphore(0)
        self._current: _DetThread | None = None
        self._last: _DetThread | None = None
        self._abort = False
        self.decision_log: list[tuple[int, int]] = []  # (n_options, chosen)
        self.decisions: list[int] = []                 # chosen indices (all points)
        self.violations: list[Violation] = []
        self.deadlocked = False

    # ---- registration -----------------------------------------------------

    def _register(self, t: _DetThread) -> int:
        self.threads.append(t)
        return len(self.threads) - 1

    def spawn(self, target, name: str, args=(), kwargs=None) -> _DetThread:
        return _DetThread(self, target, name, args=args, kwargs=kwargs)

    def find_thread(self, name: str) -> "_DetThread | None":
        for t in self.threads:
            if t.name == name or t.name == f"det-{name}" or name in t.name:
                return t
        return None

    # ---- thread-side yield points ----------------------------------------

    def _running(self) -> _DetThread | None:
        cur = self._current
        if cur is not None and cur._thread is threading.current_thread():
            return cur
        return None

    def switch_point(self) -> None:
        """Hand the token back to the driver; it may resume us immediately
        or run someone else first. No-op off a controlled thread and during
        teardown (so ``finally`` blocks unwind without scheduling)."""
        t = self._running()
        if t is None or self._abort:
            return
        self._control.release()
        t._sem.acquire()
        if self._abort:
            raise _SchedulerAbort

    def block_until(self, pred: Callable[[], bool]) -> None:
        t = self._running()
        if t is None or self._abort:
            return
        t.pred = pred
        self._control.release()
        t._sem.acquire()
        t.pred = None
        if self._abort:
            raise _SchedulerAbort

    def timeout_wait(self, pred: Callable[[], bool]) -> bool:
        """Deterministic timed wait: resumed when ``pred`` holds OR when no
        other thread can make progress (the only moment a real timeout is
        observable without reintroducing wall-clock nondeterminism).
        Returns whether ``pred`` held at resume."""
        t = self._running()
        if t is None or self._abort:
            return pred()
        t.pred = pred
        t.timeout_ok = True
        self._control.release()
        t._sem.acquire()
        t.pred = None
        t.timeout_ok = False
        if self._abort:
            raise _SchedulerAbort
        return pred()

    def branch_trace(self) -> list[int]:
        """The choices made at branch points so far — the exact list
        ``run()`` accepts back as a replay ``prefix``."""
        return [c for _, c in self.decision_log]

    def _record_exception(self, t: _DetThread, e: BaseException) -> None:
        self.violations.append(
            Violation(
                kind="exception",
                detail=f"{t.name}: {type(e).__name__}: {e}",
                schedule=self.branch_trace(),
            )
        )

    # ---- line-granularity preemption --------------------------------------

    def _trace(self, frame, event, arg):
        if event != "call":
            return None
        if frame.f_code.co_filename not in self._instrument:
            return None
        return self._trace_line

    def _trace_line(self, frame, event, arg):
        if event == "line":
            self.switch_point()
        return self._trace_line

    # ---- the driver -------------------------------------------------------

    def run(self, prefix: list[int], rng: random.Random | None = None) -> None:
        """Drive every started thread to completion, replaying ``prefix``
        decisions then defaulting to the first runnable thread (or ``rng``
        choices). Deadlock/non-termination are recorded as violations."""
        steps = 0
        while True:
            live = [t for t in self.threads if t.started and not t.finished]
            if not live:
                break
            steps += 1
            if steps > _STEP_CAP:
                self.violations.append(
                    Violation(
                        kind="nontermination",
                        detail=f"schedule exceeded {_STEP_CAP} steps",
                        schedule=self.branch_trace(),
                    )
                )
                self._teardown(live)
                return
            runnable = [t for t in live if t.pred is None or t.pred()]
            if not runnable:
                timed = [t for t in live if t.pred is not None and t.timeout_ok]
                if timed:
                    runnable = timed  # their deterministic timeout fires now
                else:
                    self.deadlocked = True
                    self.violations.append(
                        Violation(
                            kind="deadlock",
                            detail="all live threads blocked: "
                            + ", ".join(t.name for t in live),
                            schedule=self.branch_trace(),
                        )
                    )
                    self._teardown(live)
                    return
            if len(runnable) == 1:
                chosen = 0
            else:
                i = len(self.decision_log)
                if i < len(prefix):
                    chosen = min(prefix[i], len(runnable) - 1)
                elif rng is not None:
                    chosen = rng.randrange(len(runnable))
                else:
                    # Inertia: keep running the last-scheduled thread, so a
                    # frontier deviation is ONE context switch followed by
                    # run-to-completion — the decision tree enumerates
                    # schedules by preemption count.
                    chosen = 0
                    if self._last is not None and self._last in runnable:
                        chosen = runnable.index(self._last)
                self.decision_log.append((len(runnable), chosen))
            self.decisions.append(chosen)
            t = runnable[chosen]
            self._last = t
            self._current = t
            t._sem.release()
            self._control.acquire()
            self._current = None

    def _teardown(self, live: list[_DetThread]) -> None:
        """Unwind parked threads: wake each with the abort flag set; yield
        points become no-ops so ``finally`` blocks run to completion."""
        self._abort = True
        for t in live:
            if t.finished:
                continue
            t._sem.release()
            self._control.acquire()


# --------------------------------------------------------------------------
# scheduler-aware primitives (what the shims hand to the code under test)


class DetLock:
    def __init__(self, sched: DetScheduler):
        self._sched = sched
        self._owner: object = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t = self._sched._running()
        if t is None:
            # Driver-side (scenario setup) use: must be uncontended.
            if self._owner is not None:
                raise RuntimeError("driver acquired a held DetLock")
            self._owner = "<driver>"
            return True
        self._sched.switch_point()
        if self._owner is not None:
            if not blocking:
                return False
            # A Lock is not reentrant: self-acquire blocks forever — which
            # the driver reports as the deadlock it is.
            self._sched.block_until(lambda: self._owner is None)
        self._owner = t
        return True

    def release(self) -> None:
        t = self._sched._running()
        if t is None:
            if self._owner != "<driver>":
                raise RuntimeError("driver released a thread-held DetLock")
            self._owner = None
            return
        if self._owner is not t:
            raise RuntimeError("release of a DetLock the thread does not hold")
        self._owner = None
        self._sched.switch_point()

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class DetRLock(DetLock):
    def __init__(self, sched: DetScheduler):
        super().__init__(sched)
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t = self._sched._running()
        if t is not None and self._owner is t:
            self._count += 1
            return True
        ok = super().acquire(blocking, timeout)
        if ok:
            self._count = 1
        return ok

    def release(self) -> None:
        if self._count > 1:
            self._count -= 1
            return
        self._count = 0
        super().release()


class DetCondition:
    def __init__(self, sched: DetScheduler, lock: DetLock | None = None):
        self._sched = sched
        self._lock = lock if lock is not None else DetLock(sched)
        self._waiters: list[list] = []  # [notified?] cells, FIFO

    # context manager delegates to the lock
    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        return self._lock.release()

    def wait(self, timeout: float | None = None) -> bool:
        t = self._sched._running()
        if self._lock._owner is not t:
            raise RuntimeError("cond.wait() without the lock held")
        cell = [False]
        self._waiters.append(cell)
        self._lock._owner = None  # release while waiting
        if timeout is None:
            self._sched.block_until(
                lambda: cell[0] and self._lock._owner is None
            )
        else:
            self._sched.timeout_wait(
                lambda: cell[0] and self._lock._owner is None
            )
            if not cell[0] and cell in self._waiters:
                self._waiters.remove(cell)  # timed out un-notified
        notified = cell[0]
        # reacquire before returning, notified or not (threading semantics)
        while self._lock._owner is not None:
            self._sched.block_until(lambda: self._lock._owner is None)
        self._lock._owner = t
        return notified

    def wait_for(self, predicate, timeout: float | None = None) -> bool:
        while not predicate():
            self.wait(timeout)
            if timeout is not None and not predicate():
                return predicate()
        return True

    def notify(self, n: int = 1) -> None:
        for cell in self._waiters[:n]:
            cell[0] = True
        del self._waiters[:n]
        self._sched.switch_point()

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


class DetEvent:
    def __init__(self, sched: DetScheduler):
        self._sched = sched
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        self._sched.switch_point()

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: float | None = None) -> bool:
        if timeout is None:
            self._sched.block_until(lambda: self._flag)
        else:
            self._sched.timeout_wait(lambda: self._flag)
        return self._flag


class DetQueue:
    """queue.Queue with deterministic blocking/timeout semantics."""

    def __init__(self, sched: DetScheduler, maxsize: int = 0):
        self._sched = sched
        self.maxsize = maxsize
        self._items: deque = deque()

    def _full(self) -> bool:
        return self.maxsize > 0 and len(self._items) >= self.maxsize

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return self._full()

    def put(self, item, block: bool = True, timeout: float | None = None) -> None:
        self._sched.switch_point()
        if self._full():
            if not block:
                raise _queue.Full
            if timeout is not None:
                if not self._sched.timeout_wait(lambda: not self._full()):
                    raise _queue.Full
            else:
                self._sched.block_until(lambda: not self._full())
        self._items.append(item)
        self._sched.switch_point()

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: float | None = None):
        self._sched.switch_point()
        if not self._items:
            if not block:
                raise _queue.Empty
            if timeout is not None:
                if not self._sched.timeout_wait(lambda: bool(self._items)):
                    raise _queue.Empty
            else:
                self._sched.block_until(lambda: bool(self._items))
        item = self._items.popleft()
        self._sched.switch_point()
        return item

    def get_nowait(self):
        return self.get(block=False)

    def task_done(self) -> None:
        pass

    def join(self) -> None:
        pass


# --------------------------------------------------------------------------
# module shims


class _ThreadingShim:
    """Stands in for the ``threading`` module inside a patched module: the
    synchronization constructors hand back scheduler-aware twins, everything
    else (current_thread, TIMEOUT_MAX, ...) passes through."""

    def __init__(self, sched: DetScheduler):
        self._sched = sched

    def Lock(self):  # noqa: N802 — threading API
        return DetLock(self._sched)

    def RLock(self):  # noqa: N802
        return DetRLock(self._sched)

    def Condition(self, lock=None):  # noqa: N802
        return DetCondition(self._sched, lock)

    def Event(self):  # noqa: N802
        return DetEvent(self._sched)

    def Thread(self, group=None, target=None, name=None, args=(), kwargs=None,
               daemon=None):  # noqa: N802
        return self._sched.spawn(
            target, name=name or f"thread-{len(self._sched.threads)}",
            args=args, kwargs=kwargs,
        )

    def __getattr__(self, name):
        return getattr(threading, name)


class _QueueShim:
    def __init__(self, sched: DetScheduler):
        self._sched = sched

    def Queue(self, maxsize: int = 0):  # noqa: N802 — queue API
        return DetQueue(self._sched, maxsize)

    def __getattr__(self, name):
        return getattr(_queue, name)


@contextlib.contextmanager
def patched_modules(sched: DetScheduler, modules: Iterable[object]):
    """Swap each module's top-level ``threading``/``queue`` names for the
    scheduler's shims for the duration of one schedule run."""
    saved: list[tuple[object, str, object]] = []
    try:
        for mod in modules:
            if getattr(mod, "threading", None) is threading:
                saved.append((mod, "threading", threading))
                setattr(mod, "threading", _ThreadingShim(sched))
            if getattr(mod, "queue", None) is _queue:
                saved.append((mod, "queue", _queue))
                setattr(mod, "queue", _QueueShim(sched))
        yield
    finally:
        for mod, name, orig in saved:
            setattr(mod, name, orig)


# --------------------------------------------------------------------------
# exploration


@dataclasses.dataclass
class Scenario:
    """One canned concurrency scenario.

    ``setup(sched)`` builds fresh objects (inside the patched-module
    context, so their locks are scheduler-aware) and returns
    ``(thread_bodies, check)``; ``check()`` asserts the end-state
    invariants after all threads finish. ``instrument`` lists source files
    whose lines are preemption points; ``modules()`` returns the modules
    whose threading/queue names get shimmed."""

    name: str
    setup: Callable
    modules: Callable[[], list]
    instrument: Callable[[], list[str]]
    max_schedules: int = 64
    random_mode: bool = False


def _run_one(
    scenario: Scenario, prefix: list[int], rng: random.Random | None
) -> DetScheduler:
    sched = DetScheduler(instrument_files=scenario.instrument())
    with patched_modules(sched, scenario.modules()):
        bodies, check = scenario.setup(sched)
        threads = [
            sched.spawn(body, name=f"t{i}") for i, body in enumerate(bodies)
        ]
        for t in threads:
            t.started = True
            t._thread.start()
        sched.run(prefix, rng=rng)
        if check is not None and not sched.violations:
            try:
                check()
            except Exception as e:  # tpa: disable=TPA006 — the checker's contract: ANY invariant-check failure (assert, parse error, KeyError on torn state) is a schedule violation to report with its reproducing trace, not a crash
                sched.violations.append(
                    Violation(
                        kind="invariant",
                        detail=f"{type(e).__name__}: {e}"
                        if not isinstance(e, AssertionError)
                        else (str(e) or "invariant check failed"),
                        schedule=sched.branch_trace(),
                    )
                )
    return sched


def explore(
    scenario: Scenario,
    max_schedules: int | None = None,
    seed: int = 0,
) -> ScenarioResult:
    """Systematically explore ``scenario``'s interleavings up to the
    schedule cap. DFS-with-replay over the decision tree (breadth-first
    frontier: single-preemption schedules first), or seeded-random distinct
    traces when the scenario opts into random mode."""
    cap = max_schedules if max_schedules is not None else scenario.max_schedules
    violations: list[Violation] = []
    deadlocks = 0
    max_decisions = 0
    explored = 0

    if scenario.random_mode:
        seen: set[tuple] = set()
        attempts = 0
        while explored < cap and attempts < cap * 4:
            attempts += 1
            # int mix, not a tuple: hash-based Random seeding is deprecated.
            rng = random.Random(seed * 1_000_003 + attempts)
            sched = _run_one(scenario, [], rng)
            trace = tuple(c for _, c in sched.decision_log)
            if trace in seen:
                continue
            seen.add(trace)
            explored += 1
            max_decisions = max(max_decisions, len(sched.decision_log))
            violations.extend(sched.violations)
            deadlocks += int(sched.deadlocked)
    else:
        frontier: deque[list[int]] = deque([[]])
        while frontier and explored < cap:
            prefix = frontier.popleft()
            sched = _run_one(scenario, prefix, None)
            explored += 1
            max_decisions = max(max_decisions, len(sched.decision_log))
            violations.extend(sched.violations)
            deadlocks += int(sched.deadlocked)
            # Queue every untaken branch beyond the replayed prefix.
            chosen_so_far = [c for _, c in sched.decision_log]
            for i in range(len(prefix), len(sched.decision_log)):
                n, chosen = sched.decision_log[i]
                for alt in range(n):
                    if alt != chosen:
                        frontier.append(chosen_so_far[:i] + [alt])

    return ScenarioResult(
        name=scenario.name,
        schedules=explored,
        deadlocks=deadlocks,
        violations=violations,
        max_decisions=max_decisions,
    )


# --------------------------------------------------------------------------
# canned scenarios


def _module_file(mod) -> str:
    return mod.__file__


def _assert_prefix_cache_consistent(cache) -> None:
    """Walk the trie under the cache's own lock and re-derive the byte/
    block accounting from first principles."""
    with cache._lock:
        total = 0
        blocks = 0
        stack = [cache._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            assert n.refs >= 0, f"negative refcount {n.refs} on {n.edge}"
            if n.blocks is not None:
                total += n.nbytes
                blocks += 1
        assert total == cache._bytes, (
            f"byte accounting drifted: nodes hold {total}, cache says "
            f"{cache._bytes}"
        )
        assert blocks == cache.stats["blocks"], (
            f"block count drifted: {blocks} reachable vs stats "
            f"{cache.stats['blocks']}"
        )
        assert total <= cache.budget_bytes, "byte budget exceeded"


def _scenario_prefix_cache(sched: DetScheduler):
    import numpy as np

    from transformer_tpu.config import ModelConfig
    from transformer_tpu.serve.prefix_cache import PrefixCache

    cache = PrefixCache(ModelConfig(), block_tokens=2, budget_mb=1)
    blk = np.zeros((1, 2, 2, 2), np.float32)

    def read_block(start: int):
        return [{"k": blk.copy(), "v": blk.copy()}]

    # Shrink the budget to 3 blocks so the two threads contend over LRU
    # eviction, pinning, and the byte accounting — the actual race surface.
    cache.budget_bytes = 3 * 2 * blk.nbytes

    def hammer(prompts):
        def body():
            for ids in prompts:
                hit = cache.match(ids[: len(ids) - 1])
                hit.stacked(16)
                cache.insert(ids, (len(ids) // 2) * 2, read_block)
                # Pinned blocks must never be evicted: every matched node
                # stays attached to its parent until release().
                with cache._lock:
                    for n in hit._nodes:
                        assert n.parent is not None and (
                            n.parent.children.get(n.edge) is n
                        ), "pinned block evicted while referenced"
                hit.release()
                _assert_prefix_cache_consistent(cache)
        return body

    a = [[1, 2, 3, 4, 5], [1, 2, 7, 8, 9]]
    b = [[1, 2, 3, 4, 11], [13, 14, 15, 16, 17]]

    def check():
        _assert_prefix_cache_consistent(cache)
        stack = [cache._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            assert n.refs == 0, f"leaked refcount {n.refs} on {n.edge}"

    return [hammer(a), hammer(b)], check


def _scenario_kv_pool(sched: DetScheduler):
    """Two slots hammer one paged-KV allocator (kernels/kv_pool.py)
    through the full serving lifecycle — alloc (admission), device-tier
    retain (retirement donation), truncate (speculative rollback), free
    (slot recycle), alias (prefix hit), copy-on-write split (divergent
    write under sharing) — under preemption at every line. Invariants
    (``check_consistency`` re-derives the accounting from first
    principles after every step): refcounts never negative, no
    double-free, free list disjoint from every table, block-count
    conservation."""
    from transformer_tpu.kernels.kv_pool import KVPool

    pool = KVPool(8, 2, num_slots=2, slot_blocks=3)

    def worker(slot: int):
        def body():
            pool.ensure(slot, 6)                    # admission: 3 blocks
            pool.check_consistency()
            bid = int(pool.table[slot, 0])          # row owned by this thread
            pool.retain(bid)                        # trie adopts block 0
            pool.check_consistency()
            pool.truncate(slot, 2)                  # rollback to 1 block
            pool.check_consistency()
            pool.free_slot(slot)                    # retire: pin survives
            pool.check_consistency()
            pool.extend(slot, bid=bid)              # prefix hit: alias back
            pairs = pool.make_writable(slot, 0, 2)  # CoW: refs 2 -> split
            assert len(pairs) == 1, f"expected one CoW split, got {pairs}"
            pool.check_consistency()
            pool.free_slot(slot)
            pool.release(bid)                       # trie eviction
            pool.check_consistency()
        return body

    def check():
        pool.check_consistency()
        assert pool.used_blocks == 0, (
            f"blocks leaked: {pool.stats}, table {pool.table.tolist()}"
        )
        assert pool.stats["cow_splits"] == 2, pool.stats

    return [worker(0), worker(1)], check


def _scenario_registry(sched: DetScheduler, registry_factory=None):
    from transformer_tpu.obs.registry import MetricsRegistry

    reg = (registry_factory or MetricsRegistry)()
    reg.counter("warm_total", "pre-existing metric").inc()

    def scraper():
        for _ in range(2):
            text = reg.to_prometheus_text()
            for line in text.splitlines():
                assert line.startswith("#") or len(line.split()) == 2, (
                    f"torn exposition line: {line!r}"
                )

    def creator():
        for i in range(4):
            reg.counter(f"lazy_{i}_total", "created under scrape").inc()

    def check():
        names = {m.name for m in reg}
        assert {"warm_total", "lazy_0_total", "lazy_3_total"} <= names

    return [scraper, creator], check


def _scenario_prefetch(sched: DetScheduler):
    import numpy as np

    from transformer_tpu.data import pipeline

    batches = [
        (np.full((2,), i, np.int32), np.full((2,), i, np.int32))
        for i in range(3)
    ]

    def consumer():
        gen = pipeline._threaded_device_prefetch(iter(batches), depth=1)
        seen = 0
        for _ in gen:
            seen += 1
            if seen >= 1:
                break  # early exit mid-stream: the shutdown race
        gen.close()
        worker = sched.find_thread("pipeline-prefetch")
        assert worker is not None, "producer thread never spawned"
        assert worker.finished, (
            "producer thread outlived the closed iterator (join missing)"
        )

    return [consumer], None


def _scenario_eventlog(sched: DetScheduler, log_factory=None):
    from transformer_tpu.obs.events import EventLog

    buf = io.StringIO()
    log = (log_factory or EventLog)(buf)

    def writer(wid: int):
        def body():
            for i in range(3):
                log.emit("schedules.test", writer=wid, seq=i)
        return body

    def check():
        lines = buf.getvalue().splitlines()
        assert len(lines) == 6, f"expected 6 events, got {len(lines)}"
        for line in lines:
            ev = json.loads(line)  # ValueError here = torn JSONL
            assert ev["kind"] == "schedules.test"

    return [writer(0), writer(1)], check


def _scenario_router_tables(sched: DetScheduler):
    """The multi-replica router's shared tables under adversarial
    interleaving: a CLIENT thread submitting through the intake lock, the
    ROUTER thread pumping dispatch/answer/heartbeat messages, and two
    REPLICA threads feeding heartbeats and answers (including one
    deliberate DUPLICATE answer — the failover race the order-keyed
    funnel must collapse to at-most-once). Invariants: every accepted
    order answers exactly once in arrival order, the duplicate is counted
    and dropped, and the in-flight/load accounting returns to zero."""
    from transformer_tpu.serve.router import ReplicaLink, Router

    class _Scripted(ReplicaLink):
        def __init__(self, index, name, mailbox):
            super().__init__(index, name)
            self.mailbox = mailbox

        def send(self, msg):
            self.mailbox.put(msg)

    mailboxes = [DetQueue(sched), DetQueue(sched)]
    links = [_Scripted(i, f"r{i}", mailboxes[i]) for i in range(2)]
    # Constructed INSIDE the patched-module context: the router's intake
    # lock and inbox queue are scheduler-aware twins.
    router = Router(
        links, encode=lambda s: [3, 4, 5, 6, 7, 8, 9, 10], bos_id=1,
        affinity_block=4,
    )
    N = 3
    drained: list = []

    def client():
        for i in range(N):
            router.submit({"prompt": f"p{i}"})
        router.submit_done(
            {"error": "LM export serves 'prompt', not 'src'",
             "code": "routing"}
        )

    def replica(idx: int):
        def body():
            while True:
                msg = mailboxes[idx].get()
                if msg.get("type") == "shutdown":
                    return
                rid = msg["rid"]
                router.inbox.put(
                    (idx, {"type": "hb", "backlog": 0, "free": 2, "active": 1})
                )
                router.inbox.put(
                    (idx, {"type": "answer", "rid": rid,
                           "resp": {"continuation": f"r{idx}"}})
                )
                if rid == 0:
                    # The failover race: a second answer for an order the
                    # funnel has already (or will have) accepted.
                    router.inbox.put(
                        (idx, {"type": "answer", "rid": rid,
                               "resp": {"continuation": "dup"}})
                    )
        return body

    def pump():
        while len(drained) < N + 1:
            router.pump(timeout=0.01)
            drained.extend(router.drain_ready())
        # Let one straggling duplicate land before shutting the fakes down.
        router.pump(timeout=0.01)
        for mb in mailboxes:
            mb.put({"type": "shutdown"})

    def check():
        assert len(drained) == N + 1, f"answers lost: {drained}"
        errors = [d for d in drained if "error" in d]
        assert len(errors) == 1 and errors[0]["code"] == "routing"
        assert router.stats["answered"] == N
        assert router.stats["duplicate_answers"] == 1, router.stats
        assert not router._inflight, "in-flight table leaked entries"
        assert all(l.inflight == 0 for l in links), "load accounting drifted"
        assert sum(l.dispatched for l in links) == N

    return [client, pump, replica(0), replica(1)], check


def _scenario_supervisor_respawn(sched: DetScheduler):
    """The self-healing tier under adversarial interleaving: a CLIENT
    submitting orders, the ROUTER pump (which also drives the supervisor's
    respawn/warm state machine and the answer funnel), a SURVIVOR replica
    feeding heartbeats/answers/warm-up exports, and the REPLACEMENT
    worker the supervisor spawns mid-run. Replica 0 dies (EOF sentinel)
    with work possibly in flight; the supervisor must re-bootstrap it
    exactly once (no double-spawn no matter how poll/on_death/exit
    interleave), warm it from the survivor, and admit it — while the
    funnel answers every accepted order exactly once (no lost order
    through the death -> failover -> respawn window)."""
    from transformer_tpu.serve.router import ReplicaLink, Router
    from transformer_tpu.serve.supervisor import Supervisor

    pids = iter(range(1000, 2000))

    class _Scripted(ReplicaLink):
        def __init__(self, index, name, mailbox):
            super().__init__(index, name)
            self.mailbox = mailbox
            self.ok = True
            # Scripted "process identity" for the exit sentinel — without
            # it, a schedule where the respawn lands before the sentinel
            # drains would fail over the REPLACEMENT (the confusion the
            # router's pid check exists to prevent) and then sit out the
            # breaker cooldown in real time.
            self._pid = next(pids)

        def pid(self):
            return self._pid

        def send(self, msg):
            self.mailbox.put(msg)

        def alive(self):
            return self.ok

        def kill(self):
            self.ok = False

    mailboxes = [DetQueue(sched), DetQueue(sched)]
    newbie_mailbox = DetQueue(sched)
    links = [_Scripted(i, f"r{i}", mailboxes[i]) for i in range(2)]
    spawn_calls: list = []

    def spawn(index, name, role):
        # The deterministic re-bootstrap recipe. Called on the router
        # thread; the "process" announces ready through the inbox exactly
        # like a real worker's bootstrap line.
        spawn_calls.append(index)
        link = _Scripted(index, name, newbie_mailbox)
        router.inbox.put((index, {"type": "ready", "replica": name}))
        return link

    sup = Supervisor(
        spawn, backoff_ms=0.0, boot_timeout_s=300.0, warm_timeout_s=300.0,
    )
    router = Router(
        links, encode=lambda s: [3, 4, 5, 6, 7, 8, 9, 10], bos_id=1,
        affinity_block=4, supervisor=sup,
    )
    N = 3
    drained: list = []

    def client():
        for i in range(N):
            router.submit({"prompt": f"p{i}"})
        # Replica 0 dies with whatever the dispatcher already handed it;
        # the sentinel carries its pid (see _Scripted.pid).
        links[0].ok = False
        router.inbox.put((0, {"type": "exit", "pid": links[0].pid()}))

    def survivor():
        while True:
            msg = mailboxes[1].get()
            kind = msg.get("type")
            if kind == "shutdown":
                return
            if kind == "export_state":
                # The warm-up export the supervisor asked for.
                router.inbox.put((1, {
                    "type": "prefix_state",
                    "entries": [{"ids": [3, 4, 5, 6], "tokens": 7,
                                 "blocks": []}],
                }))
                continue
            rid = msg["rid"]
            router.inbox.put(
                (1, {"type": "hb", "backlog": 0, "free": 2, "active": 1})
            )
            router.inbox.put(
                (1, {"type": "answer", "rid": rid,
                     "resp": {"continuation": "s"}})
            )

    def newbie():
        while True:
            msg = newbie_mailbox.get()
            kind = msg.get("type")
            if kind == "shutdown":
                return
            if kind == "inject_state":
                tokens = sum(
                    int(e.get("tokens", 0)) for e in msg.get("entries", [])
                )
                router.inbox.put(
                    (0, {"type": "state_injected", "tokens": tokens})
                )
                continue
            if kind == "req":
                router.inbox.put(
                    (0, {"type": "answer", "rid": msg["rid"],
                         "resp": {"continuation": "n"}})
                )

    def pump():
        while len(drained) < N or sup.stats["respawns"] < 1:
            router.pump(timeout=0.01)
            drained.extend(router.drain_ready())
        router.pump(timeout=0.01)
        for mb in mailboxes:
            mb.put({"type": "shutdown"})
        newbie_mailbox.put({"type": "shutdown"})

    def check():
        assert len(drained) == N, f"orders lost/duplicated: {drained}"
        assert all("error" not in d for d in drained), drained
        assert len(spawn_calls) == 1, f"double-spawn: {spawn_calls}"
        assert sup.stats["respawns"] == 1, sup.stats
        assert sup.stats["warmed_tokens"] == 7, sup.stats
        assert not router._inflight, "in-flight table leaked entries"
        healthy = [
            l for l in router.links
            if not l.dead and not l.warming and not l.draining
        ]
        assert len(healthy) == 2, "fleet did not heal back to N"
        assert sup._slots[0].phase == "up", sup._slots[0].phase

    return [client, pump, survivor, newbie], check


def _scenario_rolling_upgrade(sched: DetScheduler):
    """The live-weights control plane under adversarial interleaving: a
    CLIENT submitting orders then SIGKILLing replica 1, the ROUTER pump
    (which drives the UpgradeCoordinator's quiesce/swap state machine,
    the supervisor's respawn machine, AND the answer funnel), scripted
    replica workers speaking the upgrade protocol, and the REPLACEMENT
    the supervisor spawns mid-rollout. No matter how death, failover,
    swap confirmations, and respawn interleave: no request is lost, no
    replica ever stages a version the coordinator did not verify, the
    respawn bootstraps at the fleet's TARGET version (never the stale
    argv weights), and the fleet's final version set is re-derived
    exactly — every live link at the target."""
    from transformer_tpu.serve.router import ReplicaLink, Router
    from transformer_tpu.serve.supervisor import Supervisor
    from transformer_tpu.serve.upgrade import UpgradeCoordinator

    pids = iter(range(1000, 2000))

    class _Scripted(ReplicaLink):
        def __init__(self, index, name, mailbox, version="vOLD"):
            super().__init__(index, name)
            self.mailbox = mailbox
            self.ok = True
            self.wv = version
            # Scripted "process identity": the exit sentinel carries it,
            # like ReplicaProcess's pid — without it, a stale EOF racing
            # the respawn would fail over the REPLACEMENT (the exact
            # confusion the router's pid check exists to prevent).
            self._pid = next(pids)

        def pid(self):
            return self._pid

        def send(self, msg):
            if not self.ok:
                raise BrokenPipeError("dead")
            self.mailbox.put(msg)

        def alive(self):
            return self.ok

        def kill(self):
            self.ok = False

    mailboxes = [DetQueue(sched), DetQueue(sched)]
    newbie_mailbox = DetQueue(sched)
    links = [_Scripted(i, f"r{i}", mailboxes[i]) for i in range(2)]
    upgrade_msgs: list = []
    spawn_targets: list = []

    def spawn(index, name, role, weight_target=None):
        # The 4-arg recipe: the supervisor hands over the fleet's target
        # so the replacement "process" bootstraps at the CURRENT version.
        spawn_targets.append(weight_target)
        version = weight_target[1] if weight_target else "vOLD"
        link = _Scripted(index, name, newbie_mailbox, version=version)
        ready = {"type": "ready", "replica": name,
                 "weight_version": version}
        router.inbox.put((index, ready))
        return link

    sup = Supervisor(
        spawn, backoff_ms=0.0, boot_timeout_s=300.0, warm_timeout_s=300.0,
    )
    # canary_window_s=0: the canary gate promotes on its first poll — the
    # verdict math is pinned by tests/test_upgrade.py; this scenario
    # explores the COORDINATION interleavings.
    up = UpgradeCoordinator(
        canary_window_s=0.0, canary_min_requests=1,
        verify=lambda p: (p, "vNEW"),
    )
    router = Router(
        links, encode=lambda s: [3, 4, 5, 6, 7, 8, 9, 10], bos_id=1,
        affinity_block=4, supervisor=sup, upgrader=up,
    )
    N = 3
    drained: list = []

    def client():
        for i in range(N):
            router.submit({"prompt": f"p{i}"})
        # Replica 1 dies with whatever it holds — possibly mid-quiesce,
        # mid-swap, or already upgraded, depending on the schedule. The
        # sentinel carries the dying process's pid so a schedule where
        # the respawn lands first cannot fail over the replacement.
        links[1].ok = False
        router.inbox.put((1, {"type": "exit", "pid": links[1].pid()}))

    def replica_body(index, mailbox, version):
        ver = [version]

        def body():
            while True:
                msg = mailbox.get()
                kind = msg.get("type")
                if kind == "shutdown":
                    return
                if kind == "export_state":
                    router.inbox.put(
                        (index, {"type": "prefix_state", "entries": []})
                    )
                elif kind == "inject_state":
                    router.inbox.put(
                        (index, {"type": "state_injected", "tokens": 0})
                    )
                elif kind == "upgrade":
                    # The scripted worker's verification stand-in: it
                    # only ever serves versions the coordinator shipped.
                    upgrade_msgs.append(dict(msg))
                    router.inbox.put((index, {
                        "type": "upgrade_staged", "ok": True,
                        "version": msg["version"],
                    }))
                    ver[0] = msg["version"]
                    router.inbox.put((index, {
                        "type": "upgraded", "ok": True,
                        "version": msg["version"],
                    }))
                elif kind == "rollback":
                    ver[0] = "vOLD"
                    router.inbox.put((index, {
                        "type": "upgraded", "ok": True, "version": "vOLD",
                    }))
                elif kind == "req":
                    router.inbox.put((index, {
                        "type": "answer", "rid": msg["rid"],
                        "resp": {"continuation": "x",
                                 "weight_version": ver[0]},
                        "slo": {"total_s": 0.01},
                    }))

        return body

    def pump():
        st = router.start_upgrade("ckpt")
        assert st["ok"], st
        while not (
            len(drained) >= N
            and up.state == "done"
            and sup.stats["respawns"] >= 1
            and all(not l.dead and l.wv == "vNEW" for l in router.links)
        ):
            router.pump(timeout=0.01)
            drained.extend(router.drain_ready())
        router.pump(timeout=0.01)
        for mb in mailboxes:
            mb.put({"type": "shutdown"})
        newbie_mailbox.put({"type": "shutdown"})

    def check():
        assert len(drained) == N, f"orders lost/duplicated: {drained}"
        assert all("error" not in d for d in drained), drained
        # No replica ever staged an unverified version.
        assert upgrade_msgs, "no replica was ever upgraded"
        assert all(m["version"] == "vNEW" for m in upgrade_msgs), (
            upgrade_msgs
        )
        # The respawn bootstrapped at the fleet's TARGET version — the
        # stale-weights regression this PR fixes.
        assert spawn_targets == [("ckpt", "vNEW")], spawn_targets
        assert up.state == "done", up.state
        assert up.stats["rollbacks"] == 0, up.stats
        # Fleet version re-derived exactly: every live link at the target.
        assert all(l.wv == "vNEW" for l in router.links), (
            [(l.name, l.wv) for l in router.links]
        )
        assert router.weight_target == ("ckpt", "vNEW")
        assert not router._inflight, "in-flight table leaked entries"

    return [
        client, pump,
        replica_body(0, mailboxes[0], "vOLD"),
        replica_body(1, mailboxes[1], "vOLD"),
        replica_body(1, newbie_mailbox, "vNEW"),
    ], check


def _pkg_files(*modnames: str) -> list[str]:
    import importlib

    return [
        _module_file(importlib.import_module(m)) for m in modnames
    ]


def _pkg_modules(*modnames: str) -> list:
    import importlib

    return [importlib.import_module(m) for m in modnames]


CANNED: dict[str, Scenario] = {
    "prefix_cache_contention": Scenario(
        name="prefix_cache_contention",
        setup=_scenario_prefix_cache,
        modules=lambda: _pkg_modules("transformer_tpu.serve.prefix_cache"),
        instrument=lambda: _pkg_files("transformer_tpu.serve.prefix_cache"),
        max_schedules=64,
    ),
    "kv_pool_contention": Scenario(
        name="kv_pool_contention",
        setup=_scenario_kv_pool,
        modules=lambda: _pkg_modules("transformer_tpu.kernels.kv_pool"),
        instrument=lambda: _pkg_files("transformer_tpu.kernels.kv_pool"),
        max_schedules=64,
    ),
    "registry_scrape_vs_create": Scenario(
        name="registry_scrape_vs_create",
        setup=_scenario_registry,
        modules=lambda: _pkg_modules("transformer_tpu.obs.registry"),
        instrument=lambda: _pkg_files("transformer_tpu.obs.registry"),
        max_schedules=64,
    ),
    "prefetch_shutdown": Scenario(
        name="prefetch_shutdown",
        setup=_scenario_prefetch,
        modules=lambda: _pkg_modules("transformer_tpu.data.pipeline"),
        instrument=lambda: _pkg_files("transformer_tpu.data.pipeline"),
        max_schedules=48,
    ),
    "eventlog_writers": Scenario(
        name="eventlog_writers",
        setup=_scenario_eventlog,
        modules=lambda: _pkg_modules("transformer_tpu.obs.events"),
        instrument=lambda: _pkg_files("transformer_tpu.obs.events"),
        max_schedules=64,
    ),
    "router_dispatch_tables": Scenario(
        name="router_dispatch_tables",
        setup=_scenario_router_tables,
        modules=lambda: _pkg_modules("transformer_tpu.serve.router"),
        instrument=lambda: _pkg_files("transformer_tpu.serve.router"),
        # 4 threads (client / router pump / 2 replicas): the tree is too
        # wide for bounded-exhaustive DFS — seeded-random distinct traces,
        # per the explorer's >2-thread policy.
        max_schedules=24,
        random_mode=True,
    ),
    "supervisor_respawn": Scenario(
        name="supervisor_respawn",
        setup=_scenario_supervisor_respawn,
        modules=lambda: _pkg_modules(
            "transformer_tpu.serve.router",
            "transformer_tpu.serve.supervisor",
        ),
        instrument=lambda: _pkg_files(
            "transformer_tpu.serve.router",
            "transformer_tpu.serve.supervisor",
        ),
        # 4 threads (client / pump+supervisor / survivor / replacement):
        # seeded-random distinct traces, per the explorer's >2-thread
        # policy.
        max_schedules=24,
        random_mode=True,
    ),
    "rolling_upgrade": Scenario(
        name="rolling_upgrade",
        setup=_scenario_rolling_upgrade,
        modules=lambda: _pkg_modules(
            "transformer_tpu.serve.router",
            "transformer_tpu.serve.supervisor",
            "transformer_tpu.serve.upgrade",
        ),
        instrument=lambda: _pkg_files(
            "transformer_tpu.serve.router",
            "transformer_tpu.serve.supervisor",
            "transformer_tpu.serve.upgrade",
        ),
        # 5 threads (client / pump+coordinator+supervisor / 2 replicas /
        # replacement): seeded-random distinct traces, >=64 per the
        # rolling-upgrade coverage bar (docs/ANALYSIS.md).
        max_schedules=64,
        random_mode=True,
    ),
}


def run_scenarios(
    names: Iterable[str] | None = None,
    max_schedules: int | None = None,
    seed: int = 0,
) -> list[ScenarioResult]:
    """Run the canned scenarios (all, or the named subset) and return their
    results — the ``python -m transformer_tpu.analysis schedules`` payload."""
    picked = list(names) if names else sorted(CANNED)
    out = []
    for name in picked:
        if name not in CANNED:
            raise KeyError(
                f"unknown scenario {name!r}; available: {sorted(CANNED)}"
            )
        out.append(explore(CANNED[name], max_schedules=max_schedules, seed=seed))
    return out
