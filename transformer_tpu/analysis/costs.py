"""Jaxpr-level resource cost model: bytes, FLOPs, and collective budgets.

The third analysis family (alongside rules/contracts/retrace): where the
retrace sentinel pins "the hot path compiles zero new programs" and the
contracts pin layouts, this module pins *resources* — statically, on CPU,
with zero device execution. Every canned program (the scheduler's
``_pool_step``/``_slot_prefill``/``_pool_verify``/``_slot_restore``, the
train step, and the explicit-collective sharded programs from
``analysis/sharding.py``) is traced with ``jax.make_jaxpr`` over abstract
inputs and measured:

- **peak_bytes** — peak live-buffer bytes via liveness over the equation
  list: non-donated inputs and constants are caller-held for the whole
  program, donated inputs and intermediates die at their last use, and a
  call-like equation (pjit/scan/while/cond/custom_vjp) contributes the max
  of its output bytes and its sub-jaxpr's own transient peak. This is a
  deterministic, hand-computable model of XLA's allocator, not a promise of
  its exact watermark — the point is that a +1-buffer regression moves the
  number by that buffer's size, every time, before any TPU sees the code.
- **flops** — 2·M·N·K per ``dot_general`` (batch dims multiplied through),
  2·|out|·(C_in/groups · prod(kernel)) per convolution, |operand| per
  ``reduce_*`` — the dot/conv/reduce accounting the arithmetic-intensity
  argument needs (Fast Transformer Decoding, PAPERS.md: decode is
  memory-bound precisely because this number is small per byte moved).
- **bytes_moved** — Σ over equations of operand + result bytes: an upper
  bound proxy for HBM traffic (XLA fuses; real traffic is lower — the
  model is for *regression deltas*, not absolute bandwidth claims).
- **arithmetic intensity** — flops / bytes_moved.
- **collectives** — the per-program collective inventory
  (``sharding.collective_inventory``): kind, mesh axis, scan-weighted
  count, estimated comm bytes. Single-chip serving programs pin the EMPTY
  set — a stray ``all_gather`` in the decode loop is a baseline failure,
  the static cousin of lint TPA204.

**KV budgets** — ``kv_cache_bytes`` prices the serve pool's dense
``max_len × slots`` KV layout per cache variant (plain/int8/rolling/GQA):
bytes per slot, bytes per token, and the MQA/GQA ratio the one-write-head
paper (PAPERS.md) argues from. This is the number the paged-KV refactor
(ROADMAP) will be measured against — today's waste, pinned in the repo.

**Baseline workflow** — ``analysis/costs_baseline.json`` stores every
program's gated numbers; ``python -m transformer_tpu.analysis costs``
fails when peak bytes or KV bytes-per-slot INCREASE or the collective set
grows (decreases are reported as improvements and only rewritten by
``--update-baseline``, same grandfather loop as the lint baselines).
FLOPs/bytes_moved are reported and diffed but not gated — they drift with
jax lowering versions; memory and collectives are the budgets that page
operators at 3am.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Iterable

from transformer_tpu.analysis.sharding import (
    _aval_bytes,
    _sub_jaxprs,
    canned_sharded_programs,
    collective_inventory,
)

# Primitives whose cost the FLOP model prices (the ISSUE's dot/conv/reduce
# scope — elementwise ops are bandwidth, not FLOP, stories).
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin",
})

# Call-like primitives: their params carry sub-jaxprs whose transient peak
# exceeds their output bytes (scan carries, pjit bodies).
_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "custom_jvp_call", "custom_vjp_call",
    "custom_vjp_call_jaxpr", "remat", "remat2", "checkpoint", "scan", "while",
    "cond", "shard_map", "custom_partitioning",
})


@dataclasses.dataclass
class CostReport:
    """Resource profile of one traced program."""

    name: str
    peak_bytes: int
    flops: int
    bytes_moved: int
    collectives: dict[str, dict[str, int]]
    arg_bytes: int
    out_bytes: int
    extras: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def intensity(self) -> float:
        return round(self.flops / self.bytes_moved, 4) if self.bytes_moved else 0.0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "peak_bytes": self.peak_bytes,
            "flops": self.flops,
            "bytes_moved": self.bytes_moved,
            "arithmetic_intensity": self.intensity,
            "collectives": self.collectives,
            "arg_bytes": self.arg_bytes,
            "out_bytes": self.out_bytes,
            **self.extras,
        }


# ==========================================================================
# per-equation FLOPs


def _dot_flops(eqn) -> int:
    ((lhs_c, rhs_c), (lhs_b, rhs_b)) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = 1
    for d in lhs_b:
        batch *= int(lhs[d])
    k = 1
    for d in lhs_c:
        k *= int(lhs[d])
    m = 1
    for i, d in enumerate(lhs):
        if i not in lhs_c and i not in lhs_b:
            m *= int(d)
    n = 1
    for i, d in enumerate(rhs):
        if i not in rhs_c and i not in rhs_b:
            n *= int(d)
    return 2 * batch * m * n * k


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval.shape
    rhs = eqn.invars[1].aval.shape  # kernel
    groups = int(eqn.params.get("feature_group_count", 1))
    out_size = 1
    for d in out:
        out_size *= int(d)
    # kernel = (spatial..., C_in/groups, C_out) in whatever dim order; the
    # product over all non-C_out dims is C_in/groups * prod(spatial).
    dn = eqn.params.get("dimension_numbers")
    rhs_spec = getattr(dn, "rhs_spec", None)
    if rhs_spec is not None:
        k_per_out = 1
        for i, d in enumerate(rhs):
            if i != rhs_spec[0]:  # rhs_spec[0] is the out-feature dim
                k_per_out *= int(d)
    else:
        k_per_out = 1
        for d in rhs:
            k_per_out *= int(d)
    del groups  # C_in/groups is already rhs's in-feature dim
    return 2 * out_size * k_per_out


def _eqn_flops(eqn) -> int:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name.startswith("conv_general"):
        return _conv_flops(eqn)
    if name in _REDUCE_PRIMS:
        return sum(
            _aval_bytes(v.aval) // max(1, _itemsize(v.aval))
            for v in eqn.invars
            if hasattr(v, "aval")
        )
    return 0


def _itemsize(aval) -> int:
    import numpy as np

    dtype = getattr(aval, "dtype", None)
    return np.dtype(dtype).itemsize if dtype is not None else 1


# ==========================================================================
# liveness / peak bytes


def _is_var(v) -> bool:
    import jax

    return not isinstance(v, jax.core.Literal)


def _peak_extra(jaxpr) -> int:
    """Transient peak of a sub-jaxpr counting ONLY its constants,
    intermediates, and outputs — the inputs are the caller's buffers and are
    already counted live at the call site."""
    persistent = sum(_aval_bytes(v.aval) for v in jaxpr.constvars)
    return persistent + _liveness_peak(jaxpr, initial_alive={})


def _liveness_peak(jaxpr, initial_alive: dict[Any, int]) -> int:
    """Max over equations of (alive-before + equation transient). ``alive``
    tracks buffers that die at their last use (donated inputs and
    intermediates); vars never entered into ``alive`` (non-donated inputs,
    a sub-jaxpr's inputs) are someone else's accounting."""
    out_set = {v for v in jaxpr.outvars if _is_var(v)}
    last_use: dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if _is_var(v):
                last_use[v] = i
    alive = dict(initial_alive)
    peak = sum(alive.values())
    for i, eqn in enumerate(jaxpr.eqns):
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        transient = out_bytes
        if eqn.primitive.name in _CALL_PRIMS:
            # max, not sum: _peak_extra already holds the sub-jaxpr's
            # outputs live at its end, and those ARE this call's outvars.
            for value in eqn.params.values():
                for sub in _sub_jaxprs(value):
                    transient = max(transient, _peak_extra(sub))
        peak = max(peak, sum(alive.values()) + transient)
        # outputs become live if anything later (or the caller) reads them
        for v in eqn.outvars:
            if v in out_set or last_use.get(v, -1) > i:
                alive[v] = _aval_bytes(v.aval)
        # buffers whose last use was this equation die (outputs survive)
        for v in list(alive):
            if v not in out_set and last_use.get(v, -1) <= i:
                del alive[v]
    return max(peak, sum(alive.values()))


def _pallas_grid_size(eqn) -> int:
    """Total grid steps of a ``pallas_call`` equation (1 if unknown)."""
    grid = getattr(eqn.params.get("grid_mapping"), "grid", None) or ()
    n = 1
    for d in grid:
        try:
            n *= int(d)
        except TypeError:  # symbolic / dynamic dims: leave unweighted
            return 1
    return max(1, n)


def _walk_eqns_hbm(jaxpr, weight: int = 1, in_kernel: bool = False):
    """``walk_eqns_weighted`` with Pallas awareness: yields ``(eqn, weight,
    in_kernel)``. A kernel BODY's equations run once per grid step (weight
    multiplied by the grid size — that is what their FLOPs cost), but their
    ref reads/writes move VMEM, not HBM: the ``pallas_call`` equation
    itself, priced once over its operands and outputs, is the program's HBM
    statement — exactly the proxy the gather path gets from its ``take``
    equations. (``pl.when``-guarded steps still count: the weighting is a
    static upper bound, same spirit as the scan trip-count multiply.)"""
    for eqn in jaxpr.eqns:
        yield eqn, weight, in_kernel
        mult = weight
        kernel = in_kernel
        if eqn.primitive.name == "scan":
            mult = weight * int(eqn.params.get("length", 1))
        elif eqn.primitive.name == "pallas_call":
            kernel = True
            mult = weight * _pallas_grid_size(eqn)
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                yield from _walk_eqns_hbm(sub, mult, kernel)


def pallas_call_flops(eqn, outer_weight: int = 1) -> int:
    """Grid-weighted FLOPs of ONE ``pallas_call`` equation, priced with the
    SAME walk/pricing helpers ``jaxpr_costs`` uses — the kernel verifier
    (analysis/kernels.py) reports this number, so the two families cannot
    drift (tests assert the totals agree eqn-for-eqn)."""
    total = 0
    mult = outer_weight * _pallas_grid_size(eqn)
    for value in eqn.params.values():
        for sub in _sub_jaxprs(value):
            for e, w, _ in _walk_eqns_hbm(sub, mult, True):
                total += w * _eqn_flops(e)
    return total


def jaxpr_costs(
    name: str,
    closed,
    donated_invars: set | None = None,
    axis_sizes: dict[str, int] | None = None,
) -> CostReport:
    """Cost report for a ClosedJaxpr. ``donated_invars`` is the set of
    top-level input Vars whose buffers the caller donates (they die at last
    use instead of living the whole program)."""
    jaxpr = closed.jaxpr
    donated = donated_invars or set()

    const_bytes = sum(_aval_bytes(v.aval) for v in jaxpr.constvars)
    arg_bytes = sum(_aval_bytes(v.aval) for v in jaxpr.invars)
    out_bytes = sum(
        _aval_bytes(v.aval) for v in jaxpr.outvars if hasattr(v, "aval")
    )
    held = sum(
        _aval_bytes(v.aval) for v in jaxpr.invars if v not in donated
    ) + const_bytes
    alive0 = {v: _aval_bytes(v.aval) for v in jaxpr.invars if v in donated}
    peak = held + _liveness_peak(jaxpr, initial_alive=alive0)

    flops = 0
    moved = 0
    for eqn, weight, in_kernel in _walk_eqns_hbm(jaxpr):
        flops += weight * _eqn_flops(eqn)
        if in_kernel or eqn.primitive.name in _CALL_PRIMS:
            # Call bodies are walked (don't double-count the call); Pallas
            # kernel bodies move VMEM, not HBM (the pallas_call equation
            # already priced the HBM side).
            continue
        moved += weight * (
            sum(_aval_bytes(v.aval) for v in eqn.invars if hasattr(v, "aval"))
            + sum(_aval_bytes(v.aval) for v in eqn.outvars)
        )
    return CostReport(
        name=name,
        peak_bytes=int(peak),
        flops=int(flops),
        bytes_moved=int(moved),
        collectives=collective_inventory(closed, axis_sizes),
        arg_bytes=int(arg_bytes),
        out_bytes=int(out_bytes),
    )


def program_costs(
    name: str,
    fn: Callable,
    *args,
    donate_argnums: Iterable[int] = (),
    axis_sizes: dict[str, int] | None = None,
) -> CostReport:
    """Trace ``fn`` over abstract ``args`` (ShapeDtypeStructs — zero device
    execution) and price the jaxpr. ``donate_argnums`` mirrors ``jax.jit``
    donation: those arguments' flattened leaves die at last use."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    donated: set = set()
    donate = set(donate_argnums)
    if donate:
        flat_counts = [len(jax.tree_util.tree_leaves(a)) for a in args]
        offset = 0
        invars = closed.jaxpr.invars
        for i, count in enumerate(flat_counts):
            if i in donate:
                donated.update(invars[offset : offset + count])
            offset += count
    return jaxpr_costs(name, closed, donated, axis_sizes)


# ==========================================================================
# KV budgets


def kv_cache_bytes(cfg, max_total: int) -> dict[str, Any]:
    """Device bytes of ONE slot's dense KV cache (every per-position buffer
    in the cache's own storage layout — int8 codes + fp32 scales, GQA head
    counts, rolling-window buffer lengths), plus the derived per-token
    cost. This is the ``max_len × slots`` waste the paged-KV refactor will
    be measured against."""
    import jax

    from transformer_tpu.models.decoder import init_decoder_caches
    from transformer_tpu.ops.attention import kv_buffer_keys

    caches = jax.eval_shape(lambda: init_decoder_caches(cfg, 1, max_total))
    per_slot = 0
    buf_len = max_total
    for layer in caches:
        for key in kv_buffer_keys(layer):
            aval = layer[key]
            per_slot += _aval_bytes(aval)
            buf_len = int(aval.shape[1])
    return {
        "bytes_per_slot": int(per_slot),
        "bytes_per_token": int(per_slot // max(1, buf_len)),
        "buffer_tokens": buf_len,
        "max_total": max_total,
        "layers": len(caches),
    }


def kv_pool_bytes(
    cfg, max_total: int, num_slots: int, pool_blocks: int, block_tokens: int
) -> dict[str, Any]:
    """Device bytes of the PAGED pool amortized per slot: the pool is
    shared, so bytes/slot = pool bytes / slots — the number that must be
    SMALLER than the dense ``kv_cache_bytes`` figure whenever the pool is
    provisioned below ``slots x max_total`` (the refactor's banked win;
    gated per paged program via ``kv_bytes_per_slot``)."""
    import jax

    from transformer_tpu.ops.attention import init_block_pool, kv_buffer_keys

    pool = jax.eval_shape(
        lambda: [
            init_block_pool(
                pool_blocks, block_tokens, cfg.kv_heads, cfg.head_dim,
                cfg.compute_dtype, quantize=cfg.kv_cache_int8,
            )
            for _ in range(cfg.num_layers)
        ]
    )
    total = sum(
        _aval_bytes(layer[key]) for layer in pool for key in kv_buffer_keys(layer)
    )
    return {
        "bytes_per_slot": int(total // max(1, num_slots)),
        "bytes_per_token": int(
            total // max(1, pool_blocks * block_tokens)
        ),
        "pool_bytes": int(total),
        "pool_blocks": pool_blocks,
        "block_tokens": block_tokens,
        "max_total": max_total,
        "layers": len(pool),
    }


# ==========================================================================
# canned programs


_SERVE_SLOTS = 2
_SERVE_TOTAL = 32
_VERIFY_W = 4
_PREFILL_LEN = 8
_RESTORE_BLOCK = 4
# Paged-pool canned sizing (the banked WIN): blocks of _PAGED_BLOCK tokens,
# pool provisioned for HALF the dense worst case — slot cost proportional
# to used tokens is the whole point, and the budget gate fails if a
# regression re-densifies it (kv_bytes_per_slot increase).
_PAGED_BLOCK = 8
_PAGED_POOL_BLOCKS = 1 + _SERVE_SLOTS * (_SERVE_TOTAL // 2 // _PAGED_BLOCK)

# The serving cache variants (analysis/configs.py FAST_MATRIX): plain bf16,
# int8+scales, rolling window, grouped-query.
SERVE_VARIANTS = ("lm_bf16", "lm_int8_cache", "lm_window", "lm_gqa")
# Paged layout refuses rolling windows (absolute-position rows are evicted
# on wrap) — the other three variants store their layouts inside blocks.
PAGED_VARIANTS = ("lm_bf16", "lm_int8_cache", "lm_gqa")


def _abstract_model(cfg):
    import jax
    import numpy as np

    from transformer_tpu.models.transformer import transformer_init

    key = jax.ShapeDtypeStruct((2,), np.uint32)
    return jax.eval_shape(lambda k: transformer_init(k, cfg), key)


def canned_cost_reports() -> tuple[list[CostReport], list[str]]:
    """Cost reports for every canned program, plus the names skipped on
    this host (sharded programs need >= 2 devices)."""
    import jax
    import numpy as np

    from transformer_tpu.analysis.configs import FAST_MATRIX, TINY_TRAIN
    from transformer_tpu.models.decoder import init_decoder_caches
    from transformer_tpu.ops.attention import slice_kv_blocks
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import abstract_pool_caches

    reports: list[CostReport] = []
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.int32)  # noqa: E731

    # -- the decode hot loop, per cache variant -----------------------------
    for variant in SERVE_VARIANTS:
        cfg = FAST_MATRIX[variant]
        params = _abstract_model(cfg)
        pool = abstract_pool_caches(cfg, _SERVE_SLOTS, _SERVE_TOTAL)
        step_raw = sched._pool_step.__wrapped__
        r = program_costs(
            f"serve.pool_step[{variant}]",
            lambda p, c, t: step_raw(p, c, t, cfg),
            params, pool, i32(_SERVE_SLOTS),
            donate_argnums=(1,),  # mirrors _pool_step's donate_argnums=(1,)
        )
        kv = kv_cache_bytes(cfg, _SERVE_TOTAL)
        r.extras["kv_bytes_per_slot"] = kv["bytes_per_slot"]
        reports.append(r)

    # -- the PAGED decode hot loop, per non-rolling variant -----------------
    # kv_bytes_per_slot here is the banked paged-KV win: the pool is
    # provisioned for half the dense worst case, so a regression that
    # re-densifies the layout (or silently re-inflates the pool) fails the
    # budget gate the moment it lands.
    from transformer_tpu.serve.scheduler import abstract_paged_pool

    for variant in PAGED_VARIANTS:
        cfg = FAST_MATRIX[variant]
        params = _abstract_model(cfg)
        pool, table, index = abstract_paged_pool(
            cfg, _SERVE_SLOTS, _SERVE_TOTAL, _PAGED_POOL_BLOCKS, _PAGED_BLOCK
        )
        step_raw = sched._pool_step_paged.__wrapped__
        r = program_costs(
            f"serve.pool_step_paged[{variant}]",
            lambda p, c, tb, ix, t: step_raw(
                p, c, tb, ix, t, cfg, _PAGED_BLOCK, _SERVE_TOTAL
            ),
            params, pool, table, index, i32(_SERVE_SLOTS),
            donate_argnums=(1,),
        )
        r.extras["kv_bytes_per_slot"] = kv_pool_bytes(
            cfg, _SERVE_TOTAL, _SERVE_SLOTS, _PAGED_POOL_BLOCKS, _PAGED_BLOCK
        )["bytes_per_slot"]
        reports.append(r)

    # -- the FUSED paged decode hot loop (--decode_kernel paged_flash) ------
    # Same shapes and donation as the gather twins, but attention reads the
    # pool buffers in place through the block table and the dense-FFN
    # sublayer is one Pallas kernel: the dense-ordered gathered view (one
    # full pool pass written then re-read per step) and the per-sublayer HBM
    # round trips are gone from the program, so bytes_moved DROPS vs
    # serve.pool_step_paged[...]. compare_to_baseline enforces the drop
    # STRUCTURALLY (fused < gather, per variant) on the live reports — not
    # just against the banked numbers — so un-fusing the path can never land
    # silently. interpret=False prices the real TPU program; tracing never
    # lowers, so no TPU is needed here.
    for variant in PAGED_VARIANTS:
        cfg = FAST_MATRIX[variant]
        params = _abstract_model(cfg)
        pool, table, index = abstract_paged_pool(
            cfg, _SERVE_SLOTS, _SERVE_TOTAL, _PAGED_POOL_BLOCKS, _PAGED_BLOCK
        )
        flash_raw = sched._pool_step_paged_flash.__wrapped__
        r = program_costs(
            f"serve.pool_step_paged_flash[{variant}]",
            lambda p, c, tb, ix, t: flash_raw(
                p, c, tb, ix, t, cfg, _PAGED_BLOCK, False
            ),
            params, pool, table, index, i32(_SERVE_SLOTS),
            donate_argnums=(1,),
        )
        r.extras["kv_bytes_per_slot"] = kv_pool_bytes(
            cfg, _SERVE_TOTAL, _SERVE_SLOTS, _PAGED_POOL_BLOCKS, _PAGED_BLOCK
        )["bytes_per_slot"]
        reports.append(r)

    cfg = FAST_MATRIX["lm_bf16"]
    params = _abstract_model(cfg)
    pool, table, index = abstract_paged_pool(
        cfg, _SERVE_SLOTS, _SERVE_TOTAL, _PAGED_POOL_BLOCKS, _PAGED_BLOCK
    )
    prefill_paged_raw = sched._slot_prefill_paged.__wrapped__
    reports.append(
        program_costs(
            f"serve.slot_prefill_paged[lm_bf16,n={_PREFILL_LEN}]",
            lambda p, c, tb, s, pr, st: prefill_paged_raw(
                p, c, tb, s, pr, st, cfg, 0, _PAGED_BLOCK, _SERVE_TOTAL
            ),
            params, pool, table, i32(), i32(1, _PREFILL_LEN), i32(),
        )
    )

    # -- admission, verify, restore (plain variant: the structural shapes
    # are identical across variants; the per-variant BYTES are covered by
    # the pool_step + kv_cache sections above) ------------------------------
    cfg = FAST_MATRIX["lm_bf16"]
    params = _abstract_model(cfg)
    pool = abstract_pool_caches(cfg, _SERVE_SLOTS, _SERVE_TOTAL)

    prefill_raw = sched._slot_prefill.__wrapped__
    reports.append(
        program_costs(
            f"serve.slot_prefill[lm_bf16,n={_PREFILL_LEN}]",
            lambda p, c, s, pr, st: prefill_raw(p, c, s, pr, st, cfg, 0),
            params, pool, i32(), i32(1, _PREFILL_LEN), i32(),
        )
    )

    verify_raw = sched._pool_verify.__wrapped__
    reports.append(
        program_costs(
            f"serve.pool_verify[lm_bf16,W={_VERIFY_W}]",
            lambda p, c, t: verify_raw(p, c, t, cfg),
            params, pool, i32(_SERVE_SLOTS, _VERIFY_W),
            donate_argnums=(1,),
        )
    )

    restore_raw = sched._slot_restore.__wrapped__
    blocks = jax.eval_shape(
        lambda: [
            slice_kv_blocks(c, 0, _RESTORE_BLOCK)
            for c in init_decoder_caches(cfg, 1, _SERVE_TOTAL)
        ]
    )
    reports.append(
        program_costs(
            f"serve.slot_restore[lm_bf16,blocks={_RESTORE_BLOCK}]",
            lambda c, s, b: restore_raw(c, s, b),
            pool, i32(), blocks,
        )
    )

    # -- the train step -----------------------------------------------------
    reports.append(train_step_costs(cfg, TINY_TRAIN, name="train.step[lm_bf16]"))

    # -- sharded programs (explicit collectives) ----------------------------
    programs, skipped = canned_sharded_programs()
    for name, (fn, args, axis_sizes) in programs.items():
        reports.append(program_costs(name, fn, *args, axis_sizes=axis_sizes))
    return reports, skipped


def train_step_costs(cfg, train_cfg, name: str = "train.step") -> CostReport:
    """Abstract one-optimizer-step cost (the prediction ``obs summarize``
    cross-checks against recorded ``device.memory_stats()`` samples)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from transformer_tpu.train.state import TrainState, make_optimizer
    from transformer_tpu.train.trainer import make_train_step

    step_fn = make_train_step(cfg, train_cfg)
    params = _abstract_model(cfg)
    tx = make_optimizer(cfg, train_cfg)
    state = jax.eval_shape(
        lambda p: TrainState(step=jnp.int32(0), params=p, opt_state=tx.init(p)),
        params,
    )
    B, L = train_cfg.batch_size, train_cfg.sequence_length
    ids = jax.ShapeDtypeStruct((B, L), np.int32)
    key = jax.ShapeDtypeStruct((2,), np.uint32)
    # donate_argnums=(0,) mirrors the Trainer's jit (trainer.py,
    # donate_state=True default): the incoming state's buffers are updated
    # in place, so they must not be double-counted against the new state.
    r = program_costs(name, step_fn, state, ids, ids, key, donate_argnums=(0,))
    r.extras["tokens_per_step"] = B * L
    return r


# ==========================================================================
# baseline workflow


def default_costs_baseline_path() -> str:
    from transformer_tpu.analysis.baselines import _package_root

    return os.path.join(_package_root(), "analysis", "costs_baseline.json")


def load_costs_baseline(path: str | None) -> dict:
    if path is None or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_costs_baseline(
    reports: list[CostReport],
    kv: dict[str, dict],
    path: str,
    keep: dict[str, dict] | None = None,
) -> None:
    """Write the budget baseline. ``keep`` carries forward existing program
    entries that this host could not reproduce (skipped for insufficient
    devices) — an update on a small host must not silently drop the
    sharded programs' collective budgets from CI."""
    payload = {
        "programs": {
            **(keep or {}),
            **{r.name: {
                "peak_bytes": r.peak_bytes,
                "flops": r.flops,
                "bytes_moved": r.bytes_moved,
                "collectives": {
                    k: v["count"] for k, v in sorted(r.collectives.items())
                },
                **(
                    {"kv_bytes_per_slot": r.extras["kv_bytes_per_slot"]}
                    if "kv_bytes_per_slot" in r.extras
                    else {}
                ),
            }
            for r in reports
            },
        },
        "kv_cache": {
            variant: {
                "bytes_per_slot": entry["bytes_per_slot"],
                "bytes_per_token": entry["bytes_per_token"],
            }
            for variant, entry in sorted(kv.items())
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


@dataclasses.dataclass
class CostsResult:
    reports: list[CostReport]
    kv: dict[str, dict]
    skipped: list[str]
    regressions: list[str]
    notes: list[str]

    @property
    def ok(self) -> bool:
        return not self.regressions

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "programs": [r.to_dict() for r in self.reports],
            "kv_cache": self.kv,
            "skipped": self.skipped,
            "regressions": self.regressions,
            "notes": self.notes,
        }


def compare_to_baseline(
    reports: list[CostReport],
    kv: dict[str, dict],
    baseline: dict,
    skipped: Iterable[str] = (),
) -> tuple[list[str], list[str]]:
    """(regressions, notes). Gated: program peak_bytes increases, KV
    bytes-per-slot/-token increases, collective-set growth (new kind/axis or
    count increase), lost or unbaselined coverage. Advisory: decreases and
    FLOP / bytes_moved drift in either direction."""
    regressions: list[str] = []
    notes: list[str] = []
    base_programs = baseline.get("programs", {})
    seen = set()
    for r in reports:
        seen.add(r.name)
        base = base_programs.get(r.name)
        if base is None:
            regressions.append(
                f"{r.name}: not in the baseline — new programs must be "
                "budgeted (run --update-baseline and commit the diff)"
            )
            continue
        if r.peak_bytes > base["peak_bytes"]:
            regressions.append(
                f"{r.name}: peak_bytes {r.peak_bytes} > budget "
                f"{base['peak_bytes']} (+{r.peak_bytes - base['peak_bytes']})"
            )
        elif r.peak_bytes < base["peak_bytes"]:
            notes.append(
                f"{r.name}: peak_bytes improved {base['peak_bytes']} -> "
                f"{r.peak_bytes} (--update-baseline to bank it)"
            )
        kv_budget = base.get("kv_bytes_per_slot")
        kv_now = r.extras.get("kv_bytes_per_slot")
        if kv_budget is not None and kv_now is not None and kv_now > kv_budget:
            regressions.append(
                f"{r.name}: kv_bytes_per_slot {kv_now} > budget {kv_budget}"
            )
        base_coll = base.get("collectives", {})
        now_coll = {k: v["count"] for k, v in r.collectives.items()}
        for key, count in sorted(now_coll.items()):
            if key not in base_coll:
                regressions.append(
                    f"{r.name}: stray collective {key} (x{count}) — not in "
                    "the budgeted set"
                )
            elif count > base_coll[key]:
                regressions.append(
                    f"{r.name}: collective {key} count {count} > budget "
                    f"{base_coll[key]}"
                )
        for key in sorted(set(base_coll) - set(now_coll)):
            notes.append(f"{r.name}: collective {key} no longer issued")
        for field in ("flops", "bytes_moved"):
            now, was = getattr(r, field), base.get(field)
            if was is not None and now != was:
                notes.append(f"{r.name}: {field} {was} -> {now} (advisory)")
    # Structural fusion gate: every fused paged step must move strictly
    # fewer bytes than its gather twin — the eliminated dense-view HBM pass
    # is THE banked win of the paged_flash kernels, and unlike the advisory
    # per-program bytes_moved drift, the fused-vs-gather ORDERING is a
    # property of the program structure, not of jax lowering versions.
    by_name = {r.name: r for r in reports}
    for name in sorted(by_name):
        if not name.startswith("serve.pool_step_paged_flash["):
            continue
        twin = by_name.get(
            name.replace("pool_step_paged_flash", "pool_step_paged")
        )
        if twin is not None and by_name[name].bytes_moved >= twin.bytes_moved:
            regressions.append(
                f"{name}: bytes_moved {by_name[name].bytes_moved} >= gather "
                f"twin's {twin.bytes_moved} ({twin.name}) — the fused kernel "
                "no longer eliminates the gathered-view HBM pass"
            )
    # Structural sharded-serving gate: the --mesh serving programs are
    # collective-free BY CONSTRUCTION (params replicate, the pool shards a
    # batch-like storage axis — serve/sharded.py) and their byte-parity
    # guarantee depends on it. Like the fused-vs-gather ordering, this is a
    # property of the program structure: even a baselined count would be
    # wrong, so any explicit collective here fails regardless of what the
    # baseline says. (GSPMD-inserted collectives are gated on the compiled
    # HLO in run_costs — tracing cannot see them.)
    for name in sorted(by_name):
        if not (name.startswith("serve.") and "mesh=" in name):
            continue
        if by_name[name].collectives:
            kinds = ", ".join(sorted(by_name[name].collectives))
            regressions.append(
                f"{name}: explicit collective(s) in the sharded serving hot "
                f"loop ({kinds}) — the --mesh byte-parity layout forbids "
                "them (serve/sharded.py)"
            )
    skipped = set(skipped)
    for name in sorted(set(base_programs) - seen):
        if name in skipped:
            notes.append(f"{name}: skipped on this host (insufficient devices)")
        else:
            regressions.append(
                f"{name}: in the baseline but no longer produced — budget "
                "coverage lost"
            )
    base_kv = baseline.get("kv_cache", {})
    for variant, entry in sorted(kv.items()):
        base_entry = base_kv.get(variant)
        if base_entry is None:
            regressions.append(
                f"kv_cache[{variant}]: not in the baseline — run "
                "--update-baseline"
            )
            continue
        for field in ("bytes_per_slot", "bytes_per_token"):
            if entry[field] > base_entry[field]:
                regressions.append(
                    f"kv_cache[{variant}]: {field} {entry[field]} > budget "
                    f"{base_entry[field]}"
                )
            elif entry[field] < base_entry[field]:
                notes.append(
                    f"kv_cache[{variant}]: {field} improved "
                    f"{base_entry[field]} -> {entry[field]}"
                )
    return regressions, notes


def run_costs(
    baseline_path: str | None = None, compare: bool = True
) -> CostsResult:
    """Compute every canned cost report + KV budget and (optionally) diff
    against the checked-in baseline."""
    from transformer_tpu.analysis.configs import FAST_MATRIX

    reports, skipped = canned_cost_reports()
    kv = {
        variant: kv_cache_bytes(FAST_MATRIX[variant], _SERVE_TOTAL)
        for variant in SERVE_VARIANTS
    }
    kv.update({
        f"{variant}_paged": kv_pool_bytes(
            FAST_MATRIX[variant], _SERVE_TOTAL, _SERVE_SLOTS,
            _PAGED_POOL_BLOCKS, _PAGED_BLOCK,
        )
        for variant in PAGED_VARIANTS
    })
    regressions: list[str] = []
    notes: list[str] = []
    if compare:
        if baseline_path is None:
            baseline_path = default_costs_baseline_path()
        baseline = load_costs_baseline(baseline_path)
        if baseline:
            regressions, notes = compare_to_baseline(
                reports, kv, baseline, skipped
            )
        else:
            notes.append(
                f"no baseline at {baseline_path} — run --update-baseline "
                "to pin budgets"
            )
        # Compiled-HLO collective gate (analysis/sharding.py): GSPMD
        # partitions AFTER tracing, so a collective it inserts into the
        # sharded decode step is invisible to every jaxpr-level number
        # above. Compile the dense mesh-2 decode twins for real and fail
        # hard on any collective op in the HLO text.
        from transformer_tpu.analysis.sharding import serving_hlo_collectives

        hlo_inventory, hlo_skipped = serving_hlo_collectives()
        for name, found in sorted(hlo_inventory.items()):
            if found:
                regressions.append(
                    f"{name}: GSPMD-inserted collective(s) in the COMPILED "
                    "decode step: "
                    + ", ".join(
                        f"{k} x{v}" for k, v in sorted(found.items())
                    )
                    + " — the sharded serving hot loop must stay "
                    "collective-free (serve/sharded.py)"
                )
            else:
                notes.append(f"{name}: compiled HLO collective-free")
        for name in hlo_skipped:
            notes.append(
                f"{name}: compiled-HLO collective gate skipped "
                "(insufficient devices)"
            )
    return CostsResult(
        reports=reports, kv=kv, skipped=skipped,
        regressions=regressions, notes=notes,
    )


def summarize(result: CostsResult) -> str:
    lines = []
    for r in result.reports:
        coll = (
            ", ".join(f"{k} x{v['count']}" for k, v in sorted(r.collectives.items()))
            or "none"
        )
        lines.append(
            f"{r.name}: peak {_fmt_bytes(r.peak_bytes)}, "
            f"{_fmt_count(r.flops)} FLOPs, {_fmt_bytes(r.bytes_moved)} moved "
            f"(intensity {r.intensity}), collectives: {coll}"
        )
    for variant, entry in sorted(result.kv.items()):
        if "pool_blocks" in entry:
            geom = (
                f"pool {entry['pool_blocks']} x {entry['block_tokens']}-token "
                f"blocks, max_total {entry['max_total']}"
            )
        else:
            geom = (
                f"buffer {entry['buffer_tokens']} of max_total "
                f"{entry['max_total']}"
            )
        lines.append(
            f"kv_cache[{variant}]: {_fmt_bytes(entry['bytes_per_slot'])}/slot, "
            f"{_fmt_bytes(entry['bytes_per_token'])}/token ({geom})"
        )
    for s in result.skipped:
        lines.append(f"SKIP {s} (needs >= 2 devices)")
    for n in result.notes:
        lines.append(f"note: {n}")
    for reg in result.regressions:
        lines.append(f"REGRESSION: {reg}")
    lines.append(
        f"{len(result.reports)} program(s), {len(result.regressions)} "
        f"regression(s)"
    )
    return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def _fmt_count(n: int) -> str:
    for unit in ("", "K", "M", "G", "T"):
        if abs(n) < 1000 or unit == "T":
            return f"{n:.1f}{unit}" if unit else str(n)
        n /= 1000
    return str(n)
