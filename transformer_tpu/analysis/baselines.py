"""Shared finding/fingerprint/suppression/baseline plumbing for every lint
family (TPA001–007 rules, TPA101–105 concurrency, TPA201–205 sharding).

Extracted from ``analysis/rules.py`` so a new rule family costs one module,
not a re-implementation of the workflow: a :class:`Finding` with a
line-number-free fingerprint, inline ``# tpa: disable=CODE`` suppressions,
and a checked-in JSON baseline with the ``--update-baseline`` grandfather
loop. Behavior is pinned bit-identical to the pre-extraction code by the
existing tests in ``tests/test_analysis.py`` (fingerprint format, baseline
JSON schema, suppression grammar are all load-bearing — baselines checked
into the repo reference them).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Iterable

# Inline suppression grammar: `# tpa: disable` (blanket) or
# `# tpa: disable=TPA001,TPA006 — reason` (listed codes only).
_SUPPRESS_RE = re.compile(r"#\s*tpa:\s*disable(?:\s*=\s*([A-Z0-9_,\s]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. ``fingerprint`` is line-number-free (code + file +
    enclosing symbol + stripped source text) so baselines survive unrelated
    edits above the finding."""

    code: str
    path: str
    line: int
    symbol: str
    message: str
    snippet: str

    @property
    def fingerprint(self) -> str:
        return f"{self.code}:{self.path}:{self.symbol}:{self.snippet}"

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint,
        }

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} [{self.symbol}] {self.message}"


@dataclasses.dataclass
class RulesReport:
    findings: list[Finding]
    baselined: list[Finding]
    files_checked: int

    @property
    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.code] = out.get(f.code, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "files_checked": self.files_checked,
            "counts": self.counts,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
        }


def line_suppressed(lines: list[str], finding: Finding) -> bool:
    """Is ``finding`` suppressed by a ``# tpa: disable`` comment on its own
    line? (``lines`` is the module source, pre-split.)"""
    if not 0 < finding.line <= len(lines):
        return False
    m = _SUPPRESS_RE.search(lines[finding.line - 1])
    if not m:
        return False
    codes = m.group(1)
    if codes is None:
        return True  # blanket `# tpa: disable`
    return finding.code in {c.strip() for c in codes.split(",")}


def _package_root() -> str:
    import transformer_tpu

    return os.path.dirname(os.path.abspath(transformer_tpu.__file__))


def load_baseline(path: str | None) -> dict[str, str]:
    """fingerprint -> justification. Missing file = empty baseline."""
    if path is None or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: dict[str, str] = {}
    for entry in data.get("findings", []):
        out[entry["fingerprint"]] = entry.get("reason", "")
    return out


def write_baseline(report: RulesReport, path: str, reason: str = "grandfathered") -> None:
    """Persist every current finding as the new baseline (the `--update-
    baseline` workflow: lint, eyeball, grandfather what stays)."""
    payload = {
        "findings": [
            {"fingerprint": f.fingerprint, "reason": reason, "line": f.line}
            for f in (*report.findings, *report.baselined)
        ]
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def _iter_py_files(paths: Iterable[str]) -> Iterable[tuple[str, str]]:
    """(abs_path, display_path) for every .py under ``paths``."""
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            yield p, os.path.basename(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    full = os.path.join(dirpath, fname)
                    yield full, os.path.relpath(full, os.path.dirname(p))
