// Native subword tokenizer: BPE training + greedy longest-match encode.
//
// C++ twin of transformer_tpu/data/tokenizer.py (the reference implementation
// and fallback) — the capability counterpart of the native tokenizer the
// reference inherits from tfds (`SubwordTextEncoder.build_from_corpus`,
// reference utils.py:96-111, implemented in TF's C++/py runtime). Both paths
// must produce bit-identical vocabularies and id sequences; tests/test_native.py
// asserts parity.
//
// Conventions (mirroring tokenizer.py):
//   - id 0 is pad and never produced; piece ids run 1..n_pieces.
//   - each whitespace-split word is escaped per codepoint ('_' -> "\u",
//     '\\' -> "\\\\", '<' -> "\<") and suffixed with the word-end marker '_'.
//   - unseen codepoints fall back to byte tokens "<0xNN>", always in the
//     alphabet.
//
// The API crosses the C boundary with '\n'-joined words/pieces: words and
// pieces can never contain whitespace (words are whitespace-split upstream and
// escapes introduce none), so '\n' is an unambiguous separator.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

inline size_t utf8_len(unsigned char lead) {
  if (lead < 0x80) return 1;
  if (lead < 0xE0) return 2;  // 0xC0..0xDF
  if (lead < 0xF0) return 3;
  if (lead < 0xF8) return 4;
  return 1;  // invalid lead byte: consume one byte
}

// Escape one word and append the word-end marker, exactly like
// tokenizer._word_to_symbols joined: per-codepoint escaping of '_', '\\', '<'.
void append_escaped_word(const std::string &word, std::string *out) {
  size_t i = 0;
  while (i < word.size()) {
    unsigned char c = word[i];
    if (c == '_') {
      *out += "\\u";
      ++i;
    } else if (c == '\\') {
      *out += "\\\\";
      ++i;
    } else if (c == '<') {
      *out += "\\<";
      ++i;
    } else {
      size_t L = std::min(utf8_len(c), word.size() - i);
      out->append(word, i, L);
      i += L;
    }
  }
  out->push_back('_');
}

// ----------------------------------------------------------------- encoder

struct TrieNode {
  std::unordered_map<uint8_t, int32_t> kids;
  int32_t piece_id = 0;  // 0 = not a piece end
};

struct Tokenizer {
  std::vector<std::string> pieces;  // index i -> id i+1
  std::vector<TrieNode> trie;      // node 0 = root; byte-labelled edges
  int32_t byte_ids[256];

  void build_index() {
    trie.clear();
    trie.emplace_back();
    for (size_t i = 0; i < pieces.size(); ++i) {
      int32_t node = 0;
      for (unsigned char c : pieces[i]) {
        auto it = trie[node].kids.find(c);
        if (it == trie[node].kids.end()) {
          trie.emplace_back();
          int32_t nn = static_cast<int32_t>(trie.size()) - 1;
          trie[node].kids.emplace(c, nn);
          node = nn;
        } else {
          node = it->second;
        }
      }
      trie[node].piece_id = static_cast<int32_t>(i) + 1;
    }
    char buf[8];
    for (int b = 0; b < 256; ++b) {
      std::snprintf(buf, sizeof buf, "<0x%02X>", b);
      byte_ids[b] = find_piece(buf);
    }
  }

  int32_t find_piece(const char *s) const {
    int32_t node = 0;
    for (const char *p = s; *p; ++p) {
      auto it = trie[node].kids.find(static_cast<uint8_t>(*p));
      if (it == trie[node].kids.end()) return 0;
      node = it->second;
    }
    return trie[node].piece_id;
  }

  // Greedy longest match over the escaped word string. A trie walk finds the
  // longest matching piece in bytes; since pieces are valid UTF-8 and matching
  // starts at a codepoint boundary, longest-in-bytes == longest-in-codepoints,
  // i.e. identical to the Python scan over text[i:j] char slices.
  void encode_escaped(const std::string &text, std::vector<int32_t> *out) const {
    size_t i = 0, n = text.size();
    while (i < n) {
      int32_t node = 0, best_id = 0;
      size_t best_end = i, j = i;
      while (j < n) {
        auto it = trie[node].kids.find(static_cast<uint8_t>(text[j]));
        if (it == trie[node].kids.end()) break;
        node = it->second;
        ++j;
        if (trie[node].piece_id) {
          best_id = trie[node].piece_id;
          best_end = j;
        }
      }
      if (best_id) {
        out->push_back(best_id);
        i = best_end;
      } else {
        size_t L = std::min(utf8_len(static_cast<unsigned char>(text[i])), n - i);
        for (size_t k = 0; k < L; ++k)
          out->push_back(byte_ids[static_cast<uint8_t>(text[i + k])]);
        i += L;
      }
    }
  }
};

// ----------------------------------------------------------------- trainer

// Interned symbol strings: pair comparisons in the merge heap must order by
// the *string* contents (matching Python's tuple comparison of str pairs,
// which UTF-8 byte order reproduces exactly).
struct StrPool {
  std::vector<std::string> strs;
  std::unordered_map<std::string, int32_t> ids;

  int32_t get(const std::string &s) {
    auto it = ids.find(s);
    if (it != ids.end()) return it->second;
    strs.push_back(s);
    int32_t id = static_cast<int32_t>(strs.size()) - 1;
    ids.emplace(s, id);
    return id;
  }
};

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

struct HeapEntry {
  int64_t count;
  int32_t a, b;
};

struct Trainer {
  StrPool pool;
  std::vector<std::vector<int32_t>> words;
  std::vector<int64_t> freqs;
  std::unordered_map<uint64_t, int64_t> pair_counts;
  std::unordered_map<uint64_t, std::unordered_set<int32_t>> pair_words;

  struct Cmp {
    const StrPool *pool;
    // priority_queue top = "largest": highest count first, then the
    // lexicographically smallest (a, b) string pair (heapq pops min of
    // (-count, pair)).
    bool operator()(const HeapEntry &x, const HeapEntry &y) const {
      if (x.count != y.count) return x.count < y.count;
      int c = pool->strs[x.a].compare(pool->strs[y.a]);
      if (c != 0) return c > 0;
      return pool->strs[x.b].compare(pool->strs[y.b]) > 0;
    }
  };
  std::priority_queue<HeapEntry, std::vector<HeapEntry>, Cmp> heap;

  Trainer() : heap(Cmp{&pool}) {}

  void bump(int32_t a, int32_t b, int64_t delta, int32_t wi) {
    uint64_t key = pair_key(a, b);
    auto it = pair_counts.find(key);
    int64_t c = (it == pair_counts.end() ? 0 : it->second) + delta;
    if (c <= 0) {
      if (it != pair_counts.end()) pair_counts.erase(it);
    } else {
      pair_counts[key] = c;
      heap.push({c, a, b});
    }
    if (delta > 0) pair_words[key].insert(wi);
  }

  // corpus: '\n'-joined *unique* words in first-occurrence order (Counter
  // insertion order upstream), with a parallel frequency array — so the
  // payload is O(unique words), not O(corpus tokens).
  Tokenizer *train(const char *corpus, int64_t len, const int64_t *counts,
                   int64_t n_words, int32_t target_vocab,
                   int32_t min_pair_count) {
    std::vector<std::string> uniq;
    std::vector<int64_t> uniq_freq;
    uniq.reserve(static_cast<size_t>(n_words));
    uniq_freq.reserve(static_cast<size_t>(n_words));
    {
      const char *p = corpus, *end = corpus + len;
      int64_t wi = 0;
      while (p < end && wi < n_words) {
        const char *nl = static_cast<const char *>(memchr(p, '\n', end - p));
        size_t wl = (nl ? nl : end) - p;
        if (wl > 0) {
          uniq.emplace_back(p, wl);
          uniq_freq.push_back(counts[wi]);
          ++wi;
        }
        p = nl ? nl + 1 : end;
      }
    }

    // Alphabet, insertion-ordered: 256 byte tokens, the three escape pieces,
    // the word-end marker, then every symbol as first seen across words.
    std::vector<int32_t> vocab_order;
    std::unordered_set<int32_t> vocab_set;
    auto add_vocab = [&](const std::string &s) {
      int32_t id = pool.get(s);
      if (vocab_set.insert(id).second) vocab_order.push_back(id);
      return id;
    };
    char buf[8];
    for (int b = 0; b < 256; ++b) {
      std::snprintf(buf, sizeof buf, "<0x%02X>", b);
      add_vocab(buf);
    }
    add_vocab("\\u");
    add_vocab("\\\\");
    add_vocab("\\<");
    add_vocab("_");

    // Word symbol sequences (per-codepoint, escaped, '_'-terminated).
    words.reserve(uniq.size());
    freqs = std::move(uniq_freq);
    for (const std::string &w : uniq) {
      std::string esc;
      append_escaped_word(w, &esc);
      std::vector<int32_t> seq;
      size_t i = 0;
      while (i < esc.size()) {
        size_t L;
        unsigned char c = esc[i];
        if (c == '\\' && i + 1 < esc.size())
          L = 2;  // escape pieces are single symbols
        else
          L = std::min(utf8_len(c), esc.size() - i);
        seq.push_back(add_vocab(esc.substr(i, L)));
        i += L;
      }
      words.push_back(std::move(seq));
    }

    // Initial pair statistics + heap.
    for (size_t wi = 0; wi < words.size(); ++wi) {
      const auto &seq = words[wi];
      int64_t f = freqs[wi];
      for (size_t i = 0; i + 1 < seq.size(); ++i) {
        uint64_t key = pair_key(seq[i], seq[i + 1]);
        pair_counts[key] += f;
        pair_words[key].insert(static_cast<int32_t>(wi));
      }
    }
    for (const auto &kv : pair_counts) {
      int32_t a = static_cast<int32_t>(kv.first >> 32);
      int32_t b = static_cast<int32_t>(kv.first & 0xFFFFFFFFu);
      heap.push({kv.second, a, b});
    }

    // Merge loop — identical control flow to the Python trainer (lazy heap
    // with stale-entry skip, neighbour-pair incremental updates).
    while (static_cast<int64_t>(vocab_order.size()) < target_vocab &&
           !heap.empty()) {
      HeapEntry e = heap.top();
      heap.pop();
      uint64_t key = pair_key(e.a, e.b);
      auto it = pair_counts.find(key);
      if (it == pair_counts.end() || it->second != e.count) continue;  // stale
      if (e.count < min_pair_count) break;
      std::string merged_str = pool.strs[e.a] + pool.strs[e.b];
      int32_t merged = pool.get(merged_str);
      if (vocab_set.insert(merged).second) vocab_order.push_back(merged);
      pair_counts.erase(key);
      std::vector<int32_t> affected;
      {
        auto pw = pair_words.find(key);
        if (pw != pair_words.end()) {
          affected.assign(pw->second.begin(), pw->second.end());
          pair_words.erase(pw);
        }
      }
      for (int32_t wi : affected) {
        std::vector<int32_t> &seq = words[wi];
        int64_t f = freqs[wi];
        std::vector<int32_t> out;
        out.reserve(seq.size());
        bool changed = false;
        size_t i = 0;
        while (i < seq.size()) {
          if (i + 1 < seq.size() && seq[i] == e.a && seq[i + 1] == e.b) {
            if (!out.empty()) {
              bump(out.back(), e.a, -f, wi);
              bump(out.back(), merged, f, wi);
            }
            if (i + 2 < seq.size()) {
              bump(e.b, seq[i + 2], -f, wi);
              bump(merged, seq[i + 2], f, wi);
            }
            out.push_back(merged);
            i += 2;
            changed = true;
          } else {
            out.push_back(seq[i]);
            ++i;
          }
        }
        if (changed) seq = std::move(out);
      }
    }

    Tokenizer *tok = new Tokenizer();
    tok->pieces.reserve(vocab_order.size());
    for (int32_t id : vocab_order) tok->pieces.push_back(pool.strs[id]);
    tok->build_index();
    return tok;
  }
};

}  // namespace

extern "C" {

// pieces_blob: '\n'-joined piece strings, ids assigned 1..n in order.
void *tpu_tok_create(const char *pieces_blob, int64_t blob_len) {
  Tokenizer *tok = new Tokenizer();
  const char *p = pieces_blob, *end = pieces_blob + blob_len;
  while (p < end) {
    const char *nl = static_cast<const char *>(memchr(p, '\n', end - p));
    size_t n = (nl ? nl : end) - p;
    if (n > 0) tok->pieces.emplace_back(p, n);
    p = nl ? nl + 1 : end;
  }
  tok->build_index();
  return tok;
}

// corpus: '\n'-joined unique words in first-occurrence order with a parallel
// counts array (whitespace splitting and counting stay upstream so Python
// str.split()/Counter semantics are preserved exactly).
void *tpu_tok_train(const char *corpus, int64_t len, const int64_t *counts,
                    int64_t n_words, int32_t target_vocab,
                    int32_t min_pair_count) {
  Trainer tr;
  return tr.train(corpus, len, counts, n_words, target_vocab, min_pair_count);
}

void tpu_tok_free(void *t) { delete static_cast<Tokenizer *>(t); }

int32_t tpu_tok_num_pieces(void *t) {
  return static_cast<int32_t>(static_cast<Tokenizer *>(t)->pieces.size());
}

// Writes the '\n'-joined pieces into buf (if cap suffices); returns the
// required byte count.
int64_t tpu_tok_pieces_blob(void *t, char *buf, int64_t cap) {
  Tokenizer *tok = static_cast<Tokenizer *>(t);
  int64_t need = 0;
  for (const auto &p : tok->pieces) need += static_cast<int64_t>(p.size()) + 1;
  if (need > cap || buf == nullptr) return need;
  char *w = buf;
  for (const auto &p : tok->pieces) {
    memcpy(w, p.data(), p.size());
    w += p.size();
    *w++ = '\n';
  }
  return need;
}

// words: '\n'-joined words of one text. Returns the number of ids produced;
// if it exceeds cap the caller must retry with a larger buffer (out is only
// valid up to min(returned, cap)).
int64_t tpu_tok_encode(void *t, const char *words, int64_t len, int32_t *out,
                       int64_t cap) {
  Tokenizer *tok = static_cast<Tokenizer *>(t);
  std::vector<int32_t> ids;
  ids.reserve(static_cast<size_t>(len) + 8);
  std::string esc;
  const char *p = words, *end = words + len;
  while (p < end) {
    const char *nl = static_cast<const char *>(memchr(p, '\n', end - p));
    size_t n = (nl ? nl : end) - p;
    if (n > 0) {
      esc.clear();
      append_escaped_word(std::string(p, n), &esc);
      tok->encode_escaped(esc, &ids);
    }
    p = nl ? nl + 1 : end;
  }
  int64_t count = static_cast<int64_t>(ids.size());
  if (out != nullptr && cap > 0)
    memcpy(out, ids.data(),
           static_cast<size_t>(std::min(count, cap)) * sizeof(int32_t));
  return count;
}

}  // extern "C"
