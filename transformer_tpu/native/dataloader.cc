// Native prefetching batch loader.
//
// Counterpart of the tf.data C++ pipeline the reference leans on
// (TextLineDataset -> shuffle -> padded_batch, reference utils.py:77-159):
// a background worker thread assembles fixed-shape padded int32 batches from
// the pre-tokenized corpus into a bounded ring of slots, overlapping host-side
// batch assembly with device steps. The Python twin is
// transformer_tpu/data/pipeline.py:Seq2SeqDataset (in-memory, same padding
// semantics: pad id 0, truncate-to-length, all-pad fill rows for the final
// partial batch so every shard sees identical batch counts).
//
// Shuffling uses an explicit splitmix64-keyed Fisher-Yates so epoch order is
// reproducible across platforms/stdlib versions for a given (seed, epoch).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

struct Loader {
  // Corpus: flattened ids + offsets (offsets[i]..offsets[i+1] = example i).
  std::vector<int32_t> src_flat, tgt_flat;
  std::vector<int64_t> src_off, tgt_off;
  int64_t n_examples = 0;

  int32_t global_batch = 0, local_batch = 0, lo = 0;
  int32_t src_len = 0, tgt_len = 0, pad_id = 0;

  // Length bucketing (pipeline.py Seq2SeqDataset.length_buckets): ascending
  // widths; example i lands in the smallest bucket that fits
  // max(len(src_i), len(tgt_i)); batches form within buckets and are padded
  // to the bucket width only. Empty = single fixed width.
  std::vector<int32_t> bucket_widths;
  std::vector<int32_t> bucket_of;  // per-example bucket index

  // Slot ring: each slot holds one (src, tgt) local batch plus its padded
  // widths (== src_len/tgt_len unbucketed, == the bucket width bucketed).
  struct Slot {
    std::vector<int32_t> src, tgt;
    int32_t src_w = 0, tgt_w = 0;
    bool full = false;
  };
  std::vector<Slot> slots;
  std::mutex mu;
  std::condition_variable cv_producer, cv_consumer;
  // Queue of filled slot ids in production order.
  std::vector<int32_t> ready;
  int64_t produced = 0, total_batches = 0;
  bool epoch_done = true, stop = false;
  std::atomic<bool> cancel{false};  // abandons the in-flight epoch
  std::thread worker;

  ~Loader() { shutdown(); }

  void shutdown() {
    {
      std::unique_lock<std::mutex> lk(mu);
      stop = true;
    }
    cv_producer.notify_all();
    cv_consumer.notify_all();
    if (worker.joinable()) worker.join();
  }

  void fill_row(int32_t *dst, const std::vector<int32_t> &flat,
                const std::vector<int64_t> &off, int64_t idx, int32_t len) {
    if (pad_id == 0)
      std::memset(dst, 0, sizeof(int32_t) * static_cast<size_t>(len));
    else
      std::fill(dst, dst + len, pad_id);
    if (idx < 0) return;  // all-pad fill row of a partial final batch
    int64_t n = off[idx + 1] - off[idx];
    if (n > len) n = len;  // truncate-to-length (pipeline.py _pad)
    std::memcpy(dst, flat.data() + off[idx], sizeof(int32_t) * static_cast<size_t>(n));
  }

  template <typename T>
  static void fisher_yates(std::vector<T> &v, uint64_t &s) {
    for (int64_t i = static_cast<int64_t>(v.size()) - 1; i > 0; --i) {
      int64_t j = static_cast<int64_t>(splitmix64(s) % static_cast<uint64_t>(i + 1));
      std::swap(v[static_cast<size_t>(i)], v[static_cast<size_t>(j)]);
    }
  }

  // One planned global batch: rows come from (*pool)[base + lo + row],
  // padded to (src_w, tgt_w); positions past the pool are all-pad fill.
  struct PlanBatch {
    const std::vector<int64_t> *pool;
    int64_t base;
    int32_t src_w, tgt_w;
  };

  void run_epoch(uint64_t seed, bool shuffle, bool drop_remainder) {
    uint64_t s = seed;
    std::vector<int64_t> order;                 // unbucketed pool
    std::vector<std::vector<int64_t>> members;  // per-bucket pools
    std::vector<PlanBatch> plan;

    auto plan_pool = [&](const std::vector<int64_t> &pool, int32_t sw, int32_t tw) {
      int64_t n = static_cast<int64_t>(pool.size());
      int64_t nb = n / global_batch;
      if (!drop_remainder && n % global_batch) ++nb;
      for (int64_t b = 0; b < nb; ++b)
        plan.push_back(PlanBatch{&pool, b * global_batch, sw, tw});
    };

    if (bucket_widths.empty()) {
      order.resize(static_cast<size_t>(n_examples));
      for (int64_t i = 0; i < n_examples; ++i) order[static_cast<size_t>(i)] = i;
      if (shuffle) fisher_yates(order, s);
      plan_pool(order, src_len, tgt_len);
    } else {
      // Batches form inside each bucket, then the batch PLAN is shuffled so
      // an epoch interleaves widths (pipeline.py _bucketed_batches; the
      // PRNG differs from the numpy path — splitmix64 here — but is equally
      // deterministic per (seed, epoch) and identical on every host).
      members.resize(bucket_widths.size());
      for (int64_t i = 0; i < n_examples; ++i)
        members[static_cast<size_t>(bucket_of[static_cast<size_t>(i)])]
            .push_back(i);
      for (size_t b = 0; b < members.size(); ++b) {
        if (shuffle) fisher_yates(members[b], s);
        plan_pool(members[b], bucket_widths[b], bucket_widths[b]);
      }
      if (shuffle) fisher_yates(plan, s);
    }

    int64_t nb = static_cast<int64_t>(plan.size());
    {
      std::unique_lock<std::mutex> lk(mu);
      total_batches = nb;
      produced = 0;
      epoch_done = (nb == 0);
      ready.clear();
      for (auto &sl : slots) sl.full = false;
    }
    cv_consumer.notify_all();

    for (int64_t b = 0; b < nb; ++b) {
      int32_t slot_id = -1;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_producer.wait(lk, [&] {
          if (stop || cancel.load()) return true;
          for (size_t i = 0; i < slots.size(); ++i)
            if (!slots[i].full) return true;
          return false;
        });
        if (stop || cancel.load()) return;
        for (size_t i = 0; i < slots.size(); ++i)
          if (!slots[i].full) {
            slot_id = static_cast<int32_t>(i);
            break;
          }
      }
      Slot &slot = slots[static_cast<size_t>(slot_id)];
      const PlanBatch &pb = plan[static_cast<size_t>(b)];
      slot.src_w = pb.src_w;
      slot.tgt_w = pb.tgt_w;
      int64_t pool_n = static_cast<int64_t>(pb.pool->size());
      for (int32_t row = 0; row < local_batch; ++row) {
        int64_t gpos = pb.base + lo + row;
        int64_t idx = gpos < pool_n ? (*pb.pool)[static_cast<size_t>(gpos)] : -1;
        fill_row(slot.src.data() + static_cast<size_t>(row) * pb.src_w,
                 src_flat, src_off, idx, pb.src_w);
        fill_row(slot.tgt.data() + static_cast<size_t>(row) * pb.tgt_w,
                 tgt_flat, tgt_off, idx, pb.tgt_w);
      }
      {
        std::unique_lock<std::mutex> lk(mu);
        slot.full = true;
        ready.push_back(slot_id);
        ++produced;
        if (produced == total_batches) epoch_done = true;
      }
      cv_consumer.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// buckets/n_buckets: ascending bucket widths (length bucketing); pass
// n_buckets == 0 for the single-fixed-width loader. The largest bucket must
// cover every example (the Python caller validates this before creating).
void *tpu_dl_create(const int32_t *src_flat, const int64_t *src_off,
                    const int32_t *tgt_flat, const int64_t *tgt_off,
                    int64_t n_examples, int32_t global_batch,
                    int32_t local_batch, int32_t lo, int32_t src_len,
                    int32_t tgt_len, int32_t pad_id, int32_t queue_depth,
                    const int32_t *buckets, int32_t n_buckets) {
  Loader *L = new Loader();
  L->src_flat.assign(src_flat, src_flat + src_off[n_examples]);
  L->src_off.assign(src_off, src_off + n_examples + 1);
  L->tgt_flat.assign(tgt_flat, tgt_flat + tgt_off[n_examples]);
  L->tgt_off.assign(tgt_off, tgt_off + n_examples + 1);
  L->n_examples = n_examples;
  L->global_batch = global_batch;
  L->local_batch = local_batch;
  L->lo = lo;
  L->src_len = src_len;
  L->tgt_len = tgt_len;
  L->pad_id = pad_id;
  if (n_buckets > 0) {
    L->bucket_widths.assign(buckets, buckets + n_buckets);
    L->bucket_of.resize(static_cast<size_t>(n_examples));
    for (int64_t i = 0; i < n_examples; ++i) {
      int64_t sn = src_off[i + 1] - src_off[i];
      int64_t tn = tgt_off[i + 1] - tgt_off[i];
      int64_t need = sn > tn ? sn : tn;
      int32_t b = n_buckets - 1;  // over-length truncates into the last bucket
      for (int32_t w = 0; w < n_buckets; ++w)
        if (need <= buckets[w]) {
          b = w;
          break;
        }
      L->bucket_of[static_cast<size_t>(i)] = b;
    }
  }
  L->slots.resize(static_cast<size_t>(queue_depth > 0 ? queue_depth : 2));
  // Bucket widths apply to BOTH sides of a batch and are bounded only by
  // max(src_len, tgt_len), so bucketed slots must size each side at that
  // max — sizing at the per-side len would overflow when a bucket is wider
  // than the narrower side.
  int32_t src_cap = src_len, tgt_cap = tgt_len;
  if (n_buckets > 0) {
    int32_t maxw = src_len > tgt_len ? src_len : tgt_len;
    src_cap = tgt_cap = maxw;
  }
  for (auto &s : L->slots) {
    s.src.resize(static_cast<size_t>(local_batch) * src_cap);
    s.tgt.resize(static_cast<size_t>(local_batch) * tgt_cap);
  }
  return L;
}

void tpu_dl_free(void *p) { delete static_cast<Loader *>(p); }

// Launch the producer for one epoch. Any previous epoch must be drained
// (or the loader freed) first.
void tpu_dl_start_epoch(void *p, uint64_t seed, int32_t shuffle,
                        int32_t drop_remainder) {
  Loader *L = static_cast<Loader *>(p);
  if (L->worker.joinable()) {
    // Abandon any undrained previous epoch so join cannot block on a full
    // ring (the consumer may have stopped iterating early).
    L->cancel.store(true);
    L->cv_producer.notify_all();
    L->worker.join();
    L->cancel.store(false);
  }
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->epoch_done = false;
    L->produced = 0;
    L->total_batches = -1;  // unknown until run_epoch computes it
    L->ready.clear();
    for (auto &s : L->slots) s.full = false;
  }
  L->worker = std::thread([L, seed, shuffle, drop_remainder] {
    L->run_epoch(seed, shuffle != 0, drop_remainder != 0);
  });
}

// Blocks until a batch is ready; copies it into the caller's buffers (sized
// for the loader's max widths) and reports the batch's actual padded widths
// in widths_out[0] (src) and widths_out[1] (tgt) — smaller than the maxima
// for bucketed batches. Returns 1 on success, 0 when the epoch is exhausted.
int32_t tpu_dl_next(void *p, int32_t *src_out, int32_t *tgt_out,
                    int32_t *widths_out) {
  Loader *L = static_cast<Loader *>(p);
  int32_t slot_id = -1;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_consumer.wait(lk, [&] {
      return L->stop || !L->ready.empty() ||
             (L->epoch_done && L->ready.empty());
    });
    if (L->stop || L->ready.empty()) return 0;
    slot_id = L->ready.front();
    L->ready.erase(L->ready.begin());
  }
  Loader::Slot &slot = L->slots[static_cast<size_t>(slot_id)];
  std::memcpy(src_out, slot.src.data(),
              static_cast<size_t>(L->local_batch) * slot.src_w * sizeof(int32_t));
  std::memcpy(tgt_out, slot.tgt.data(),
              static_cast<size_t>(L->local_batch) * slot.tgt_w * sizeof(int32_t));
  widths_out[0] = slot.src_w;
  widths_out[1] = slot.tgt_w;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    slot.full = false;
  }
  L->cv_producer.notify_one();
  return 1;
}

}  // extern "C"
