"""Native (C++) runtime extensions, loaded via ctypes.

The reference gets its native speed from TensorFlow's C++ runtime (tf.data
pipeline, tfds SubwordTextEncoder); this package is the framework-owned
equivalent: a small C++ library compiled on first use with the system
toolchain and bound through ctypes (no pybind11 dependency).

Components:
  - tokenizer.cc — BPE trainer + greedy longest-match encoder, bit-identical
    to transformer_tpu/data/tokenizer.py (the fallback path).

The library is built lazily into this directory. Disable entirely (pure
Python fallback) with ``TRANSFORMER_TPU_NO_NATIVE=1``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libtpu_native.so")
_SOURCES = ["tokenizer.cc", "dataloader.cc"]

_lib: ctypes.CDLL | bool | None = None  # None = not tried, False = unavailable


def _build() -> str | None:
    """Compile the shared library if missing/stale; returns its path or None."""
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    try:
        if os.path.exists(_LIB_PATH) and all(
            os.path.getmtime(_LIB_PATH) >= os.path.getmtime(s) for s in srcs
        ):
            return _LIB_PATH
    except OSError:
        # A source file is missing (incomplete checkout): a stale .so may
        # lack symbols, so treat native as unavailable rather than crash.
        return None
    # Build into a temp file then atomically rename, so concurrent importers
    # (multi-host training) never load a half-written library.
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        cxx = os.environ.get("CXX", "g++")
        cmd = [
            cxx, "-O2", "-std=c++17", "-fPIC", "-shared", "-o", tmp, *srcs,
            "-lpthread",
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH
    except (OSError, subprocess.SubprocessError):
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return None


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, or None if disabled/unbuildable."""
    global _lib
    if _lib is False:
        return None
    if _lib is not None:
        return _lib
    if os.environ.get("TRANSFORMER_TPU_NO_NATIVE"):
        _lib = False
        return None
    path = _build()
    if path is None:
        _lib = False
        return None
    try:
        lib = ctypes.CDLL(path)
        _bind(lib)
    except (OSError, AttributeError):  # dlopen failure or missing symbol
        _lib = False
        return None
    _lib = lib
    return lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.tpu_tok_create.restype = ctypes.c_void_p
    lib.tpu_tok_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.tpu_tok_train.restype = ctypes.c_void_p
    lib.tpu_tok_train.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    lib.tpu_tok_free.restype = None
    lib.tpu_tok_free.argtypes = [ctypes.c_void_p]
    lib.tpu_tok_num_pieces.restype = ctypes.c_int32
    lib.tpu_tok_num_pieces.argtypes = [ctypes.c_void_p]
    lib.tpu_tok_pieces_blob.restype = ctypes.c_int64
    lib.tpu_tok_pieces_blob.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.tpu_tok_encode.restype = ctypes.c_int64
    lib.tpu_tok_encode.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
    ]
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.tpu_dl_create.restype = ctypes.c_void_p
    lib.tpu_dl_create.argtypes = [
        i32p, i64p, i32p, i64p,
        ctypes.c_int64,  # n_examples
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # global/local/lo
        ctypes.c_int32, ctypes.c_int32,  # src_len/tgt_len
        ctypes.c_int32,  # pad_id
        ctypes.c_int32,  # queue_depth
        i32p, ctypes.c_int32,  # bucket widths, n_buckets (0 = unbucketed)
    ]
    lib.tpu_dl_free.restype = None
    lib.tpu_dl_free.argtypes = [ctypes.c_void_p]
    lib.tpu_dl_start_epoch.restype = None
    lib.tpu_dl_start_epoch.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.tpu_dl_next.restype = ctypes.c_int32
    lib.tpu_dl_next.argtypes = [ctypes.c_void_p, i32p, i32p, i32p]


class NativeBatchLoader:
    """ctypes handle to the C++ prefetching loader; owns the native object."""

    def __init__(self, handle: int, lib: ctypes.CDLL, local_batch: int,
                 src_len: int, tgt_len: int, bucketed: bool = False):
        self._handle = ctypes.c_void_p(handle)
        self._lib = lib
        self.local_batch = local_batch
        # Receive-buffer capacities: bucket widths apply to BOTH sides and
        # are bounded by max(src_len, tgt_len), so bucketed buffers must be
        # sized at that max on each side (mirrors the C++ slot sizing).
        if bucketed:
            self.src_len = self.tgt_len = max(src_len, tgt_len)
        else:
            self.src_len = src_len
            self.tgt_len = tgt_len
        self._generation = 0  # starting an epoch invalidates prior iterators

    def __del__(self):  # noqa: D105
        h, self._handle = self._handle, None
        if h:
            self._lib.tpu_dl_free(h)

    @classmethod
    def create(
        cls,
        src: list,
        tgt: list,
        global_batch: int,
        local_batch: int,
        lo: int,
        src_len: int,
        tgt_len: int,
        pad_id: int = 0,
        queue_depth: int = 3,
        length_buckets: tuple = (),
    ) -> "NativeBatchLoader | None":
        lib = get_lib()
        if lib is None:
            return None
        src_off = np.zeros(len(src) + 1, dtype=np.int64)
        np.cumsum([len(a) for a in src], out=src_off[1:])
        tgt_off = np.zeros(len(tgt) + 1, dtype=np.int64)
        np.cumsum([len(a) for a in tgt], out=tgt_off[1:])
        src_flat = (
            np.concatenate(src).astype(np.int32)
            if len(src)
            else np.zeros(0, np.int32)
        )
        tgt_flat = (
            np.concatenate(tgt).astype(np.int32)
            if len(tgt)
            else np.zeros(0, np.int32)
        )
        buckets = np.asarray(sorted(length_buckets), dtype=np.int32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        handle = lib.tpu_dl_create(
            src_flat.ctypes.data_as(i32p), src_off.ctypes.data_as(i64p),
            tgt_flat.ctypes.data_as(i32p), tgt_off.ctypes.data_as(i64p),
            len(src), global_batch, local_batch, lo, src_len, tgt_len,
            pad_id, queue_depth,
            buckets.ctypes.data_as(i32p), len(buckets),
        )
        return (
            cls(handle, lib, local_batch, src_len, tgt_len,
                bucketed=len(buckets) > 0)
            if handle
            else None
        )

    def epoch(self, seed: int, shuffle: bool, drop_remainder: bool):
        """Start the producer and yield (src, tgt) int32 batches (bucketed
        loaders yield each batch at its bucket width).

        One live iterator per loader: starting a new epoch cancels the
        in-flight one (its iterator terminates cleanly at the next pull
        instead of stealing the new epoch's batches)."""
        self._generation += 1
        my_generation = self._generation
        self._lib.tpu_dl_start_epoch(
            self._handle,
            ctypes.c_uint64(seed & 0xFFFFFFFFFFFFFFFF),
            int(shuffle),
            int(drop_remainder),
        )
        i32p = ctypes.POINTER(ctypes.c_int32)
        while self._generation == my_generation:
            src = np.empty((self.local_batch, self.src_len), dtype=np.int32)
            tgt = np.empty((self.local_batch, self.tgt_len), dtype=np.int32)
            widths = np.empty(2, dtype=np.int32)
            ok = self._lib.tpu_dl_next(
                self._handle,
                src.ctypes.data_as(i32p),
                tgt.ctypes.data_as(i32p),
                widths.ctypes.data_as(i32p),
            )
            if not ok:
                return
            sw, tw = int(widths[0]), int(widths[1])
            # The C++ side packs rows at the batch's own stride; reshape the
            # filled prefix rather than slicing the max-width view.
            yield (
                src.reshape(-1)[: self.local_batch * sw].reshape(self.local_batch, sw),
                tgt.reshape(-1)[: self.local_batch * tw].reshape(self.local_batch, tw),
            )


class NativeTokenizer:
    """ctypes handle to a C++ tokenizer; owns the underlying object."""

    def __init__(self, handle: int, lib: ctypes.CDLL):
        self._handle = ctypes.c_void_p(handle)
        self._lib = lib

    def __del__(self):  # noqa: D105
        h, self._handle = self._handle, None
        if h:
            self._lib.tpu_tok_free(h)

    @classmethod
    def from_pieces(cls, pieces: list[str]) -> "NativeTokenizer | None":
        lib = get_lib()
        if lib is None:
            return None
        blob = "\n".join(pieces).encode("utf-8")
        handle = lib.tpu_tok_create(blob, len(blob))
        return cls(handle, lib) if handle else None

    @classmethod
    def train(
        cls,
        word_freq: "dict[str, int]",
        target_vocab_size: int,
        min_pair_count: int,
    ) -> "NativeTokenizer | None":
        """Train BPE over a {unique word: count} mapping in first-occurrence
        order (whitespace splitting and counting stay in Python so
        ``str.split()``/``Counter`` semantics are preserved exactly)."""
        lib = get_lib()
        if lib is None:
            return None
        blob = "\n".join(word_freq).encode("utf-8")
        counts = np.fromiter(
            word_freq.values(), dtype=np.int64, count=len(word_freq)
        )
        handle = lib.tpu_tok_train(
            blob,
            len(blob),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(word_freq),
            target_vocab_size,
            min_pair_count,
        )
        return cls(handle, lib) if handle else None

    def pieces(self) -> list[str]:
        need = self._lib.tpu_tok_pieces_blob(self._handle, None, 0)
        buf = ctypes.create_string_buffer(int(need))
        self._lib.tpu_tok_pieces_blob(self._handle, buf, need)
        blob = buf.raw[:need].decode("utf-8")
        return [p for p in blob.split("\n") if p]

    def encode_words(self, words: list[str]) -> list[int]:
        if not words:
            return []
        blob = "\n".join(words).encode("utf-8")
        # Escaping can expand input (e.g. '_' -> '\\u' emits up to 2
        # byte-fallback ids per input byte), so size for 2 ids per escaped
        # byte + 1 word-end marker per word; the retry below then never fires.
        cap = 2 * len(blob) + len(words) + 8
        out = np.empty(cap, dtype=np.int32)
        n = self._lib.tpu_tok_encode(
            self._handle,
            blob,
            len(blob),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cap,
        )
        if n > cap:  # defensive: cap bound above should always suffice
            out = np.empty(int(n), dtype=np.int32)
            n = self._lib.tpu_tok_encode(
                self._handle,
                blob,
                len(blob),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                int(n),
            )
        return out[:n].tolist()
