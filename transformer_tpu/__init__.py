"""transformer_tpu — a TPU-native (JAX/XLA/Pallas/pjit) Transformer framework.

A from-scratch rebuild of the capabilities of the reference TF2.0 framework
(kuetuofa/Transformer): encoder-decoder Transformer for seq2seq translation,
single-chip and distributed (data/tensor/sequence-parallel) training, a subword
text pipeline, a training engine with noam-schedule Adam, masked cross-entropy,
checkpoint rotation/restore, metrics, greedy decoding and model export.

Design stance (see SURVEY.md §7): functional core — pure ``init``/``apply``
functions over parameter pytrees, a mesh-aware training engine driven by
``jax.sharding`` annotations, and Pallas kernels for the hot attention path.
Nothing here is a translation of the reference's Keras class graph.
"""

from transformer_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)

__version__ = "0.1.0"

__all__ = [
    "MeshConfig",
    "ModelConfig",
    "TrainConfig",
]
