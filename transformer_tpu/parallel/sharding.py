"""Partition rules: parameter-path regex -> PartitionSpec.

The t5x-style approach, matched to this framework's parameter tree layout
(``ops/attention.py:mha_init``, ``ops/ffn.py:ffn_init``, ``ops/nn.py``):

==========================================  =============================
path suffix                                  spec (dims of the array)
==========================================  =============================
embedding/table          (V, M)              ('fsdp', None)
query|key|value/kernel   (M, H, D)           ('fsdp', 'model', None)
query|key|value/bias     (H, D)              ('model', None)
out/kernel               (H, D, M)           ('model', None, 'fsdp')
ffn in/kernel            (M, F)              ('fsdp', 'model')
ffn in/bias              (F,)                ('model',)
ffn out/kernel           (F, M)              ('model', 'fsdp')
final/kernel             (M, V)              ('fsdp', 'model')
final/bias               (V,)                ('model',)
layernorm scale/bias                          replicated
==========================================  =============================

Attention is head-sharded and the FFN column/row-sharded on 'model' (tensor
parallelism: the pair of matmuls per block needs exactly one psum, which XLA
inserts). 'fsdp' shards the remaining large dimension zero-style. Any
dimension that doesn't divide its mesh axis falls back to replicated — a
static check, not a runtime surprise.

Optimizer state (Adam mu/nu) mirrors the parameter tree inside the optax
state pytree, so the same path-suffix rules apply wherever a parameter path
appears; scalars (step, count) replicate.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path-suffix regex, spec builder). First match wins.
_RULES: list[tuple[str, P]] = [
    (r"embedding/table$", P("fsdp", None)),
    (r"(query|key|value)/kernel$", P("fsdp", "model", None)),
    (r"(query|key|value)/bias$", P("model", None)),
    # MoE (ops/moe.py): experts stacked on a leading E axis shard over
    # 'expert' (expert parallelism), composing with tp on dff and fsdp on
    # d_model exactly like the dense FFN; the router stays replicated.
    # The router is (M, E): a few KB, replicated by design so every token's
    # routing decision is local (no gather before dispatch); the expert
    # weights it routes TO are what's sharded.
    (r"moe/router/kernel$", P(None, None)),  # tpa: disable=TPA205 — tiny by design
    (r"moe/in/kernel$", P("expert", "fsdp", "model")),
    (r"moe/in/bias$", P("expert", "model")),
    (r"moe/out/kernel$", P("expert", "model", "fsdp")),
    (r"moe/out/bias$", P("expert", None)),
    (r"out/kernel$", P("model", None, "fsdp")),
    (r"out/bias$", P(None)),
    (r"ffn/in/kernel$", P("fsdp", "model")),
    (r"ffn/in/bias$", P("model")),
    (r"ffn/gate/kernel$", P("fsdp", "model")),  # gated FFN (swiglu et al.)
    (r"ffn/gate/bias$", P("model")),
    (r"ffn/out/kernel$", P("model", "fsdp")),
    (r"ffn/out/bias$", P(None)),
    (r"final/kernel$", P("fsdp", "model")),
    (r"final/bias$", P("model")),
    (r"(ln1|ln2|ln_ffn|final_ln)/(scale|bias)$", P(None)),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _divisible(spec: P, shape: tuple[int, ...], mesh: Mesh) -> P:
    """Drop sharding on any dim that doesn't divide its mesh axis, names the
    mesh doesn't carry (hand-built meshes without e.g. an 'expert' axis), or
    when the spec has more dims than the array — scalars in odd spots."""
    if len(spec) > len(shape):
        return P()
    out = []
    for dim, axis in enumerate(spec):
        if axis is None:
            out.append(None)
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        if any(a not in mesh.shape for a in axes):
            out.append(None)
            continue
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        out.append(axis if shape[dim] % size == 0 else None)
    return P(*out)


def param_partition_spec(path, leaf, mesh: Mesh) -> P:
    """Spec for one leaf given its tree path (works for params and for optax
    state, whose leaves carry the same path suffixes)."""
    s = _path_str(path)
    shape = getattr(leaf, "shape", ())
    if not shape:
        return P()
    for pattern, spec in _RULES:
        if re.search(pattern, s):
            return _divisible(spec, shape, mesh)
    return P()


def state_shardings(state_shape: Any, mesh: Mesh) -> Any:
    """NamedShardings for a TrainState (or any pytree) from its eval_shape."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, param_partition_spec(path, leaf, mesh)),
        state_shape,
    )


def batch_spec(mesh: Mesh, shard_seq: bool = False) -> P:
    """(B, S) token batches shard over batch on data×fsdp×expert (fsdp is
    data parallelism with parameter sharding on top; the expert axis splits
    tokens too, so MoE dispatch becomes a GSPMD all-to-all instead of full
    replication) and optionally over sequence on 'seq' (ring attention).
    Axes a hand-built mesh doesn't carry are skipped."""
    axes = tuple(a for a in ("data", "fsdp", "expert") if a in mesh.shape)
    return P(axes, "seq" if shard_seq else None)
