"""Pipeline parallelism: GPipe microbatch schedule over a ``pipe`` mesh axis.

No reference counterpart exists (SURVEY.md §2.4 — the reference's only
strategy is mirrored data parallelism, ``distributed_train.py:137-139``); this
is net-new TPU-native machinery. Design:

- Layer parameters for the N homogeneous layers of a stack are *stacked* on a
  leading axis and sharded over ``pipe``: each device (stage) holds
  ``N / pipe`` contiguous layers and scans over them locally.
- The batch is split into M microbatches. A ``lax.scan`` over
  ``T = M + P - 1`` ticks runs the classic GPipe schedule: at tick ``t``
  stage ``s`` processes microbatch ``t - s``; activations hop to the next
  stage via ``lax.ppermute`` over ICI (a nearest-neighbour link on a ring
  mesh axis, the same transport ring attention uses).
- Stage 0 feeds from the microbatch buffer; the last stage's outputs are
  collected and ``psum``-broadcast over ``pipe`` so every device returns the
  full output (activations are microbatch-sized, so the broadcast is cheap
  relative to the FLOPs it closes over).

The schedule runs under ``shard_map``, so it composes with the ``data`` axis
(batch-dim sharding splits the microbatches per data-parallel group and the
schedule runs identically in each group) and, via ``param_specs``, with
``fsdp``: stage-interior layer parameters stay sharded over the fsdp axis at
rest and are all-gathered **one layer at a time** inside the stage's layer
scan (ZeRO-3 style), so no device ever holds more than one layer's full
weights transiently — the pipe axis finally buys parameter-memory scaling
when stacked with fsdp. Tensor-sharding interiors over ``model`` is not
wired through this path.

Everything is differentiable: ``ppermute``/``psum`` have transposes, so
``jax.grad`` through ``pipeline_apply`` yields exactly the backward schedule
(activations are rematerialized per microbatch by XLA as usual).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from transformer_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

Params = Any


def stack_layer_params(layers: Sequence[Params]) -> Params:
    """Stack a list of per-layer parameter trees into one tree whose leaves
    have a leading layer axis (shardable over ``pipe``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layer_params(stacked: Params, num_layers: int) -> list[Params]:
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(num_layers)]


def _gather_layer(lp: Params, specs: Params | None, fsdp_axis: str) -> Params:
    """All-gather one layer's fsdp-sharded leaves to full arrays (ZeRO-3:
    done per layer inside the stage scan, so only one layer's full weights
    are ever live). ``specs`` carries each leaf's *unstacked* PartitionSpec;
    None means everything is already replicated."""
    if specs is None:
        return lp

    def gather(leaf, spec):
        for d, ax in enumerate(spec):
            if ax == fsdp_axis:
                leaf = jax.lax.all_gather(leaf, fsdp_axis, axis=d, tiled=True)
        return leaf

    return jax.tree.map(gather, lp, specs, is_leaf=lambda x: x is None)


def _stacked_params_spec(
    stacked_params: Params, param_specs: Params | None, axis: str
) -> Params:
    """shard_map specs for stage-stacked layer params: leading layer dim on
    ``axis``, plus any interior fsdp dims from ``param_specs`` (shared by the
    GPipe and 1F1B paths so their at-rest layouts cannot diverge)."""
    if param_specs is None:
        return jax.tree.map(lambda _: P(axis), stacked_params)
    return jax.tree.map(
        lambda spec: P(axis) if spec is None else P(axis, *spec),
        param_specs,
        is_leaf=lambda s: isinstance(s, P) or s is None,
    )


def pipeline_apply(
    stacked_params: Params,
    layer_fn: Callable[..., jax.Array],
    x: jax.Array,
    mb_consts: tuple[jax.Array, ...] = (),
    *,
    mesh: Mesh,
    num_microbatches: int,
    base_rng: jax.Array | None = None,
    axis: str = "pipe",
    batch_axes: tuple[str, ...] = ("data", "fsdp"),
    param_specs: Params | None = None,
    fsdp_axis: str = "fsdp",
    with_aux: bool = False,
    auto_axes: tuple[str, ...] = (),
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Run a homogeneous layer stack over ``x`` with the GPipe schedule.

    Args:
      stacked_params: layer params stacked on a leading axis of size
        ``num_layers`` (the ``pipe`` mesh axis size must divide it).
      layer_fn: ``layer_fn(layer_params, x, rng, *consts) -> x`` applying ONE
        layer; ``rng`` is None when ``base_rng`` is None (deterministic).
        With ``with_aux=True`` the contract is ``-> (x, aux_scalar)`` instead
        (e.g. a MoE layer's load-balance loss).
      x: ``(B, ...)`` activations (e.g. post-embedding ``(B, S, D)``).
      mb_consts: per-example side inputs streamed with the schedule (masks,
        cross-attention memory) — each ``(B, ...)``, microbatched like ``x``.
      num_microbatches: M; must divide the per-data-shard batch.
      base_rng: optional dropout seed; folded per (layer, microbatch) so the
        pipelined run matches a sequential run that folds the same way.
      batch_axes: mesh axes the batch dimension is sharded over.
      param_specs: optional tree of *per-layer* PartitionSpecs (no leading
        layer axis) whose ``fsdp_axis`` entries mark dims sharded over fsdp;
        those leaves stay sharded at rest and are gathered per layer inside
        the stage scan. None = stages hold their layers whole.
      auto_axes: mesh axes left OUT of the manual shard_map region (GSPMD
        keeps handling them): pass ``("model",)`` to compose the GPipe
        schedule with tensor parallelism — stage-interior layer math stays
        model-axis-sharded and XLA inserts the head/dff collectives, while
        the schedule's ppermute/psum ride the manual ``pipe`` axis.

    Returns ``(B, ...)`` outputs, replicated over ``pipe`` — plus, with
    ``with_aux``, a replicated fp32 scalar: the per-layer aux losses summed
    over layers, averaged over microbatches and batch shards (aux is a batch
    statistic, so the pipelined value is the mean of per-microbatch values —
    the same approximation gradient accumulation makes).
    """
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    n_stages = mesh.shape[axis]
    if num_layers % n_stages:
        raise ValueError(
            f"pipe axis size {n_stages} must divide num_layers {num_layers}"
        )
    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)

    params_spec = _stacked_params_spec(stacked_params, param_specs, axis)
    bspec = P(batch_axes)  # batch dim sharded, rest replicated
    consts_spec = tuple(P(batch_axes) for _ in mb_consts)
    rng_spec = P()

    M = num_microbatches
    T = M + n_stages - 1

    manual = tuple(a for a in mesh.axis_names if a not in auto_axes)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(params_spec, bspec, consts_spec, rng_spec),
        out_specs=(bspec, P()) if with_aux else bspec,
        check_vma=False,
        axis_names=set(manual),
    )
    def _pipelined(local_params, x_local, consts_local, rng):
        batch = x_local.shape[0]
        if batch % M:
            raise ValueError(
                f"num_microbatches {M} must divide the per-shard batch {batch}"
            )
        mb = batch // M
        x_mbs = x_local.reshape(M, mb, *x_local.shape[1:])
        consts_mbs = tuple(
            c.reshape(M, mb, *c.shape[1:]) for c in consts_local
        )
        stage = jax.lax.axis_index(axis)
        layers_per_stage = num_layers // n_stages

        def apply_stage(h, mb_idx):
            consts_mb = tuple(c[mb_idx] for c in consts_mbs)

            def one_layer(h, xs):
                local_i, lp = xs
                lp = _gather_layer(lp, param_specs, fsdp_axis)
                if base_rng is None:
                    r = None
                else:
                    global_layer = stage * layers_per_stage + local_i
                    r = jax.random.fold_in(
                        jax.random.fold_in(rng, global_layer), mb_idx
                    )
                out = layer_fn(lp, h, r, *consts_mb)
                if with_aux:
                    h, aux = out
                    return h, jnp.asarray(aux, jnp.float32)
                return out, jnp.float32(0.0)

            h, layer_aux = jax.lax.scan(
                one_layer, h, (jnp.arange(layers_per_stage), local_params)
            )
            return h, jnp.sum(layer_aux)

        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            buf, aux_acc = carry
            mb_idx = jnp.clip(t - stage, 0, M - 1)
            inp = jnp.where(stage == 0, x_mbs[jnp.clip(t, 0, M - 1)], buf)
            out, aux = apply_stage(inp, mb_idx)
            # Only ticks where this stage holds a REAL microbatch contribute
            # aux (warm-up/drain ticks process in-flight garbage).
            valid = jnp.logical_and(t >= stage, t - stage < M)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            if n_stages > 1:
                nxt = jax.lax.ppermute(out, axis, fwd_perm)
            else:
                nxt = out
            return (nxt, aux_acc), out

        (_, aux_acc), outs = jax.lax.scan(
            tick, (jnp.zeros_like(x_mbs[0]), jnp.float32(0.0)), jnp.arange(T)
        )
        # outs[t] on the last stage holds microbatch t-(P-1); earlier stages
        # hold in-flight garbage. Select + broadcast.
        result = outs[n_stages - 1 :]
        is_last = (stage == n_stages - 1).astype(result.dtype)
        result = jax.lax.psum(result * is_last, axis)
        result = result.reshape(batch, *x_local.shape[1:])
        if not with_aux:
            return result
        # Sum over stages (each stage saw its own layers), mean over
        # microbatches, mean over batch shards -> one replicated scalar.
        aux = jax.lax.psum(aux_acc, axis) / M
        aux = jax.lax.pmean(aux, batch_axes)
        return result, aux

    return _pipelined(stacked_params, x, mb_consts, base_rng if base_rng is not None else jax.random.PRNGKey(0))


# --------------------------------------------------------------------------
# Model-level integration: pipelined encoder/decoder stacks + full forward.
# --------------------------------------------------------------------------


def _layer_fsdp_specs(layer_params: Params, mesh: Mesh) -> Params | None:
    """Per-leaf PartitionSpecs for ONE layer's params, restricted to the fsdp
    axis (the only interior sharding the GPipe path composes with): the same
    path-suffix rules the rest layout uses (``parallel/sharding.py``), with
    model/other axes dropped. None when the mesh has no fsdp axis."""
    if mesh.shape.get("fsdp", 1) == 1:
        return None
    from transformer_tpu.parallel.sharding import param_partition_spec

    def spec_for(path, leaf):
        spec = param_partition_spec(path, leaf, mesh)
        return P(*(ax if ax == "fsdp" else None for ax in spec))

    return jax.tree_util.tree_map_with_path(spec_for, layer_params)


def pipelined_transformer_apply(
    params: Params,
    inp: jax.Array | None,
    tar: jax.Array,
    cfg,
    *,
    mesh: Mesh,
    num_microbatches: int,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    pad_id: int = 0,
    return_hidden: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Pipeline-parallel counterpart of ``models.transformer.transformer_apply``
    (same logits, no attention-weight plumbing): embedding prologue and final
    projection run replicated on every stage (they are tiny next to the layer
    stacks); the encoder and decoder layer stacks run under the GPipe schedule.

    Layer params are stacked on entry — callers that jit this (they should)
    pay that restructuring once at trace time.

    A mesh with a ``model`` axis composes: the GPipe region goes manual over
    {data, fsdp, pipe} only and the ``model`` axis stays GSPMD-auto, so
    stage-interior layer math keeps its tensor-parallel sharding (heads/dff
    on ``model``) with XLA-inserted collectives.

    MoE models (``cfg.moe_experts > 0``, homogeneous stacks only —
    ``moe_every == 1``) return ``(logits, moe_aux)`` instead of bare logits:
    the layers' load-balance losses ride the schedule as a second scan
    output (``pipeline_apply(with_aux=True)``).

    ``return_hidden=True`` stops before the vocab projection and returns the
    (B, S, d_model) decoder hiddens (post final-LN for pre-LN stacks) — the
    pipelined counterpart of ``transformer_hidden_apply``, for the chunked
    vocab-projection/CE path (``TrainConfig.loss_chunks``).
    """
    from transformer_tpu.models.decoder import decoder_layer_apply
    from transformer_tpu.models.encoder import embed_prologue, encoder_layer_apply
    from transformer_tpu.models.transformer import _logits
    from transformer_tpu.ops.masks import make_padding_mask
    from transformer_tpu.ops.nn import layernorm_apply

    if rng is None:
        r_embed_e = r_embed_d = r_enc = r_dec = None
    else:
        r_embed_e, r_embed_d, r_enc, r_dec = jax.random.split(rng, 4)

    moe = bool(cfg.moe_experts)
    # Tensor parallelism composes by exclusion: the 'model' axis stays out
    # of the manual region (GSPMD-auto), so stage interiors keep their
    # heads/dff sharding with XLA-inserted collectives.
    auto = ("model",) if mesh.shape.get("model", 1) > 1 else ()

    if cfg.decoder_only:
        self_mask = make_padding_mask(tar, pad_id)
        x = embed_prologue(
            params["decoder"]["embedding"], tar, cfg, r_embed_d, deterministic
        )
        stacked = stack_layer_params(params["decoder"]["layers"])

        def dec_layer(lp, h, r, smask):
            out = decoder_layer_apply(
                lp, h, None, smask, None, cfg, r, deterministic
            )
            return (out[0], out[4]) if moe else out[0]

        if cfg.remat:
            dec_layer = jax.checkpoint(dec_layer)
        x = pipeline_apply(
            stacked, dec_layer, x, (self_mask,),
            mesh=mesh, num_microbatches=num_microbatches, base_rng=r_dec,
            param_specs=_layer_fsdp_specs(params["decoder"]["layers"][0], mesh),
            with_aux=moe, auto_axes=auto,
        )
        if moe:
            x, aux = x
        if cfg.norm_scheme == "pre":
            x = layernorm_apply(
                params["decoder"]["final_ln"], x, cfg.layernorm_epsilon
            )
        if return_hidden:
            return (x, aux) if moe else x
        logits = _logits(params, x, cfg)
        return (logits, aux) if moe else logits

    enc_mask = make_padding_mask(inp, pad_id)
    self_mask = make_padding_mask(tar, pad_id)

    x = embed_prologue(
        params["encoder"]["embedding"], inp, cfg, r_embed_e, deterministic
    )
    enc_stacked = stack_layer_params(params["encoder"]["layers"])

    def enc_layer(lp, h, r, mask):
        out = encoder_layer_apply(lp, h, mask, cfg, r, deterministic)
        return (out[0], out[2]) if moe else out[0]

    if cfg.remat:
        # Same activation-memory lever as the sequential path (encoder_apply /
        # decoder_apply wrap their layer calls); without this the flag would
        # silently do nothing under pipeline parallelism.
        enc_layer = jax.checkpoint(enc_layer)
    enc_out = pipeline_apply(
        enc_stacked, enc_layer, x, (enc_mask,),
        mesh=mesh, num_microbatches=num_microbatches, base_rng=r_enc,
        param_specs=_layer_fsdp_specs(params["encoder"]["layers"][0], mesh),
        with_aux=moe, auto_axes=auto,
    )
    enc_aux = None
    if moe:
        enc_out, enc_aux = enc_out
    if cfg.norm_scheme == "pre":
        enc_out = layernorm_apply(
            params["encoder"]["final_ln"], enc_out, cfg.layernorm_epsilon
        )

    y = embed_prologue(
        params["decoder"]["embedding"], tar, cfg, r_embed_d, deterministic
    )
    dec_stacked = stack_layer_params(params["decoder"]["layers"])

    def dec_layer(lp, h, r, enc_mb, smask, cmask):
        out = decoder_layer_apply(
            lp, h, enc_mb, smask, cmask, cfg, r, deterministic
        )
        return (out[0], out[4]) if moe else out[0]

    if cfg.remat:
        dec_layer = jax.checkpoint(dec_layer)
    y = pipeline_apply(
        dec_stacked, dec_layer, y, (enc_out, self_mask, enc_mask),
        mesh=mesh, num_microbatches=num_microbatches, base_rng=r_dec,
        param_specs=_layer_fsdp_specs(params["decoder"]["layers"][0], mesh),
        with_aux=moe, auto_axes=auto,
    )
    if moe:
        y, dec_aux = y
    if cfg.norm_scheme == "pre":
        y = layernorm_apply(
            params["decoder"]["final_ln"], y, cfg.layernorm_epsilon
        )
    if return_hidden:
        return (y, enc_aux + dec_aux) if moe else y
    logits = _logits(params, y, cfg)
    return (logits, enc_aux + dec_aux) if moe else logits


# --------------------------------------------------------------------------
# 1F1B: interleaved forward/backward schedule with an O(stages) activation
# stash (manual autodiff — jax.grad cannot interleave backward ticks with
# forward ticks, so the engine owns its own vjp chaining).
# --------------------------------------------------------------------------


def gpipe_ticks(num_microbatches: int, num_stages: int) -> int:
    """Wall ticks of the GPipe forward schedule: M + P - 1 (its backward is
    the autodiff transpose, another M + P - 1). Bubble fraction per
    direction: (P-1)/(M+P-1)."""
    return num_microbatches + num_stages - 1

def one_f1b_ticks(num_microbatches: int, num_stages: int) -> int:
    """Wall ticks of the combined 1F1B schedule: M + 2(P-1). Each tick runs
    ONE stage-forward and ONE stage-backward on every stage (SPMD cannot
    skip work per-stage), so total compute ticks are M + 2P - 2 of (F+B)
    versus GPipe's (M + P - 1) F plus (M + P - 1) B — a slightly LONGER
    wall schedule. What 1F1B buys is memory, not ticks: microbatch i's
    stage input is stashed at tick s+i and consumed by its backward at tick
    2(P-1)+i-s, so at most ``one_f1b_stash_slots(P)`` microbatch
    activations are ever live per stage, independent of M. GPipe's
    autodiff backward stashes all M (well, M+P-1 scan residuals). At pod
    scale the bubble is shrunk by raising M, which is exactly the regime
    where GPipe's O(M) stash stops fitting and this schedule keeps working.
    """
    return num_microbatches + 2 * (num_stages - 1)

def one_f1b_stash_slots(num_stages: int) -> int:
    """Ring-buffer slots for stage-input stashes under 1F1B: 2P - 1.

    Stage s's input for microbatch i is written at tick s+i and read back
    at tick 2(P-1)+i-s; the longest lifetime (stage 0) spans 2(P-1) ticks,
    during which 2P-1 distinct microbatches get written — so a ring of
    2P-1 slots never overwrites a live entry (the same-tick write/read at
    the last stage aliases deliberately: it reads the input it just
    wrote)."""
    return 2 * num_stages - 1


def pipeline_train_1f1b(
    stacked_params: Params,
    nonlayer_params: Params,
    h0: jax.Array,
    mb_streams: tuple[jax.Array, ...],
    layer_fn: Callable,
    head_fn: Callable,
    inv_denom: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    base_rng: jax.Array | None = None,
    axis: str = "pipe",
    batch_axes: tuple[str, ...] = ("data", "fsdp"),
    param_specs: Params | None = None,
    fsdp_axis: str = "fsdp",
    auto_axes: tuple[str, ...] = (),
    grad_streams: tuple[int, ...] = (),
    with_aux: bool = False,
    aux_weight: float = 0.0,
) -> tuple[dict, jax.Array, Params, Params] | tuple[
    dict, jax.Array, Params, Params, tuple[jax.Array, ...]
]:
    """One fused forward+backward pass of a homogeneous layer stack under the
    non-interleaved 1F1B schedule, returning loss sums and gradients.

    ``auto_axes`` composes tensor parallelism exactly like ``pipeline_apply``:
    pass ``("model",)`` to keep that axis OUT of the manual region — stage
    interiors (and the loss head's vocab projection) stay model-axis-sharded
    with XLA-inserted collectives, including through the engine's internal
    ``jax.vjp``s, while the schedule's ppermute/psum ride the manual axes.

    ``with_aux`` carries a per-layer auxiliary loss (MoE load balancing)
    through the manual backward: the ``layer_fn`` contract becomes
    ``-> (h, aux_scalar)`` (matching ``pipeline_apply(with_aux=True)``),
    the objective gains ``aux_weight * aux_model`` where ``aux_model`` is
    the per-layer auxes summed over layers, averaged over microbatches and
    batch shards (exactly ``pipeline_apply``'s aux — the gradient seed for
    each layer call is therefore ``aux_weight / (M * n_batch_shards)``,
    applied through each stage vjp's second cotangent), and ``sums`` gains
    ``"moe_aux"``: ``aux_model`` itself, normalized by the engine so the
    reported metric and the gradient seed share one divisor.

    ``grad_streams`` names indices into ``mb_streams`` whose cotangents the
    engine must also return (appended as a fifth tuple element, each shaped
    and batch-sharded like its stream). This is the seq2seq hook: the
    decoder stack streams the encoder output into every layer's
    cross-attention, and its cotangent — accumulated across all decoder
    stages and microbatches — seeds the encoder backward outside.

    The engine is its own autodiff: ``jax.grad`` over the GPipe scan must
    finish ALL forwards before its transposed backward starts (that is what
    reverse-mode means), which forces the O(M)-microbatch activation stash.
    Here each scan tick runs one stage-forward AND one stage-backward
    (``jax.vjp`` of the stage, rematerialized from a stashed stage input),
    cotangents hop backward over the same ``ppermute`` ring the activations
    hop forward on, and the stash is a ``one_f1b_stash_slots(P)``-deep ring —
    activation memory is O(P), independent of M. See ``one_f1b_ticks`` for
    the tick/bubble accounting.

    Args:
      stacked_params: layer params stacked on a leading axis (sharded over
        ``axis`` by the shard_map in_spec, exactly as ``pipeline_apply``).
      nonlayer_params: the FULL parameter tree with the pipelined stack's
        layer list replaced by an empty container — embedding/final-LN/output
        leaves replicated into every stage (the loss head needs them; grads
        for them are psum'd over ``axis`` + ``batch_axes``).
      h0: (B_local, S, D) post-prologue activations (prologue runs OUTSIDE,
        under plain GSPMD, so its params may keep any sharding; its backward
        chains through the returned ``d_h0``).
      mb_streams: per-example side inputs, each (B_local, ...) — microbatched
        like ``h0`` and handed to ``layer_fn``/``head_fn`` per microbatch
        (token ids for mask building, shifted targets for the loss).
      layer_fn: ``layer_fn(lp, h, rng|None, *streams_mb) -> h`` for ONE layer.
      head_fn: ``head_fn(nonlayer_params, h_out_mb, *streams_mb, inv_denom)
        -> (objective_scalar, sums_dict)`` — the loss head applied to the
        last stage's output microbatch. ``objective`` must already be scaled
        so cotangent seed 1.0 yields final-normalization gradients
        (i.e. objective = loss_sum * inv_denom); ``sums_dict`` carries fp32
        scalars {"loss_sum", "weight", "correct"}.
      inv_denom: fp32 scalar, 1/denominator of the loss normalization
        (computed OUTSIDE over the full batch: per-microbatch normalizers
        would weight microbatches wrongly under "tokens" normalization).

    Returns ``(sums, d_h0, d_stacked, d_nonlayer)``:
      sums: global fp32 scalars {"loss_sum", "weight", "correct"}, plus
        "moe_aux" (the normalized model-level aux) when ``with_aux``.
      d_h0: cotangent of ``h0`` (batch-sharded like ``h0``) — feed it to the
        prologue's ``jax.vjp`` to finish the chain.
      d_stacked: gradient tree like ``stacked_params`` (stage-sharded).
      d_nonlayer: gradient tree like ``nonlayer_params`` (replicated).

    Numerics match the GPipe + autodiff path up to summation order: the same
    per-(layer, microbatch) rng folding, the same stage math, gradients
    accumulated per microbatch instead of transposed en bloc.
    """
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    n_stages = mesh.shape[axis]
    if num_layers % n_stages:
        raise ValueError(
            f"pipe axis size {n_stages} must divide num_layers {num_layers}"
        )
    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)

    # fsdp composition (ZeRO-3): layer leaves stay fsdp-sharded at rest and
    # are all-gathered one layer at a time inside stage_fwd; the gather's
    # vjp is a reduce_scatter, which both SUMS gradient contributions
    # across the fsdp shards (each holds different microbatch rows — fsdp
    # is a batch axis too) and re-shards them to the at-rest layout. Same
    # machinery as the GPipe path.
    params_spec = _stacked_params_spec(stacked_params, param_specs, axis)
    nonlayer_spec = jax.tree.map(lambda _: P(), nonlayer_params)
    bspec = P(batch_axes)
    streams_spec = tuple(P(batch_axes) for _ in mb_streams)

    M = num_microbatches
    T = one_f1b_ticks(M, n_stages)
    S_buf = one_f1b_stash_slots(n_stages)
    layers_per_stage = num_layers // n_stages
    # The scan carry accumulates the RAW aux sum ("moe_aux_sum"); the
    # returned dict carries the normalized "moe_aux" (the engine owns the
    # divisor so the metric can never drift from the gradient seed below).
    sum_keys = ("loss_sum", "weight", "correct") + (
        ("moe_aux_sum",) if with_aux else ()
    )
    out_sum_keys = ("loss_sum", "weight", "correct") + (
        ("moe_aux",) if with_aux else ()
    )
    sums_spec = {k: P() for k in out_sum_keys}
    # d(objective)/d(one layer call's aux): the model-level aux is the mean
    # over microbatches AND batch shards of per-call sums (pipeline_apply's
    # definition), entering the objective with coefficient aux_weight.
    n_batch_shards = 1
    for a in batch_axes:
        n_batch_shards *= mesh.shape[a]
    aux_seed = jnp.float32(aux_weight / (M * n_batch_shards))
    manual = tuple(a for a in mesh.axis_names if a not in auto_axes)
    out_specs = (sums_spec, bspec, params_spec, nonlayer_spec)
    if grad_streams:
        out_specs = out_specs + (tuple(bspec for _ in grad_streams),)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(params_spec, nonlayer_spec, bspec, streams_spec, P(), P()),
        out_specs=out_specs,
        check_vma=False,
        axis_names=set(manual),
    )
    def _engine(local_params, nonlayer, h0_local, streams_local, rng, inv_d):
        batch = h0_local.shape[0]
        if batch % M:
            raise ValueError(
                f"num_microbatches {M} must divide the per-shard batch {batch}"
            )
        mb = batch // M
        h_mbs = h0_local.reshape(M, mb, *h0_local.shape[1:])
        streams_mbs = tuple(
            s.reshape(M, mb, *s.shape[1:]) for s in streams_local
        )
        stage = jax.lax.axis_index(axis)
        is_last = stage == n_stages - 1
        is_first = stage == 0

        def stage_fwd(lp, h, mb_idx, streams_mb):
            """-> (h, aux_sum): aux is this stage's layer auxes summed (a
            constant 0.0 the compiler drops when with_aux is off)."""

            def one_layer(h, xs):
                local_i, layer_p = xs
                # ZeRO-3: gather this one layer's fsdp-sharded leaves to
                # full arrays just-in-time (no-op when param_specs is None).
                layer_p = _gather_layer(layer_p, param_specs, fsdp_axis)
                if base_rng is None:
                    r = None
                else:
                    global_layer = stage * layers_per_stage + local_i
                    r = jax.random.fold_in(
                        jax.random.fold_in(rng, global_layer), mb_idx
                    )
                out = layer_fn(layer_p, h, r, *streams_mb)
                if with_aux:
                    h_out, aux = out
                    return h_out, jnp.asarray(aux, jnp.float32)
                return out, jnp.float32(0.0)

            h, layer_aux = jax.lax.scan(
                one_layer, h, (jnp.arange(layers_per_stage), lp)
            )
            return h, jnp.sum(layer_aux)

        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
        bwd_perm = [(i + 1, i) for i in range(n_stages - 1)]

        def masked_add(acc, g, valid):
            return jax.tree.map(
                lambda a, x: a + jnp.where(valid, x, 0).astype(a.dtype), acc, g
            )

        def tick(carry, t):
            fwd_buf, bwd_buf, stash, d_stk, d_non, sums = carry

            # ---- forward half: stage s runs F of microbatch t - s ----
            f_mb = t - stage
            f_c = jnp.clip(f_mb, 0, M - 1)
            streams_f = tuple(s[f_c] for s in streams_mbs)
            inp = jnp.where(is_first, h_mbs[f_c], fwd_buf)
            # Ring-stash the stage INPUT (backward rematerializes from it).
            # Unconditional write: slot f_c % S_buf is free by construction
            # (one_f1b_stash_slots) and garbage ticks write garbage that is
            # overwritten before any valid backward reads it.
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, inp, f_c % S_buf, 0
            )
            # Forward-half aux is discarded: the backward half recomputes it
            # (rematerialization) where the valid-tick masking lives.
            out, _ = stage_fwd(local_params, inp, f_c, streams_f)
            fwd_nxt = (
                jax.lax.ppermute(out, axis, fwd_perm) if n_stages > 1 else out
            )

            # ---- backward half: stage s runs B of microbatch
            #      t - 2(P-1) + s, rematerializing its forward ----
            b_mb = t - 2 * (n_stages - 1) + stage
            b_valid = jnp.logical_and(b_mb >= 0, b_mb < M)
            b_c = jnp.clip(b_mb, 0, M - 1)
            streams_b = tuple(s[b_c] for s in streams_mbs)
            x_in = stash[b_c % S_buf]
            # The vjp also covers the grad_streams operands (e.g. the
            # encoder output a decoder stack cross-attends): their per-tick
            # cotangents ride the scan output and are re-indexed per stage
            # after it.
            gs_b = tuple(streams_b[i] for i in grad_streams)

            def fwd_for_vjp(lp, h, gs):
                merged = list(streams_b)
                for idx, val in zip(grad_streams, gs):
                    merged[idx] = val
                return stage_fwd(lp, h, b_c, tuple(merged))

            (h_out_rec, aux_rec), stage_vjp = jax.vjp(
                fwd_for_vjp, local_params, x_in, gs_b
            )
            # Loss head on the (recomputed) last-stage output: its vjp both
            # seeds the backward chain and yields the head-param grads.
            _, head_vjp, head_sums = jax.vjp(
                lambda nl, h: head_fn(nl, h, *streams_b, inv_d),
                nonlayer, h_out_rec, has_aux=True,
            )
            d_non_mb, d_head_h = head_vjp(jnp.float32(1.0))
            d_out = jnp.where(is_last, d_head_h.astype(bwd_buf.dtype), bwd_buf)
            # Second cotangent: the aux objective term seeds EVERY stage's
            # backward (garbage-tick contributions die in the masked adds).
            d_lp, d_in, d_gs = stage_vjp((d_out, aux_seed))
            d_stk = masked_add(d_stk, d_lp, b_valid)
            d_non = masked_add(d_non, d_non_mb, jnp.logical_and(b_valid, is_last))
            head_mask = jnp.logical_and(b_valid, is_last)
            new_sums = {
                k: sums[k] + jnp.where(head_mask, head_sums[k], 0.0)
                for k in head_sums
            }
            if with_aux:
                # Aux accumulates at every stage (each owns its layers'
                # auxes), not just the loss-head stage.
                new_sums["moe_aux_sum"] = sums["moe_aux_sum"] + jnp.where(
                    b_valid, aux_rec, 0.0
                )
            sums = new_sums
            bwd_nxt = (
                jax.lax.ppermute(d_in, axis, bwd_perm) if n_stages > 1 else d_in
            )
            d_gs = tuple(
                jnp.where(b_valid, g, 0).astype(g.dtype) for g in d_gs
            )
            return (fwd_nxt, bwd_nxt, stash, d_stk, d_non, sums), (d_in, d_gs)

        zero_act = jnp.zeros_like(h_mbs[0])
        init = (
            zero_act,
            zero_act,
            jnp.zeros((S_buf, *zero_act.shape), zero_act.dtype),
            jax.tree.map(jnp.zeros_like, local_params),
            jax.tree.map(jnp.zeros_like, nonlayer),
            {k: jnp.float32(0.0) for k in sum_keys},
        )
        (_, _, _, d_stk, d_non, sums), (d_in_ticks, d_gs_ticks) = jax.lax.scan(
            tick, init, jnp.arange(T)
        )

        # Stage 0's backward for microbatch i lands at tick 2(P-1)+i: the
        # tail slice of the per-tick d_in outputs, masked to stage 0 and
        # broadcast over pipe, is d(h0) in microbatch order.
        d_h0_mbs = d_in_ticks[2 * (n_stages - 1) :]
        d_h0_mbs = jax.lax.psum(
            d_h0_mbs * is_first.astype(d_h0_mbs.dtype), axis
        )
        d_h0 = d_h0_mbs.reshape(batch, *h0_local.shape[1:])

        # grad_streams cotangents: stage s's contribution for microbatch i
        # sits at tick 2(P-1)+i-s, so a per-stage dynamic slice of length M
        # (start 2(P-1)-s, traced) re-indexes ticks -> microbatches; psum
        # over pipe then sums every stage's contribution. Batch-sharded like
        # the stream itself (no psum over batch axes).
        d_streams_out = tuple(
            jax.lax.psum(
                jax.lax.dynamic_slice_in_dim(
                    parts, 2 * (n_stages - 1) - stage, M, axis=0
                ),
                axis,
            ).reshape(batch, *parts.shape[2:])
            for parts in d_gs_ticks
        )

        reduce_axes = (axis,) + batch_axes
        sums = {k: jax.lax.psum(v, reduce_axes) for k, v in sums.items()}
        if with_aux:
            # Raw (stage, layer, microbatch, shard) sum -> pipeline_apply's
            # model-level definition: mean over microbatches + batch shards.
            sums["moe_aux"] = sums.pop("moe_aux_sum") / (M * n_batch_shards)
        d_non = jax.tree.map(lambda g: jax.lax.psum(g, reduce_axes), d_non)
        if batch_axes:
            if param_specs is None:
                d_stk = jax.tree.map(
                    lambda g: jax.lax.psum(g, batch_axes), d_stk
                )
            else:
                # Per-leaf reduction: a leaf sharded over fsdp already had
                # its fsdp-sum done by the gather's reduce_scatter transpose
                # (each shard now holds ITS slice of the summed grads) —
                # psum'ing it over fsdp again would add different slices.
                # Replicated leaves still need the full batch-axes sum.
                def reduce_leaf(g, spec):
                    sharded = spec is not None and fsdp_axis in tuple(spec)
                    axes = tuple(
                        a for a in batch_axes
                        if not (sharded and a == fsdp_axis)
                    )
                    return jax.lax.psum(g, axes) if axes else g

                d_stk = jax.tree.map(
                    reduce_leaf, d_stk, param_specs,
                    is_leaf=lambda x: x is None,
                )
        if grad_streams:
            return sums, d_h0, d_stk, d_non, d_streams_out
        return sums, d_h0, d_stk, d_non

    rng_in = base_rng if base_rng is not None else jax.random.PRNGKey(0)
    return _engine(
        stacked_params, nonlayer_params, h0, mb_streams, rng_in,
        jnp.asarray(inv_denom, jnp.float32),
    )
