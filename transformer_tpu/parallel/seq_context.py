"""Sequence-parallel execution context.

Routing problem: ``attention_impl="ring"`` is a *stack-level* transform — the
attention core must run under ``shard_map`` against the concrete device mesh,
but the model code (``ops.attention.mha_apply``) is mesh-agnostic on purpose.
Rather than threading a mesh through every ``*_apply`` signature, the
distributed engine enters this context around the jitted forward
(``parallel.distributed.make_sharded_steps``), and ``mha_apply`` reads it at
trace time. The context is only consulted while tracing, so the usual
contextvar/jit caveats don't apply: the traced program bakes in the mesh.

The reference has no counterpart (its attention materializes the full (S, S)
score tensor on one device, ``Attention.py:20`` — SURVEY §5 long-context).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from transformer_tpu.parallel.compat import shard_map


@dataclasses.dataclass(frozen=True)
class SeqParallelContext:
    mesh: Mesh
    axis: str = "seq"
    batch_axes: tuple[str, ...] = ("data", "fsdp")
    model_axis: str | None = "model"  # heads axis sharding, if the mesh has it

    @property
    def axis_size(self) -> int:
        return self.mesh.shape[self.axis]


_ctx: contextvars.ContextVar[SeqParallelContext | None] = contextvars.ContextVar(
    "sequence_parallel_context", default=None
)


@contextlib.contextmanager
def sequence_parallel(ctx: SeqParallelContext):
    """Activate sequence parallelism for every ``mha_apply`` traced inside."""
    token = _ctx.set(ctx)
    try:
        yield ctx
    finally:
        _ctx.reset(token)


def current_seq_context() -> SeqParallelContext | None:
    return _ctx.get()


def seq_parallel_attention(
    ctx: SeqParallelContext,
    impl: str,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None,
    causal: bool,
    window: int = 0,
) -> jax.Array:
    """Run ring/Ulysses attention over global (B, S, H, D) activations inside
    ``shard_map`` on ``ctx.mesh``: S split on the seq axis, B on the batch
    axes, heads on the model axis (transparent — attention is head-local)."""
    from transformer_tpu.parallel.ring_attention import (
        ring_attention,
        ulysses_attention,
    )

    inner = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    sp = ctx.axis_size
    s_q, s_k = q.shape[1], k.shape[1]
    if s_q % sp or s_k % sp:
        raise ValueError(
            f"sequence lengths (q={s_q}, kv={s_k}) must be divisible by the "
            f"'{ctx.axis}' mesh axis size {sp} for sequence parallelism"
        )
    mesh = ctx.mesh
    bdim = tuple(a for a in ctx.batch_axes if mesh.shape.get(a, 1) > 1) or None
    hdim = (
        ctx.model_axis
        if ctx.model_axis and mesh.shape.get(ctx.model_axis, 1) > 1
        else None
    )
    act = P(bdim, ctx.axis, hdim, None)
    # Grouped-query kv normally rides at H_kv heads (the GQA bandwidth win
    # extends to the ring's ppermute / ulysses' all-to-all payloads): kv
    # heads block-shard over the model axis exactly like q heads, keeping
    # the per-shard group mapping aligned (q-head block i pairs with
    # kv-head block i). Two corners where that alignment is impossible fall
    # back to repeating kv to full heads (replicating kv heads under
    # sharded q heads would MISALIGN the groups, so repeat is the only
    # correct fallback): H_kv not divisible by the model axis, or — for
    # ulysses, whose all-to-all splits the head dim — by the seq axis.
    if k.shape[2] != q.shape[2]:
        model_misaligned = hdim is not None and k.shape[2] % mesh.shape[hdim]
        # Ulysses runs PER MODEL-SHARD, so its head all-to-all must divide
        # the LOCAL kv head count (global // model axis when block-sharded).
        local_kv = (
            k.shape[2]
            if model_misaligned or hdim is None
            else k.shape[2] // mesh.shape[hdim]
        )
        if model_misaligned or (impl == "ulysses" and local_kv % sp):
            reps = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)
    fn = functools.partial(
        inner, axis_name=ctx.axis, axis_size=sp, causal=causal, window=window
    )
    if kv_mask is None:
        sharded = shard_map(
            lambda q, k, v: fn(q, k, v),
            mesh=mesh,
            in_specs=(act, act, act),
            out_specs=act,
            check_vma=False,
        )
        return sharded(q, k, v)
    sharded = shard_map(
        lambda q, k, v, m: fn(q, k, v, kv_mask=m),
        mesh=mesh,
        in_specs=(act, act, act, P(bdim, ctx.axis)),
        out_specs=act,
        check_vma=False,
    )
    return sharded(q, k, v, kv_mask)
