"""``shard_map`` across jax generations — one import for the whole package.

Newer jax exposes ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=...,
check_vma=..., axis_names=...)``; older releases only ship
``jax.experimental.shard_map.shard_map(f, mesh, in_specs, out_specs,
check_rep=..., auto=...)``. The two differ in exactly two spellings:

- ``check_vma`` (new) == ``check_rep`` (old): verify the body's replication
  claims against ``out_specs``.
- ``axis_names`` (new) names the MANUAL axes; ``auto`` (old) names the
  complement — the mesh axes left to GSPMD inside the region.

Import ``shard_map`` from here instead of from jax: on a new jax the call
passes straight through, on an old one the kwargs are translated. Without
this shim, ``from jax import shard_map`` at module scope makes the whole
``transformer_tpu.parallel`` package (and every test that touches it)
unimportable on older jax — the seq/pipe/ring machinery would be gated on
the newest release for the sake of two kwarg names.
"""

from __future__ import annotations

from typing import Any

import jax


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    check_vma: bool = True,
    axis_names: Any = None,
):
    """Dispatch to ``jax.shard_map`` when present, else translate to
    ``jax.experimental.shard_map.shard_map``. ``axis_names=None`` means
    every mesh axis is manual (both APIs' default)."""
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return native(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as legacy

    auto = (
        frozenset(mesh.axis_names) - frozenset(axis_names)
        if axis_names is not None
        else frozenset()
    )
    return legacy(
        f, mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, auto=auto,
    )
