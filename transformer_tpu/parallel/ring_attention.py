"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context story at all — sequence length is capped at
50 and the full (B, H, S, S) score tensor is materialized per step
(``Attention.py:20``, ``utils.py:22``; SURVEY.md §5 "Long-context"). These are
the TPU-native mechanisms that make the 4096-token decoder-only config
(BASELINE.json configs[4]) scale past one chip:

- **Ring attention** (``ring_attention``): activations are sharded along the
  sequence on the ``seq`` mesh axis. Each device scores its local query chunk
  against every key/value chunk as the chunks rotate around the ring via
  ``lax.ppermute`` over ICI, folding each contribution in with the same
  online-softmax update the flash kernel uses. Peak memory is O(S/P · S/P)
  per device and the permute overlaps with the matmuls under XLA's latency
  hiding scheduler.

- **Ulysses** (``ulysses_attention``): two ``lax.all_to_all``s re-shard the
  activation from sequence-sharded to head-sharded and back, so each device
  runs *full-sequence* attention on H/P heads. Cheaper collectives for
  moderate S (2 all-to-alls vs P-1 permutes of the whole KV), but requires
  num_heads % P == 0 and the full S on every chip.

Both are **per-shard** functions: call them inside ``shard_map`` (or any
context where ``axis_name`` is bound). ``make_sequence_parallel_attention``
wraps either in shard_map against a concrete mesh for stack-level use.

Mask/causality semantics mirror ``kernels.flash_attention``: an optional
(B, S_local) key-padding mask (True = attend) plus a structural causal flag;
chunk-level causality is resolved from ring positions, so above-diagonal
chunk pairs contribute nothing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from transformer_tpu.kernels.flash_attention import _MASK_GUARD, _MASKED


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    kv_mask: jax.Array | None = None,
    causal: bool = False,
) -> jax.Array:
    """Blockwise ring attention over a sequence-sharded activation.

    Args:
      q, k, v: (B, C, H, D) local chunks, C = S / axis_size. Chunk i on
        device i covers global positions [i*C, (i+1)*C).
      axis_name: mesh axis the sequence is sharded over (bound in shard_map).
      axis_size: number of devices on that axis (static Python int — the ring
        is unrolled so XLA can overlap each ppermute with the next matmul).
      kv_mask: optional (B, C) bool, True where the local key is real.
      causal: structural causal masking across global positions.

    Returns (B, C, H, D) in q's dtype.
    """
    b, c, h, d = q.shape
    my_idx = jax.lax.axis_index(axis_name)
    scale = d**-0.5
    # Matmul INPUTS stay in the model dtype (bf16 feeds the MXU at full
    # rate; fp32 inputs run at 1/8 throughput) and ACCUMULATE in fp32 via
    # preferred_element_type — the flash kernel's numerics.
    qt = q.transpose(0, 2, 1, 3)  # (B, H, C, D)

    m = jnp.full((b, h, c, 1), _MASKED, jnp.float32)
    l = jnp.zeros((b, h, c, 1), jnp.float32)
    acc = jnp.zeros((b, h, c, d), jnp.float32)

    shift = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    k_cur, v_cur = k, v
    mask_cur = kv_mask

    rows = jax.lax.broadcasted_iota(jnp.int32, (c, c), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (c, c), 1)

    for t in range(axis_size):
        src = (my_idx - t) % axis_size  # which global chunk we hold this step
        kt = k_cur.transpose(0, 2, 1, 3)  # (B, H, C, D)
        vt = v_cur.transpose(0, 2, 1, 3)
        s = (
            jnp.einsum(
                "bhqd,bhkd->bhqk", qt, kt,
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # (B, H, C, C) fp32
        if mask_cur is not None:
            s = jnp.where(mask_cur[:, None, None, :], s, _MASKED)
        if causal:
            # Global row = my_idx*C + r, global col = src*C + c: the whole
            # chunk pair is below (src < my), on (src == my), or above the
            # diagonal — where() keeps it branch-free and XLA-friendly.
            visible = (src * c + cols) <= (my_idx * c + rows)
            s = jnp.where(visible[None, None], s, _MASKED)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(s > _MASK_GUARD, jnp.exp(s - m_new), 0.0)
        correction = jnp.exp(m - m_new)
        l = correction * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * correction + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(q.dtype), vt,
            preferred_element_type=jnp.float32,
        )
        m = m_new
        if t + 1 < axis_size:
            k_cur = jax.lax.ppermute(k_cur, axis_name, shift)
            v_cur = jax.lax.ppermute(v_cur, axis_name, shift)
            if mask_cur is not None:
                mask_cur = jax.lax.ppermute(mask_cur, axis_name, shift)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe).transpose(0, 2, 1, 3)  # (B, C, H, D)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    kv_mask: jax.Array | None = None,
    causal: bool = False,
) -> jax.Array:
    """Ulysses-style sequence parallelism: all-to-all from sequence-sharded
    (B, C, H, D) to head-sharded (B, S, H/P, D), full-sequence attention per
    device, and all-to-all back. Requires H % axis_size == 0."""
    b, c, h, d = q.shape
    if h % axis_size:
        raise ValueError(
            f"ulysses needs num_heads ({h}) divisible by the seq axis ({axis_size})"
        )

    def seq_to_heads(x):  # (B, C, H, D) -> (B, S, H/P, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):  # (B, S, H/P, D) -> (B, C, H, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    q_full, k_full, v_full = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    mask = None
    if kv_mask is not None:
        full_kv = jax.lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)  # (B, S)
        mask = full_kv[:, None, None, :]
    if causal:
        s_full = q_full.shape[1]
        cmask = jnp.tril(jnp.ones((s_full, s_full), dtype=jnp.bool_))[None, None]
        mask = cmask if mask is None else jnp.logical_and(mask, cmask)

    from transformer_tpu.ops.attention import dot_product_attention

    out, _ = dot_product_attention(q_full, k_full, v_full, mask)
    return heads_to_seq(out)


def make_sequence_parallel_attention(
    mesh: Mesh,
    impl: str = "ring",
    axis: str = "seq",
    batch_axes: tuple[str, ...] = (),
):
    """Wrap ring/ulysses attention in shard_map against a concrete mesh.

    Returns ``fn(q, k, v, kv_mask=None, causal=False)`` over *global*
    (B, S, H, D) arrays with S sharded on ``axis`` (and optionally B on
    ``batch_axes``) — the stack-level entry point used by the long-context
    trunk and the parity tests.
    """
    axis_size = mesh.shape[axis]
    inner = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    bdim = tuple(batch_axes) if batch_axes else None
    act = P(bdim, axis, None, None)
    mask_spec = P(bdim, axis)

    def call(q, k, v, kv_mask=None, causal=False):
        fn = functools.partial(
            inner, axis_name=axis, axis_size=axis_size, causal=causal
        )
        if kv_mask is None:
            sharded = jax.shard_map(
                lambda q, k, v: fn(q, k, v),
                mesh=mesh,
                in_specs=(act, act, act),
                out_specs=act,
                check_vma=False,
            )
            return sharded(q, k, v)
        sharded = jax.shard_map(
            lambda q, k, v, m: fn(q, k, v, kv_mask=m),
            mesh=mesh,
            in_specs=(act, act, act, mask_spec),
            out_specs=act,
            check_vma=False,
        )
        return sharded(q, k, v, kv_mask)

    return call
