"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context story at all — sequence length is capped at
50 and the full (B, H, S, S) score tensor is materialized per step
(``Attention.py:20``, ``utils.py:22``; SURVEY.md §5 "Long-context"). These are
the TPU-native mechanisms that make the 4096-token decoder-only config
(BASELINE.json configs[4]) scale past one chip:

- **Ring attention** (``ring_attention``): activations are sharded along the
  sequence on the ``seq`` mesh axis. Each device scores its local query chunk
  against every key/value chunk as the chunks rotate around the ring via
  ``lax.ppermute`` over ICI, folding each contribution in with the same
  online-softmax update the flash kernel uses. Peak memory is O(S/P · S/P)
  per device and the permute overlaps with the matmuls under XLA's latency
  hiding scheduler.

- **Ulysses** (``ulysses_attention``): two ``lax.all_to_all``s re-shard the
  activation from sequence-sharded to head-sharded and back, so each device
  runs *full-sequence* attention on H/P heads. Cheaper collectives for
  moderate S (2 all-to-alls vs P-1 permutes of the whole KV), but requires
  num_heads % P == 0 and the full S on every chip.

Both are **per-shard** functions: call them inside ``shard_map`` (or any
context where ``axis_name`` is bound). ``make_sequence_parallel_attention``
wraps either in shard_map against a concrete mesh for stack-level use.

Mask/causality semantics mirror ``kernels.flash_attention``: an optional
(B, S_local) key-padding mask (True = attend) plus a structural causal flag;
chunk-level causality is resolved from ring positions, so above-diagonal
chunk pairs contribute nothing.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from transformer_tpu.parallel.compat import shard_map

from transformer_tpu.kernels.flash_attention import (
    _MASKED,
    _FlashConfig,
    _largest_divisor_block,
    flash_chunk_bwd,
    flash_ring_step,
)


@dataclasses.dataclass(frozen=True)
class _RingConfig:
    """Static ring configuration (hashable: the nondiff custom-vjp arg)."""

    axis_name: str
    axis_size: int
    causal: bool
    has_mask: bool
    block_q: int
    block_k: int
    num_heads: int
    scale: float
    interpret: bool
    num_kv_heads: int = 0  # 0 = same as num_heads (plain MHA)
    # Sliding window (causal only). The band is STATIC per hop: at hop t the
    # visiting kv chunk sits exactly t chunks behind the local q chunk
    # (src = (my - t) mod P), so in local tile coordinates the window
    # constraint col_global > row_global - W becomes col > row - (W - t·C) —
    # a static band the kernels skip tiles against. Hops with the whole
    # chunk below the band are dropped from the ring entirely, so ICI
    # traffic is O(window), not O(S).
    window: int = 0
    chunk: int = 0  # local chunk length C (set when window > 0)

    def flash(self, causal: bool, band: int | None = None) -> _FlashConfig:
        """Kernel config for one chunk pair; ``causal`` means 'this is the
        diagonal pair' (intra-chunk causality — local coordinates coincide
        with global ones there); ``band`` is the hop's static window band."""
        return _FlashConfig(
            causal=causal,
            has_mask=self.has_mask,
            block_q=self.block_q,
            block_k=self.block_k,
            num_heads=self.num_heads,
            scale=self.scale,
            interpret=self.interpret,
            num_kv_heads=self.num_kv_heads,
            band=band,
        )

    def kept_hops(self) -> int:
        """How many ring hops can contribute at all under the window: hop t
        is dead once even its newest position (local col c-1 against local
        row 0) falls out of the band (W <= t·C - C + 1). Monotonic in t, so
        the ring simply stops early. Without a window: all P hops."""
        if not self.window:
            return self.axis_size
        t = 0
        while t < self.axis_size and self.window > t * self.chunk - self.chunk + 1:
            t += 1
        return t

    def hop_band(self, t: int) -> int | None:
        return (self.window - t * self.chunk) if self.window else None


def _ring_block(c: int, requested: int) -> int:
    """A TPU-legal tile size that divides the chunk exactly (no padding in
    the ring: carries are chunk-shaped): 8-aligned divisor, else the whole
    chunk (a block equal to the full dim is always legal)."""
    blk = _largest_divisor_block(c, requested)
    return blk if blk % 8 == 0 else c


def _fold(x: jax.Array) -> jax.Array:
    """(B, C, H, D) -> (B*H, C, D): heads become independent grid rows."""
    b, c, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, c, d)


def _unfold(x: jax.Array, b: int, h: int) -> jax.Array:
    bh, c, d = x.shape
    return x.reshape(b, h, c, d).transpose(0, 2, 1, 3)


def _tile_mask(kv_mask: jax.Array | None, block_k: int) -> jax.Array | None:
    """(B, C) -> the kernels' pre-tiled (B, C/block_k, 1, block_k) int32."""
    if kv_mask is None:
        return None
    b, c = kv_mask.shape
    return kv_mask.astype(jnp.int32).reshape(b, c // block_k, 1, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring(cfg: _RingConfig, q, k, v, kv_mask):
    out, _ = _ring_fwd_impl(cfg, q, k, v, kv_mask)
    return out


def _ring_fwd_impl(cfg: _RingConfig, q, k, v, kv_mask):
    """Forward ring: one ``flash_ring_step`` Pallas call per hop folds the
    visiting KV chunk into the online-softmax carry — scores exist only as
    (block_q, block_k) VMEM tiles, never as a (C, C) HBM tensor."""
    b, c, h, d = q.shape
    P_ = cfg.axis_size
    my = jax.lax.axis_index(cfg.axis_name)
    shift = [(i, (i + 1) % P_) for i in range(P_)]
    qf = _fold(q)
    nq = c // cfg.block_q
    m = jnp.full((b * h, nq, cfg.block_q, 1), _MASKED, jnp.float32)
    l = jnp.zeros_like(m)
    acc = jnp.zeros((b * h, c, d), jnp.float32)

    k_cur, v_cur, mask_cur = k, v, kv_mask
    hops = cfg.kept_hops()  # < P_ under a window: the ring stops early
    for t in range(hops):  # unrolled: XLA overlaps each ppermute with compute
        src = (my - t) % P_  # global index of the chunk visiting this step
        kf, vf = _fold(k_cur), _fold(v_cur)
        mt = _tile_mask(mask_cur, cfg.block_k)
        band = cfg.hop_band(t)  # static per hop (relative offset == t)

        def step(fcfg, m, l, acc, kf=kf, vf=vf, mt=mt):
            return flash_ring_step(fcfg, qf, kf, vf, mt, m, l, acc)

        if cfg.causal:
            # The whole chunk pair is below (fold fully), on (fold with
            # intra-chunk causality), or above the diagonal (skip).
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            m, l, acc = jax.lax.switch(
                branch,
                [
                    functools.partial(step, cfg.flash(False, band)),
                    functools.partial(step, cfg.flash(True, band)),
                    lambda m, l, acc: (m, l, acc),
                ],
                m, l, acc,
            )
        else:
            m, l, acc = step(cfg.flash(False), m, l, acc)
        if t + 1 < hops:
            k_cur = jax.lax.ppermute(k_cur, cfg.axis_name, shift)
            v_cur = jax.lax.ppermute(v_cur, cfg.axis_name, shift)
            if mask_cur is not None:
                mask_cur = jax.lax.ppermute(mask_cur, cfg.axis_name, shift)

    l_col = l.reshape(b * h, c, 1)
    l_safe = jnp.where(l_col == 0.0, 1.0, l_col)
    out = _unfold((acc / l_safe), b, h).astype(q.dtype)
    lse = m + jnp.log(jnp.where(l == 0.0, 1.0, l))  # (B*H, nq, bq, 1)
    return out, lse


def _ring_fwd_rule(cfg, q, k, v, kv_mask):
    out, lse = _ring_fwd_impl(cfg, q, k, v, kv_mask)
    return out, (q, k, v, kv_mask, out, lse)


def _ring_bwd_rule(cfg, residuals, do):
    """Ring backward: dq accumulates locally; dk/dv ride the ring WITH their
    k/v chunks (P hops total, so every chunk's gradient arrives back home
    with all devices' contributions folded in). Probability tiles are
    recomputed per chunk from the forward's global logsumexp — the exact
    flash decomposition, O(block²) VMEM per tile."""
    q, k, v, kv_mask, out, lse = residuals
    b, c, h, d = q.shape
    P_ = cfg.axis_size
    my = jax.lax.axis_index(cfg.axis_name)
    shift = [(i, (i + 1) % P_) for i in range(P_)]
    qf, dof, outf = _fold(q), _fold(do), _fold(out)
    nq = c // cfg.block_q
    delta = jnp.sum(
        dof.astype(jnp.float32) * outf.astype(jnp.float32), axis=-1
    ).reshape(b * h, nq, cfg.block_q, 1)

    h_kv = k.shape[2]
    dq = jnp.zeros((b * h, c, d), jnp.float32)
    dk_cur = jnp.zeros((b * h_kv, c, d), jnp.float32)
    dv_cur = jnp.zeros((b * h_kv, c, d), jnp.float32)
    k_cur, v_cur, mask_cur = k, v, kv_mask

    hops = cfg.kept_hops()
    for t in range(hops):
        src = (my - t) % P_
        kf, vf = _fold(k_cur), _fold(v_cur)
        mt = _tile_mask(mask_cur, cfg.block_k)
        band = cfg.hop_band(t)

        def step(fcfg, dq, dk_acc, dv_acc, kf=kf, vf=vf, mt=mt):
            dq_s, dk_s, dv_s = flash_chunk_bwd(
                fcfg, qf, kf, vf, mt, lse, delta, dof
            )
            return (
                dq + dq_s.astype(jnp.float32),
                dk_acc + dk_s.astype(jnp.float32),
                dv_acc + dv_s.astype(jnp.float32),
            )

        if cfg.causal:
            branch = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
            dq, dk_cur, dv_cur = jax.lax.switch(
                branch,
                [
                    functools.partial(step, cfg.flash(False, band)),
                    functools.partial(step, cfg.flash(True, band)),
                    lambda dq, dk_acc, dv_acc: (dq, dk_acc, dv_acc),
                ],
                dq, dk_cur, dv_cur,
            )
        else:
            dq, dk_cur, dv_cur = step(cfg.flash(False), dq, dk_cur, dv_cur)
        # Full ring: rotate EVERY hop (unlike the forward's P-1) — after P
        # hops the kv chunks, and the gradients riding with them, are home
        # again. Early-stopped ring (window): skip the last hop's rotation
        # (its k/v would never be used) and fold ALL remaining displacement
        # into the single re-home permute below.
        if t + 1 < hops or hops == P_:
            k_cur = jax.lax.ppermute(k_cur, cfg.axis_name, shift)
            v_cur = jax.lax.ppermute(v_cur, cfg.axis_name, shift)
            dk_cur = jax.lax.ppermute(dk_cur, cfg.axis_name, shift)
            dv_cur = jax.lax.ppermute(dv_cur, cfg.axis_name, shift)
            if mask_cur is not None:
                mask_cur = jax.lax.ppermute(mask_cur, cfg.axis_name, shift)

    if hops < P_:
        # dk/dv sit hops-1 rotations from the loop; one permute covering
        # the remaining P - (hops - 1) steps re-homes them (skip the no-op
        # when that wraps to a full circle).
        offset = (P_ - (hops - 1)) % P_
        if offset:
            rehome = [(i, (i + offset) % P_) for i in range(P_)]
            dk_cur = jax.lax.ppermute(dk_cur, cfg.axis_name, rehome)
            dv_cur = jax.lax.ppermute(dv_cur, cfg.axis_name, rehome)

    return (
        _unfold(dq, b, h).astype(q.dtype),
        _unfold(dk_cur, b, h_kv).astype(k.dtype),
        _unfold(dv_cur, b, h_kv).astype(v.dtype),
        None,
    )


_ring.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    kv_mask: jax.Array | None = None,
    causal: bool = False,
    window: int = 0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Blockwise ring attention over a sequence-sharded activation.

    The inner loop IS the flash kernel (``kernels.flash_attention``): each
    ring hop folds the visiting KV chunk into the online-softmax carry with
    one ``flash_ring_step`` Pallas call, so per-device memory is O(block_q ×
    block_k) VMEM tiles + the O(C·D) carry — never the (C, C) fp32 score
    block the r2 XLA-einsum version materialized per hop. The backward pass
    recomputes probability tiles from the forward's global logsumexp and
    rotates dk/dv home with their chunks (custom VJP).

    Args:
      q, k, v: (B, C, H, D) local chunks, C = S / axis_size. Chunk i on
        device i covers global positions [i*C, (i+1)*C). Grouped-query
        attention: k/v may carry FEWER heads (B, C, H_kv, D) with
        H % H_kv == 0 — kv stays at H_kv heads through the whole ring, so
        both the Pallas tiles AND the per-hop ppermute payload shrink by
        the group factor (the GQA bandwidth win extends to ICI).
      axis_name: mesh axis the sequence is sharded over (bound in shard_map).
      axis_size: number of devices on that axis (static Python int — the ring
        is unrolled so XLA can overlap each ppermute with the next matmul).
      kv_mask: optional (B, C) bool, True where the local key is real.
      causal: structural causal masking across global positions (chunk pairs
        fully above the diagonal skip their kernel launch entirely).
      window: causal sliding window (requires ``causal``). The hop-t band
        offset is STATIC (the visiting chunk is always exactly t chunks
        behind), so the band is a compile-time kernel constraint AND the
        ring stops after ceil-ish window/C hops — out-of-band chunks are
        never even ppermuted, making ICI traffic O(window), not O(S).
      block_q, block_k: requested tile sizes; shrunk to TPU-legal divisors
        of the chunk length.
      interpret: run the Pallas kernels in interpret mode (default: off-TPU).

    Returns (B, C, H, D) in q's dtype.
    """
    b, c, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {h_kv}"
        )
    if window and not causal:
        raise ValueError("ring window requires causal=True")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cfg = _RingConfig(
        axis_name=axis_name,
        axis_size=axis_size,
        causal=causal,
        has_mask=kv_mask is not None,
        block_q=_ring_block(c, block_q),
        block_k=_ring_block(c, block_k),
        num_heads=h,
        scale=d**-0.5,
        interpret=bool(interpret),
        num_kv_heads=h_kv,
        window=int(window),
        chunk=c,
    )
    if kv_mask is not None:
        kv_mask = jnp.broadcast_to(kv_mask, (b, c))
    return _ring(cfg, q, k, v, kv_mask)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    kv_mask: jax.Array | None = None,
    causal: bool = False,
    window: int = 0,
) -> jax.Array:
    """Ulysses-style sequence parallelism: all-to-all from sequence-sharded
    (B, C, H, D) to head-sharded (B, S, H/P, D), full-sequence attention per
    device, and all-to-all back. Requires H % axis_size == 0.

    Grouped-query kv (k/v with H_kv < H heads, H % H_kv == 0) rides the
    all-to-all at its own head count when H_kv % axis_size == 0: each device
    then holds q-head block i and kv-head block i, which pair exactly (local
    group == global group), and the kv all-to-all payload shrinks by the
    group factor. Callers fall back to repeating kv when H_kv doesn't divide
    the axis (``seq_context.seq_parallel_attention``)."""
    b, c, h, d = q.shape
    h_kv = k.shape[2]
    if h % axis_size:
        raise ValueError(
            f"ulysses needs num_heads ({h}) divisible by the seq axis ({axis_size})"
        )
    if h_kv % axis_size:
        raise ValueError(
            f"ulysses with grouped kv needs kv heads ({h_kv}) divisible by "
            f"the seq axis ({axis_size}); repeat kv to full heads first"
        )

    def seq_to_heads(x):  # (B, C, H, D) -> (B, S, H/P, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):  # (B, S, H/P, D) -> (B, C, H, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    q_full, k_full, v_full = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    # Per-device full-sequence attention runs the FLASH kernel, not the
    # dense XLA path: at the long-context shapes the seq axis exists for,
    # a dense (S, S) causal mask + score tensor per device would be the
    # exact O(S²) HBM blow-up sequence parallelism is meant to avoid.
    # Causality stays structural (above-diagonal tiles skip their launch)
    # and key padding rides as a (B, S) vector.
    full_kv = (
        jax.lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)  # (B, S)
        if kv_mask is not None
        else None
    )
    from transformer_tpu.kernels.flash_attention import flash_attention

    # Windowed attention passes straight through: each device holds the FULL
    # sequence for its head block, so the flash kernel's structural band
    # applies unchanged.
    out = flash_attention(
        q_full, k_full, v_full, kv_mask=full_kv, causal=causal, window=window
    )
    return heads_to_seq(out)


def make_sequence_parallel_attention(
    mesh: Mesh,
    impl: str = "ring",
    axis: str = "seq",
    batch_axes: tuple[str, ...] = (),
):
    """Wrap ring/ulysses attention in shard_map against a concrete mesh.

    Returns ``fn(q, k, v, kv_mask=None, causal=False)`` over *global*
    (B, S, H, D) arrays with S sharded on ``axis`` (and optionally B on
    ``batch_axes``) — the stack-level entry point used by the long-context
    trunk and the parity tests.
    """
    axis_size = mesh.shape[axis]
    inner = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    bdim = tuple(batch_axes) if batch_axes else None
    act = P(bdim, axis, None, None)
    mask_spec = P(bdim, axis)

    def call(q, k, v, kv_mask=None, causal=False, window=0):
        fn = functools.partial(
            inner, axis_name=axis, axis_size=axis_size, causal=causal,
            window=window,
        )
        if kv_mask is None:
            sharded = shard_map(
                lambda q, k, v: fn(q, k, v),
                mesh=mesh,
                in_specs=(act, act, act),
                out_specs=act,
                check_vma=False,
            )
            return sharded(q, k, v)
        sharded = shard_map(
            lambda q, k, v, m: fn(q, k, v, kv_mask=m),
            mesh=mesh,
            in_specs=(act, act, act, mask_spec),
            out_specs=act,
            check_vma=False,
        )
        return sharded(q, k, v, kv_mask)

    return call
