"""Device-mesh construction and multi-host initialization.

Replaces the reference's replica topology (an explicit ``'/device:GPU:i'``
list handed to MirroredStrategy, ``distributed_train.py:137-138``) with a
logical 6-axis mesh:

    ('data', 'fsdp', 'model', 'seq', 'pipe', 'expert')

- gradients psum over 'data'+'fsdp'+'expert' (ICI),
- parameters/optimizer shard over 'fsdp',
- attention heads / dff shard over 'model',
- sequence blocks shard over 'seq' (ring attention),
- layer-stack stages over 'pipe' (GPipe schedule; activations hop
  stage-to-stage via ppermute — ``parallel/pipeline.py``),
- MoE expert weights over 'expert' (token slots reach their experts via the
  GSPMD-inserted all-to-all — ``ops/moe.py``).

TPU pods are multi-process by construction — ``initialize_distributed`` wraps
``jax.distributed.initialize`` so the same entry point works single-host (no-op)
and on a pod slice; the reference has no multi-host story at all (SURVEY §2.4).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

from transformer_tpu.config import MeshConfig


def make_mesh(cfg: MeshConfig, devices: list | None = None) -> Mesh:
    """Build the logical mesh over the given (default: all) devices.

    Axis order puts 'data' slowest and 'seq'/'model' fastest so that the
    axes with the heaviest collectives (TP all-reduces, ring permutes) land on
    nearest-neighbour ICI links when the physical topology allows.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    want = cfg.num_devices
    if want != len(devices):
        raise ValueError(
            f"mesh {cfg.axis_sizes} needs {want} devices, have {len(devices)} "
            f"({[str(d) for d in devices[:4]]}...). Enforced like the "
            "reference's batch/replica divisibility check "
            "(distributed_train.py:154-158)."
        )
    if cfg.dcn_data > 1:
        return _hybrid_mesh(cfg, devices)
    if devices and devices[0].platform == "tpu":
        # Topology-aware placement: on real TPU slices the physical ICI
        # graph is a torus, and a naive row-major reshape can put a
        # heavy-collective axis (model all-reduce, seq/pipe ring) across
        # non-adjacent chips. mesh_utils maps logical axes onto physical
        # torus axes (deterministic for a given topology, so every host in
        # a pod computes the same assignment). CPU/GPU fall through to the
        # plain reshape — there is no torus to exploit.
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(
                cfg.axis_sizes, devices=devices, allow_split_physical_axes=True
            )
            return Mesh(arr, cfg.axis_names)
        except Exception as e:  # unusual topology: the reshape below is valid
            import warnings

            warnings.warn(
                "topology-aware mesh placement unavailable "
                f"({type(e).__name__}: {e}); falling back to row-major "
                "device order — heavy-collective axes may land on "
                "non-adjacent chips",
                RuntimeWarning,
                stacklevel=2,
            )
    arr = np.asarray(devices).reshape(cfg.axis_sizes)
    return Mesh(arr, cfg.axis_names)


def _hybrid_mesh(cfg: MeshConfig, devices: list) -> Mesh:
    """Multi-slice mesh: the data axis spans ``cfg.dcn_data`` DCN-connected
    granules (TPU slices, or processes off-TPU), every other axis stays
    inside one granule. Slow DCN hops then carry only the data-parallel
    gradient all-reduce; fsdp gathers, tensor-parallel all-reduces, and the
    seq/pipe rings all ride intra-slice ICI (the reference's single-host
    NCCL topology has no counterpart — SURVEY §2.4 multi-host).
    """
    from jax.experimental import mesh_utils

    if cfg.data % cfg.dcn_data:
        raise ValueError(
            f"dcn_data={cfg.dcn_data} must divide the data axis ({cfg.data}): "
            "the data axis is the only one spanning DCN"
        )
    per_slice = (cfg.data // cfg.dcn_data, *cfg.axis_sizes[1:])
    dcn = (cfg.dcn_data, 1, 1, 1, 1, 1)
    # Granule choice: TPU multi-slice runs distinguish devices by
    # slice_index; everywhere else (CPU/GPU fleets — and single-slice
    # backends, where slice_index exists but is 0 on every device) the
    # process is the DCN granule. Decide by whichever attribute actually
    # distinguishes more than one granule.
    slice_vals = {getattr(d, "slice_index", None) for d in devices}
    try:
        arr = mesh_utils.create_hybrid_device_mesh(
            per_slice, dcn, devices=devices,
            process_is_granule=len(slice_vals) <= 1,
            allow_split_physical_axes=True,  # parity with the flat TPU path
        )
    except ValueError as e:
        hint = (
            " Hint: dcn_data must equal the number of DCN granules (TPU "
            "slices, or processes off-TPU) the devices span."
            if "granule" in str(e) or "slices" in str(e).lower()
            else ""
        )
        raise ValueError(
            f"hybrid mesh {per_slice} x dcn {dcn} failed: {e}.{hint}"
        ) from e
    return Mesh(arr, cfg.axis_names)


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bring-up. On TPU pods the runtime provides everything and a
    bare ``jax.distributed.initialize()`` suffices; explicit args support
    CPU/GPU fleets.

    Must run before any JAX call that initializes the XLA backend (including
    ``jax.process_count()``/``jax.devices()``) — ``jax.distributed.initialize``
    raises otherwise, so this function probes initialization state without
    touching the backend and re-raises real bring-up failures instead of
    silently degrading to a single-host run."""
    is_initialized = getattr(jax.distributed, "is_initialized", None)
    if is_initialized is not None:
        if is_initialized():
            return  # already initialized (e.g. by the launcher)
    else:  # older JAX without the public probe
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return
    if coordinator_address is None and num_processes is None and process_id is None:
        # Auto-detection: only meaningful where a cluster environment exists
        # (TPU pod metadata, SLURM, ...). Absent one, stay single-process.
        try:
            jax.distributed.initialize()
        except (RuntimeError, ValueError, OSError):
            # No cluster environment to auto-detect (missing coordinator
            # address / unreachable peers): stay single-process.
            return
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
