"""Device-mesh construction and multi-host initialization.

Replaces the reference's replica topology (an explicit ``'/device:GPU:i'``
list handed to MirroredStrategy, ``distributed_train.py:137-138``) with a
logical 6-axis mesh:

    ('data', 'fsdp', 'model', 'seq', 'pipe', 'expert')

- gradients psum over 'data'+'fsdp'+'expert' (ICI),
- parameters/optimizer shard over 'fsdp',
- attention heads / dff shard over 'model',
- sequence blocks shard over 'seq' (ring attention),
- layer-stack stages over 'pipe' (GPipe schedule; activations hop
  stage-to-stage via ppermute — ``parallel/pipeline.py``),
- MoE expert weights over 'expert' (token slots reach their experts via the
  GSPMD-inserted all-to-all — ``ops/moe.py``).

TPU pods are multi-process by construction — ``initialize_distributed`` wraps
``jax.distributed.initialize`` so the same entry point works single-host (no-op)
and on a pod slice; the reference has no multi-host story at all (SURVEY §2.4).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

from transformer_tpu.config import MeshConfig


def make_mesh(cfg: MeshConfig, devices: list | None = None) -> Mesh:
    """Build the logical mesh over the given (default: all) devices.

    Axis order puts 'data' slowest and 'seq'/'model' fastest so that the
    axes with the heaviest collectives (TP all-reduces, ring permutes) land on
    nearest-neighbour ICI links when the physical topology allows.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    want = cfg.num_devices
    if want != len(devices):
        raise ValueError(
            f"mesh {cfg.axis_sizes} needs {want} devices, have {len(devices)} "
            f"({[str(d) for d in devices[:4]]}...). Enforced like the "
            "reference's batch/replica divisibility check "
            "(distributed_train.py:154-158)."
        )
    if devices and devices[0].platform == "tpu":
        # Topology-aware placement: on real TPU slices the physical ICI
        # graph is a torus, and a naive row-major reshape can put a
        # heavy-collective axis (model all-reduce, seq/pipe ring) across
        # non-adjacent chips. mesh_utils maps logical axes onto physical
        # torus axes (deterministic for a given topology, so every host in
        # a pod computes the same assignment). CPU/GPU fall through to the
        # plain reshape — there is no torus to exploit.
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(
                cfg.axis_sizes, devices=devices, allow_split_physical_axes=True
            )
            return Mesh(arr, cfg.axis_names)
        except Exception as e:  # unusual topology: the reshape below is valid
            import warnings

            warnings.warn(
                "topology-aware mesh placement unavailable "
                f"({type(e).__name__}: {e}); falling back to row-major "
                "device order — heavy-collective axes may land on "
                "non-adjacent chips",
                RuntimeWarning,
                stacklevel=2,
            )
    arr = np.asarray(devices).reshape(cfg.axis_sizes)
    return Mesh(arr, cfg.axis_names)


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bring-up. On TPU pods the runtime provides everything and a
    bare ``jax.distributed.initialize()`` suffices; explicit args support
    CPU/GPU fleets.

    Must run before any JAX call that initializes the XLA backend (including
    ``jax.process_count()``/``jax.devices()``) — ``jax.distributed.initialize``
    raises otherwise, so this function probes initialization state without
    touching the backend and re-raises real bring-up failures instead of
    silently degrading to a single-host run."""
    is_initialized = getattr(jax.distributed, "is_initialized", None)
    if is_initialized is not None:
        if is_initialized():
            return  # already initialized (e.g. by the launcher)
    else:  # older JAX without the public probe
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return
    if coordinator_address is None and num_processes is None and process_id is None:
        # Auto-detection: only meaningful where a cluster environment exists
        # (TPU pod metadata, SLURM, ...). Absent one, stay single-process.
        try:
            jax.distributed.initialize()
        except Exception:
            return
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
