"""Distributed engine (L6): device meshes, sharding rules, sharded train
steps, and sequence-parallel ring attention.

The TPU-native replacement for the reference's
``tf.distribute.MirroredStrategy``/NCCL layer (``distributed_train.py``):
instead of a strategy object fanning a step out to replicas with hidden
all-reduces, a ``jax.sharding.Mesh`` plus PartitionSpecs on state and batch
turn the *same* train step into an SPMD program — XLA inserts the gradient
psum (over ICI within a slice, DCN across slices) where the shardings demand
it. No launcher daemon, no per-replica iterators, no explicit collectives in
user code.
"""

from transformer_tpu.parallel.mesh import make_mesh
from transformer_tpu.parallel.sharding import (
    batch_spec,
    param_partition_spec,
    state_shardings,
)
from transformer_tpu.parallel.distributed import (
    DistributedTrainer,
    create_sharded_state,
    make_sharded_multistep,
    make_sharded_steps,
    put_batch,
)
from transformer_tpu.parallel.pipeline import (
    pipeline_apply,
    pipelined_transformer_apply,
    stack_layer_params,
    unstack_layer_params,
)

__all__ = [
    "DistributedTrainer",
    "pipeline_apply",
    "pipelined_transformer_apply",
    "stack_layer_params",
    "unstack_layer_params",
    "batch_spec",
    "create_sharded_state",
    "make_mesh",
    "make_sharded_multistep",
    "make_sharded_steps",
    "param_partition_spec",
    "put_batch",
    "state_shardings",
]
