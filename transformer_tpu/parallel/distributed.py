"""Sharded state construction, sharded train/eval steps, and the distributed
trainer.

Counterpart of the reference's ``DistributedTrain`` (``distributed_train.py:
25-121``) — but where the reference wraps the inherited step in
``strategy.experimental_run`` and lets MirroredStrategy mirror variables and
all-reduce gradients via NCCL, here the *same* pure train step from
``train/trainer.py`` is jitted with shardings: parameters/optimizer sharded
per ``parallel/sharding.py``, batches sharded over the data axes, and XLA
materializes the gradient psum over ICI. One code path; axes are config,
not subclasses. Supported compositions (enforced by the checks below, and
test-pinned in tests/test_distributed.py::TestCompositionMatrix):

    data × fsdp × model × seq     (seq needs attention_impl ring/ulysses)
    data × fsdp × model × pipe    (model stays GSPMD-auto inside GPipe)
    data × fsdp × expert          (MoE; expert also shards the batch dim)
    NOT: pipe × {seq, expert} — the seq/expert shard_map contexts cannot
    fire inside the GPipe manual region (documented rejection).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.train.state import TrainState, create_train_state, make_optimizer
from transformer_tpu.train.trainer import Trainer, make_eval_step, make_train_step
from transformer_tpu.parallel.sharding import batch_spec, state_shardings


def create_sharded_state(
    rng: jax.Array, model_cfg: ModelConfig, train_cfg: TrainConfig, mesh: Mesh
) -> tuple[TrainState, Any]:
    """Initialize the train state directly into its shards: the init function
    is jitted with out_shardings, so each device materializes only its slice —
    no host-side full copy, which is what makes >HBM models initializable."""
    init = lambda r: create_train_state(r, model_cfg, train_cfg)
    shape = jax.eval_shape(init, rng)
    shardings = state_shardings(shape, mesh)
    state = jax.jit(init, out_shardings=shardings)(rng)
    return state, shardings


def _pipelined_forward(
    mesh: Mesh, model_cfg: ModelConfig, train_cfg: TrainConfig,
    hidden: bool = False,
) -> Callable:
    """GPipe forward for meshes with a ``pipe`` axis: parameters stay in the
    regular (unstacked) tree — stacking happens at trace time inside
    ``pipelined_transformer_apply`` — so state, optimizer, checkpointing and
    shardings are untouched; only the forward changes.

    ``hidden=True`` builds the pre-vocab-projection variant for the chunked
    loss (contract: always returns ``(hiddens, moe_aux|None)``)."""
    from transformer_tpu.parallel.pipeline import pipelined_transformer_apply

    num_mb = train_cfg.pp_microbatches or mesh.shape["pipe"]

    def forward(params, src, tar_inp, rng, deterministic):
        out = pipelined_transformer_apply(
            params, src, tar_inp, model_cfg,
            mesh=mesh, num_microbatches=num_mb,
            rng=None if deterministic else rng, deterministic=deterministic,
            return_hidden=hidden,
        )
        if hidden:
            return out if isinstance(out, tuple) else (out, None)
        return out

    return forward


def _seq_parallel_forward(
    mesh: Mesh, model_cfg: ModelConfig, base_forward: Callable | None,
    hidden: bool = False,
) -> Callable:
    """Forward wrapper for meshes with a ``seq`` axis and a sequence-parallel
    attention impl ("ring"/"ulysses"): activates the SeqParallelContext so
    every ``mha_apply`` traced inside runs its attention core under shard_map
    with the sequence split over the ``seq`` axis (KV ring over ICI).

    ``hidden=True`` wraps the pre-vocab-projection forward instead (chunked
    loss; contract: always returns ``(hiddens, moe_aux|None)``) — the
    pad/slice logic is identical, it just acts on (B, S, d_model)."""
    from transformer_tpu.config import PAD_ID
    from transformer_tpu.parallel.seq_context import (
        SeqParallelContext,
        sequence_parallel,
    )
    from transformer_tpu.train.trainer import (
        _default_forward,
        _default_hidden_forward,
    )

    import jax.numpy as jnp

    inner = base_forward or (
        _default_hidden_forward(model_cfg) if hidden else _default_forward(model_cfg)
    )
    ctx = SeqParallelContext(mesh=mesh)
    sp = mesh.shape["seq"]

    def pad_ids(ids):
        # Ring/Ulysses need S % sp == 0, but teacher forcing feeds S-1 tokens
        # (train/trainer._shift_targets). Trailing PAD positions are inert:
        # masked out of attention by the padding mask, causally unable to
        # influence earlier positions, and their logits are sliced off below.
        if ids is None:
            return None, 0
        extra = (-ids.shape[1]) % sp
        if extra:
            ids = jnp.pad(ids, ((0, 0), (0, extra)), constant_values=PAD_ID)
        return ids, extra

    def forward(params, src, tar_inp, rng, deterministic):
        src_p, _ = pad_ids(src)
        tar_p, extra = pad_ids(tar_inp)
        with sequence_parallel(ctx):
            out = inner(params, src_p, tar_p, rng, deterministic)
        logits, aux = out if isinstance(out, tuple) else (out, None)
        logits = logits[:, : logits.shape[1] - extra]
        if hidden:
            return logits, aux  # (hiddens, aux|None): fixed-arity contract
        return logits if aux is None else (logits, aux)

    return forward


def _expert_parallel_forward(
    mesh: Mesh, model_cfg: ModelConfig, base_forward: Callable | None,
    hidden: bool = False,
) -> Callable:
    """Forward wrapper for MoE models on meshes with an ``expert`` axis:
    activates the ``ops.moe.expert_mesh`` context so every ``moe_apply``
    traced inside annotates its dispatch/combine boundaries — GSPMD then
    moves token slots to their experts with one all-to-all over ICI instead
    of its replicate-then-slice fallback."""
    from transformer_tpu.ops.moe import expert_mesh
    from transformer_tpu.train.trainer import (
        _default_forward,
        _default_hidden_forward,
    )

    inner = base_forward or (
        _default_hidden_forward(model_cfg) if hidden else _default_forward(model_cfg)
    )

    def forward(params, src, tar_inp, rng, deterministic):
        with expert_mesh(mesh):
            return inner(params, src, tar_inp, rng, deterministic)

    return forward


def make_1f1b_train_step(
    mesh: Mesh,
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    tx: Any = None,
) -> Callable:
    """Train step using the 1F1B pipeline schedule
    (``parallel.pipeline.pipeline_train_1f1b``): same optimizer/metrics
    contract as ``make_train_step``, but loss AND gradients come out of the
    manual interleaved schedule — activation stash is O(stages), not
    O(microbatches), which is what lets pp_microbatches grow to shrink the
    bubble at pod scale without blowing HBM.

    Supported surface (hard-checked): dense and homogeneous-MoE
    (``moe_every == 1``) models on data x fsdp x model x pipe meshes —
    fsdp composes ZeRO-3 style (layer params stay sharded at rest,
    gathered one layer at a time inside the stage, grads reduce-scattered
    by the gather's vjp) and the model axis stays GSPMD-auto (stage
    interiors keep heads/dff sharding through the engine's internal
    vjps). MoE's load-balance aux rides the engine's manual backward
    (``pipeline_train_1f1b(with_aux=True)``: each stage vjp gets the aux
    objective's constant cotangent seed) and the seq2seq encoder half's
    aux seeds its GPipe vjp directly. Seq2seq runs a HYBRID: the decoder
    stack (the 3-sublayer half that dominates memory) runs the 1F1B
    engine with the encoder output as a gradient stream, while the
    encoder stack runs the GPipe forward with its autodiff backward (its
    activation stash stays O(microbatches); the decoder's is O(stages)).
    GPipe keeps chunked loss; that raises here with a pointer back to
    pp_schedule=gpipe.
    """
    import jax.numpy as jnp
    import optax

    from transformer_tpu.config import PAD_ID
    from transformer_tpu.models.decoder import decoder_layer_apply
    from transformer_tpu.models.encoder import embed_prologue, encoder_layer_apply
    from transformer_tpu.models.transformer import project_logits
    from transformer_tpu.ops.masks import make_padding_mask
    from transformer_tpu.ops.nn import layernorm_apply
    from transformer_tpu.parallel.pipeline import (
        _layer_fsdp_specs,
        pipeline_apply,
        pipeline_train_1f1b,
        stack_layer_params,
        unstack_layer_params,
    )
    from transformer_tpu.train.loss import masked_cross_entropy
    from transformer_tpu.train.trainer import _shift_targets

    if model_cfg.moe_experts and model_cfg.moe_every > 1:
        # Same homogeneity rule _raw_sharded_steps enforces for any pipe>1
        # mesh, repeated here so direct callers get the message too.
        raise ValueError(
            "pipe>1 requires a homogeneous layer stack: set moe_every=1 "
            "(every layer MoE) — mixed dense/MoE stacks cannot stack over "
            "the pipe axis"
        )
    if train_cfg.loss_chunks > 1:
        raise ValueError(
            "pp_schedule='1f1b' already bounds logits memory per microbatch; "
            "loss_chunks>1 is unsupported with it (use pp_schedule='gpipe')"
        )
    if train_cfg.grad_accum_steps > 1:
        raise ValueError(
            "pp_schedule='1f1b' accumulates per microbatch already; raise "
            "pp_microbatches instead of grad_accum_steps"
        )
    unsupported = {
        a: mesh.shape[a]
        for a in ("seq", "expert")
        if mesh.shape.get(a, 1) > 1
    }
    if unsupported:
        raise ValueError(
            f"pp_schedule='1f1b' composes with 'data', 'fsdp' and 'model', "
            f"not {unsupported} (the seq/expert shard_map contexts cannot "
            "fire inside the 1f1b manual region — same rejection as GPipe; "
            "use a non-pipe mesh for those axes)"
        )
    if "pipe" not in mesh.shape:
        raise ValueError(
            "pp_schedule='1f1b' needs a 'pipe' mesh axis "
            f"(mesh axes: {tuple(mesh.shape)})"
        )

    tx = tx or make_optimizer(model_cfg, train_cfg)
    num_mb = train_cfg.pp_microbatches or mesh.shape["pipe"]

    seq2seq = not model_cfg.decoder_only
    moe = bool(model_cfg.moe_experts)
    # Tensor parallelism composes by exclusion, like GPipe: the model axis
    # stays GSPMD-auto so stage interiors keep their heads/dff sharding
    # through the engine's internal vjps.
    auto = ("model",) if mesh.shape.get("model", 1) > 1 else ()

    if seq2seq:
        def layer_fn(lp, h, r, enc_mb, src_mb, ti_mb, to_mb):
            smask = make_padding_mask(ti_mb, PAD_ID)
            cmask = make_padding_mask(src_mb, PAD_ID)
            out = decoder_layer_apply(
                lp, h, enc_mb, smask, cmask, model_cfg, r, r is None
            )
            return (out[0], out[4]) if moe else out[0]
    else:
        def layer_fn(lp, h, r, ti_mb, to_mb):
            smask = make_padding_mask(ti_mb, PAD_ID)
            out = decoder_layer_apply(
                lp, h, None, smask, None, model_cfg, r, r is None
            )
            return (out[0], out[4]) if moe else out[0]

    if model_cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)

    def _head(nonlayer, h_mb, to_mb, inv_d):
        if model_cfg.norm_scheme == "pre":
            h_mb = layernorm_apply(
                nonlayer["decoder"]["final_ln"], h_mb, model_cfg.layernorm_epsilon
            )
        logits = project_logits(nonlayer, h_mb, model_cfg)
        _, m = masked_cross_entropy(
            logits, to_mb,
            label_smoothing=train_cfg.label_smoothing,
            normalization="tokens",  # only the sums are consumed
        )
        # Objective pre-scaled by 1/denom: cotangent seed 1.0 then yields
        # gradients in the final normalization directly.
        return m["loss_sum"] * inv_d, {
            "loss_sum": m["loss_sum"],
            "weight": m["weight"],
            "correct": m["correct"],
        }

    # Explicit per-branch stream binding (mirrors layer_fn): a positional
    # "*rest" unpack would silently misread targets if the streams tuple
    # built in train_step ever changed order.
    if seq2seq:
        def head_fn(nonlayer, h_mb, enc_mb, src_mb, ti_mb, to_mb, inv_d):
            return _head(nonlayer, h_mb, to_mb, inv_d)
    else:
        def head_fn(nonlayer, h_mb, ti_mb, to_mb, inv_d):
            return _head(nonlayer, h_mb, to_mb, inv_d)

    def train_step(state: TrainState, src, tgt, rng):
        tar_inp, tar_out = _shift_targets(tgt)
        step_rng = jax.random.fold_in(rng, state.step)
        # Same 4-way split as pipelined_transformer_apply, so the rng
        # streams line up with the GPipe path.
        r_embed_e, r_embed_d, r_enc, r_dec = jax.random.split(step_rng, 4)
        weight = jnp.sum((tar_out != PAD_ID).astype(jnp.float32))
        if train_cfg.loss_normalization == "tokens":
            denom = jnp.maximum(weight, 1.0)
        else:  # "batch": the reference's rule, train.py:88
            denom = jnp.float32(train_cfg.batch_size)
        params = state.params

        enc_vjp = None
        enc_aux = None
        if seq2seq:
            # Encoder half: GPipe forward with jax.vjp providing its
            # autodiff backward (stash O(microbatches) for this half; the
            # decoder half below gets the O(stages) 1f1b stash). The vjp is
            # seeded later with the decoder engine's d(enc_out) stream —
            # plus, for MoE, the aux objective's constant seed.
            def enc_forward(p):
                x = embed_prologue(
                    p["encoder"]["embedding"], src, model_cfg, r_embed_e, False
                )

                def enc_layer(lp, h, r, emask):
                    out = encoder_layer_apply(
                        lp, h, emask, model_cfg, r, r is None
                    )
                    return (out[0], out[2]) if moe else out[0]

                if model_cfg.remat:
                    enc_layer = jax.checkpoint(enc_layer)
                out = pipeline_apply(
                    stack_layer_params(p["encoder"]["layers"]),
                    enc_layer, x, (make_padding_mask(src, PAD_ID),),
                    mesh=mesh, num_microbatches=num_mb, base_rng=r_enc,
                    param_specs=_layer_fsdp_specs(
                        p["encoder"]["layers"][0], mesh
                    ),
                    with_aux=moe, auto_axes=auto,
                )
                aux = None
                if moe:
                    out, aux = out
                if model_cfg.norm_scheme == "pre":
                    out = layernorm_apply(
                        p["encoder"]["final_ln"], out,
                        model_cfg.layernorm_epsilon,
                    )
                return (out, aux) if moe else out

            if moe:
                (enc_out, enc_aux), enc_vjp = jax.vjp(enc_forward, params)
            else:
                enc_out, enc_vjp = jax.vjp(enc_forward, params)

        def prologue(p):
            return embed_prologue(
                p["decoder"]["embedding"], tar_inp, model_cfg, r_embed_d, False
            )

        h0, pro_vjp = jax.vjp(prologue, params)
        stacked = stack_layer_params(params["decoder"]["layers"])
        nonlayer = {**params, "decoder": {**params["decoder"], "layers": ()}}
        if seq2seq:
            # The head never reads the encoder subtree (its real grads come
            # from enc_vjp outside) — strip it entirely rather than
            # replicate a vocab-sized embedding into the engine and psum
            # its zero gradients every step.
            nonlayer = {k: v for k, v in nonlayer.items() if k != "encoder"}
            streams = (enc_out, src, tar_inp, tar_out)
            gs = (0,)  # d(enc_out) comes back to seed the encoder backward
        else:
            streams = (tar_inp, tar_out)
            gs = ()
        engine_out = pipeline_train_1f1b(
            stacked, nonlayer, h0, streams,
            layer_fn, head_fn, 1.0 / denom,
            mesh=mesh, num_microbatches=num_mb, base_rng=r_dec,
            param_specs=_layer_fsdp_specs(params["decoder"]["layers"][0], mesh),
            auto_axes=auto,
            grad_streams=gs,
            with_aux=moe, aux_weight=model_cfg.moe_aux_weight,
        )
        if seq2seq:
            sums, d_h0, d_stacked, d_nonlayer, (d_enc,) = engine_out
        else:
            sums, d_h0, d_stacked, d_nonlayer = engine_out
        (d_pro,) = pro_vjp(d_h0)
        layer_grads = unstack_layer_params(d_stacked, model_cfg.num_layers)
        d_engine = {
            **d_nonlayer,
            "decoder": {**d_nonlayer["decoder"], "layers": layer_grads},
        }
        if seq2seq:
            # The engine never saw the encoder subtree — restore the full
            # param structure with zeros (the real encoder grads come from
            # enc_vjp, which differentiates wrt the FULL param tree).
            d_engine = {
                **d_engine,
                "encoder": jax.tree.map(jnp.zeros_like, params["encoder"]),
            }
        grads = jax.tree.map(jnp.add, d_pro, d_engine)
        if seq2seq:
            if moe:
                # The encoder stack's aux enters the objective with
                # coefficient moe_aux_weight: seed its cotangent alongside
                # the activation stream's.
                (d_enc_params,) = enc_vjp((
                    d_enc.astype(enc_out.dtype),
                    jnp.float32(model_cfg.moe_aux_weight),
                ))
            else:
                (d_enc_params,) = enc_vjp(d_enc.astype(enc_out.dtype))
            grads = jax.tree.map(jnp.add, grads, d_enc_params)
        metrics = {
            "loss": sums["loss_sum"] / denom,
            "loss_sum": sums["loss_sum"],
            "weight": sums["weight"],
            "correct": sums["correct"],
            # Same training-health scalar trainer._apply reports, computed
            # on the manually-assembled 1F1B gradients.
            "grad_norm": optax.global_norm(grads).astype(jnp.float32),
        }
        if moe:
            # The engine already normalized its aux to the GPipe forward's
            # model-level definition; add the encoder half's scalar.
            metrics["moe_aux"] = (
                sums["moe_aux"] if enc_aux is None
                else enc_aux + sums["moe_aux"]
            )
        updates, new_opt_state = tx.update(grads, state.opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        return new_state, metrics

    return train_step


def _raw_sharded_steps(
    mesh: Mesh,
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
) -> tuple[Callable, Callable]:
    """Validation + the mesh-aware forward chain, returning the UNJITTED
    train/eval step functions — shared by :func:`make_sharded_steps` (plain
    jit-with-shardings) and :func:`make_sharded_multistep` (K-step scan)."""
    if (
        model_cfg.moe_experts
        and model_cfg.moe_every > 1
        and mesh.shape.get("pipe", 1) > 1
    ):
        # Homogeneous MoE stacks (moe_every == 1) pipeline fine — layer
        # params stack and the aux loss rides the schedule
        # (pipeline_apply(with_aux=True)). A mixed dense/MoE stack has
        # per-layer trees of different SHAPE, which stack_layer_params
        # cannot stack.
        raise ValueError(
            "pipe>1 requires a homogeneous layer stack: set moe_every=1 "
            "(every layer MoE) — mixed dense/MoE stacks cannot stack over "
            "the pipe axis"
        )
    if model_cfg.encoder_only and (
        mesh.shape.get("pipe", 1) > 1 or mesh.shape.get("seq", 1) > 1
    ):
        # The pipelined/sequence-parallel forward builders are written for
        # the decoder-bearing families; encoder-only (MLM) shards over
        # data / fsdp / model / expert via plain GSPMD today.
        raise ValueError(
            "encoder_only models support data/fsdp/model/expert mesh axes; "
            "pipe and seq are not wired for the encoder-only forward"
        )
    ep = mesh.shape.get("expert", 1)
    if ep > 1 and model_cfg.moe_experts % ep:
        # Without this check _divisible would silently replicate every expert
        # weight — the user would get the memory profile of no EP at all.
        raise ValueError(
            f"moe_experts {model_cfg.moe_experts} must be divisible by the "
            f"expert mesh axis ({ep}) for expert weights to shard"
        )
    def build_forward(hidden: bool) -> Callable | None:
        fn = (
            _pipelined_forward(mesh, model_cfg, train_cfg, hidden=hidden)
            if mesh.shape.get("pipe", 1) > 1
            else None
        )
        if (
            mesh.shape.get("seq", 1) > 1
            and model_cfg.attention_impl in ("ring", "ulysses")
        ):
            fn = _seq_parallel_forward(mesh, model_cfg, fn, hidden=hidden)
        if model_cfg.moe_experts and mesh.shape.get("expert", 1) > 1:
            fn = _expert_parallel_forward(mesh, model_cfg, fn, hidden=hidden)
        return fn

    forward_fn = build_forward(hidden=False)
    # The chunked vocab-projection/CE path needs the pre-projection forward;
    # built through the SAME wrapper chain, so loss_chunks composes with
    # pipeline / sequence-parallel / expert meshes (r2 VERDICT missing-#3).
    hidden_forward_fn = (
        build_forward(hidden=True) if train_cfg.loss_chunks > 1 else None
    )
    # (pp_schedule values are validated at TrainConfig construction.)
    if (
        mesh.shape.get("pipe", 1) > 1
        and train_cfg.pp_schedule == "1f1b"
    ):
        # 1F1B swaps the TRAIN step only (loss+grads from the manual
        # interleaved schedule); eval has no backward, so the GPipe forward
        # built above stays — identical logits, no stash to bound. Without
        # a pipe axis pp_schedule is inert (like pp_microbatches).
        train = make_1f1b_train_step(mesh, model_cfg, train_cfg)
    else:
        train = make_train_step(
            model_cfg, train_cfg, forward_fn=forward_fn,
            hidden_forward_fn=hidden_forward_fn,
        )
    return (
        train,
        make_eval_step(
            model_cfg, train_cfg, forward_fn=forward_fn,
            hidden_forward_fn=hidden_forward_fn,
        ),
    )


def _metric_shardings(mesh: Mesh, model_cfg: ModelConfig) -> dict:
    repl = NamedSharding(mesh, P())
    metrics_sh = {
        "loss": repl, "loss_sum": repl, "weight": repl, "correct": repl,
        # grad_norm: every train-step builder (trainer._apply, the 1F1B
        # manual path) emits it; out_shardings must mirror the pytree.
        "grad_norm": repl,
    }
    if model_cfg.moe_experts:
        metrics_sh["moe_aux"] = repl
    return metrics_sh


def make_sharded_steps(
    mesh: Mesh,
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    shardings: Any,
    shard_seq: bool = False,
    donate: bool = True,
) -> tuple[Callable, Callable]:
    """jit the train/eval steps with explicit in/out shardings over ``mesh``.

    A mesh with ``pipe > 1`` swaps in the GPipe-pipelined forward; all other
    axes keep the plain SPMD-sharded step."""
    raw_train, raw_eval = _raw_sharded_steps(mesh, model_cfg, train_cfg)
    data_sh = NamedSharding(mesh, batch_spec(mesh, shard_seq))
    repl = NamedSharding(mesh, P())
    metrics_sh = _metric_shardings(mesh, model_cfg)
    train_step = jax.jit(
        raw_train,
        in_shardings=(shardings, data_sh, data_sh, repl),
        out_shardings=(shardings, metrics_sh),
        donate_argnums=(0,) if donate else (),
    )
    # Eval is forward-only: its metric pytree has no grad_norm leaf.
    eval_sh = {k: v for k, v in metrics_sh.items() if k != "grad_norm"}
    eval_step = jax.jit(
        raw_eval,
        in_shardings=(shardings, data_sh, data_sh),
        out_shardings=eval_sh,
    )
    return train_step, eval_step


def make_sharded_multistep(
    mesh: Mesh,
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    shardings: Any,
    shard_seq: bool = False,
    donate: bool = True,
) -> Callable:
    """``steps_per_dispatch`` over a mesh: the same wrapped forward chain as
    :func:`make_sharded_steps`, but K optimizer steps run inside one jitted
    ``lax.scan`` per dispatch (``trainer.make_multistep_train_step``).
    Batches arrive stacked (K, B, S); the leading (scan) axis is unsharded,
    each inner step's batch keeps the normal data/seq sharding."""
    from transformer_tpu.train.trainer import make_multistep_train_step

    raw_train, _ = _raw_sharded_steps(mesh, model_cfg, train_cfg)
    stacked_sh = NamedSharding(mesh, P(None, *batch_spec(mesh, shard_seq)))
    repl = NamedSharding(mesh, P())
    metrics_sh = _metric_shardings(mesh, model_cfg)
    return jax.jit(
        make_multistep_train_step(
            raw_train,
            has_moe=bool(model_cfg.moe_experts),
            loss_normalization=train_cfg.loss_normalization,
            batch_size=train_cfg.batch_size,
        ),
        in_shardings=(shardings, stacked_sh, stacked_sh, repl),
        out_shardings=(shardings, metrics_sh),
        donate_argnums=(0,) if donate else (),
    )


def put_batch(batch: np.ndarray, mesh: Mesh, shard_seq: bool = False) -> jax.Array:
    """Host batch -> sharded device array.

    Single-process: a plain ``device_put`` with a NamedSharding scatters the
    array across local devices. Multi-process (TPU pod): each host holds only
    its slice of the global batch (``Seq2SeqDataset.shard_index``), and
    ``make_array_from_process_local_data`` assembles the logical global array —
    the role the reference's ``strategy.make_dataset_iterator`` played
    (``distributed_train.py:151-152``), without a per-replica iterator protocol.
    """
    stacked = batch.ndim == 3  # (K, B, S): steps_per_dispatch groups
    if shard_seq:
        # Sequence sharding needs S divisible by the seq axis; trailing PAD
        # columns are inert (masked out of attention and loss) and the
        # seq-parallel forward re-pads/slices around teacher forcing anyway.
        from transformer_tpu.config import PAD_ID

        sp = mesh.shape["seq"]
        extra = (-batch.shape[-1]) % sp
        if extra:
            pad = [(0, 0)] * (batch.ndim - 1) + [(0, extra)]
            batch = np.pad(batch, pad, constant_values=PAD_ID)
    spec = batch_spec(mesh, shard_seq)
    if stacked:
        spec = P(None, *spec)  # scan axis unsharded
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_process_local_data(sharding, batch)


class DistributedTrainer(Trainer):
    """Trainer whose steps run SPMD over a mesh.

    Mirrors the reference's subclass relationship (``DistributedTrain(Train)``,
    ``distributed_train.py:25``) — everything except step construction and
    batch placement is inherited."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        mesh: Mesh,
        rng: jax.Array | None = None,
        shard_seq: bool = False,
        **kwargs: Any,
    ) -> None:
        batch_axes = mesh.shape["data"] * mesh.shape["fsdp"] * mesh.shape.get("expert", 1)
        if train_cfg.batch_size % batch_axes:
            raise ValueError(
                f"global batch size {train_cfg.batch_size} must be divisible "
                f"by data×fsdp×expert = {batch_axes} "
                "(reference check: distributed_train.py:154-158)"
            )
        n_stages = mesh.shape.get("pipe", 1)
        if n_stages > 1:
            # (Heterogeneous-MoE+pipe is rejected by make_sharded_steps.)
            # Supported with pipe: data (microbatches split per group), fsdp
            # (ZeRO-3 per-layer gather inside the stage scan), and model
            # (stage interiors stay GSPMD-auto over the model axis —
            # pipeline_apply(auto_axes)). See README "Composition matrix".
            unsupported = {
                a: mesh.shape[a]
                for a in ("seq", "expert")
                if mesh.shape.get(a, 1) > 1
            }
            if unsupported:
                raise ValueError(
                    f"pipe>1 composes with 'data', 'fsdp' and 'model' "
                    f"(parallel/pipeline.py), but not with {unsupported}: "
                    "sequence/expert sharding inside stages is not wired "
                    "through the GPipe path (the seq/expert shard_map "
                    "contexts cannot fire inside its manual region)."
                )
            if model_cfg.num_layers % n_stages:
                raise ValueError(
                    f"pipe axis size {n_stages} must divide num_layers "
                    f"{model_cfg.num_layers}"
                )
            per_shard = train_cfg.batch_size // (
                mesh.shape["data"] * mesh.shape["fsdp"]
            )
            num_mb = train_cfg.pp_microbatches or n_stages
            if per_shard % num_mb:
                raise ValueError(
                    f"pp_microbatches {num_mb} must divide the per-data-shard "
                    f"batch {per_shard}"
                )
        if mesh.shape.get("seq", 1) > 1:
            # A seq axis only helps if activations are actually split along
            # the sequence; ring/ulysses then keeps attention split too
            # (plain xla attention under GSPMD would all-gather the sequence).
            shard_seq = True
            if model_cfg.attention_impl not in ("ring", "ulysses"):
                raise ValueError(
                    f"MeshConfig(seq={mesh.shape['seq']}) needs a sequence-"
                    "parallel attention impl: set ModelConfig(attention_impl="
                    "'ring') (or 'ulysses'); plain "
                    f"{model_cfg.attention_impl!r} attention would all-gather "
                    "the sequence and defeat the axis"
                )
        rng = rng if rng is not None else jax.random.PRNGKey(train_cfg.seed)
        state, shardings = create_sharded_state(rng, model_cfg, train_cfg, mesh)
        self.mesh = mesh
        self.shard_seq = shard_seq
        self.shardings = shardings
        super().__init__(model_cfg, train_cfg, state, **kwargs)
        # Replace the plain-jit steps built by Trainer.__init__ with the
        # sharded versions (always jitted: eager SPMD doesn't exist),
        # honouring the caller's donate_state choice (tied-weight configs
        # must not donate: one buffer aliased into two consumers fails at
        # TPU execution time).
        donate = kwargs.get("donate_state", True)
        self.train_step_fn, self.eval_step_fn = make_sharded_steps(
            mesh, model_cfg, train_cfg, shardings, shard_seq, donate=donate
        )
        self.train_step = self._sharded_train_step
        self.eval_step = self._sharded_eval_step
        if train_cfg.steps_per_dispatch > 1:
            # Replace the PLAIN multi-step Trainer.__init__ built (it has no
            # shardings) with the mesh-aware one: same forward chain, K-step
            # scan, stacked batches sharded on their (B, S) axes only.
            self.multi_step_fn = make_sharded_multistep(
                mesh, model_cfg, train_cfg, shardings, shard_seq,
                donate=donate,
            )
            self.multi_step = self._sharded_multi_step
        if self.telemetry is not None:
            # The plain-step wrappers installed by Trainer.__init__ were just
            # replaced by the sharded steps — re-route them through the
            # dispatch-timing wrapper.
            self._wrap_steps_for_dispatch_timing()

    def _sharded_train_step(self, state, src, tgt, rng):
        src = put_batch(np.asarray(src), self.mesh, self.shard_seq)
        tgt = put_batch(np.asarray(tgt), self.mesh, self.shard_seq)
        return self.train_step_fn(state, src, tgt, rng)

    def _sharded_multi_step(self, state, src, tgt, rng):
        src = put_batch(np.asarray(src), self.mesh, self.shard_seq)
        tgt = put_batch(np.asarray(tgt), self.mesh, self.shard_seq)
        return self.multi_step_fn(state, src, tgt, rng)

    def _sharded_eval_step(self, state, src, tgt):
        src = put_batch(np.asarray(src), self.mesh, self.shard_seq)
        tgt = put_batch(np.asarray(tgt), self.mesh, self.shard_seq)
        return self.eval_step_fn(state, src, tgt)
