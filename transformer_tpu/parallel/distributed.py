"""Sharded state construction, sharded train/eval steps, and the distributed
trainer.

Counterpart of the reference's ``DistributedTrain`` (``distributed_train.py:
25-121``) — but where the reference wraps the inherited step in
``strategy.experimental_run`` and lets MirroredStrategy mirror variables and
all-reduce gradients via NCCL, here the *same* pure train step from
``train/trainer.py`` is jitted with shardings: parameters/optimizer sharded
per ``parallel/sharding.py``, batches sharded over the data axes, and XLA
materializes the gradient psum over ICI. One code path, any mesh shape —
dp / fsdp / tp / sp are config, not subclasses.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.train.state import TrainState, create_train_state, make_optimizer
from transformer_tpu.train.trainer import Trainer, make_eval_step, make_train_step
from transformer_tpu.parallel.sharding import batch_spec, state_shardings


def create_sharded_state(
    rng: jax.Array, model_cfg: ModelConfig, train_cfg: TrainConfig, mesh: Mesh
) -> tuple[TrainState, Any]:
    """Initialize the train state directly into its shards: the init function
    is jitted with out_shardings, so each device materializes only its slice —
    no host-side full copy, which is what makes >HBM models initializable."""
    init = lambda r: create_train_state(r, model_cfg, train_cfg)
    shape = jax.eval_shape(init, rng)
    shardings = state_shardings(shape, mesh)
    state = jax.jit(init, out_shardings=shardings)(rng)
    return state, shardings


def make_sharded_steps(
    mesh: Mesh,
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    shardings: Any,
    shard_seq: bool = False,
    donate: bool = True,
) -> tuple[Callable, Callable]:
    """jit the train/eval steps with explicit in/out shardings over ``mesh``."""
    data_sh = NamedSharding(mesh, batch_spec(mesh, shard_seq))
    repl = NamedSharding(mesh, P())
    metrics_sh = {
        "loss": repl, "loss_sum": repl, "weight": repl, "correct": repl
    }
    train_step = jax.jit(
        make_train_step(model_cfg, train_cfg),
        in_shardings=(shardings, data_sh, data_sh, repl),
        out_shardings=(shardings, metrics_sh),
        donate_argnums=(0,) if donate else (),
    )
    eval_step = jax.jit(
        make_eval_step(model_cfg, train_cfg),
        in_shardings=(shardings, data_sh, data_sh),
        out_shardings=metrics_sh,
    )
    return train_step, eval_step


def put_batch(batch: np.ndarray, mesh: Mesh, shard_seq: bool = False) -> jax.Array:
    """Host batch -> sharded device array.

    Single-process: a plain ``device_put`` with a NamedSharding scatters the
    array across local devices. Multi-process (TPU pod): each host holds only
    its slice of the global batch (``Seq2SeqDataset.shard_index``), and
    ``make_array_from_process_local_data`` assembles the logical global array —
    the role the reference's ``strategy.make_dataset_iterator`` played
    (``distributed_train.py:151-152``), without a per-replica iterator protocol.
    """
    sharding = NamedSharding(mesh, batch_spec(mesh, shard_seq))
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_process_local_data(sharding, batch)


class DistributedTrainer(Trainer):
    """Trainer whose steps run SPMD over a mesh.

    Mirrors the reference's subclass relationship (``DistributedTrain(Train)``,
    ``distributed_train.py:25``) — everything except step construction and
    batch placement is inherited."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        mesh: Mesh,
        rng: jax.Array | None = None,
        shard_seq: bool = False,
        **kwargs: Any,
    ) -> None:
        if train_cfg.batch_size % (mesh.shape["data"] * mesh.shape["fsdp"]):
            raise ValueError(
                f"global batch size {train_cfg.batch_size} must be divisible "
                f"by data×fsdp = {mesh.shape['data'] * mesh.shape['fsdp']} "
                "(reference check: distributed_train.py:154-158)"
            )
        rng = rng if rng is not None else jax.random.PRNGKey(train_cfg.seed)
        state, shardings = create_sharded_state(rng, model_cfg, train_cfg, mesh)
        self.mesh = mesh
        self.shard_seq = shard_seq
        self.shardings = shardings
        super().__init__(model_cfg, train_cfg, state, **kwargs)
        # Replace the plain-jit steps built by Trainer.__init__ with the
        # sharded versions (always jitted: eager SPMD doesn't exist).
        self.train_step_fn, self.eval_step_fn = make_sharded_steps(
            mesh, model_cfg, train_cfg, shardings, shard_seq
        )
        self.train_step = self._sharded_train_step
        self.eval_step = self._sharded_eval_step

    def _sharded_train_step(self, state, src, tgt, rng):
        src = put_batch(np.asarray(src), self.mesh, self.shard_seq)
        tgt = put_batch(np.asarray(tgt), self.mesh, self.shard_seq)
        return self.train_step_fn(state, src, tgt, rng)

    def _sharded_eval_step(self, state, src, tgt):
        src = put_batch(np.asarray(src), self.mesh, self.shard_seq)
        tgt = put_batch(np.asarray(tgt), self.mesh, self.shard_seq)
        return self.eval_step_fn(state, src, tgt)
