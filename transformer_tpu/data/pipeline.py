"""Host-side input pipeline: corpus reading, tokenization, static-shape
batching, shuffling, and device prefetch.

Counterpart of the reference's ``utils.py:65-161`` (TextLineDataset zip →
py_function encode → filter → shuffle → padded_batch), redesigned for TPU:

- **Static shapes.** The reference pads each batch to its own max length
  (``utils.py:154``) — under XLA every new shape is a recompile. Here train
  batches are padded to one fixed ``sequence_length`` (and test batches to a
  single rounded-up max), so the train step compiles exactly once.
- **Whole-corpus tokenization up front.** The reference tokenizes per example
  inside the hot loop via ``tf.py_function`` (``utils.py:149-150``) — a
  host-side bottleneck. The bundled corpus is tiny; encoding it once into
  int32 arrays removes Python from the steady-state loop entirely.
- **Epoch-seeded full shuffle** instead of a 100k-element shuffle buffer
  (``utils.py:154``): with the corpus in memory a true permutation is free and
  deterministic given (seed, epoch).

BOS/EOS framing matches the reference (``utils.py:137-143``): each side gets
``[vocab_size] + ids + [vocab_size + 1]``, pad id 0.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import queue
import threading
from collections.abc import Iterator

import numpy as np

from transformer_tpu.config import PAD_ID
from transformer_tpu.data.seeding import epoch_rng
from transformer_tpu.data.tokenizer import SubwordTokenizer

# Fault-injection slot (``data.prefetch``): ``serve.resilience.install``
# plants the plane's hook here so chaos tests can fail the prefetch worker
# deterministically — the injected OSError rides the worker's existing
# failure[] handoff and re-raises at the consumer, proving the cross-thread
# error path end-to-end without this module importing the serve stack.
fault_hook = None


def corpus_files(dataset_path: str, split: str) -> tuple[list[str], list[str]]:
    """Glob the src/tgt line files for one split — the reference's file
    convention (``utils.py:65-80,130-133``), shared by the in-memory and
    streaming readers so both accept exactly the same corpora."""
    src_files = sorted(glob.glob(os.path.join(dataset_path, f"src-{split}*.txt")))
    tgt_files = sorted(glob.glob(os.path.join(dataset_path, f"tgt-{split}*.txt")))
    if not src_files or not tgt_files:
        raise FileNotFoundError(
            f"no {split} corpus under {dataset_path!r} "
            f"(expected src-{split}*.txt / tgt-{split}*.txt)"
        )
    return src_files, tgt_files


def read_parallel_corpus(
    dataset_path: str, split: str = "train"
) -> tuple[list[str], list[str]]:
    """Read zipped src/tgt line files matching ``{src,tgt}-{split}*.txt``
    (the reference's glob convention, ``utils.py:65-80,130-133``)."""
    src_files, tgt_files = corpus_files(dataset_path, split)
    src_lines: list[str] = []
    tgt_lines: list[str] = []
    for sf, tf in zip(src_files, tgt_files):
        with open(sf, encoding="utf-8") as f:
            src_lines.extend(line.rstrip("\n") for line in f)
        with open(tf, encoding="utf-8") as f:
            tgt_lines.extend(line.rstrip("\n") for line in f)
    if len(src_lines) != len(tgt_lines):
        raise ValueError(
            f"parallel corpus length mismatch: {len(src_lines)} src vs "
            f"{len(tgt_lines)} tgt lines"
        )
    return src_lines, tgt_lines


def load_or_build_tokenizer(
    vocab_file: str,
    corpus: list[str] | None = None,
    target_vocab_size: int = 2**15,
):  # -> SubwordTokenizer | tfds_compat.TfdsSubwordTokenizer (duck-typed)
    """Load a persisted vocab, else train from the corpus and persist —
    the reference's first-run-builds behavior (``utils.py:96-111``).

    A vocab file in tfds ``SubwordTextEncoder`` format (saved by a real run
    of the reference under TF) is detected by its header and loaded through
    ``data.tfds_compat`` — same id space, so BLEU comparisons against that
    run share a vocabulary."""
    if os.path.exists(vocab_file):
        # SubwordTokenizer.load sniffs the format and routes tfds-format
        # files through data.tfds_compat automatically.
        return SubwordTokenizer.load(vocab_file)
    if corpus is None:
        raise FileNotFoundError(f"vocab file {vocab_file!r} missing and no corpus given")
    tok = SubwordTokenizer.build_from_corpus(corpus, target_vocab_size)
    os.makedirs(os.path.dirname(vocab_file) or ".", exist_ok=True)
    tok.save(vocab_file)
    return tok


def _encode_and_frame(
    lines: list[str], tok: SubwordTokenizer
) -> list[np.ndarray]:
    bos, eos = tok.bos_id, tok.eos_id
    return [
        np.asarray([bos, *tok.encode(line), eos], dtype=np.int32) for line in lines
    ]


def _threaded_device_prefetch(
    it: Iterator[tuple[np.ndarray, np.ndarray]], depth: int = 2
) -> Iterator:
    """Python fallback for ``prefetch=True`` without the native loader:
    a background thread assembles batches and ``jax.device_put``s them up
    to ``depth`` ahead, so host-side batch assembly and H2D transfer
    overlap with device steps instead of serializing with them. Yields
    batches in EXACTLY the source iterator's order (bit-identical to the
    ``prefetch=False`` path — pinned by test); exceptions in the worker
    re-raise at the consumer. The worker NEVER outlives the iterator: both
    the exhausted path and an early consumer exit (break / exception /
    generator close) drain the queue and join the thread before returning,
    so its in-flight ``device_put`` buffers are released with it
    (tests/test_data.py pins this; ``analysis/schedules.py
    prefetch_shutdown`` explores the shutdown interleavings)."""
    import jax

    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    failure: list[BaseException] = []
    sentinel = object()

    def worker() -> None:
        try:
            for item in it:
                if fault_hook is not None:
                    fault_hook("data.prefetch")
                payload = jax.device_put(item)
                # Bounded put that gives up if the consumer went away
                # (early break / generator close): a daemon thread parked
                # forever on a full queue would pin the batch buffers.
                while not stop.is_set():
                    try:
                        q.put(payload, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001  # tpa: disable=TPA006 — cross-thread reraise: the worker forwards EVERY failure to the consumer thread, which re-raises it; swallowing here would hang the consumer on a silent EOF instead
            failure.append(e)  # tpa: disable=TPA101 — handoff, not a race: the consumer reads `failure` only after thread.join() below, a real happens-before edge
        finally:
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    break
                except queue.Full:
                    continue

    thread = threading.Thread(
        target=worker, name="pipeline-prefetch", daemon=True
    )
    thread.start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        thread.join()
        if failure:
            raise failure[0]
    finally:
        stop.set()
        # Early exit (break, consumer exception, generator close) leaves
        # the worker alive — possibly parked on a full queue with a
        # device_put batch in hand. Drain the queue to unblock it and JOIN
        # before returning: a daemon thread outliving the iterator would
        # pin its in-flight device buffers for the rest of the process
        # (and a future consumer could observe its stale queue).
        while thread.is_alive():
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            thread.join(timeout=0.05)


@dataclasses.dataclass
class Seq2SeqDataset:
    """In-memory parallel dataset yielding fixed-shape (B, L) int32 batches.

    ``shard_index``/``shard_count`` slice the *batch dimension* for multi-host
    training: each host materializes only its slice of every global batch
    (batch order is identical on all hosts because the shuffle is
    (seed, epoch)-keyed, not stateful).
    """

    src: list[np.ndarray]
    tgt: list[np.ndarray]
    batch_size: int
    src_len: int
    tgt_len: int
    shuffle: bool = True
    seed: int = 0
    drop_remainder: bool = True
    shard_index: int = 0
    shard_count: int = 1
    # Length bucketing: a small ascending tuple of widths (e.g. (24, 36, 50),
    # last == src_len/tgt_len). Each example lands in the smallest bucket that
    # fits max(len(src), len(tgt)); batches are formed within buckets and
    # padded to the bucket width only. XLA compiles once per bucket —
    # len(buckets) static shapes instead of one — and short sentences stop
    # paying full-sequence-length FLOPs (the reference's per-batch ragged
    # padding, utils.py:154, bought the same saving at the cost of a
    # recompile per batch shape). () = single fixed width.
    length_buckets: tuple[int, ...] = ()
    # Opt-in C++ prefetching loader (transformer_tpu/native/dataloader.cc):
    # batch assembly runs in a background thread, overlapped with device
    # steps. Composes with length_buckets (per-bucket batches at bucket
    # width, plan interleaved). Shuffle order differs from the Python path
    # (splitmix64 Fisher-Yates vs numpy Philox) but is equally deterministic
    # per (seed, epoch); the unshuffled order and padding semantics are
    # identical.
    prefetch: bool = False
    _native: object = dataclasses.field(
        default=None, init=False, repr=False, compare=False
    )

    def __post_init__(self) -> None:
        if len(self.src) != len(self.tgt):
            raise ValueError("src/tgt example count mismatch")
        if self.batch_size % self.shard_count:
            raise ValueError(
                f"global batch size {self.batch_size} not divisible by "
                f"shard count {self.shard_count}"
            )
        if self.length_buckets:
            self.length_buckets = tuple(sorted(self.length_buckets))
            if self.length_buckets[-1] > max(self.src_len, self.tgt_len):
                raise ValueError(
                    f"largest bucket {self.length_buckets[-1]} exceeds the "
                    f"dataset width {max(self.src_len, self.tgt_len)}"
                )
            lengths = np.asarray(
                [max(len(s), len(t)) for s, t in zip(self.src, self.tgt)]
            )
            if lengths.size and int(lengths.max()) > self.length_buckets[-1]:
                # Refuse rather than silently clamp: clamping would cut
                # sentences mid-stream (and drop their EOS) with no
                # diagnostic. The largest bucket must cover the data — for
                # load_dataset that means buckets[-1] == sequence_length.
                n_over = int((lengths > self.length_buckets[-1]).sum())
                raise ValueError(
                    f"{n_over} examples exceed the largest length bucket "
                    f"{self.length_buckets[-1]} (longest is "
                    f"{int(lengths.max())}); make the last bucket as wide as "
                    "the length filter (sequence_length)"
                )
            # Example i -> smallest bucket that fits it.
            which = np.searchsorted(np.asarray(self.length_buckets), lengths)
            self._bucket_members = [
                np.flatnonzero(which == b)
                for b in range(len(self.length_buckets))
            ]

    def _batches_per_bucket(self, n: int) -> int:
        full, rem = divmod(n, self.batch_size)
        return full + (1 if rem and not self.drop_remainder else 0)

    def __len__(self) -> int:
        if self.length_buckets:
            return sum(
                self._batches_per_bucket(len(m)) for m in self._bucket_members
            )
        return self._batches_per_bucket(len(self.src))

    @property
    def num_examples(self) -> int:
        return len(self.src)

    def _native_loader(self):
        if self._native is None:
            from transformer_tpu import native

            local = self.batch_size // self.shard_count
            self._native = (
                native.NativeBatchLoader.create(
                    self.src, self.tgt, self.batch_size, local,
                    self.shard_index * local, self.src_len, self.tgt_len,
                    pad_id=PAD_ID,
                    length_buckets=self.length_buckets,
                )
                or False
            )
        return self._native or None

    def batches(self, epoch: int = 0) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        if self.prefetch:
            loader = self._native_loader()
            if loader is not None:
                seed = (self.seed * 0x9E3779B97F4A7C15 + epoch) & (2**64 - 1)
                yield from loader.epoch(seed, self.shuffle, self.drop_remainder)
                return
            import warnings

            warnings.warn(
                "prefetch requested but the native loader is unavailable; "
                "falling back to a Python background-thread double-buffer "
                "(jax.device_put one batch ahead). Batch order matches the "
                "prefetch=False Python path bit for bit — which differs "
                "from the native loader's shuffle, so with multi-host "
                "sharding EVERY host must take the same path (all native "
                "or all fallback) or the global shuffle desynchronizes",
                RuntimeWarning,
                stacklevel=2,
            )
            yield from _threaded_device_prefetch(self._python_batches(epoch))
            return
        yield from self._python_batches(epoch)

    def _python_batches(
        self, epoch: int
    ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """The in-memory Python batcher (bucketed or flat) — the order
        oracle every other path is pinned against."""
        if self.length_buckets:
            yield from self._bucketed_batches(epoch)
            return
        order = np.arange(len(self.src))
        if self.shuffle:
            rng = epoch_rng(self.seed, epoch)
            rng.shuffle(order)
        local = self.batch_size // self.shard_count
        lo = self.shard_index * local
        for start in range(0, len(order) - (self.batch_size - 1 if self.drop_remainder else 0), self.batch_size):
            global_idx = order[start : start + self.batch_size]
            if len(global_idx) < self.batch_size:
                # Final partial batch (drop_remainder=False): pad with empty
                # (-1) rows up to the full batch size. Every shard then yields
                # the SAME batch count and static shape — a short tail must
                # never make one host run a step its peers skip (multi-host
                # SPMD would deadlock), and all-pad rows carry zero metric
                # weight so results are unchanged.
                fill = np.full(self.batch_size - len(global_idx), -1, dtype=np.int64)
                global_idx = np.concatenate([global_idx, fill])
            yield self._pad(global_idx[lo : lo + local])

    def _bucketed_batches(
        self, epoch: int
    ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Form batches inside each length bucket, then emit them in a
        (seed, epoch)-shuffled global order so an epoch interleaves widths
        (all-short-first would skew the gradient distribution mid-epoch).
        Deterministic across hosts: same permutations on every process."""
        rng = epoch_rng(self.seed, epoch)
        plan: list[tuple[int, np.ndarray]] = []
        for b, members in enumerate(self._bucket_members):
            perm = (
                members[rng.permutation(len(members))]
                if self.shuffle
                else members
            )
            n_batches = self._batches_per_bucket(len(perm))
            for k in range(n_batches):
                gidx = perm[k * self.batch_size : (k + 1) * self.batch_size]
                if len(gidx) < self.batch_size:
                    fill = np.full(
                        self.batch_size - len(gidx), -1, dtype=np.int64
                    )
                    gidx = np.concatenate([gidx, fill])
                plan.append((self.length_buckets[b], gidx))
        if self.shuffle:
            rng.shuffle(plan)
        local = self.batch_size // self.shard_count
        lo = self.shard_index * local
        for width, gidx in plan:
            yield self._pad(gidx[lo : lo + local], width, width)

    def _pad(
        self,
        idx: np.ndarray,
        src_len: int | None = None,
        tgt_len: int | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        src_len = self.src_len if src_len is None else src_len
        tgt_len = self.tgt_len if tgt_len is None else tgt_len
        src = np.full((len(idx), src_len), PAD_ID, dtype=np.int32)
        tgt = np.full((len(idx), tgt_len), PAD_ID, dtype=np.int32)
        for row, i in enumerate(idx):
            if i < 0:
                continue  # padding row
            s = self.src[i][:src_len]  # over-length examples truncate
            t = self.tgt[i][:tgt_len]
            src[row, : len(s)] = s
            tgt[row, : len(t)] = t
        return src, tgt


def _round_up(n: int, multiple: int = 8) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def make_lm_dataset(
    lines: list[str],
    tok: SubwordTokenizer,
    batch_size: int,
    sequence_length: int,
    seed: int = 0,
    shard_index: int = 0,
    shard_count: int = 1,
    shuffle: bool = True,
    drop_remainder: bool = True,
) -> Seq2SeqDataset:
    """Causal-LM dataset: the corpus as one token stream, chunked into
    fixed ``sequence_length`` windows (the data path for the decoder-only /
    long-context configs — BASELINE configs[4]; no reference counterpart,
    the reference is seq2seq-only).

    Documents are joined with EOS separators; each window is BOS-prefixed so
    the decode convention matches translation (BOS feeds position 0). The
    same ``Seq2SeqDataset`` machinery provides shuffling/sharding; src is
    the window itself (``transformer_apply`` ignores ``inp`` when
    ``cfg.decoder_only``).
    """
    stream: list[np.ndarray] = []
    for line in lines:
        ids = tok.encode(line)
        if ids:
            stream.append(np.asarray(ids + [tok.eos_id], dtype=np.int32))
    if not stream:
        raise ValueError("empty corpus for LM dataset")
    flat = np.concatenate(stream)
    # Windows carry BOS + (sequence_length - 1) stream tokens: teacher
    # forcing shifts inside the train step, so consecutive windows need no
    # overlap.
    body = sequence_length - 1
    n_windows = len(flat) // body
    if n_windows == 0:
        raise ValueError(
            f"corpus ({len(flat)} tokens) shorter than one "
            f"{sequence_length}-token window"
        )
    windows = [
        np.concatenate(
            [[tok.bos_id], flat[i * body : (i + 1) * body]]
        ).astype(np.int32)
        for i in range(n_windows)
    ]
    return Seq2SeqDataset(
        windows,
        windows,
        batch_size=batch_size,
        src_len=sequence_length,
        tgt_len=sequence_length,
        shuffle=shuffle,
        seed=seed,
        shard_index=shard_index,
        shard_count=shard_count,
        drop_remainder=drop_remainder,
    )


def load_lm_splits(
    dataset_path: str,
    vocab_file: str,
    batch_size: int,
    sequence_length: int,
    target_vocab_size: int = 2**15,
    seed: int = 0,
    shard_index: int = 0,
    shard_count: int = 1,
) -> tuple[Seq2SeqDataset, Seq2SeqDataset | None, SubwordTokenizer]:
    """Causal-LM train (+ optional test) datasets over the target-side
    corpus — the single loading path shared by ``cli.train --decoder_only``
    and ``cli.distributed_train --decoder_only``. Eval sees every window
    exactly once (unshuffled, zero-weight-padded tail batch)."""
    _, tgt_lines = read_parallel_corpus(dataset_path, "train")
    tok = load_or_build_tokenizer(vocab_file, tgt_lines, target_vocab_size)
    train = make_lm_dataset(
        tgt_lines, tok,
        batch_size=batch_size,
        sequence_length=sequence_length,
        seed=seed,
        shard_index=shard_index,
        shard_count=shard_count,
    )
    test: Seq2SeqDataset | None
    try:
        _, test_tgt = read_parallel_corpus(dataset_path, "test")
        test = make_lm_dataset(
            test_tgt, tok,
            batch_size=batch_size,
            sequence_length=sequence_length,
            seed=seed,
            shard_index=shard_index,
            shard_count=shard_count,
            shuffle=False,
            drop_remainder=False,
        )
    except FileNotFoundError:
        test = None
    except ValueError:
        test = None  # test split shorter than one window
    return train, test, tok


def load_dataset(
    dataset_path: str,
    src_vocab_file: str,
    tgt_vocab_file: str,
    batch_size: int,
    sequence_length: int,
    target_vocab_size: int = 2**15,
    seed: int = 0,
    shard_index: int = 0,
    shard_count: int = 1,
    require_test: bool = False,
    prefetch: bool = False,
    length_buckets: tuple[int, ...] = (),
    exclude_test_overlap: bool = False,
    streaming: bool = False,
    buffer_size: int = 10000,
) -> tuple[Seq2SeqDataset, Seq2SeqDataset | None, SubwordTokenizer, SubwordTokenizer]:
    """Build train (+ optional test) datasets plus both tokenizers —
    the counterpart of reference ``load_dataset`` (``utils.py:114-161``).

    ``streaming=True`` swaps the train split for a
    ``data.streaming.StreamingSeq2SeqDataset``: the corpus is read and
    tokenized line-by-line with a ``buffer_size``-example shuffle buffer
    (the reference's ``--buffer_size`` semantics, ``utils.py:154``), so host
    memory stays O(buffer_size) no matter how large the corpus files are.
    Vocab files must already exist in streaming mode (building a vocabulary
    needs its own corpus pass — run once without streaming, or train vocabs
    on a sample). The (small) test split stays in-memory.

    Train examples with either side longer than ``sequence_length`` (after
    BOS/EOS framing) are dropped, mirroring the reference filter
    (``utils.py:145-147,153``). The reference also *loads* test files that it
    doesn't ship (``utils.py:132-133``, quirk §2.3.10) — here the test split is
    optional and simply skipped when absent unless ``require_test``.

    ``exclude_test_overlap`` drops every train pair whose exact (src, tgt)
    line pair also appears in the test split. The bundled test split is drawn
    from the train corpus tail (data/README.md), so without this the BLEU
    north star would be scored in-sample; with it, held-out. Tokenizer vocabs
    are still built from the FULL train files, so persisted ``*.subwords``
    caches are identical with and without the holdout.
    """
    if streaming:
        if prefetch or length_buckets:
            raise ValueError(
                "streaming=True does not compose with prefetch or "
                "length_buckets (the native loader and bucket planner need "
                "the in-memory example table)"
            )
        if not (os.path.exists(src_vocab_file) and os.path.exists(tgt_vocab_file)):
            raise FileNotFoundError(
                "streaming=True needs pre-built vocab files "
                f"({src_vocab_file!r}, {tgt_vocab_file!r}): vocabulary "
                "construction requires its own corpus pass — run once "
                "without streaming (or build vocabs from a sample) first"
            )
        from transformer_tpu.data.streaming import StreamingSeq2SeqDataset

        src_tok = SubwordTokenizer.load(src_vocab_file)
        tgt_tok = SubwordTokenizer.load(tgt_vocab_file)
        held: set[tuple[str, str]] = set()
        if exclude_test_overlap:
            try:
                held_src, held_tgt = read_parallel_corpus(dataset_path, "test")
                held = set(zip(held_src, held_tgt))
            except FileNotFoundError:
                pass
        stream_train = StreamingSeq2SeqDataset(
            dataset_path, src_tok, tgt_tok,
            batch_size=batch_size, sequence_length=sequence_length,
            buffer_size=buffer_size, seed=seed,
            shard_index=shard_index, shard_count=shard_count,
            exclude_pairs=held,
        )
        test = _build_test_split(
            dataset_path, src_tok, tgt_tok, batch_size, sequence_length,
            shard_index, shard_count, require_test,
        )
        return stream_train, test, src_tok, tgt_tok

    src_lines, tgt_lines = read_parallel_corpus(dataset_path, "train")
    src_tok = load_or_build_tokenizer(src_vocab_file, src_lines, target_vocab_size)
    tgt_tok = load_or_build_tokenizer(tgt_vocab_file, tgt_lines, target_vocab_size)

    if exclude_test_overlap:
        try:
            held_src, held_tgt = read_parallel_corpus(dataset_path, "test")
        except FileNotFoundError:
            held_src, held_tgt = [], []
        held = set(zip(held_src, held_tgt))
        if held:
            keep_pair = [
                i
                for i in range(len(src_lines))
                if (src_lines[i], tgt_lines[i]) not in held
            ]
            src_lines = [src_lines[i] for i in keep_pair]
            tgt_lines = [tgt_lines[i] for i in keep_pair]

    src_ids = _encode_and_frame(src_lines, src_tok)
    tgt_ids = _encode_and_frame(tgt_lines, tgt_tok)
    keep = [
        i
        for i in range(len(src_ids))
        if len(src_ids[i]) <= sequence_length and len(tgt_ids[i]) <= sequence_length
    ]
    train = Seq2SeqDataset(
        [src_ids[i] for i in keep],
        [tgt_ids[i] for i in keep],
        batch_size=batch_size,
        src_len=sequence_length,
        tgt_len=sequence_length,
        shuffle=True,
        seed=seed,
        shard_index=shard_index,
        shard_count=shard_count,
        prefetch=prefetch,  # composes with length_buckets (native bucketed plan)
        length_buckets=length_buckets,
    )

    test = _build_test_split(
        dataset_path, src_tok, tgt_tok, batch_size, sequence_length,
        shard_index, shard_count, require_test,
    )
    return train, test, src_tok, tgt_tok


def _build_test_split(
    dataset_path: str,
    src_tok: SubwordTokenizer,
    tgt_tok: SubwordTokenizer,
    batch_size: int,
    sequence_length: int,
    shard_index: int,
    shard_count: int,
    require_test: bool,
) -> Seq2SeqDataset | None:
    """The (small, always in-memory) test split shared by the in-memory and
    streaming train paths."""
    try:
        test_src_lines, test_tgt_lines = read_parallel_corpus(dataset_path, "test")
    except FileNotFoundError:
        if require_test:
            raise
        return None

    def _truncate_keep_eos(arrs: list[np.ndarray], eos: int) -> list[np.ndarray]:
        # Over-length eval examples are cut to fit the positional table,
        # but keep the EOS frame token the model always trained with.
        return [
            a if len(a) <= sequence_length
            else np.concatenate([a[: sequence_length - 1], [eos]]).astype(np.int32)
            for a in arrs
        ]

    tsrc = _truncate_keep_eos(_encode_and_frame(test_src_lines, src_tok), src_tok.eos_id)
    ttgt = _truncate_keep_eos(_encode_and_frame(test_tgt_lines, tgt_tok), tgt_tok.eos_id)
    # No length *filter* on test (reference ``utils.py:157-159``) — pad to
    # one rounded-up max so eval compiles once, but cap at
    # ``sequence_length``: the positional table is sized to it, so longer
    # examples are truncated rather than crashing eval (the reference only
    # survived these because its table was vocab-sized, quirk §2.3.5).
    return Seq2SeqDataset(
        tsrc,
        ttgt,
        batch_size=batch_size,
        src_len=min(_round_up(max(len(a) for a in tsrc)), sequence_length),
        tgt_len=min(_round_up(max(len(a) for a in ttgt)), sequence_length),
        shuffle=False,
        drop_remainder=False,
        shard_index=shard_index,
        shard_count=shard_count,
    )
