"""Streaming input pipeline: bounded-memory training on corpora that do not
fit in host RAM.

The in-memory path (``data/pipeline.py Seq2SeqDataset``) tokenizes the whole
corpus up front — the right call for the bundled 10k-pair corpus, and the one
capability gap vs the reference, whose ``TextLineDataset`` streams from disk
(``utils.py:77-80``) with a bounded shuffle buffer (``utils.py:154``,
``--buffer_size``). This module closes that gap TPU-side:

- **Line streams, chunked decode.** src/tgt files are read line-by-line and
  tokenized on the fly; no list of all examples ever exists.
- **Reservoir-style shuffle buffer** with the reference's semantics: a
  ``buffer_size``-example buffer is filled from the stream; each emitted
  example is drawn uniformly from the buffer and its slot refilled from the
  stream — exactly ``tf.data.Dataset.shuffle(buffer_size)``, but
  deterministic per ``(seed, epoch)`` (NumPy Philox keyed on both), so every
  host computes the same global batch sequence and slices its own rows.
- **Memory bound is structural**: peak example storage is ``buffer_size``
  (assert-pinned in tests/test_data.py), independent of corpus size.

Static shapes, PAD/BOS/EOS framing, the train-side length filter, and the
multi-host slice convention all match ``Seq2SeqDataset`` — the trainer
cannot tell the two apart (same ``.batches(epoch)`` / ``.num_examples``
surface).
"""

from __future__ import annotations

from collections.abc import Iterator

import numpy as np

from transformer_tpu.config import PAD_ID
from transformer_tpu.data.seeding import epoch_rng
from transformer_tpu.data.tokenizer import SubwordTokenizer


def _line_pairs(
    src_files: list[str], tgt_files: list[str]
) -> Iterator[tuple[str, str]]:
    """Zip the src/tgt line streams file by file; a length mismatch is an
    error at the point it is discovered (the in-memory reader checks the
    same invariant after reading everything). zip_longest rather than zip:
    plain zip consumes one extra line from the longer stream before noticing
    exhaustion, which would hide an off-by-one corpus corruption."""
    from itertools import zip_longest

    for sf, tf in zip_longest(src_files, tgt_files):
        if sf is None or tf is None:
            raise ValueError(
                f"parallel corpus file-count mismatch: {src_files} vs {tgt_files}"
            )
        with open(sf, encoding="utf-8") as fs, open(tf, encoding="utf-8") as ft:
            for s_line, t_line in zip_longest(fs, ft):
                if s_line is None or t_line is None:
                    raise ValueError(
                        f"parallel corpus length mismatch between {sf} and {tf}"
                    )
                yield s_line.rstrip("\n"), t_line.rstrip("\n")


class StreamingSeq2SeqDataset:
    """Disk-streaming counterpart of ``Seq2SeqDataset``: fixed-shape (B, L)
    int32 batches from corpora of unbounded size with O(buffer_size) host
    memory.

    Tokenizers must already exist (build them once with
    ``load_or_build_tokenizer`` — vocabulary construction needs its own
    corpus pass and is out of scope for the steady-state stream).
    """

    def __init__(
        self,
        dataset_path: str,
        src_tok: SubwordTokenizer,
        tgt_tok: SubwordTokenizer,
        batch_size: int,
        sequence_length: int,
        split: str = "train",
        buffer_size: int = 10000,
        seed: int = 0,
        shard_index: int = 0,
        shard_count: int = 1,
        shuffle: bool = True,
        drop_remainder: bool = True,
        length_filter: bool = True,
        exclude_pairs: set[tuple[str, str]] | None = None,
    ) -> None:
        if batch_size % shard_count:
            raise ValueError(
                f"global batch size {batch_size} not divisible by "
                f"shard count {shard_count}"
            )
        if buffer_size < 1:
            raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
        from transformer_tpu.data.pipeline import corpus_files

        self.src_files, self.tgt_files = corpus_files(dataset_path, split)
        self.src_tok = src_tok
        self.tgt_tok = tgt_tok
        self.batch_size = batch_size
        self.src_len = sequence_length
        self.tgt_len = sequence_length
        self.buffer_size = buffer_size
        self.seed = seed
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.length_filter = length_filter
        self.exclude_pairs = exclude_pairs or set()
        self._num_lines: int | None = None
        # Test hook: high-water mark of examples simultaneously resident
        # (shuffle buffer + one forming batch) across the last epoch — the
        # structural memory bound this class exists to provide.
        self.peak_resident_examples = 0

    @property
    def num_examples(self) -> int:
        """Raw line-pair count (pre length-filter — counting post-filter
        examples would need a full tokenization pass). One cheap line scan,
        cached."""
        if self._num_lines is None:
            n = 0
            for sf in self.src_files:
                with open(sf, encoding="utf-8") as f:
                    n += sum(1 for _ in f)
            self._num_lines = n
        return self._num_lines

    def _example_stream(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        s_bos, s_eos = self.src_tok.bos_id, self.src_tok.eos_id
        t_bos, t_eos = self.tgt_tok.bos_id, self.tgt_tok.eos_id
        for s_line, t_line in _line_pairs(self.src_files, self.tgt_files):
            if (s_line, t_line) in self.exclude_pairs:
                continue
            s = np.asarray(
                [s_bos, *self.src_tok.encode(s_line), s_eos], dtype=np.int32
            )
            t = np.asarray(
                [t_bos, *self.tgt_tok.encode(t_line), t_eos], dtype=np.int32
            )
            if self.length_filter and (
                len(s) > self.src_len or len(t) > self.tgt_len
            ):
                continue  # the reference's train filter, utils.py:145-147
            yield s, t

    def batches(self, epoch: int = 0) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        rng = epoch_rng(self.seed, epoch)
        local = self.batch_size // self.shard_count
        lo = self.shard_index * local

        def emit(batch):
            rows = batch[lo : lo + local]
            src = np.full((local, self.src_len), PAD_ID, dtype=np.int32)
            tgt = np.full((local, self.tgt_len), PAD_ID, dtype=np.int32)
            for r, (s, t) in enumerate(rows):
                src[r, : len(s)] = s
                tgt[r, : len(t)] = t
            return src, tgt

        buf_len = [0]  # live buffer size, for the resident high-water mark

        def drawn() -> Iterator[tuple[np.ndarray, np.ndarray]]:
            """The example sequence after (optional) buffered shuffling."""
            stream = self._example_stream()
            if not self.shuffle:
                # No buffer at all: slot-replacement would reorder a FIFO.
                yield from stream
                return
            buf: list[tuple[np.ndarray, np.ndarray]] = []
            for ex in stream:
                buf.append(ex)
                if len(buf) >= self.buffer_size:
                    break
            while buf:
                buf_len[0] = len(buf)
                j = int(rng.integers(len(buf)))
                out = buf[j]
                nxt = next(stream, None)
                if nxt is not None:
                    buf[j] = nxt
                else:
                    buf[j] = buf[-1]
                    buf.pop()
                yield out

        batch: list[tuple[np.ndarray, np.ndarray]] = []
        peak = 0
        for ex in drawn():
            batch.append(ex)
            peak = max(peak, buf_len[0] + len(batch))
            if len(batch) == self.batch_size:
                yield emit(batch)
                batch = []
        if batch and not self.drop_remainder:
            # Same tail convention as Seq2SeqDataset: pad to the full batch
            # with all-PAD rows (zero metric weight) so every shard emits
            # the same batch count.
            pad_row = (
                np.zeros(0, dtype=np.int32),
                np.zeros(0, dtype=np.int32),
            )
            batch.extend(pad_row for _ in range(self.batch_size - len(batch)))
            yield emit(batch)
        self.peak_resident_examples = peak
