"""The ONE definition of the epoch-shuffle seeding contract.

Every shuffling data path — the in-memory permutation, the bucketed batch
plan, and the streaming reservoir buffer — must draw from a PRNG keyed on
``(seed, epoch)``: deterministic given the pair, different across epochs,
and identical on every host (multi-host training slices rows out of a
GLOBAL batch order, so a drifting shuffle is silent batch corruption, not a
slow path). NumPy's ``default_rng`` feeds the tuple through SeedSequence,
so (0, 1) and (1, 0) land in unrelated streams — no manual mixing needed.

Previously this construction was repeated verbatim in three places
(``pipeline.Seq2SeqDataset.batches``, ``pipeline.Seq2SeqDataset.
_bucketed_batches``, ``streaming.StreamingSeq2SeqDataset.batches``); a
drift in any one of them would have been the corruption described above.
(The native C++ loader derives its own splitmix64 seed — documented in
``Seq2SeqDataset.prefetch`` — and is intentionally outside this contract.)
"""

from __future__ import annotations

import numpy as np


def keyed_rng(*key: int) -> np.random.Generator:
    """A deterministic PRNG keyed on an integer tuple (SeedSequence mixes
    the components, so (0, 1) and (1, 0) land in unrelated streams). The
    ONE place the tuple-keyed ``default_rng`` construction lives — the
    epoch shuffle below and the speculative-decoding acceptance draws
    (``serve/speculative.py``: keyed on (request seed, absolute position),
    so accept/reject decisions are reproducible per position) both route
    through it."""
    return np.random.default_rng(key)


def epoch_rng(seed: int, epoch: int) -> np.random.Generator:
    """The framework-wide epoch-shuffle PRNG: Philox via ``default_rng``
    keyed on ``(seed, epoch)``."""
    return keyed_rng(seed, epoch)
