"""Subword tokenizer: BPE trained from a corpus, greedy longest-match encode.

Capability counterpart of the reference's
``tfds.features.text.SubwordTextEncoder`` usage (``utils.py:96-111``):
``build_from_corpus(corpus, target_vocab_size=2**15)`` on first run, persisted
to a ``*.subwords`` vocab file, loaded thereafter. Conventions preserved so the
rest of the stack matches the reference pipeline semantics:

- id 0 is reserved for padding (never produced by ``encode``);
- subword ids run 1..vocab_size;
- BOS/EOS are *not* part of the vocab — the pipeline appends
  ``vocab_size`` / ``vocab_size + 1`` (``utils.py:137-143``), and models are
  built with ``vocab_size + 2`` embedding rows (``train.py:232-233``).

Word-boundary convention: each whitespace-separated word is encoded with a
trailing ``"_"`` marker (so ``decode(encode(s)) == s`` for any whitespace-
normalized string). Characters never seen at training time fall back to
byte-escape tokens ``<0xNN>``, which are always in the alphabet, so ``encode``
is total. The hot encode path has a C++ twin (``transformer_tpu/native``);
this module is the reference implementation and fallback.
"""

from __future__ import annotations

import heapq
import os
from collections import Counter
from collections.abc import Iterable, Iterator

_WORD_END = "_"
_ESCAPED_UNDERSCORE = "\\u"  # literal underscore in text is escaped on encode
_ESCAPED_BACKSLASH = "\\\\"  # literal backslash likewise (escape the escape)
_ESCAPED_LT = "\\<"  # literal '<' escaped so text can never collide with the
# byte-fallback token namespace "<0xNN>" (decode would otherwise reinterpret
# literal text like "<0x41>" as byte 0x41)


def _escape_char(ch: str) -> str:
    if ch == "_":
        return _ESCAPED_UNDERSCORE
    if ch == "\\":
        return _ESCAPED_BACKSLASH
    if ch == "<":
        return _ESCAPED_LT
    return ch


def _word_to_symbols(word: str) -> list[str]:
    """Split a word into its initial symbol sequence: characters with literal
    underscores/backslashes escaped, plus the word-end marker."""
    return [_escape_char(ch) for ch in word] + [_WORD_END]


def _byte_token(b: int) -> str:
    return f"<0x{b:02X}>"


class SubwordTokenizer:
    """BPE subword tokenizer with save/load and greedy longest-match encode."""

    def __init__(self, subwords: list[str]):
        if not subwords:
            raise ValueError("empty vocabulary")
        self.subwords = list(subwords)
        # id 0 = pad; real tokens start at 1.
        self._piece_to_id = {piece: i + 1 for i, piece in enumerate(self.subwords)}
        if len(self._piece_to_id) != len(self.subwords):
            raise ValueError("duplicate subwords in vocabulary")
        self._max_piece_len = max(len(p) for p in self.subwords)
        self._native = None  # lazily-built C++ encoder (False = unavailable)

    # ------------------------------------------------------------------ sizes
    @property
    def vocab_size(self) -> int:
        """Number of real subwords + 1 (id 0 = pad), i.e. ids are
        0..vocab_size-1 — matching the reference's convention where model BOS
        is ``tokenizer.vocab_size`` (``utils.py:139``)."""
        return len(self.subwords) + 1

    @property
    def bos_id(self) -> int:
        return self.vocab_size

    @property
    def eos_id(self) -> int:
        return self.vocab_size + 1

    @property
    def model_vocab_size(self) -> int:
        """Embedding rows a model needs: all subword ids + pad + BOS + EOS
        (reference ``train.py:232-233``)."""
        return self.vocab_size + 2

    # ----------------------------------------------------------------- encode
    def _encode_symbols(self, symbols: list[str]) -> list[int]:
        """Greedy longest-match over the concatenated symbol string."""
        text = "".join(symbols)
        out: list[int] = []
        i, n = 0, len(text)
        while i < n:
            end = min(n, i + self._max_piece_len)
            match_id = None
            for j in range(end, i, -1):
                tid = self._piece_to_id.get(text[i:j])
                if tid is not None:
                    match_id = tid
                    i = j
                    break
            if match_id is None:
                # Byte fallback for unseen characters.
                for b in text[i].encode("utf-8"):
                    out.append(self._piece_to_id[_byte_token(b)])
                i += 1
            else:
                out.append(match_id)
        return out

    def _native_encoder(self):
        if self._native is None:
            # The C++ byte fallback requires every <0xNN> token (it cannot
            # raise KeyError like the Python path does on an incomplete
            # hand-built vocab) — only engage it for full alphabets.
            if all(_byte_token(b) in self._piece_to_id for b in range(256)):
                from transformer_tpu import native

                self._native = (
                    native.NativeTokenizer.from_pieces(self.subwords) or False
                )
            else:
                self._native = False
        return self._native or None

    def encode(self, text: str) -> list[int]:
        words = text.split()
        if not words:
            return []
        nat = self._native_encoder()
        if nat is not None:
            return nat.encode_words(words)
        ids: list[int] = []
        for word in words:
            ids.extend(self._encode_symbols(_word_to_symbols(word)))
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        pieces: list[str] = []
        for tid in ids:
            if tid <= 0 or tid > len(self.subwords):
                continue  # pad / BOS / EOS / out-of-range: dropped
            pieces.append(self.subwords[tid - 1])
        text = "".join(pieces)
        # Undo byte-escapes first, then word-end markers and underscore escapes.
        out_bytes: list[int] = []
        result: list[str] = []
        i = 0
        while i < len(text):
            if text.startswith("<0x", i) and len(text) >= i + 6 and text[i + 5] == ">":
                out_bytes.append(int(text[i + 3 : i + 5], 16))
                i += 6
                continue
            if out_bytes:
                result.append(bytes(out_bytes).decode("utf-8", errors="replace"))
                out_bytes = []
            if text.startswith(_ESCAPED_BACKSLASH, i):
                result.append("\\")
                i += 2
            elif text.startswith(_ESCAPED_UNDERSCORE, i):
                result.append("_")
                i += 2
            elif text.startswith(_ESCAPED_LT, i):
                result.append("<")
                i += 2
            elif text[i] == _WORD_END:
                result.append(" ")
                i += 1
            else:
                result.append(text[i])
                i += 1
        if out_bytes:
            result.append(bytes(out_bytes).decode("utf-8", errors="replace"))
        return "".join(result).rstrip(" ")

    # ------------------------------------------------------------- train/save
    @classmethod
    def build_from_corpus(
        cls,
        corpus: Iterable[str],
        target_vocab_size: int = 2**15,
        min_pair_count: int = 2,
    ) -> "SubwordTokenizer":
        """Train BPE until ``target_vocab_size`` pieces (or until no pair
        occurs ``min_pair_count`` times). Incremental pair-count maintenance
        with a lazy max-heap — full recounts per merge would be quadratic and
        unusable at 2^15 on a 1-core host. Prefers the bit-identical C++
        trainer (transformer_tpu/native) when available."""
        word_freq: Counter[str] = Counter()
        for line in corpus:
            word_freq.update(line.split())

        from transformer_tpu import native

        nat = native.NativeTokenizer.train(
            word_freq, target_vocab_size, min_pair_count
        )
        if nat is not None:
            return cls(nat.pieces())

        words: list[list[str]] = []
        freqs: list[int] = []
        for w, f in word_freq.items():
            words.append(_word_to_symbols(w))
            freqs.append(f)

        # Alphabet: 256 byte-fallback tokens + escape pieces + all seen symbols.
        alphabet: dict[str, None] = {_byte_token(b): None for b in range(256)}
        alphabet[_ESCAPED_UNDERSCORE] = None
        alphabet[_ESCAPED_BACKSLASH] = None
        alphabet[_ESCAPED_LT] = None
        alphabet[_WORD_END] = None
        for sym_seq in words:
            for s in sym_seq:
                alphabet[s] = None
        vocab: dict[str, None] = dict(alphabet)

        # pair -> total count; pair -> set of word indices containing it.
        pair_counts: Counter[tuple[str, str]] = Counter()
        pair_words: dict[tuple[str, str], set[int]] = {}
        for wi, sym_seq in enumerate(words):
            f = freqs[wi]
            for a, b in zip(sym_seq, sym_seq[1:]):
                pair_counts[(a, b)] += f
                pair_words.setdefault((a, b), set()).add(wi)

        heap: list[tuple[int, tuple[str, str]]] = [
            (-c, p) for p, c in pair_counts.items()
        ]
        heapq.heapify(heap)

        def bump(pair: tuple[str, str], delta: int, wi: int) -> None:
            c = pair_counts[pair] + delta
            if c <= 0:
                pair_counts.pop(pair, None)
            else:
                pair_counts[pair] = c
                heapq.heappush(heap, (-c, pair))
            s = pair_words.setdefault(pair, set())
            if delta > 0:
                s.add(wi)

        while len(vocab) < target_vocab_size and heap:
            neg_c, pair = heapq.heappop(heap)
            c = pair_counts.get(pair)
            if c is None or -neg_c != c:
                continue  # stale heap entry
            if c < min_pair_count:
                break
            merged = pair[0] + pair[1]
            vocab[merged] = None
            del pair_counts[pair]
            affected = pair_words.pop(pair, set())
            for wi in affected:
                sym_seq = words[wi]
                f = freqs[wi]
                out: list[str] = []
                i = 0
                changed = False
                while i < len(sym_seq):
                    if (
                        i + 1 < len(sym_seq)
                        and sym_seq[i] == pair[0]
                        and sym_seq[i + 1] == pair[1]
                    ):
                        # Update neighbour pair counts around the merge site.
                        if out:
                            bump((out[-1], pair[0]), -f, wi)
                            bump((out[-1], merged), f, wi)
                        if i + 2 < len(sym_seq):
                            nxt = sym_seq[i + 2]
                            bump((pair[1], nxt), -f, wi)
                            bump((merged, nxt), f, wi)
                        out.append(merged)
                        i += 2
                        changed = True
                    else:
                        out.append(sym_seq[i])
                        i += 1
                if changed:
                    words[wi] = out

        # Longer pieces first is not required (encode is longest-match via
        # scanning), but a stable, frequency-ish order keeps ids reproducible.
        return cls(list(vocab.keys()))

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("transformer_tpu_subwords_v1\n")
            for piece in self.subwords:
                f.write(piece.encode("unicode_escape").decode("ascii") + "\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str):
        """Load a vocab file. A file in tfds ``SubwordTextEncoder`` format
        (the reference's ``save_to_file`` output, ``utils.py:100,104``) is
        detected by its header and returned as a duck-typed
        ``data.tfds_compat.TfdsSubwordTokenizer`` — every CLI/pipeline
        entry point thereby accepts vocabularies saved by a real run of the
        reference, which is what makes BLEU comparisons share an id space."""
        with open(path, encoding="utf-8") as f:
            header = f.readline().rstrip("\n")
        if header.startswith("### SubwordTextEncoder"):
            from transformer_tpu.data.tfds_compat import TfdsSubwordTokenizer

            return TfdsSubwordTokenizer.load(path)
        if header != "transformer_tpu_subwords_v1":
            raise ValueError(
                f"{path}: neither a transformer_tpu nor a tfds subword "
                "vocab file"
            )
        with open(path, encoding="utf-8") as f:
            f.readline()  # header
            subwords = [
                line.rstrip("\n").encode("ascii").decode("unicode_escape")
                for line in f
                if line.rstrip("\n")
            ]
        return cls(subwords)

    def __len__(self) -> int:
        return self.vocab_size

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_native"] = None  # ctypes handle is not picklable; rebuilt lazily
        return state


def iter_lines(*paths: str) -> Iterator[str]:
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                yield line.rstrip("\n")
