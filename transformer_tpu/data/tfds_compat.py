"""Loader for tfds-format ``.subwords`` vocab files
(``tfds.deprecated.text.SubwordTextEncoder`` — the reference's tokenizer,
``utils.py:96-111``).

The point is BLEU comparability (SURVEY §7 hard part d): a run of the
reference under real TF persists its vocabulary via
``SubwordTextEncoder.save_to_file`` (``utils.py:100,104``); loading that file
here lets this framework train/decode in the SAME id space, so quality
comparisons share a vocabulary instead of comparing across two different
subword inductions.

Implemented from the t2t/tfds subword-text-encoder conventions:

- **File format**: ``### SubwordTextEncoder`` header line (+ optional
  ``### Metadata: ...`` lines), then one subword per line wrapped in single
  quotes, with ``\\`` and ``\n`` backslash-escaped.
- **Id space**: 0 = pad; 1..len(subwords) = subwords, in file order;
  len(subwords)+1 .. len(subwords)+256 = raw bytes 0..255 (fallback);
  ``vocab_size`` = 1 + len(subwords) + 256. BOS/EOS stay OUTSIDE the vocab
  as ``vocab_size`` / ``vocab_size + 1``, exactly like the reference pipeline
  (``utils.py:137-143``) and this repo's own tokenizer.
- **Tokenization**: text splits into maximal runs of alphanumeric vs
  non-alphanumeric characters; a single space between two alphanumeric runs
  is dropped (it is re-inserted by decode's join rule).
- **Token escaping**: within a token, ``\\`` -> ``\\\\``, ``_`` -> ``\\u``,
  characters outside the subword alphabet -> ``\\<ord>;``; an ``_`` is
  appended as the end-of-token marker. Subwords greedily longest-prefix
  match the escaped token; anything unmatched falls back to byte ids.

Caveat, stated honestly: tfds is not installed in this environment, so the
implementation is reconstructed from the documented/source conventions and
pinned by round-trip fixtures (tests/test_data.py::TestTfdsCompat), not by
diffing against a live tfds encoder. Id-space layout and file parsing are
the load-bearing parts for comparability and are exact per the format above.
"""

from __future__ import annotations

from collections.abc import Iterable

_HEADER = "### SubwordTextEncoder"
_UNDERSCORE = "_"


def _is_alnum(ch: str) -> bool:
    return ch.isalnum()


def _tokenize(text: str) -> list[str]:
    """Alternating alnum / non-alnum runs; single inter-word spaces dropped."""
    if not text:
        return []
    tokens: list[str] = []
    start = 0
    alnum = [_is_alnum(c) for c in text]
    for pos in range(1, len(text)):
        if alnum[pos] != alnum[pos - 1]:
            tok = text[start:pos]
            if tok != " " or start == 0:
                tokens.append(tok)
            start = pos
    tokens.append(text[start:])
    return tokens


def _join_tokens(tokens: list[str]) -> str:
    """Inverse of _tokenize: re-insert the single space between two
    alphanumeric-adjacent tokens."""
    out: list[str] = []
    prev_alnum = False
    for i, tok in enumerate(tokens):
        if not tok:
            continue
        cur_alnum = _is_alnum(tok[0])
        if i > 0 and prev_alnum and cur_alnum:
            out.append(" ")
        out.append(tok)
        prev_alnum = _is_alnum(tok[-1])
    return "".join(out)


class TfdsSubwordTokenizer:
    """Duck-type of ``SubwordTokenizer`` (encode/decode/vocab_size/bos_id/
    eos_id/model_vocab_size) over a tfds-format vocabulary."""

    def __init__(self, subwords: list[str]):
        if not subwords:
            raise ValueError("empty tfds subword vocabulary")
        self.subwords = list(subwords)
        self._piece_to_id = {s: i + 1 for i, s in enumerate(self.subwords)}
        self._max_len = max(len(s) for s in self.subwords)
        self._byte_base = 1 + len(self.subwords)  # id of byte 0
        # Alphabet: every character appearing in any subword, plus the escape
        # machinery characters — tfds guarantees those are always in its
        # alphabet (its build adds "\\_u;0123456789" unconditionally), and
        # without them the escape sequences emitted below would themselves
        # get re-escaped. Characters outside the alphabet escape to
        # "\<ord>;" during encode (the tfds rule).
        self._alphabet = {c for s in self.subwords for c in s}
        self._alphabet.update("\\_u;0123456789")
        # token -> ids memo: encode() runs per corpus line on the data hot
        # path and natural-language tokens repeat heavily (real tfds
        # memoizes for the same reason).
        self._token_cache: dict[str, list[int]] = {}

    # ------------------------------------------------------------------ sizes
    @property
    def vocab_size(self) -> int:
        return 1 + len(self.subwords) + 256  # pad + subwords + byte fallback

    @property
    def bos_id(self) -> int:
        return self.vocab_size  # reference convention, utils.py:139

    @property
    def eos_id(self) -> int:
        return self.vocab_size + 1

    @property
    def model_vocab_size(self) -> int:
        return self.vocab_size + 2

    # ----------------------------------------------------------------- encode
    def _escape_token(self, token: str) -> str:
        # tfds rule verbatim: backslash/underscore get backslash-escapes
        # first, then any char outside the alphabet (and always newline)
        # becomes "\<ord>;". The escape chars themselves are alphabet
        # members by construction, so they pass through literally.
        body = [
            c if (c in self._alphabet and c != "\n") else f"\\{ord(c)};"
            for c in token.replace("\\", "\\\\").replace(_UNDERSCORE, "\\u")
        ]
        return "".join(body) + _UNDERSCORE

    def _unescape_token(self, escaped: str) -> str:
        out: list[str] = []
        i = 0
        while i < len(escaped):
            c = escaped[i]
            if c == "\\" and i + 1 < len(escaped):
                nxt = escaped[i + 1]
                if nxt == "u":
                    out.append(_UNDERSCORE)
                    i += 2
                    continue
                if nxt == "\\":
                    out.append("\\")
                    i += 2
                    continue
                if nxt.isdigit():
                    j = i + 1
                    while j < len(escaped) and escaped[j].isdigit():
                        j += 1
                    if j < len(escaped) and escaped[j] == ";":
                        out.append(chr(int(escaped[i + 1 : j])))
                        i = j + 1
                        continue
            out.append(c)
            i += 1
        return "".join(out)

    def _token_to_ids(self, token: str) -> list[int]:
        escaped = self._escape_token(token)
        ids: list[int] = []
        pos = 0
        n = len(escaped)
        while pos < n:
            end = min(n, pos + self._max_len)
            match = None
            for j in range(end, pos, -1):
                tid = self._piece_to_id.get(escaped[pos:j])
                if tid is not None:
                    match = tid
                    pos = j
                    break
            if match is not None:
                ids.append(match)
            else:
                # Byte fallback for a character no subword covers.
                for b in escaped[pos].encode("utf-8"):
                    ids.append(self._byte_base + b)
                pos += 1
        return ids

    def encode(self, text: str) -> list[int]:
        ids: list[int] = []
        for token in _tokenize(text):
            cached = self._token_cache.get(token)
            if cached is None:
                cached = self._token_to_ids(token)
                if len(self._token_cache) < 1_000_000:  # bound the memo
                    self._token_cache[token] = cached
            ids.extend(cached)
        return ids

    # ----------------------------------------------------------------- decode
    def decode(self, ids: Iterable[int]) -> str:
        pieces: list[str] = []
        byte_buf: list[int] = []

        def flush_bytes() -> None:
            if byte_buf:
                pieces.append(bytes(byte_buf).decode("utf-8", errors="replace"))
                byte_buf.clear()

        for tid in ids:
            tid = int(tid)
            if 1 <= tid <= len(self.subwords):
                flush_bytes()
                pieces.append(self.subwords[tid - 1])
            elif self._byte_base <= tid < self._byte_base + 256:
                byte_buf.append(tid - self._byte_base)
            # pad / BOS / EOS / out-of-range: dropped
        flush_bytes()
        concatenated = "".join(pieces)
        # "_" marks token ends; split, unescape each token, re-join.
        tokens = [
            self._unescape_token(t) for t in concatenated.split(_UNDERSCORE)
        ]
        return _join_tokens([t for t in tokens if t])

    def __len__(self) -> int:
        return self.vocab_size

    # ------------------------------------------------------------- file format
    @classmethod
    def load(cls, path: str) -> "TfdsSubwordTokenizer":
        with open(path, encoding="utf-8") as f:
            first = f.readline().rstrip("\n")
            if not first.startswith(_HEADER):
                raise ValueError(
                    f"{path}: not a tfds SubwordTextEncoder vocab file "
                    f"(header {first[:40]!r})"
                )
            subwords: list[str] = []
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("### "):
                    continue  # metadata lines
                if len(line) >= 2 and line[0] == "'" and line[-1] == "'":
                    line = line[1:-1]
                subwords.append(
                    line.replace("\\n", "\n").replace("\\\\", "\\")
                )
        return cls(subwords)

    def save(self, path: str) -> None:
        """Write back in tfds format (round-trip support for fixtures)."""
        import os

        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(_HEADER + "\n")
            f.write("### Metadata: {}\n")
            for s in self.subwords:
                f.write(
                    "'" + s.replace("\\", "\\\\").replace("\n", "\\n") + "'\n"
                )
        os.replace(tmp, path)


