"""Data pipeline (L5): subword tokenizer + host-side input pipeline feeding
device-sharded, static-shape batches — counterpart of the reference's
``utils.py`` tfds/tf.data path."""

from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.data.pipeline import (
    Seq2SeqDataset,
    load_dataset,
    load_lm_splits,
    load_or_build_tokenizer,
    make_lm_dataset,
    read_parallel_corpus,
)

__all__ = [
    "Seq2SeqDataset",
    "SubwordTokenizer",
    "load_dataset",
    "load_lm_splits",
    "load_or_build_tokenizer",
    "make_lm_dataset",
    "read_parallel_corpus",
]
