"""Autoregressive greedy decoding.

Counterpart of the reference's ``Train.predict`` (``train.py:91-121``) with its
defects fixed by design (SURVEY.md §2.3.2/§2.3.9):

- decoder specials come from the **target** tokenizer (the reference uses the
  source tokenizer's BOS/EOS for the decoder, ``train.py:100-106``);
- decode stops early on EOS (commented out in the reference,
  ``train.py:114-116``) — structurally, finished rows keep emitting pad;
- the loop is an early-exit ``lax.while_loop`` over a fixed-size buffer with
  per-layer KV caches: one compile, O(S) work per token, and the loop exits
  the tick after every row has finished (a serve bucket or eval batch pays
  for its longest actual output, not the bucket width) — not the reference's
  concat-grow re-encode-everything loop (``train.py:109-118``) that
  re-traces per step;
- output is detokenized text, not raw ids (``train.py:118-121``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from transformer_tpu.config import PAD_ID, ModelConfig
from transformer_tpu.models.decoder import init_decoder_caches, precompute_cross_kvs
from transformer_tpu.models.encoder import encoder_apply
from transformer_tpu.models.transformer import (
    transformer_apply,
    transformer_decode_step,
    transformer_prefill,
)
from transformer_tpu.ops.masks import make_padding_mask


def _dummy_rows(ids: jax.Array) -> jax.Array:
    """(B, S) ids -> (B, 1) True for all-PAD rows: the power-of-two
    bucketing dummies ``_pad_batch`` appends. They start decoding
    "finished" so a garbage row can never pin the early-exit while_loops
    below at the full ``max_len`` budget."""
    return ~jnp.any(ids != PAD_ID, axis=1, keepdims=True)


def sample_token(
    logits: jax.Array,
    key: jax.Array,
    *,
    sample: bool = False,
    temperature: float | jax.Array = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """(B, V) logits -> (B,) int32 next-token ids. ``sample=False`` is greedy
    argmax; ``sample=True`` draws from softmax(logits/temperature), optionally
    truncated to the ``top_k`` highest-probability tokens and/or the nucleus
    of tokens whose cumulative probability reaches ``top_p`` (top-k first,
    then top-p over the survivors). Shared by ``lm_generate`` and the serving
    scheduler (``transformer_tpu/serve``) so both paths pick identically."""
    if not sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / jnp.maximum(
        jnp.asarray(temperature, jnp.float32), 1e-6
    )
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        # Nucleus: keep the smallest prefix of the probability-sorted
        # vocab whose mass reaches top_p (the top token always survives:
        # its exclusive-cumulative mass is 0 < top_p).
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        exclusive = jnp.cumsum(probs, axis=-1) - probs
        kept = exclusive < top_p
        thresh = jnp.min(
            jnp.where(kept, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "max_len", "bos_id", "eos_id"))
def greedy_decode(
    params,
    src_ids: jax.Array,
    cfg: ModelConfig,
    max_len: int,
    bos_id: int,
    eos_id: int,
) -> jax.Array:
    """(B, S_src) source ids -> (B, max_len) generated target ids.

    Generated rows start after BOS; positions after a row's EOS are pad.
    For ``cfg.decoder_only`` pass ``src_ids=None`` semantics are not needed —
    seq2seq translation is the reference capability this mirrors.

    Generation starts from a prefilled cache: the BOS "prompt" goes through
    ``transformer_prefill`` (the same entry point ``lm_generate`` uses for
    long prompts), and the while_loop continues from the prefill logits.
    """
    batch = src_ids.shape[0]
    if max_len < 1:
        return jnp.full((batch, max_len), PAD_ID, jnp.int32)
    enc_mask = make_padding_mask(src_ids)
    enc_out, _ = encoder_apply(params["encoder"], src_ids, enc_mask, cfg)
    caches = init_decoder_caches(cfg, batch, max_len + 1)
    cross_kvs = precompute_cross_kvs(params["decoder"], enc_out, cfg)

    def pick_and_store(t, logits, finished, tokens):
        """One selection tick: the token for position t+1 from position-t
        logits, with finished rows frozen to PAD (shared by the hoisted
        prefill tick and the loop body — identical math by construction)."""
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        nxt = jnp.where(finished, jnp.full_like(nxt, PAD_ID), nxt)
        finished = jnp.logical_or(finished, nxt == eos_id)
        tokens = jax.lax.dynamic_update_index_in_dim(tokens, nxt[:, 0], t, 1)
        return nxt, finished, tokens

    # while_loop, not scan: the loop EXITS once every row has emitted EOS,
    # so a serve bucket or eval batch pays for its longest actual output,
    # not the bucket width. Untouched tail positions keep their PAD init —
    # bit-identical to the full-length scan (finished rows write PAD).
    def cond(carry):
        t, _, _, finished, _ = carry
        return jnp.logical_and(t < max_len, ~jnp.all(finished))

    def body(carry):
        t, tok, caches, finished, tokens = carry
        logits, caches = transformer_decode_step(
            params, tok, enc_out, enc_mask, caches, t, cfg, cross_kvs=cross_kvs
        )
        nxt, finished, tokens = pick_and_store(t, logits, finished, tokens)
        return (t + 1, nxt, caches, finished, tokens)

    # Tick 0 hoisted out of the loop as a prefill of the BOS token.
    logits0, caches = transformer_prefill(
        params, jnp.full((batch, 1), bos_id, jnp.int32),
        enc_out, enc_mask, caches, 0, cfg, cross_kvs=cross_kvs,
    )
    nxt, finished, tokens = pick_and_store(
        0, logits0, _dummy_rows(src_ids),
        jnp.full((batch, max_len), PAD_ID, jnp.int32),
    )
    init = (jnp.int32(1), nxt, caches, finished, tokens)
    *_, tokens = jax.lax.while_loop(cond, body, init)
    return tokens  # (B, max_len)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new", "eos_id", "sample", "top_k", "top_p",
        "prefill_len", "prefill_chunk",
    ),
)
def lm_generate(
    params,
    prompt_ids: jax.Array,
    cfg: ModelConfig,
    max_new: int,
    eos_id: int,
    rng: jax.Array | None = None,
    sample: bool = False,
    temperature: float | jax.Array = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    prefill_len: int = 0,
    prefill_chunk: int = 0,
) -> jax.Array:
    """Causal-LM continuation: (B, P) BOS-led prompt (PAD-right allowed) ->
    (B, max_new) generated ids. The inference path for ``cfg.decoder_only``
    models (the seq2seq entry point is ``greedy_decode``; no reference
    counterpart — the reference is translation-only).

    One compiled program. ``prefill_len = n > 0`` runs the first ``n``
    prompt positions through ``transformer_prefill`` — single-pass
    teacher-forcing forwards (in ``prefill_chunk``-sized chunks), writing
    all their K/V into the caches in O(n / chunk) matmul-rich calls — and
    the early-exit ``lax.while_loop`` continues token-by-token from there
    (remaining ragged prompt tail, then generation). ``prefill_len = 0``
    walks every position through the loop one token per tick (the legacy
    shape). CALLER CONTRACT for bit-identical outputs: ``n`` must not
    exceed the shortest REAL (non-dummy) row's prompt length — prefill
    teacher-forces ``prompt_ids[:, :n]`` for every row, which is exactly
    what the loop would have fed only while every row is still inside its
    prompt (``generate`` computes a safe ``n`` host-side).

    ``sample=False`` is greedy argmax; ``sample=True`` draws via
    ``sample_token`` (softmax/temperature with optional top-k and top-p
    nucleus truncation). Sampling parity across prefill lengths holds
    because each tick's rng is ``fold_in(rng, t)`` — position-keyed, not
    sequential, so skipped in-prompt picks never shift later draws.
    ``temperature`` is a traced scalar — varying it does NOT recompile; the
    mode flag, ``top_k`` (a shape), and ``top_p`` (gates a sort) are static.
    """
    batch, prompt_len = prompt_ids.shape
    total = prompt_len + max_new
    caches = init_decoder_caches(cfg, batch, total + 1)
    prompt_lens = jnp.sum(prompt_ids != PAD_ID, axis=1, keepdims=True)  # (B,1)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def pick(logits, key):
        return sample_token(
            logits, key, sample=sample, temperature=temperature,
            top_k=top_k, top_p=top_p,
        )

    def advance(t, logits, caches, finished, toks):
        """Selection tick t: choose the token for position t+1 (next prompt
        token while in-prompt, else the pick), freeze finished rows, store
        the emission. Shared by the loop body and the hoisted prefill tick."""
        sampled = pick(logits, jax.random.fold_in(rng, t))[:, None]
        in_prompt = (t + 1) < prompt_lens  # next position still prompt?
        nxt_prompt = jax.lax.dynamic_slice_in_dim(
            prompt_ids, jnp.minimum(t + 1, prompt_len - 1), 1, axis=1
        )
        nxt = jnp.where(in_prompt, nxt_prompt, sampled)
        nxt = jnp.where(finished, jnp.full_like(nxt, PAD_ID), nxt)
        finished = jnp.logical_or(
            finished, jnp.logical_and(~in_prompt, nxt == eos_id)
        )
        emitted = jnp.where(in_prompt, PAD_ID, nxt[:, :1])
        toks = jax.lax.dynamic_update_index_in_dim(toks, emitted[:, 0], t, 1)
        return nxt, caches, finished, toks

    # while_loop with an early exit (like greedy_decode): once every row
    # has finished generating, remaining ticks are pure PAD — skip them.
    # Untouched buffer tail stays PAD, so outputs match the full scan.
    def cond(carry):
        t, _, _, finished, _ = carry
        return jnp.logical_and(t < total - 1, ~jnp.all(finished))

    def body(carry):
        t, tok, caches, finished, toks = carry
        logits, caches = transformer_decode_step(
            params, tok, None, None, caches, t, cfg
        )
        nxt, caches, finished, toks = advance(t, logits, caches, finished, toks)
        return (t + 1, nxt, caches, finished, toks)

    finished = _dummy_rows(prompt_ids)  # bucketing dummies start finished
    toks = jnp.full((batch, total - 1), PAD_ID, jnp.int32)
    # Clamp the prefill below the last loop tick (total - 1) so the hoisted
    # selection tick always has a buffer slot to write.
    n = min(prefill_len, prompt_len, total - 1)
    if n >= 1:
        logits, caches = transformer_prefill(
            params, prompt_ids[:, :n], None, None, caches, 0, cfg,
            chunk=prefill_chunk,
        )
        # Replay tick n-1's selection (the prefill's last logits ARE that
        # tick's logits); ticks 0..n-2 selected nothing — every row was
        # in-prompt, so their emissions were PAD, already the buffer init.
        nxt, caches, finished, toks = advance(n - 1, logits, caches, finished, toks)
        init = (jnp.int32(n), nxt, caches, finished, toks)
    else:
        init = (jnp.int32(0), prompt_ids[:, :1], caches, finished, toks)
    *_, toks = jax.lax.while_loop(cond, body, init)
    # toks[:, t] holds the token generated for position t+1; generation
    # starts at each row's prompt_len. Gather each row's max_new tokens.
    cols = prompt_lens - 1 + jnp.arange(max_new)[None, :]  # (B, max_new)
    # Clamp BOTH ends: all-PAD bucketing dummy rows have prompt_len 0, so
    # cols would start at -1 and take_along_axis would wrap to the last
    # buffer column — garbage if a caller ever reads the dummy rows.
    cols = jnp.clip(cols, 0, total - 2)
    return jnp.take_along_axis(toks, cols, axis=1)


@partial(
    jax.jit,
    static_argnames=("cfg", "max_len", "bos_id", "eos_id", "beam_size", "alpha"),
)
def beam_search_decode(
    params,
    src_ids: jax.Array,
    cfg: ModelConfig,
    max_len: int,
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    alpha: float = 0.6,
) -> jax.Array:
    """(B, S_src) source ids -> (B, max_len) ids of the best beam.

    Capability beyond the reference (greedy only, ``train.py:112``). TPU-shaped
    throughout: static beam width, one compiled program — beams ride the batch
    dimension (B·K) through the same KV-cached decode step greedy uses, an
    early-exit ``lax.while_loop`` advances all beams one token per tick
    (exiting once every beam is frozen), and beam reordering is
    a batched gather of cache rows. Finished beams are frozen by forcing PAD
    with probability one. Scores use GNMT length normalization
    ``log p / ((5+len)/6)^alpha`` applied at selection time.
    """
    batch = src_ids.shape[0]
    K = beam_size
    vocab = cfg.target_vocab_size
    NEG = jnp.float32(-1e9)
    if max_len < 1:
        return jnp.full((batch, max_len), PAD_ID, jnp.int32)

    enc_mask = make_padding_mask(src_ids)
    enc_out, _ = encoder_apply(params["encoder"], src_ids, enc_mask, cfg)
    # Beams ride the batch dim: replicate encoder state K times -> (B*K, ...).
    expand = lambda x: jnp.repeat(x, K, axis=0)  # noqa: E731
    enc_out_k = expand(enc_out)
    enc_mask_k = expand(enc_mask)
    caches = init_decoder_caches(cfg, batch * K, max_len + 1)
    cross_kvs = [
        (expand(k), expand(v))
        for k, v in precompute_cross_kvs(params["decoder"], enc_out, cfg)
    ]

    def select(t, logits, caches, scores, finished, tokens_buf):
        """Beam-advance tick t: expand position-(t+1) candidates from the
        position-t logits, keep the top K per row, reorder beam state by
        parent. Shared by the loop body and the hoisted prefill tick."""
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(batch, K, vocab)
        # Frozen beams: only PAD continues, at zero cost.
        pad_only = jnp.full((vocab,), NEG).at[PAD_ID].set(0.0)
        logp = jnp.where(finished[:, :, None], pad_only[None, None, :], logp)
        # First tick: all K beams are identical — keep only beam 0's
        # candidates or top-k would pick K copies of the same token.
        live = jnp.where(
            (t == 0) & (jnp.arange(K) > 0), NEG, 0.0
        )[None, :, None]
        combined = scores[:, :, None] + logp + live  # (B, K, V)
        flat_scores, flat_idx = jax.lax.top_k(
            combined.reshape(batch, K * vocab), K
        )
        parent = flat_idx // vocab  # (B, K)
        nxt_tok = (flat_idx % vocab).astype(jnp.int32)

        # Reorder per-batch state by parent beam (batched row gather).
        row = (jnp.arange(batch)[:, None] * K + parent).reshape(-1)  # (B*K,)
        caches = jax.tree.map(
            lambda c: c[row] if c.ndim >= 1 and c.shape[0] == batch * K else c,
            caches,
        )
        tokens_buf = jnp.take_along_axis(
            tokens_buf, parent[:, :, None], axis=1
        )
        tokens_buf = jax.lax.dynamic_update_index_in_dim(
            tokens_buf, nxt_tok, t, axis=2
        )
        finished = jnp.take_along_axis(finished, parent, axis=1)
        new_finished = jnp.logical_or(finished, nxt_tok == eos_id)
        emit = jnp.where(finished, PAD_ID, nxt_tok)  # pad after freeze
        tok = emit.reshape(batch * K, 1)
        return tok, caches, flat_scores, new_finished, tokens_buf

    # while_loop with an early exit (like greedy_decode): once every beam
    # of every row is frozen, further ticks only append PAD at zero score —
    # identical selection, so skip them.
    def cond(carry):
        t, _, _, _, finished, _ = carry
        return jnp.logical_and(t < max_len, ~jnp.all(finished))

    def body(carry):
        t, tok, caches, scores, finished, tokens_buf = carry
        # tok: (B*K, 1); scores/finished: (B, K); tokens_buf: (B, K, max_len)
        logits, caches = transformer_decode_step(
            params, tok, enc_out_k, enc_mask_k, caches, t, cfg,
            cross_kvs=cross_kvs,
        )
        out = select(t, logits, caches, scores, finished, tokens_buf)
        return (t + 1, *out)

    # Tick 0 hoisted out of the loop as a prefill of the BOS token — beams
    # start generation from the prefilled caches.
    logits0, caches = transformer_prefill(
        params, jnp.full((batch * K, 1), bos_id, jnp.int32),
        enc_out_k, enc_mask_k, caches, 0, cfg, cross_kvs=cross_kvs,
    )
    tok, caches, scores, finished, tokens_buf = select(
        0, logits0, caches,
        jnp.zeros((batch, K), jnp.float32),
        # Bucketing dummies start with every beam frozen.
        jnp.broadcast_to(_dummy_rows(src_ids), (batch, K)),
        jnp.full((batch, K, max_len), PAD_ID, jnp.int32),
    )
    init = (jnp.int32(1), tok, caches, scores, finished, tokens_buf)
    _, tok, caches, scores, finished, tokens_buf = jax.lax.while_loop(
        cond, body, init
    )
    # Length-normalized selection: len = tokens up to and incl. EOS (finished)
    # or max_len (unfinished).
    lengths = jnp.sum(tokens_buf != PAD_ID, axis=-1).astype(jnp.float32)
    lengths = jnp.maximum(lengths, 1.0)
    norm = ((5.0 + lengths) / 6.0) ** alpha
    best = jnp.argmax(scores / norm, axis=1)  # (B,)
    return jnp.take_along_axis(
        tokens_buf, best[:, None, None], axis=1
    )[:, 0, :]


def lm_generate_speculative(
    params,
    prompt_ids,
    cfg: ModelConfig,
    max_new: int,
    eos_id: int,
    *,
    speculate_k: int,
    drafter=None,
    sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int = 0,
    prefill_chunk: int = 0,
) -> tuple[list[int], dict]:
    """Standalone speculative counterpart of ``lm_generate`` (batch-1):
    a drafter proposes ``speculate_k`` lookahead tokens, one multi-token
    verify forward scores them all, the accepted prefix is kept and the
    rejected tail is erased by O(1) cache-index rollback. Greedy output is
    byte-identical to ``lm_generate``'s (test-pinned); sampling is
    distribution-lossless via rejection acceptance. Returns ``(tokens,
    stats)`` — ``stats["verify_forwards"]`` divides into ``len(tokens)``
    for tokens-per-forward. ``drafter=None`` uses the model-free n-gram
    drafter; see ``transformer_tpu.serve.speculative`` for the drafter
    interface and the draft-model variant."""
    from transformer_tpu.serve.speculative import speculative_generate

    return speculative_generate(
        params, cfg, prompt_ids, max_new, eos_id,
        speculate_k=speculate_k, drafter=drafter, sample=sample,
        temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
        prefill_chunk=prefill_chunk,
    )


def _pad_batch(encoded: list[list[int]], width: int):
    """Stack variable-length id lists into a PAD-canvas of power-of-two rows
    (shared by ``translate`` and ``generate``); returns (ids, n_real_rows)."""
    import numpy as np

    n = len(encoded)
    rows = _bucket(n, 1 << 30, floor=1)
    ids = np.full((rows, width), PAD_ID, dtype=np.int32)
    for i, e in enumerate(encoded):
        ids[i, : min(len(e), width)] = e[:width]
    return ids, n


def _detokenize_rows(out, n: int, tokenizer) -> list[str]:
    """Strip PAD/EOS from the first ``n`` rows and decode to text."""
    texts = []
    for row in out[:n]:
        toks = [int(t) for t in row if t not in (PAD_ID, tokenizer.eos_id)]
        texts.append(tokenizer.decode(toks))
    return texts


def prefill_len_for(prompt_len: int, chunk: int = 0) -> int:
    """How many prompt positions to run through single-pass prefill for a
    (shortest-in-batch) real prompt length: ``chunk`` times the largest
    power of two of whole chunks the prompt covers, else (no chunking, or
    under one chunk) the largest power of two <= prompt_len. Rounding the
    CHUNK COUNT to a power of two — not just down to a chunk multiple —
    keeps the set of distinct static prefill signatures O(log(max_len)),
    so serving never recompiles per prompt length even with a small
    ``prefill_chunk`` on a long-context model; the un-prefixed remainder
    walks through the decode loop one token per tick, which is exact for
    any length."""
    if prompt_len < 1:
        return 0
    n = 1
    # chunk <= 0 (including a typo'd negative flag) means "no chunking" —
    # a negative value must never reach the multiply below.
    if chunk > 0 and prompt_len >= chunk:
        while n * 2 <= prompt_len // chunk:
            n *= 2
        return n * chunk
    while n * 2 <= prompt_len:
        n *= 2
    return n


def generate(
    params,
    cfg: ModelConfig,
    tokenizer,
    prompts: str | list[str],
    max_new: int = 64,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int = 0,
    prefill_chunk: int = 0,
    speculate_k: int = 0,
    drafter=None,
) -> list[str]:
    """Text-in/text-out continuation for ``cfg.decoder_only`` models: each
    prompt is BOS-led (matching the LM training windows, ``data.pipeline.
    make_lm_dataset``), generation stops per-row at EOS, output is
    detokenized continuation text. Prompt widths bucket like ``translate``.
    ``temperature`` 0 = greedy; > 0 samples (with optional top-k and/or
    top-p nucleus truncation).

    The shared prompt prefix — up to the shortest prompt in the batch,
    bucketed by ``prefill_len_for`` — is ingested in one pass through
    ``transformer_prefill`` (``prefill_chunk`` bounds per-call activation
    memory; 0 = one chunk); outputs are bit-identical to the pure
    token-by-token loop.

    ``speculate_k > 0`` routes each prompt through speculative decoding
    (``lm_generate_speculative``, batch-1 per prompt): greedy text is
    byte-identical, at fewer model forwards per token when the drafter
    (default: the model-free n-gram prompt-lookup drafter) lands."""
    if not cfg.decoder_only:
        raise ValueError("generate() is for decoder_only models; use translate()")
    if isinstance(prompts, str):
        prompts = [prompts]
    encoded = [[tokenizer.bos_id, *tokenizer.encode(p)] for p in prompts]
    longest = max(len(e) for e in encoded)
    if longest >= cfg.max_position:
        raise ValueError(
            f"a prompt encodes to {longest} tokens but the model's "
            f"max_position is {cfg.max_position}; shorten the prompt"
        )
    # The position budget caps generation: clamp rather than raise so the
    # default max_new works for any model (standard generation semantics).
    max_new = min(max_new, cfg.max_position - longest)
    if speculate_k > 0:
        texts = []
        for e in encoded:
            toks, _ = lm_generate_speculative(
                params, e, cfg, max_new, tokenizer.eos_id,
                speculate_k=speculate_k, drafter=drafter,
                sample=temperature > 0.0, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                prefill_chunk=prefill_chunk,
            )
            texts.extend(
                _detokenize_rows(
                    [toks] if toks else [[PAD_ID]], 1, tokenizer
                )
            )
        return texts
    width = _bucket(longest, cfg.max_position, floor=8)
    ids, n = _pad_batch(encoded, width)
    # Prefill only the prefix every REAL row agrees is prompt (lm_generate's
    # caller contract); bucketing dummy rows are all-PAD and teacher-forcing
    # PAD through prefill matches what the loop feeds them.
    shortest = min(len(e) for e in encoded)
    out = jax.device_get(
        lm_generate(
            params, jnp.asarray(ids), cfg, max_new, tokenizer.eos_id,
            rng=jax.random.PRNGKey(seed),
            sample=temperature > 0.0, temperature=temperature, top_k=top_k,
            top_p=top_p,
            prefill_len=prefill_len_for(shortest, prefill_chunk),
            prefill_chunk=prefill_chunk,
        )
    )
    return _detokenize_rows(out, n, tokenizer)


def _bucket(n: int, cap: int, floor: int = 16) -> int:
    """Round ``n`` up to a power of two, clamped to [floor, cap].

    Padding to buckets instead of exact sizes bounds the number of distinct
    jit signatures at log2(cap) — without it every differently-shaped batch
    of sentences pays a fresh XLA compile (the recompile-bomb class the
    training pipeline already avoids, ``data/pipeline.py``; the reference's
    concat-grow decode re-traces per step, ``train.py:109-118``).
    """
    w = floor
    while w < n:
        w *= 2
    return min(w, cap)


def translate(
    params,
    cfg: ModelConfig,
    src_tokenizer,
    tgt_tokenizer,
    sentences: str | list[str],
    max_len: int = 64,
    src_len: int | None = None,
    truncate: bool = False,
    beam_size: int = 1,
    alpha: float = 0.6,
) -> list[str]:
    """Text in, text out. Accepts a single string or a list (the reference's
    ``predict`` silently decodes one character when handed a bare str —
    quirk §2.3.11; here both spellings work).

    Source width and batch are padded up to power-of-two buckets (capped at
    ``cfg.max_position``) so repeated calls with varying shapes reuse the
    same compiled executable; ``src_len`` pins an exact width instead.
    ``beam_size > 1`` switches from greedy to beam search (GNMT length
    penalty ``alpha``).
    """
    if cfg.encoder_only:
        raise ValueError(
            "encoder_only (MLM) models have no autoregressive decode path; "
            "score them with transformer_apply / the mlm eval step"
        )
    if isinstance(sentences, str):
        sentences = [sentences]
    encoded = [
        [src_tokenizer.bos_id, *src_tokenizer.encode(s), src_tokenizer.eos_id]
        for s in sentences
    ]
    longest = max(len(e) for e in encoded)
    if src_len is None and not truncate and longest > cfg.max_position:
        raise ValueError(
            f"a sentence encodes to {longest} tokens but the model's "
            f"max_position is {cfg.max_position}; shorten the input, or opt "
            "into truncation (truncate=True / src_len=...)"
        )
    width = src_len or _bucket(longest, cfg.max_position)
    # Truncation was opted into (truncate=True / src_len): keep clipped
    # sources well-formed by terminating them with EOS.
    encoded = [
        e if len(e) <= width else [*e[: width - 1], src_tokenizer.eos_id]
        for e in encoded
    ]
    src, n = _pad_batch(encoded, width)
    if beam_size > 1:
        out = jax.device_get(
            beam_search_decode(
                params, jnp.asarray(src), cfg, max_len,
                tgt_tokenizer.bos_id, tgt_tokenizer.eos_id,
                beam_size=beam_size, alpha=alpha,
            )
        )
    else:
        out = jax.device_get(
            greedy_decode(
                params, jnp.asarray(src), cfg, max_len,
                tgt_tokenizer.bos_id, tgt_tokenizer.eos_id,
            )
        )
    return _detokenize_rows(out, n, tgt_tokenizer)


def fill_mask(
    params,
    cfg: ModelConfig,
    tokenizer,
    texts: str | list[str],
    top_k: int = 5,
    marker: str = "[MASK]",
) -> list[dict]:
    """Masked-token inference for ``cfg.encoder_only`` (MLM) models.

    Each text contains one or more literal ``marker`` occurrences (handled
    at TEXT level — the marker never reaches the subword tokenizer, which
    would shred it). Returns one dict per text:

    ``{"filled": <text with every marker replaced by the argmax token>,
       "candidates": [[(token_text, prob), ...top_k], ...one per marker]}``

    The model's [MASK] id is the reserved top input id
    (``input_vocab_size - 1``, matching ``train/mlm.py``); PAD, [MASK]
    itself, and the tokenizer's BOS/EOS (which ``decode`` drops — an EOS
    "winner" would silently erase the marker from the filled text) are
    excluded from the candidate distribution. Width buckets to powers of
    two like ``translate`` so repeat calls share compiles; only the
    per-position top-k (never the (B, W, V) distribution) leaves the
    device.
    """
    import numpy as np

    if not cfg.encoder_only:
        raise ValueError(
            "fill_mask() is for encoder_only (MLM) models; seq2seq/LM "
            "exports decode with translate()/generate()"
        )
    if isinstance(texts, str):
        texts = [texts]
    mask_id = cfg.input_vocab_size - 1
    encoded: list[list[int]] = []
    for t in texts:
        parts = t.split(marker)
        if len(parts) < 2:
            raise ValueError(f"no {marker!r} marker in {t!r}")
        ids = [tokenizer.bos_id]
        for i, part in enumerate(parts):
            if i:
                ids.append(mask_id)
            if part:
                ids.extend(tokenizer.encode(part))
        encoded.append(ids)
    longest = max(len(e) for e in encoded)
    if longest > cfg.max_position:
        raise ValueError(
            f"a text encodes to {longest} tokens but the model's "
            f"max_position is {cfg.max_position}"
        )
    if not 1 <= top_k <= 100:
        raise ValueError(f"top_k must be in [1, 100], got {top_k}")
    width = _bucket(longest, cfg.max_position)
    ids, n = _pad_batch(encoded, width)
    vals, idx = _fill_mask_topk(
        params, jnp.asarray(ids), cfg, top_k,
        (PAD_ID, mask_id, int(tokenizer.bos_id), int(tokenizer.eos_id)),
    )
    vals, idx = np.asarray(vals), np.asarray(idx)
    out = []
    for row in range(n):
        row_ids = ids[row].copy()
        cands = []
        for pos in np.nonzero(row_ids == mask_id)[0]:
            cands.append(
                [
                    (tokenizer.decode([int(idx[row, pos, k])]).strip(),
                     float(vals[row, pos, k]))
                    for k in range(top_k)
                ]
            )
            row_ids[pos] = int(idx[row, pos, 0])
        toks = [
            int(t) for t in row_ids
            if t not in (PAD_ID, tokenizer.bos_id, tokenizer.eos_id)
        ]
        out.append({"filled": tokenizer.decode(toks), "candidates": cands})
    return out


@partial(jax.jit, static_argnames=("cfg", "top_k", "excluded_ids"))
def _fill_mask_topk(params, ids, cfg: ModelConfig, top_k, excluded_ids):
    """One bidirectional forward -> per-position top-k (probs, ids), with
    ``excluded_ids`` (PAD/[MASK]/BOS/EOS) removed from the distribution.
    top_k stays small, so (B, W, top_k) is all that crosses to the host —
    the (B, W, V) tensor this repo elsewhere treats as an OOM hazard
    (``loss_chunks``) never does."""
    logits, _ = transformer_apply(params, None, ids, cfg)
    logits = logits.astype(jnp.float32)
    excluded = jnp.zeros((logits.shape[-1],), jnp.float32)
    for i in excluded_ids:
        excluded = excluded.at[i].set(-jnp.inf)
    probs = jax.nn.softmax(logits + excluded[None, None, :], axis=-1)
    return jax.lax.top_k(probs, top_k)
