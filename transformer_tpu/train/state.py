"""Train state and optimizer construction.

The state is a plain pytree dataclass — params, optimizer state, step — so it
jits, shards with PartitionSpecs, and checkpoints as a flat array tree.
Counterpart of the reference's ``Train.__init__`` wiring (optimizer + model
refs, ``train.py:55-80``), without the Keras object graph.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.models import transformer_init
from transformer_tpu.train.schedule import noam_schedule


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


def make_lr_schedule(model_cfg: ModelConfig, train_cfg: TrainConfig):
    """THE learning-rate schedule — single definition shared by the optimizer
    and observability (TensorBoard's learning_rate scalar), so the plotted
    curve can never drift from the one actually applied."""
    if train_cfg.lr_schedule == "cosine":
        from transformer_tpu.train.schedule import cosine_schedule

        return cosine_schedule(
            train_cfg.peak_lr, train_cfg.warmup_steps, train_cfg.lr_decay_steps
        )
    if train_cfg.lr_schedule == "constant":
        from transformer_tpu.train.schedule import constant_schedule

        return constant_schedule(train_cfg.peak_lr, train_cfg.warmup_steps)
    return noam_schedule(model_cfg.d_model, train_cfg.warmup_steps)


def make_optimizer(model_cfg: ModelConfig, train_cfg: TrainConfig) -> optax.GradientTransformation:
    """Adam(β1=0.9, β2=0.98, ε=1e-9) under the noam schedule — the reference's
    optimizer exactly (``train.py:65-66``) — or Adafactor
    (``train_cfg.optimizer="adafactor"``: factored second moments, the
    big-model optimizer-memory lever; its state leaves replicate under the
    path-rule shardings, which is fine — they are vectors, not matrices).
    Plus optional global-norm clipping (absent from the reference; off by
    default)."""
    schedule = make_lr_schedule(model_cfg, train_cfg)
    if train_cfg.optimizer == "adafactor":
        tx = optax.adafactor(learning_rate=schedule)
    elif train_cfg.optimizer == "adamw":
        # Decoupled weight decay (Loshchilov & Hutter). Biases and layernorm
        # params are exempt — decaying them hurts and no modern recipe does
        # it. The mask keys on the leaf NAME, not rank: the pre-split qkv
        # biases are 2-D (H, head_dim) and must still be exempt.
        def _decay_mask(params):
            def keep(path, p):
                last = path[-1]
                name = str(getattr(last, "key", getattr(last, "name", last)))
                return p.ndim >= 2 and name != "bias"

            return jax.tree_util.tree_map_with_path(keep, params)

        tx = optax.adamw(
            learning_rate=schedule,
            b1=train_cfg.adam_beta1,
            b2=train_cfg.adam_beta2,
            eps=train_cfg.adam_epsilon,
            weight_decay=train_cfg.weight_decay,
            mask=_decay_mask,
        )
    else:
        tx = optax.adam(
            learning_rate=schedule,
            b1=train_cfg.adam_beta1,
            b2=train_cfg.adam_beta2,
            eps=train_cfg.adam_epsilon,
        )
    if train_cfg.max_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(train_cfg.max_grad_norm), tx)
    return tx


def create_train_state(
    rng: jax.Array, model_cfg: ModelConfig, train_cfg: TrainConfig
) -> TrainState:
    params = transformer_init(rng, model_cfg)
    tx = make_optimizer(model_cfg, train_cfg)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
    )
