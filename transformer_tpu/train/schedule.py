"""Learning-rate schedules.

``noam_schedule`` is the reference's ``CustomSchedule`` (``train.py:21-34``):
``d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)`` — linear warmup to
``warmup_steps`` then inverse-sqrt decay. The reference's default warmup is
60000 (``train.py:22``), not the Vaswani paper's 4000.

``cosine_schedule`` / ``constant_schedule`` are extensions (no reference
counterpart): linear warmup to an explicit peak, then cosine decay to a
floor / flat — the standard modern-LM schedules for the decoder-only
family, where noam's d_model coupling is an odd fit.
"""

from __future__ import annotations

import jax.numpy as jnp


def noam_schedule(d_model: int, warmup_steps: int = 60000):
    """Returns ``f(step) -> lr`` usable both as an optax schedule and for
    plotting/testing. ``step`` is 0-based from optax; the formula needs
    1-based to avoid 0^-0.5 = inf."""
    scale = float(d_model) ** -0.5
    warmup = float(warmup_steps) ** -1.5

    def schedule(step):
        s = jnp.asarray(step, dtype=jnp.float32) + 1.0
        return scale * jnp.minimum(s**-0.5, s * warmup)

    return schedule


def cosine_schedule(
    peak_lr: float,
    warmup_steps: int,
    decay_steps: int,
    floor_ratio: float = 0.1,
):
    """Linear warmup to ``peak_lr`` over ``warmup_steps``, then a half cosine
    down to ``peak_lr * floor_ratio`` at ``decay_steps`` (flat floor after)."""
    if decay_steps <= warmup_steps:
        raise ValueError(
            f"decay_steps ({decay_steps}) must exceed warmup_steps "
            f"({warmup_steps})"
        )
    floor = peak_lr * floor_ratio

    def schedule(step):
        s = jnp.asarray(step, dtype=jnp.float32)
        warm = peak_lr * (s + 1.0) / max(warmup_steps, 1)
        frac = jnp.clip(
            (s - warmup_steps) / (decay_steps - warmup_steps), 0.0, 1.0
        )
        cos = floor + (peak_lr - floor) * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(s < warmup_steps, warm, cos)

    return schedule


def constant_schedule(peak_lr: float, warmup_steps: int):
    """Linear warmup to ``peak_lr``, then flat."""

    def schedule(step):
        s = jnp.asarray(step, dtype=jnp.float32)
        warm = peak_lr * (s + 1.0) / max(warmup_steps, 1)
        return jnp.where(s < warmup_steps, warm, peak_lr)

    return schedule
