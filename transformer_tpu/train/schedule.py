"""Learning-rate schedules.

``noam_schedule`` is the reference's ``CustomSchedule`` (``train.py:21-34``):
``d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)`` — linear warmup to
``warmup_steps`` then inverse-sqrt decay. The reference's default warmup is
60000 (``train.py:22``), not the Vaswani paper's 4000.
"""

from __future__ import annotations

import jax.numpy as jnp


def noam_schedule(d_model: int, warmup_steps: int = 60000):
    """Returns ``f(step) -> lr`` usable both as an optax schedule and for
    plotting/testing. ``step`` is 0-based from optax; the formula needs
    1-based to avoid 0^-0.5 = inf."""
    scale = float(d_model) ** -0.5
    warmup = float(warmup_steps) ** -1.5

    def schedule(step):
        s = jnp.asarray(step, dtype=jnp.float32) + 1.0
        return scale * jnp.minimum(s**-0.5, s * warmup)

    return schedule
