"""Jitted train/eval steps and the epoch loop.

Counterpart of the reference's ``Train`` engine (``train.py:37-213``):
teacher-forcing shift, gradient step, streaming metrics, periodic eval,
TensorBoard scalars, checkpoint rotation. Deliberate fixes over the reference
(SURVEY.md §2.3): checkpoints save on the *intended* cadence (every
``checkpoint_every_epochs`` or last epoch — the reference's condition is
inverted by operator precedence, ``train.py:208``); in-loop eval runs a
bounded number of batches instead of the full test set every 100 steps
(``train.py:193-195``); restore happens *before* training so crash-resume
works (the reference restores only after, ``train.py:242-243``).
"""

from __future__ import annotations

import time
from collections.abc import Callable, Iterable
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.models import transformer_apply
from transformer_tpu.train.checkpoint import CheckpointManager
from transformer_tpu.train.loss import (
    chunked_cross_entropy_from_hidden,
    masked_cross_entropy,
)
from transformer_tpu.train.state import TrainState, make_optimizer
from transformer_tpu.utils.preemption import PreemptionGuard
from transformer_tpu.utils.profiling import Profiler, StepTimer
from transformer_tpu.utils.tensorboard import SummaryWriter


def _shift_targets(tgt: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Teacher forcing: feed ``tgt[:, :-1]``, predict ``tgt[:, 1:]``
    (reference ``train.py:130-131``)."""
    return tgt[:, :-1], tgt[:, 1:]


def _check_objective(model_cfg: ModelConfig, train_cfg: TrainConfig) -> None:
    if (train_cfg.objective == "mlm") != model_cfg.encoder_only:
        raise ValueError(
            "objective='mlm' and ModelConfig.encoder_only go together "
            "(the masked-LM loss needs the bidirectional encoder stack, and "
            "an encoder-only model has no causal shift to train on): got "
            f"objective={train_cfg.objective!r}, "
            f"encoder_only={model_cfg.encoder_only}"
        )


def _prepare_batch(
    model_cfg: ModelConfig, train_cfg: TrainConfig, tgt, step_rng
):
    """-> (model_input, labels, fwd_rng) for one step.

    causal: the teacher-forcing shift (``_shift_targets``). mlm: BERT-style
    dynamic masking from the step rng — fresh masks every step
    (``train/mlm.py``); eval passes ``step_rng=None`` and gets a CONSTANT
    mask key, so eval losses are deterministic and comparable across
    epochs/runs (the same positions are always scored).
    """
    if train_cfg.objective == "mlm":
        from transformer_tpu.train.mlm import mask_tokens

        if step_rng is None:
            r_mask, fwd_rng = jax.random.PRNGKey(train_cfg.seed), None
        else:
            r_mask, fwd_rng = jax.random.split(step_rng)
        excluded = train_cfg.mlm_excluded_ids
        if excluded is None:
            # Auto: BOS/EOS sit at the two ids below [MASK] in the
            # framework's MLM vocab layout (config.py mlm_excluded_ids).
            mask_id = model_cfg.input_vocab_size - 1
            excluded = (mask_id - 2, mask_id - 1)
        inp, labels = mask_tokens(
            tgt, r_mask, model_cfg.input_vocab_size, train_cfg.mlm_mask_rate,
            excluded_ids=excluded,
        )
        return inp, labels, fwd_rng
    tar_inp, tar_out = _shift_targets(tgt)
    return tar_inp, tar_out, step_rng


def make_train_step(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    tx: optax.GradientTransformation | None = None,
    forward_fn: Callable | None = None,
    hidden_forward_fn: Callable | None = None,
) -> Callable[[TrainState, jax.Array, jax.Array, jax.Array], tuple[TrainState, dict]]:
    """Build the (jittable) train step: forward, masked CE, grad, Adam update.

    The returned function is pure — jit it (single chip), or jit with
    shardings (distributed): gradients summed across the ``data`` axis emerge
    from XLA's psum with no explicit collective here.

    ``forward_fn(params, src, tar_inp, rng, deterministic) -> logits``
    overrides the forward pass (e.g. the GPipe-pipelined forward when the
    mesh has a ``pipe`` axis); default is the plain ``transformer_apply``.

    ``hidden_forward_fn`` is the pre-vocab-projection counterpart (returns
    (B, S, d_model) hiddens), used when ``train_cfg.loss_chunks > 1``: the
    chunked vocab-projection/CE path then composes with custom forwards
    (pipeline / sequence-parallel) and with gradient accumulation — the
    long-context-at-scale combination (ring attention + 32k vocab) is
    exactly where the (B, S, V) logits OOM.
    """
    tx = tx or make_optimizer(model_cfg, train_cfg)
    _check_objective(model_cfg, train_cfg)
    chunked = train_cfg.loss_chunks > 1
    if chunked:
        if forward_fn is not None and hidden_forward_fn is None:
            raise ValueError(
                "loss_chunks>1 needs the hidden-state forward: a custom "
                "forward_fn must come with the matching hidden_forward_fn "
                "(parallel.distributed.make_sharded_steps builds both)"
            )
        hidden_forward = hidden_forward_fn or _default_hidden_forward(model_cfg)
    if forward_fn is None:
        forward_fn = _default_forward(model_cfg)
    accum = max(1, train_cfg.grad_accum_steps)

    def _apply(state, grads, metrics):
        # Pre-clip global gradient norm: the training-health scalar every
        # telemetry sink exports (docs/OBSERVABILITY.md). Computed here so
        # the plain and grad-accum paths report the same quantity (the
        # accum path passes already-normalized whole-batch grads).
        metrics = {
            **metrics,
            "grad_norm": optax.global_norm(grads).astype(jnp.float32),
        }
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        return new_state, metrics

    def train_step(state: TrainState, src, tgt, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        tar_inp, tar_out, fwd_rng = _prepare_batch(
            model_cfg, train_cfg, tgt, step_rng
        )

        def loss_fn(params):
            if chunked:
                x, aux = hidden_forward(params, src, tar_inp, fwd_rng, False)
                loss, metrics = _chunked_loss(params, x, tar_out, model_cfg, train_cfg)
            else:
                logits, aux = _split_forward_out(
                    forward_fn(params, src, tar_inp, fwd_rng, False)
                )
                loss, metrics = masked_cross_entropy(
                    logits, tar_out,
                    label_smoothing=train_cfg.label_smoothing,
                    normalization=train_cfg.loss_normalization,
                    batch_size=train_cfg.batch_size,
                )
            metrics = {"loss": loss, **metrics}
            total = loss
            if aux is not None:
                # MoE load-balance loss: differentiated (keeps the router
                # honest) but reported separately — "loss" stays comparable
                # CE across dense and MoE configs.
                total = loss + model_cfg.moe_aux_weight * aux
            if model_cfg.moe_experts:
                # Key presence follows the CONFIG, not the forward's return
                # shape, so metric pytrees (and distributed out_shardings)
                # stay fixed even under a custom aux-less forward_fn.
                metrics["moe_aux"] = jnp.float32(0.0) if aux is None else aux
            return total, metrics

        (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        return _apply(state, grads, metrics)

    def accum_train_step(state: TrainState, src, tgt, rng):
        """Gradient accumulation: lax.scan over ``accum`` micro-steps, each a
        full forward/backward on 1/accum of the batch; gradients are summed
        in the un-normalized (loss-SUM) domain and divided once at the end,
        so the update equals the whole-batch gradient exactly (for "tokens"
        normalization the denominator is the global non-pad token count —
        chunk-mean averaging would weight chunks unequally)."""
        step_rng = jax.random.fold_in(rng, state.step)
        tar_inp, tar_out, step_rng = _prepare_batch(
            model_cfg, train_cfg, tgt, step_rng
        )
        batch = src.shape[0]
        if batch % accum:
            raise ValueError(
                f"grad_accum_steps {accum} must divide the batch {batch}"
            )
        mb = batch // accum
        chunks = (
            src.reshape(accum, mb, *src.shape[1:]),
            tar_inp.reshape(accum, mb, *tar_inp.shape[1:]),
            tar_out.reshape(accum, mb, *tar_out.shape[1:]),
            jnp.arange(accum),
        )

        def sum_loss_fn(params, s, ti, to, r):
            if chunked:
                x, aux = hidden_forward(params, s, ti, r, False)
                _, m = chunked_cross_entropy_from_hidden(
                    params, x, to, model_cfg,
                    num_chunks=train_cfg.loss_chunks,
                    label_smoothing=train_cfg.label_smoothing,
                    normalization="tokens",  # only the sums are consumed
                )
            else:
                logits, aux = _split_forward_out(forward_fn(params, s, ti, r, False))
                _, m = masked_cross_entropy(
                    logits, to,
                    label_smoothing=train_cfg.label_smoothing,
                    normalization="tokens",  # only the sums are consumed
                )
            obj = m["loss_sum"]
            if model_cfg.moe_experts:  # key presence follows the config
                # Scaled so that the /denom at the end yields a mean of
                # per-chunk aux losses in BOTH normalizations: token-weighted
                # under "tokens" (denom = total non-pad tokens), uniform under
                # "batch" (denom = batch_size) — without the scale matching
                # the denominator, the effective aux weight would grow with
                # tokens-per-sample under the reference's "batch" rule.
                if train_cfg.loss_normalization == "tokens":
                    aux_scale = m["weight"]
                else:
                    aux_scale = jnp.float32(train_cfg.batch_size) / accum
                m["moe_aux_sum"] = (0.0 if aux is None else aux) * aux_scale
                obj = obj + model_cfg.moe_aux_weight * m["moe_aux_sum"]
            return obj, m

        grad_fn = jax.grad(sum_loss_fn, has_aux=True)

        def body(acc, chunk):
            acc_g, acc_m = acc
            s, ti, to, i = chunk
            g, m = grad_fn(state.params, s, ti, to, jax.random.fold_in(step_rng, i))
            acc_g = jax.tree.map(jnp.add, acc_g, g)
            acc_m = {k: acc_m[k] + m[k] for k in acc_m}
            return (acc_g, acc_m), None

        zero_g = jax.tree.map(jnp.zeros_like, state.params)
        zero_m = {
            "loss_sum": jnp.zeros((), jnp.float32),
            "weight": jnp.zeros((), jnp.float32),
            "correct": jnp.zeros((), jnp.float32),
        }
        if model_cfg.moe_experts:
            zero_m["moe_aux_sum"] = jnp.zeros((), jnp.float32)
        (grads, m), _ = jax.lax.scan(body, (zero_g, zero_m), chunks)
        if train_cfg.loss_normalization == "tokens":
            denom = jnp.maximum(m["weight"], 1.0)
        else:  # "batch": the reference's rule, train.py:88
            denom = jnp.float32(train_cfg.batch_size)
        grads = jax.tree.map(lambda g: g / denom, grads)
        loss = m["loss_sum"] / denom
        aux_sum = m.pop("moe_aux_sum", None)
        metrics = {"loss": loss, **m}
        if aux_sum is not None:
            metrics["moe_aux"] = aux_sum / denom  # mean per-chunk aux (see above)
        return _apply(state, grads, metrics)

    return accum_train_step if accum > 1 else train_step


def make_multistep_train_step(
    step_fn: Callable,
    has_moe: bool = False,
    loss_normalization: str = "tokens",
    batch_size: int = 0,
) -> Callable[[TrainState, jax.Array, jax.Array, jax.Array], tuple[TrainState, dict]]:
    """Wrap a train step so K optimizer steps run inside ONE ``lax.scan``
    per host dispatch (``TrainConfig.steps_per_dispatch``).

    Input batches are stacked on a leading axis: ``src``/``tgt`` are
    (K, B, S). Per-step dropout keys stay exactly what K sequential calls
    would have used — ``step_fn`` folds ``state.step`` into ``rng`` and the
    step counter advances inside the scan — so the trajectory matches K
    separate dispatches to float tolerance (XLA compiles one fused scan
    program, so low-order bits can differ; parity asserted at rtol≈1e-5 in
    tests/test_train.py).

    Metrics come back pre-reduced ON DEVICE over the K steps (sums for
    ``loss_sum``/``weight``/``correct``; token-weighted mean for
    ``moe_aux``), in the exact form ``MetricAccumulator.update`` expects —
    no (K,)-shaped host transfer, async dispatch preserved.
    """

    def multistep(state: TrainState, src, tgt, rng):
        def body(s, xs):
            sb, tb = xs
            s, m = step_fn(s, sb, tb, rng)
            return s, m

        state, ms = jax.lax.scan(body, state, (src, tgt))
        k = ms["loss_sum"].shape[0]
        out = {
            "loss_sum": ms["loss_sum"].sum(0),
            "weight": ms["weight"].sum(0),
            "correct": ms["correct"].sum(0),
        }
        if loss_normalization == "batch" and batch_size:
            # Match the single-step metric's normalization (reference rule,
            # train.py:88): mean of the K per-step losses, each loss_sum/B.
            out["loss"] = out["loss_sum"] / jnp.float32(batch_size * k)
        else:
            out["loss"] = out["loss_sum"] / jnp.maximum(out["weight"], 1.0)
        if has_moe:
            # update() re-multiplies moe_aux by weight; pre-dividing the
            # weighted sum here keeps the epoch aggregate the same
            # token-weighted mean K separate updates would produce.
            out["moe_aux"] = (ms["moe_aux"] * ms["weight"]).sum(0) / jnp.maximum(
                out["weight"], 1.0
            )
        if "grad_norm" in ms:
            # Mean over the K optimizer steps: one representative
            # training-health scalar per dispatch (guarded — custom step_fns
            # without the metric stay supported).
            out["grad_norm"] = ms["grad_norm"].mean(0)
        return state, out

    return multistep


def _split_forward_out(out) -> tuple[jax.Array, jax.Array | None]:
    """Forward functions return logits, or (logits, moe_aux_loss) for MoE
    configs — normalize to a pair."""
    return out if isinstance(out, tuple) else (out, None)


def _collect_moe_aux(attn: dict) -> jax.Array:
    """Sum the stacks' reserved load-balance keys (models/encoder.py
    encoder_apply docstring) into one fp32 scalar."""
    return jnp.asarray(
        attn.get("moe_aux_encoder", 0.0) + attn.get("moe_aux_decoder", 0.0),
        jnp.float32,
    )


def _chunked_loss(params, hidden, tar_out, model_cfg, train_cfg):
    """The train/eval-shared call into the chunked vocab-projection/CE path."""
    return chunked_cross_entropy_from_hidden(
        params, hidden, tar_out, model_cfg,
        num_chunks=train_cfg.loss_chunks,
        label_smoothing=train_cfg.label_smoothing,
        normalization=train_cfg.loss_normalization,
        batch_size=train_cfg.batch_size,
    )


def _default_hidden_forward(model_cfg: ModelConfig) -> Callable:
    """Like ``_default_forward`` but stops before the vocab projection:
    returns ((B, S, d_model) hiddens, moe_aux|None) for the chunked-loss
    path (``train_cfg.loss_chunks``)."""
    from transformer_tpu.models import transformer_hidden_apply

    def forward(params, src, tar_inp, rng, deterministic):
        x, attn = transformer_hidden_apply(
            params, src, tar_inp, model_cfg,
            rng=None if deterministic else rng, deterministic=deterministic,
        )
        return x, _collect_moe_aux(attn) if model_cfg.moe_experts else None

    return forward


def _default_forward(model_cfg: ModelConfig) -> Callable:
    if model_cfg.moe_experts:

        def forward_moe(params, src, tar_inp, rng, deterministic):
            logits, attn = transformer_apply(
                params, src, tar_inp, model_cfg,
                rng=None if deterministic else rng, deterministic=deterministic,
            )
            return logits, _collect_moe_aux(attn)

        return forward_moe

    def forward(params, src, tar_inp, rng, deterministic):
        logits, _ = transformer_apply(
            params, src, tar_inp, model_cfg,
            rng=None if deterministic else rng, deterministic=deterministic,
        )
        return logits

    return forward


def make_eval_step(
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    forward_fn: Callable | None = None,
    hidden_forward_fn: Callable | None = None,
) -> Callable[[TrainState, jax.Array, jax.Array], dict]:
    """Forward-only eval step (reference ``test_step``, ``train.py:144-157``)."""
    _check_objective(model_cfg, train_cfg)
    chunked = train_cfg.loss_chunks > 1
    if chunked and forward_fn is not None and hidden_forward_fn is None:
        # Same contract as make_train_step: silently materializing the full
        # (B, S, V) logits would OOM in exactly the config loss_chunks exists
        # to protect.
        raise ValueError(
            "loss_chunks>1 needs the hidden-state forward: a custom "
            "forward_fn must come with the matching hidden_forward_fn "
            "(parallel.distributed.make_sharded_steps builds both)"
        )
    if chunked:
        hidden_forward = hidden_forward_fn or _default_hidden_forward(model_cfg)
    if forward_fn is None:
        forward_fn = _default_forward(model_cfg)

    def eval_step(state: TrainState, src, tgt):
        tar_inp, tar_out, _ = _prepare_batch(model_cfg, train_cfg, tgt, None)
        if chunked:
            x, aux = hidden_forward(state.params, src, tar_inp, None, True)
            loss, metrics = _chunked_loss(state.params, x, tar_out, model_cfg, train_cfg)
            metrics = {"loss": loss, **metrics}
            if model_cfg.moe_experts:
                metrics["moe_aux"] = jnp.float32(0.0) if aux is None else aux
            return metrics
        logits, aux = _split_forward_out(
            forward_fn(state.params, src, tar_inp, None, True)
        )
        loss, metrics = masked_cross_entropy(
            logits, tar_out,
            label_smoothing=train_cfg.label_smoothing,
            normalization=train_cfg.loss_normalization,
            batch_size=train_cfg.batch_size,
        )
        metrics = {"loss": loss, **metrics}
        if model_cfg.moe_experts:  # key presence follows the config
            metrics["moe_aux"] = jnp.float32(0.0) if aux is None else aux
        return metrics

    return eval_step


class MetricAccumulator:
    """Exact accumulation of device-computed sums — replacement for the
    reference's Keras streaming metrics (``train.py:70-73,181-184``).

    Sums are kept as (device) arrays and added lazily, so updating metrics
    every step does NOT force a host-device sync — reading ``.loss`` /
    ``.accuracy`` (at log boundaries) is the only blocking point. This
    preserves JAX async dispatch: step N+1 enqueues while N runs.
    """

    _KEYS = ("loss_sum", "weight", "correct")

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._sums: dict[str, Any] | None = None

    def update(self, metrics: dict[str, Any]) -> None:
        part = {k: metrics[k] for k in self._KEYS}
        if "moe_aux" in metrics:
            # Token-weighted so the epoch aggregate is the same weighted mean
            # the per-step metric reports (steps with more real tokens count
            # proportionally).
            part["moe_aux_w"] = metrics["moe_aux"] * metrics["weight"]
        if self._sums is None:
            self._sums = part
        else:
            self._sums = {k: self._sums.get(k, 0.0) + part[k] for k in part}

    def _get(self, key: str) -> float:
        return 0.0 if self._sums is None else float(self._sums[key])

    @property
    def loss_sum(self) -> float:
        return self._get("loss_sum")

    @property
    def weight(self) -> float:
        return self._get("weight")

    @property
    def correct(self) -> float:
        return self._get("correct")

    @property
    def loss(self) -> float:
        return self.loss_sum / max(self.weight, 1.0)

    @property
    def accuracy(self) -> float:
        return self.correct / max(self.weight, 1.0)

    @property
    def moe_aux(self) -> float | None:
        """Token-weighted mean MoE load-balance loss, or None for dense runs."""
        if self._sums is None or "moe_aux_w" not in self._sums:
            return None
        return float(self._sums["moe_aux_w"]) / max(self.weight, 1.0)


def _dispatch_groups(batches, k: int):
    """Group consecutive SAME-SHAPE batches into stacks of up to ``k`` for
    the multi-step dispatch path: yields ``(src, tgt, n)`` with src/tgt
    stacked to (n, B, S) when n > 1, or the single batch unstacked when a
    group has one member (shape change mid-group, epoch tail). Grouping
    only ever joins identical shapes, so length-bucketed pipelines work —
    each distinct (n, B, S) signature costs one jit re-trace, bounded by
    #buckets × #tail-lengths per run."""
    buf: list = []
    sig = None
    for b in batches:
        s = (b[0].shape, b[1].shape)
        if buf and s != sig:
            yield _stack_group(buf)
            buf = []
        buf.append(b)
        sig = s
        if len(buf) == k:
            yield _stack_group(buf)
            buf = []
    if buf:
        yield _stack_group(buf)


def _stack_group(buf: list):
    if len(buf) == 1:
        src, tgt = buf[0]
        return src, tgt, 1
    return (
        np.stack([b[0] for b in buf]),
        np.stack([b[1] for b in buf]),
        len(buf),
    )


class Trainer:
    """Epoch-driven training loop.

    ``enable_function=False`` runs the steps un-jitted — the reference's eager
    debug mode (``--enable_function``, ``train.py:175-177``).
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        state: TrainState,
        log_dir: str | None = None,
        checkpoint: CheckpointManager | None = None,
        donate_state: bool = True,
        log_fn: Callable[[str], None] = print,
        profiler: "Profiler | None" = None,
        telemetry=None,
    ) -> None:
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg
        self.state = state
        self.checkpoint = checkpoint
        self.log_fn = log_fn
        self.profiler = profiler
        self.step_timer = StepTimer(
            tokens_per_step=train_cfg.batch_size * train_cfg.sequence_length
        )
        self.train_metrics = MetricAccumulator()
        self.eval_metrics = MetricAccumulator()
        self.writers = {}
        if log_dir:
            self.writers = {
                "train": SummaryWriter(f"{log_dir}/train"),
                "test": SummaryWriter(f"{log_dir}/test"),
            }
        # Telemetry (obs.Telemetry | None): host-side recording at the sync
        # points the loop already has (log/eval/epoch boundaries) — zero new
        # device ops, zero recompiles (analysis telemetry_inert contract).
        self.telemetry = telemetry
        # Tracing (--trace): train.fit/train.step/train.eval/ckpt.* spans on
        # the "train" lane of the same event log the scheduler traces into.
        self._tracer = getattr(telemetry, "tracer", None)
        self._last_metrics: dict | None = None
        self._window_mark = (0, 0, 0.0)  # (steps, tokens, time) at last record
        if telemetry is not None:
            reg = telemetry.registry
            self._m_loss = reg.gauge("train_loss", "streaming epoch train loss")
            self._m_acc = reg.gauge("train_accuracy", "streaming token accuracy")
            self._m_gnorm = reg.gauge("train_grad_norm", "latest global grad norm")
            self._m_eloss = reg.gauge("train_eval_loss", "latest eval loss")
            self._m_eacc = reg.gauge("train_eval_accuracy", "latest eval accuracy")
            self._m_steps = reg.counter("train_steps_total", "optimizer steps")
            self._m_tokens = reg.counter("train_tokens_total", "target tokens")
            # Bound to the SAME sample stream StepTimer populates — the
            # registry exports it, no duplicate quantile accounting.
            reg.histogram(
                "train_step_seconds", "per-step wall time (synced windows)",
                hist=self.step_timer.histogram,
            )

        train_step = make_train_step(model_cfg, train_cfg)
        eval_step = make_eval_step(model_cfg, train_cfg)
        self.multi_step = None
        if train_cfg.enable_function:
            if train_cfg.steps_per_dispatch > 1:
                # K optimizer steps per host dispatch (one jitted scan):
                # amortizes the per-step dispatch overhead the BASELINE.md
                # [deviceloop] probe isolates. jit re-traces per distinct
                # stacked shape (tail groups, length buckets) and caches.
                self.multi_step = jax.jit(
                    make_multistep_train_step(
                        train_step,
                        has_moe=bool(model_cfg.moe_experts),
                        loss_normalization=train_cfg.loss_normalization,
                        batch_size=train_cfg.batch_size,
                    ),
                    donate_argnums=(0,) if donate_state else (),
                )
            # Donating the state buffers lets XLA update params in place —
            # halves peak HBM for the optimizer step.
            train_step = jax.jit(train_step, donate_argnums=(0,) if donate_state else ())
            eval_step = jax.jit(eval_step)
        self.train_step = train_step
        self.eval_step = eval_step
        if telemetry is not None:
            self._wrap_steps_for_dispatch_timing()

    def _wrap_steps_for_dispatch_timing(self) -> None:
        """Route the step callables through ``obs.telemetry.timed_call`` —
        the jaxpr-inert wrapper the ``telemetry_inert`` contract pins. Under
        async dispatch this histogram measures host dispatch latency (a
        host-stall detector); StepTimer's synced windows stay the
        device-throughput source of truth. DistributedTrainer re-invokes
        this after swapping in its sharded steps. With tracing on, the same
        callables additionally run through ``obs.trace.traced_call`` — one
        ``train.step`` span per dispatch, parented under the open
        ``train.fit`` span (the contract pins that wrapper's jaxpr inertness
        too). Both wrappers chain ``__wrapped__``, and every probe that
        needs the jitted fn unwraps the CHAIN, not one level."""
        from transformer_tpu.obs.telemetry import timed_call

        self._m_dispatch = self.telemetry.registry.histogram(
            "train_dispatch_seconds", "host dispatch latency per step call"
        )
        self.train_step = timed_call(self.train_step, self._m_dispatch)
        if self.multi_step is not None:
            self.multi_step = timed_call(self.multi_step, self._m_dispatch)
        profiler = getattr(self.telemetry, "profiler", None)
        if profiler is not None:
            # Third sibling in the chain (same jaxpr-inertness contract):
            # the roofline sentinel's train.step stream.
            from transformer_tpu.obs.profile import profile_call

            self.train_step = profile_call(
                self.train_step, profiler, "train.step"
            )
            if self.multi_step is not None:
                self.multi_step = profile_call(
                    self.multi_step, profiler, "train.step"
                )
        if self._tracer is not None:
            from transformer_tpu.obs.trace import traced_call

            self.train_step = traced_call(
                self.train_step, self._tracer, "train.step", lane="train"
            )
            if self.multi_step is not None:
                self.multi_step = traced_call(
                    self.multi_step, self._tracer, "train.step", lane="train"
                )

    # ------------------------------------------------------------------ loop
    def _span(self, name: str, **attrs):
        """A ``train``-lane tracing span, or a no-op context without a
        tracer — the trainer's sites all parent via the thread-local stack
        (everything nests under the ``train.fit`` root)."""
        if self._tracer is None:
            import contextlib

            return contextlib.nullcontext()
        return self._tracer.span(name, lane="train", **attrs)

    def evaluate(
        self,
        batches: Iterable,
        max_batches: int | None = None,
        guard: "PreemptionGuard | None" = None,
    ) -> None:
        with self._span("train.eval"):
            self.eval_metrics.reset()
            for i, (src, tgt) in enumerate(batches):
                if max_batches is not None and i >= max_batches:
                    break
                if guard is not None and guard.should_stop:
                    return  # preemption: abandon eval, caller checkpoints
                m = self.eval_step(self.state, src, tgt)
                self.eval_metrics.update(m)

    def fit(
        self,
        train_ds,
        test_ds=None,
        rng: jax.Array | None = None,
        epoch_callback: Callable[[int, "Trainer"], object] | None = None,
    ) -> None:
        """Tracing wrapper: the whole run is one ``train.fit`` span —
        every step/eval/checkpoint span nests under it via the tracer's
        thread-local stack, and the ``with`` closes it on every exit path
        (returns, preemption, exceptions)."""
        with self._span("train.fit", epochs=self.train_cfg.epochs):
            self._fit(train_ds, test_ds, rng, epoch_callback)

    def _fit(
        self,
        train_ds,
        test_ds=None,
        rng: jax.Array | None = None,
        epoch_callback: Callable[[int, "Trainer"], object] | None = None,
    ) -> None:
        """``epoch_callback(epoch, trainer)``, if given, runs after each
        epoch's metrics/eval/summaries and before the checkpoint save —
        the hook for in-training quality tracking (e.g. periodic BLEU in
        ``benchmarks/bleu_run.py``). A truthy return value requests an
        early stop: the epoch's checkpoint is still saved, then the loop
        exits — the hook for metric-driven stopping rules (keep-best BLEU,
        ``train/probe_stop.py``) that watch something other than the eval
        loss the built-in ``early_stop_patience`` plateau rule uses."""
        cfg = self.train_cfg
        if cfg.steps_per_dispatch > 1 and self.multi_step is None:
            # Plain Trainer in eager-debug mode: no scanned step was built
            # (DistributedTrainer always jits and installs its own), so the
            # feature would silently no-op — refuse instead.
            raise ValueError(
                "steps_per_dispatch > 1 requires enable_function=True on the "
                "single-process Trainer: the multi-step dispatch is a jitted "
                "lax.scan; in eager-debug mode it would silently fall back "
                "to single-step dispatch"
            )
        rng = rng if rng is not None else jax.random.PRNGKey(cfg.seed)
        self._emit_cost_prediction()
        # Restore BEFORE training (fixes reference restore-after, train.py:242-243).
        if self.checkpoint is not None:
            def _ckpt_fallback(step, exc):
                self.log_fn(
                    f"checkpoint at step {step} unreadable "
                    f"({type(exc).__name__}); falling back"
                )
                if self.telemetry is not None:
                    self.telemetry.emit(
                        "ckpt.fallback", step=int(step),
                        reason=f"{type(exc).__name__}: {exc}",
                    )

            with self._span("ckpt.restore"):
                restored = self.checkpoint.restore_latest(
                    self.state, on_fallback=_ckpt_fallback
                )
            if restored is not None:
                self.state = restored
                self.log_fn(f"restored checkpoint at step {int(self.state.step)}")

        # Host-side step mirror: consulting state.step (a device array) every
        # iteration would block async dispatch.
        step = int(self.state.step)
        # Resume at the right EPOCH, not just the right step: a restored run
        # must train only the remaining epochs (and continue the (seed,
        # epoch)-keyed data order), not cfg.epochs more. Possible only when
        # the dataset advertises its per-epoch length.
        start_epoch = 0
        try:
            steps_per_epoch = len(train_ds)
        except TypeError:
            steps_per_epoch = 0
        if step and steps_per_epoch:
            start_epoch = min(step // steps_per_epoch, cfg.epochs)
            if start_epoch:
                self.log_fn(
                    f"resuming at epoch {start_epoch + 1}/{cfg.epochs} "
                    f"(step {step})"
                )
        if cfg.early_stop_patience and self._early_stop_marker_exists():
            # A previous run of this checkpoint directory already stopped on
            # an eval-loss plateau; a relaunch (job-scheduler retry) must not
            # train past it and overwrite the early-stopped checkpoint.
            self.log_fn(
                "early-stop marker present in checkpoint dir; not training "
                "further (delete the EARLY_STOPPED file to continue)"
            )
            return
        best_eval = float("inf")
        epochs_since_best = 0
        if cfg.early_stop_patience:
            # Plateau accounting is persisted next to the checkpoints (a tiny
            # sidecar JSON, written by the primary process at every save):
            # a preempted-and-resumed run continues its patience window
            # instead of restarting it and training `patience` extra epochs.
            best_eval, epochs_since_best = self._load_plateau_state(step)
            if epochs_since_best:
                self.log_fn(
                    f"resumed early-stop window: best eval {best_eval:.4f}, "
                    f"{epochs_since_best} epoch(s) without improvement"
                )
        with PreemptionGuard() as guard:
            for epoch in range(start_epoch, cfg.epochs):
                self.train_metrics.reset()
                self.step_timer.reset()
                self._window_mark = (0, 0, 0.0)
                epoch_start = time.time()
                batch_iter = train_ds.batches(epoch)
                if self.multi_step is not None:
                    groups = _dispatch_groups(batch_iter, cfg.steps_per_dispatch)
                else:
                    groups = ((s, t, 1) for s, t in batch_iter)
                for src, tgt, k in groups:
                    if self.profiler is not None:
                        self.profiler.maybe_trace(step, block_on=self.state)
                    if k == 1:
                        self.state, m = self.train_step(self.state, src, tgt, rng)
                        # Actual target tokens this step (length-bucketed
                        # batches are narrower than the nominal length).
                        tokens = src.shape[0] * max(tgt.shape[1] - 1, 1)
                    else:
                        # K stacked same-shape batches, one dispatch, K
                        # optimizer steps inside a jitted scan; metrics come
                        # back pre-reduced over the group.
                        self.state, m = self.multi_step(self.state, src, tgt, rng)
                        tokens = k * src.shape[1] * max(tgt.shape[2] - 1, 1)
                    self.train_metrics.update(m)
                    self._last_metrics = m  # host ref only; read at syncs
                    self.step_timer.tick(tokens, steps=k)
                    prev_step = step
                    step += k
                    if guard.should_stop:
                        self._preempt(step, guard)
                        return
                    # Boundary-crossing (not ==0) so a K-step dispatch that
                    # jumps over a log/eval boundary still triggers it; for
                    # k == 1 this is exactly the step % N == 0 cadence.
                    if cfg.log_every_steps and (
                        step // cfg.log_every_steps
                        != prev_step // cfg.log_every_steps
                    ):
                        loss = self.train_metrics.loss  # device_get: blocks
                        self.step_timer.sync()
                        aux = self.train_metrics.moe_aux
                        self.log_fn(
                            f"epoch {epoch + 1} step {step} "
                            f"loss {loss:.4f} "
                            f"acc {self.train_metrics.accuracy:.4f} "
                            + (f"moe_aux {aux:.3f} " if aux is not None else "")
                            + f"({self.step_timer.steps_per_sec:.2f} steps/s)"
                        )
                        self._record_train_window(epoch, step)
                    if (
                        test_ds is not None
                        and cfg.eval_every_steps
                        and step // cfg.eval_every_steps
                        != prev_step // cfg.eval_every_steps
                    ):
                        # Bounded in-loop eval (fixes reference full-test-set
                        # stall, train.py:193-195, and 1-batch quirk §2.3.3).
                        self.step_timer.sync()
                        self.evaluate(
                            test_ds.batches(epoch),
                            max_batches=cfg.eval_max_batches or None,
                            guard=guard,
                        )
                        self.log_fn(
                            f"  eval loss {self.eval_metrics.loss:.4f} "
                            f"acc {self.eval_metrics.accuracy:.4f}"
                        )
                        self._record_eval(epoch, step)

                epoch_loss = self.train_metrics.loss  # device_get: blocks
                self.step_timer.sync()
                if guard.should_stop:
                    self._preempt(step, guard)
                    return
                if test_ds is not None:
                    self.evaluate(test_ds.batches(epoch), guard=guard)
                    if guard.should_stop:
                        self._preempt(step, guard)
                        return
                    self._record_eval(epoch, step)
                self._write_epoch_summaries(epoch)
                self._record_train_window(epoch, step)
                self._record_epoch_telemetry(epoch, step)
                self.log_fn(
                    f"epoch {epoch + 1}/{cfg.epochs} done in "
                    f"{time.time() - epoch_start:.1f}s: "
                    f"loss {epoch_loss:.4f} "
                    f"acc {self.train_metrics.accuracy:.4f}; "
                    f"{self.step_timer.summary()}"
                )
                callback_stop = False
                if epoch_callback is not None:
                    callback_stop = bool(epoch_callback(epoch, self))
                stop_early = False
                if (
                    cfg.early_stop_patience
                    and test_ds is not None
                    and self.eval_metrics.weight > 0  # empty eval: no signal
                ):
                    # The full end-of-epoch eval above populated eval_metrics.
                    if self.eval_metrics.loss < best_eval - 1e-6:
                        best_eval = self.eval_metrics.loss
                        epochs_since_best = 0
                    else:
                        epochs_since_best += 1
                        stop_early = epochs_since_best >= cfg.early_stop_patience
                self._best_eval = best_eval
                self._epochs_since_best = epochs_since_best
                if self.checkpoint is not None and (
                    (epoch + 1) % cfg.checkpoint_every_epochs == 0
                    or (epoch + 1) == cfg.epochs
                    or stop_early
                    or callback_stop
                ):
                    with self._span("ckpt.save", step=step):
                        self.checkpoint.save(self.state)
                    if cfg.early_stop_patience:
                        self._save_plateau_state(step)
                if stop_early:
                    self.log_fn(
                        f"early stop after epoch {epoch + 1}: eval loss has "
                        f"not improved for {epochs_since_best} epoch(s) "
                        f"(best {best_eval:.4f})"
                    )
                    self._mark_early_stopped(epoch + 1)
                    break
                if callback_stop:
                    # The callback owns its own stop persistence (e.g. the
                    # probe tracker's JSON) — no EARLY_STOPPED marker here,
                    # that file gates the plateau rule's resume path.
                    self.log_fn(
                        f"stop requested by epoch callback after epoch "
                        f"{epoch + 1}"
                    )
                    break
        if self.checkpoint is not None:
            # Async managers write in the background; don't return (or let the
            # process exit) with the final checkpoint still uncommitted.
            self.checkpoint.wait()
        if self.profiler is not None:
            self.profiler.stop(block_on=self.state)
        if self.telemetry is not None:
            self.telemetry.maybe_flush(force=True)

    # ------------------------------------------------------------- telemetry
    # All recorders run at points where the loop has ALREADY paid a blocking
    # metric read (train_metrics.loss / eval_metrics.loss device_get) and a
    # step_timer.sync() — they add host float reads, never device ops.

    def _record_train_window(self, epoch: int, step: int) -> None:
        if self.telemetry is None:
            return
        st = self.step_timer
        m_steps, m_tokens, m_time = self._window_mark
        d_steps = st.count - m_steps
        if d_steps <= 0:
            return
        d_tokens = st.total_tokens - m_tokens
        window_s = st.total_time_s - m_time
        self._window_mark = (st.count, st.total_tokens, st.total_time_s)
        loss = self.train_metrics.loss
        acc = self.train_metrics.accuracy
        self._m_loss.set(loss)
        self._m_acc.set(acc)
        self._m_steps.inc(d_steps)
        self._m_tokens.inc(d_tokens)
        event = {
            "epoch": epoch + 1, "step": step, "steps": d_steps,
            "tokens": d_tokens, "window_s": round(window_s, 6),
            "loss": round(loss, 6), "accuracy": round(acc, 6),
        }
        if window_s > 0:
            event["steps_per_sec"] = round(d_steps / window_s, 3)
            event["tokens_per_sec"] = round(d_tokens / window_s, 1)
        if self._last_metrics is not None and "grad_norm" in self._last_metrics:
            gnorm = float(self._last_metrics["grad_norm"])
            self._m_gnorm.set(gnorm)
            event["grad_norm"] = round(gnorm, 6)
        self.telemetry.emit("train.window", **event)
        self.telemetry.maybe_flush()

    def _record_eval(self, epoch: int, step: int) -> None:
        if self.telemetry is None or self.eval_metrics.weight <= 0:
            return
        loss, acc = self.eval_metrics.loss, self.eval_metrics.accuracy
        self._m_eloss.set(loss)
        self._m_eacc.set(acc)
        self.telemetry.emit(
            "train.eval", epoch=epoch + 1, step=step,
            loss=round(loss, 6), accuracy=round(acc, 6),
        )

    def _emit_cost_prediction(self) -> None:
        """One ``train.predicted`` event at fit start: the jaxpr cost
        model's peak-bytes/FLOPs estimate for THIS run's plain train step
        (``analysis/costs.py``, abstract trace — no device execution).
        ``obs summarize`` cross-checks it against the ``train.memory``
        samples ``_record_epoch_telemetry`` records from
        ``device.memory_stats()`` and reports the measured/predicted ratio.
        Single-device prediction: sharded/pipelined trainers inherit it as
        a per-replica upper bound, and summarize stays tolerant when the
        event is absent. Purely advisory, so it must never break training.
        Emitted once per Trainer — callers (cli/train.py length-bucket
        loops) may invoke fit() repeatedly on the same step functions."""
        if self.telemetry is None or getattr(self, "_cost_predicted", False):
            return
        self._cost_predicted = True
        try:
            from transformer_tpu.analysis.costs import train_step_costs

            r = train_step_costs(self.model_cfg, self.train_cfg)
        except Exception as e:  # tpa: disable=TPA006 — advisory-only: any config the cost model cannot trace (custom forwards, exotic objectives) must degrade to "no prediction", never to a failed training run
            self.log_fn(f"cost-model prediction unavailable ({type(e).__name__}: {e})")
            return
        self.telemetry.registry.gauge(
            "train_predicted_peak_bytes",
            "jaxpr cost model: train-step peak live-buffer bytes",
        ).set(r.peak_bytes)
        self.telemetry.emit(
            "train.predicted",
            program="train_step",
            peak_bytes=r.peak_bytes,
            flops=r.flops,
            bytes_moved=r.bytes_moved,
            tokens_per_step=r.extras.get("tokens_per_step"),
        )

    def _record_epoch_telemetry(self, epoch: int, step: int) -> None:
        """Epoch-boundary extras: device memory stats (where the backend
        exposes them) and jit compile-cache accounting — recompiles surface
        as a visible counter, not just a retrace-sentinel test failure."""
        if self.telemetry is None:
            return
        from transformer_tpu.obs import device_memory_stats

        devices = {}
        for d in jax.local_devices():
            stats = device_memory_stats(d)
            if stats:
                devices[str(d.id)] = stats
        if devices:
            first = next(iter(devices.values()))
            for key in ("bytes_in_use", "peak_bytes_in_use"):
                if key in first:
                    self.telemetry.registry.gauge(
                        f"device_{key}", "PJRT allocator stats, device 0"
                    ).set(first[key])
            self.telemetry.emit(
                "train.memory", epoch=epoch + 1, step=step, devices=devices
            )
        cache_sizes = {}
        # *_fn variants: DistributedTrainer keeps the jitted sharded steps
        # there (its train_step attribute is a host-side placement wrapper).
        for name in ("train_step", "multi_step", "eval_step",
                     "train_step_fn", "multi_step_fn", "eval_step_fn"):
            fn = getattr(self, name, None)
            # Through the telemetry wrapper chain (timed_call, traced_call —
            # tracing adds a second __wrapped__ layer), stopping at the
            # jitted callable: jax.jit ALSO sets __wrapped__, and unwrapping
            # past it would reach the raw Python fn, which has no cache.
            while (
                fn is not None
                and not hasattr(fn, "_cache_size")
                and hasattr(fn, "__wrapped__")
            ):
                fn = fn.__wrapped__
            probe = getattr(fn, "_cache_size", None)
            if probe is not None:
                # The same accounting the analysis/retrace.py sentinel
                # budgets: compiled-program counts per jitted hot path.
                cache_sizes[name] = int(probe())
        if cache_sizes:
            self.telemetry.registry.gauge(
                "train_compiled_programs",
                "compiled executables across the jitted step caches",
            ).set(sum(cache_sizes.values()))
            self.telemetry.emit(
                "train.compile", epoch=epoch + 1, step=step,
                cache_sizes=cache_sizes,
            )
        self.telemetry.maybe_flush(force=True)

    # ---------------------------------------------------------- plateau state
    # Host-side early-stop accounting, persisted so crash-resume keeps the
    # patience window (round-2 VERDICT weak #8). Same writer discipline as
    # the EARLY_STOPPED marker: primary process writes, everyone reads.
    _best_eval: float = float("inf")
    _epochs_since_best: int = 0

    def _plateau_state_path(self) -> str | None:
        if self.checkpoint is None:
            return None
        import os

        return os.path.join(self.checkpoint.directory, "plateau.json")

    def _load_plateau_state(self, step: int) -> tuple[float, int]:
        import json
        import os

        path = self._plateau_state_path()
        if path is None or not os.path.exists(path):
            return float("inf"), 0
        try:
            with open(path) as f:
                d = json.load(f)
        except (ValueError, OSError):
            return float("inf"), 0
        if int(d.get("step", -1)) > step:
            # Sidecar is ahead of the restored checkpoint (an older rotation
            # slot was restored): its counters describe evals this run will
            # redo — reset rather than double-count them.
            return float("inf"), 0
        return (
            float(d.get("best_eval", float("inf"))),
            int(d.get("epochs_since_best", 0)),
        )

    def _save_plateau_state(self, step: int) -> None:
        import json
        import os

        path = self._plateau_state_path()
        if path is None or not getattr(self.checkpoint, "is_primary", True):
            return
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "step": step,
                    "best_eval": self._best_eval,
                    "epochs_since_best": self._epochs_since_best,
                },
                f,
            )
        os.replace(tmp, path)

    def _early_stop_marker_path(self) -> str | None:
        if self.checkpoint is None:
            return None
        import os

        return os.path.join(self.checkpoint.directory, "EARLY_STOPPED")

    def _early_stop_marker_exists(self) -> bool:
        import os

        path = self._early_stop_marker_path()
        return path is not None and os.path.exists(path)

    def _mark_early_stopped(self, epoch: int) -> None:
        path = self._early_stop_marker_path()
        if path is None or not getattr(self.checkpoint, "is_primary", True):
            return
        with open(path, "w") as f:
            f.write(f"early stop after epoch {epoch}\n")

    def _preempt(self, step: int, guard: "PreemptionGuard") -> None:
        """Graceful shutdown on SIGTERM/SIGINT: checkpoint, flush, report."""
        if self.profiler is not None:
            self.profiler.stop(block_on=self.state)
        prefix = f"preemption (signal {guard.signal_received}) at step {step}: "
        if self.checkpoint is not None:
            with self._span("ckpt.save", step=step, preempt=True):
                path = self.checkpoint.save(self.state)
                # The save must be durable before we report it (and exit).
                self.checkpoint.wait()
            if self.train_cfg.early_stop_patience:
                self._save_plateau_state(step)
            if path is not None:
                self.log_fn(prefix + f"checkpoint saved to {path}")
            else:
                # Non-primary process in a multi-host run: host 0 persists.
                self.log_fn(prefix + "checkpoint written by primary process")
        else:
            self.log_fn(prefix + "no checkpoint manager configured, state lost")
        for w in self.writers.values():
            w.flush()
        if self.telemetry is not None:
            self.telemetry.emit(
                "train.preempt", step=step, signal=guard.signal_received
            )
            self.telemetry.maybe_flush(force=True)

    def _write_epoch_summaries(self, epoch: int) -> None:
        if not self.writers:
            return
        from transformer_tpu.train.state import make_lr_schedule

        w = self.writers["train"]
        w.scalar("loss", self.train_metrics.loss, epoch)
        w.scalar("accuracy", self.train_metrics.accuracy, epoch)
        if self.train_metrics.moe_aux is not None:
            w.scalar("moe_aux", self.train_metrics.moe_aux, epoch)
        lr = make_lr_schedule(self.model_cfg, self.train_cfg)(
            int(jax.device_get(self.state.step))
        )
        w.scalar("learning_rate", float(lr), epoch)
        w.scalar("tokens_per_sec", self.step_timer.tokens_per_sec, epoch)
        if self._last_metrics is not None and "grad_norm" in self._last_metrics:
            w.scalar("grad_norm", float(self._last_metrics["grad_norm"]), epoch)
        # Step-duration distribution (p50/p95/p99 in TensorBoard's histogram
        # dashboard) — the tfevents face of the obs step-time histogram.
        w.histogram("step_time_s", self.step_timer.histogram, epoch)
        w.flush()
        if self.eval_metrics.weight > 0:
            w = self.writers["test"]
            w.scalar("loss", self.eval_metrics.loss, epoch)
            w.scalar("accuracy", self.eval_metrics.accuracy, epoch)
            w.flush()
