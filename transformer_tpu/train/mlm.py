"""Masked-LM objective: BERT-style dynamic masking.

No reference counterpart (`/root/reference` is translation-only,
``README.md:1-5``); this completes the encoder-only family
(``ModelConfig.encoder_only``) the way ``decoder_only`` completed the
causal-LM one. Masking happens INSIDE the jitted train step from the step
rng ("dynamic masking": every epoch sees fresh masks, the RoBERTa
improvement over static preprocessing) — the data pipeline stays the plain
LM-window stream, and the host does zero per-step masking work.

The [MASK] token is the model's top input id (``input_vocab_size - 1``):
callers size the model vocab ONE larger than the tokenizer's
(``cli.train --objective=mlm`` does this), so no tokenizer change and no
collision with real subwords.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from transformer_tpu.config import PAD_ID


def mask_tokens(
    tokens: jax.Array,
    rng: jax.Array,
    vocab_size: int,
    mask_rate: float = 0.15,
) -> tuple[jax.Array, jax.Array]:
    """(B, S) token ids -> (masked_input, labels) for one MLM step.

    ``mask_rate`` of the non-PAD positions are selected; of those, 80% are
    replaced by [MASK] (= ``vocab_size - 1``), 10% by a uniform random real
    token, 10% kept unchanged (the canonical 80/10/10). ``labels`` carries
    the ORIGINAL token at selected positions and PAD everywhere else, so
    ``masked_cross_entropy`` scores exactly the selected positions (its
    weight mask is ``labels != PAD_ID``).
    """
    mask_id = vocab_size - 1
    r_sel, r_kind, r_rand = jax.random.split(rng, 3)
    real = tokens != PAD_ID
    sel = jnp.logical_and(
        jax.random.uniform(r_sel, tokens.shape) < mask_rate, real
    )
    kind = jax.random.uniform(r_kind, tokens.shape)
    # Random replacements draw from [1, mask_id): real ids only — never PAD
    # (id 0 is structurally padding) and never [MASK] itself.
    rand_tok = jax.random.randint(r_rand, tokens.shape, 1, mask_id)
    masked = jnp.where(
        jnp.logical_and(sel, kind < 0.8),
        jnp.full_like(tokens, mask_id),
        jnp.where(jnp.logical_and(sel, kind < 0.9), rand_tok, tokens),
    )
    labels = jnp.where(sel, tokens, jnp.full_like(tokens, PAD_ID))
    return masked, labels
