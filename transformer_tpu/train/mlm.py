"""Masked-LM objective: BERT-style dynamic masking.

No reference counterpart (`/root/reference` is translation-only,
``README.md:1-5``); this completes the encoder-only family
(``ModelConfig.encoder_only``) the way ``decoder_only`` completed the
causal-LM one. Masking happens INSIDE the jitted train step from the step
rng ("dynamic masking": every epoch sees fresh masks, the RoBERTa
improvement over static preprocessing) — the data pipeline stays the plain
LM-window stream, and the host does zero per-step masking work.

The [MASK] token is the model's top input id (``input_vocab_size - 1``):
callers size the model vocab ONE larger than the tokenizer's
(``cli.train --objective=mlm`` does this), so no tokenizer change and no
collision with real subwords.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from transformer_tpu.config import PAD_ID


def mask_tokens(
    tokens: jax.Array,
    rng: jax.Array,
    vocab_size: int,
    mask_rate: float = 0.15,
    excluded_ids: tuple[int, ...] = (),
) -> tuple[jax.Array, jax.Array]:
    """(B, S) token ids -> (masked_input, labels) for one MLM step.

    ``mask_rate`` of the non-PAD positions are selected; of those, 80% are
    replaced by [MASK] (= ``vocab_size - 1``), 10% by a uniform random real
    token, 10% kept unchanged (the canonical 80/10/10). ``labels`` carries
    the ORIGINAL token at selected positions and PAD everywhere else, so
    ``masked_cross_entropy`` scores exactly the selected positions (its
    weight mask is ``labels != PAD_ID``).

    ``excluded_ids`` (typically the tokenizer's BOS/EOS — BERT/RoBERTa
    exclude specials from both roles) are never SELECTED as prediction
    targets and never INJECTED by the 10% random-replacement draw: a
    mid-sequence EOS from the replacement would teach the encoder a
    corrupted segmentation signal, not a cloze task.
    """
    mask_id = vocab_size - 1
    # Static (trace-time) exclusion set: only ids the random draw could
    # produce matter for the draw remap; selection excludes all of them.
    excl = tuple(sorted({int(i) for i in excluded_ids if 1 <= i < mask_id}))
    n_allowed = (mask_id - 1) - len(excl)
    if n_allowed < 1:
        raise ValueError(
            f"excluded_ids {excluded_ids} leave no real tokens to draw "
            f"random replacements from (vocab_size={vocab_size})"
        )
    r_sel, r_kind, r_rand = jax.random.split(rng, 3)
    real = tokens != PAD_ID
    for e in excluded_ids:
        real = jnp.logical_and(real, tokens != e)
    sel = jnp.logical_and(
        jax.random.uniform(r_sel, tokens.shape) < mask_rate, real
    )
    kind = jax.random.uniform(r_kind, tokens.shape)
    # Random replacements draw uniformly from the ALLOWED real ids — never
    # PAD (id 0 is structurally padding), never [MASK] itself, never an
    # excluded special. Draw a rank in the allowed set, then shift past the
    # excluded ids in ascending order (exact order-statistics remap, no
    # rejection loop — jit-friendly and still uniform).
    rand_tok = jax.random.randint(r_rand, tokens.shape, 1, n_allowed + 1)
    for e in excl:
        rand_tok = jnp.where(rand_tok >= e, rand_tok + 1, rand_tok)
    masked = jnp.where(
        jnp.logical_and(sel, kind < 0.8),
        jnp.full_like(tokens, mask_id),
        jnp.where(jnp.logical_and(sel, kind < 0.9), rand_tok, tokens),
    )
    labels = jnp.where(sel, tokens, jnp.full_like(tokens, PAD_ID))
    return masked, labels
