"""Model-quality evaluation: corpus BLEU over a parallel text file pair.

The missing piece the reference never had (it reports token accuracy only,
``train.py:140-141``) and the north-star metric of BASELINE.json ("eval BLEU
on src/tgt"): greedy-decode every source sentence and score the detokenized
hypotheses against the references with ``utils.bleu.corpus_bleu``.

Used by the training CLI (end-of-run BLEU), ``cli.evaluate`` (score a saved
export/checkpoint), and ``benchmarks/bleu_run.py`` (the convergence run that
publishes the number in BASELINE.md).
"""

from __future__ import annotations

import os
from typing import Callable

from transformer_tpu.config import ModelConfig
from transformer_tpu.train.decode import translate
from transformer_tpu.utils.bleu import corpus_bleu


def bleu_on_pairs(
    params,
    model_cfg: ModelConfig,
    src_tok,
    tgt_tok,
    src_lines: list[str],
    ref_lines: list[str],
    *,
    batch_size: int = 64,
    max_len: int = 64,
    src_len: int | None = None,
    beam_size: int = 1,
    log_fn: Callable[[str], None] | None = None,
) -> tuple[float, list[str]]:
    """(BLEU in [0,100], hypotheses). Decodes in fixed-size batches so the
    bucketed ``translate`` path compiles once per (batch, width) bucket."""
    if len(src_lines) != len(ref_lines):
        raise ValueError(
            f"src/ref line counts differ: {len(src_lines)} != {len(ref_lines)}"
        )
    hyps: list[str] = []
    for start in range(0, len(src_lines), batch_size):
        chunk = src_lines[start : start + batch_size]
        hyps.extend(
            translate(
                params, model_cfg, src_tok, tgt_tok, chunk,
                max_len=max_len, src_len=src_len, beam_size=beam_size,
                # Corpus eval must not crash on over-long sentences: clip to
                # the positional table (EOS-terminated), as standard eval does.
                truncate=True,
            )
        )
        if log_fn is not None and start // batch_size % 4 == 0:
            log_fn(f"bleu eval: {start + len(chunk)}/{len(src_lines)} decoded")
    return corpus_bleu(ref_lines, hyps), hyps


def read_lines(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        return [line.rstrip("\n") for line in f]


def perplexity_on_lines(
    params,
    model_cfg: ModelConfig,
    tok,
    lines: list[str],
    *,
    batch_size: int = 64,
    log_fn: Callable[[str], None] | None = None,
) -> tuple[float, int]:
    """Token-level perplexity of a ``decoder_only`` LM over text lines —
    the LM-family counterpart of BLEU for seq2seq (the reference has
    neither; it reports token accuracy only, ``train.py:140-141``).

    Each line becomes a BOS-led, EOS-terminated window (the LM training
    convention, ``data.pipeline.make_lm_dataset``), clipped to
    ``max_position``; rows pad to power-of-two width buckets so scoring
    compiles once per (batch, width). Returns (perplexity, token_count):
    exp of the corpus mean CE over non-pad target positions.
    """
    import jax
    import jax.numpy as jnp

    from transformer_tpu.models import transformer_apply
    from transformer_tpu.train.decode import _bucket, _pad_batch
    from transformer_tpu.train.loss import masked_cross_entropy

    if not model_cfg.decoder_only:
        raise ValueError("perplexity_on_lines is for decoder_only models")
    if not lines:
        # exp(0/1) would "score" an empty file as a perfect 1.0.
        raise ValueError("perplexity_on_lines got no input lines")

    @jax.jit
    def sums(params, ids):
        tar_inp, tar_out = ids[:, :-1], ids[:, 1:]
        logits, _ = transformer_apply(params, None, tar_inp, model_cfg)
        _, m = masked_cross_entropy(logits, tar_out)
        return m["loss_sum"], m["weight"]

    cap = model_cfg.max_position
    encoded = [[tok.bos_id, *tok.encode(l), tok.eos_id][: cap + 1] for l in lines]
    total_ls = total_w = 0.0
    for start in range(0, len(encoded), batch_size):
        chunk = encoded[start : start + batch_size]
        width = _bucket(max(len(e) for e in chunk), cap + 1, floor=8)
        ids, _ = _pad_batch(chunk, width)
        ls, w = sums(params, jnp.asarray(ids))
        total_ls += float(ls)
        total_w += float(w)
        if log_fn is not None and start // batch_size % 4 == 0:
            log_fn(f"perplexity eval: {start + len(chunk)}/{len(encoded)} scored")
    import math

    ppl = math.exp(total_ls / max(total_w, 1.0))
    return ppl, int(total_w)


def dump_attention_maps(
    params,
    model_cfg: ModelConfig,
    src_tok,
    tgt_tok,
    src_sentences: list[str],
    tgt_sentences: list[str],
    out_path: str,
) -> int:
    """Save per-layer attention maps for (source, target) sentence pairs.

    The reference returns every layer's attention weights from the forward
    pass as its interpretability surface (``Transformer.py:30-32``,
    ``Decoder.py:75-76``); here the same maps become a servable artifact: a
    teacher-forced forward per pair with ``return_weights=True``, written as
    one ``.npz`` with entries ``s{i}/<map-name>`` (encoder_layer{L},
    decoder_layer{L}_block{1,2}) plus the token ids, trimmed to the pair's
    true lengths. For ``decoder_only`` models only target-side self-attention
    exists; ``src_ids`` is omitted since the source never enters the forward.
    Flash/ring attention impls materialize no weight maps — only the ids are
    written then. Returns the number of pairs written."""
    import jax.numpy as jnp
    import numpy as np

    from transformer_tpu.models import transformer_apply

    if len(src_sentences) != len(tgt_sentences):
        raise ValueError(
            f"source/target sentence counts differ: {len(src_sentences)} != "
            f"{len(tgt_sentences)}"
        )
    arrays: dict[str, np.ndarray] = {}
    cap = model_cfg.max_position
    for i, (src, tgt) in enumerate(zip(src_sentences, tgt_sentences)):
        # Clip to the positional table: a max_len-long translation plus
        # BOS/EOS can exceed max_position (maps stay interpretable, the
        # tail is simply not plotted).
        src_ids = [src_tok.bos_id, *src_tok.encode(src), src_tok.eos_id][:cap]
        tgt_ids = [tgt_tok.bos_id, *tgt_tok.encode(tgt), tgt_tok.eos_id][:cap]
        s = jnp.asarray([src_ids], jnp.int32)
        t = jnp.asarray([tgt_ids], jnp.int32)
        _, attn = transformer_apply(
            params, None if model_cfg.decoder_only else s, t, model_cfg,
            deterministic=True, return_weights=True,
        )
        if not model_cfg.decoder_only:
            arrays[f"s{i}/src_ids"] = np.asarray(src_ids, np.int32)
        arrays[f"s{i}/tgt_ids"] = np.asarray(tgt_ids, np.int32)
        for name, w in attn.items():
            if hasattr(w, "ndim") and w.ndim == 4:  # (1, H, S_q, S_k) maps
                arrays[f"s{i}/{name}"] = np.asarray(w[0], np.float32)
    np.savez(out_path, **arrays)
    return len(src_sentences)


def bleu_on_test_files(
    params,
    model_cfg: ModelConfig,
    src_tok,
    tgt_tok,
    dataset_path: str,
    *,
    batch_size: int = 64,
    max_len: int = 64,
    limit: int = 0,
    log_fn: Callable[[str], None] | None = None,
) -> tuple[float, int] | None:
    """Score the ``{src,tgt}-test*.txt`` split under ``dataset_path`` —
    the shared end-of-run BLEU epilogue of both training CLIs. Returns
    (bleu, n_pairs), or None when no test split exists."""
    import glob

    src_tests = sorted(glob.glob(os.path.join(dataset_path, "src-test*.txt")))
    tgt_tests = sorted(glob.glob(os.path.join(dataset_path, "tgt-test*.txt")))
    if not src_tests or not tgt_tests:
        if log_fn is not None:
            log_fn(f"no test split under {dataset_path}; skipping BLEU")
        return None
    src_lines = [l for p in src_tests for l in read_lines(p)]
    ref_lines = [l for p in tgt_tests for l in read_lines(p)]
    if limit:
        src_lines = src_lines[:limit]
        ref_lines = ref_lines[:limit]
    bleu, _ = bleu_on_pairs(
        params, model_cfg, src_tok, tgt_tok, src_lines, ref_lines,
        batch_size=batch_size, max_len=max_len, log_fn=log_fn,
    )
    if log_fn is not None:
        log_fn(f"test BLEU {bleu:.2f} on {len(src_lines)} pairs")
    return bleu, len(src_lines)
