"""Model-quality evaluation: corpus BLEU over a parallel text file pair.

The missing piece the reference never had (it reports token accuracy only,
``train.py:140-141``) and the north-star metric of BASELINE.json ("eval BLEU
on src/tgt"): greedy-decode every source sentence and score the detokenized
hypotheses against the references with ``utils.bleu.corpus_bleu``.

Used by the training CLI (end-of-run BLEU), ``cli.evaluate`` (score a saved
export/checkpoint), and ``benchmarks/bleu_run.py`` (the convergence run that
publishes the number in BASELINE.md).
"""

from __future__ import annotations

from typing import Callable

from transformer_tpu.config import ModelConfig
from transformer_tpu.train.decode import translate
from transformer_tpu.utils.bleu import corpus_bleu


def bleu_on_pairs(
    params,
    model_cfg: ModelConfig,
    src_tok,
    tgt_tok,
    src_lines: list[str],
    ref_lines: list[str],
    *,
    batch_size: int = 64,
    max_len: int = 64,
    src_len: int | None = None,
    beam_size: int = 1,
    log_fn: Callable[[str], None] | None = None,
) -> tuple[float, list[str]]:
    """(BLEU in [0,100], hypotheses). Decodes in fixed-size batches so the
    bucketed ``translate`` path compiles once per (batch, width) bucket."""
    if len(src_lines) != len(ref_lines):
        raise ValueError(
            f"src/ref line counts differ: {len(src_lines)} != {len(ref_lines)}"
        )
    hyps: list[str] = []
    for start in range(0, len(src_lines), batch_size):
        chunk = src_lines[start : start + batch_size]
        hyps.extend(
            translate(
                params, model_cfg, src_tok, tgt_tok, chunk,
                max_len=max_len, src_len=src_len, beam_size=beam_size,
                # Corpus eval must not crash on over-long sentences: clip to
                # the positional table (EOS-terminated), as standard eval does.
                truncate=True,
            )
        )
        if log_fn is not None and start // batch_size % 4 == 0:
            log_fn(f"bleu eval: {start + len(chunk)}/{len(src_lines)} decoded")
    return corpus_bleu(ref_lines, hyps), hyps


def read_lines(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        return [line.rstrip("\n") for line in f]
