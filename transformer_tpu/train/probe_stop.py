"""Keep-best / early-stop accounting on a periodic quality probe (BLEU).

The trainer's built-in plateau stop (``Trainer.fit`` + ``early_stop_patience``)
watches *eval loss*; convergence runs that report a decode metric need the
decision wired to the metric itself: the bundled-corpus ladder showed
small+smoothing BLEU peaking at ~epoch 60 then *dropping* (2.34 -> 2.08 by
epoch 70) while eval loss still looked flat — a 40-epoch budget can buy
memorization. This module is the probe-side counterpart: track per-probe
BLEU, remember which probe was best (so the caller can export those params),
and stop after ``patience`` consecutive non-improving probes.

All state is persisted as one small JSON next to the run's checkpoints, so
the decision survives the resumable-run pattern (``benchmarks/bleu_run.py``
re-invoked per relay window with ``--epoch_budget``): a stop decided in one
invocation is still a stop in the next, and a best probe recorded three
windows ago is still the best.

The reference has no analogue — it trains a fixed epoch count and keeps only
rotated last-N checkpoints (``train.py:159``, ``max_to_keep=5``), so its
final model is whatever the last epoch produced.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class ProbeKeepBest:
    """Persisted best-probe tracker with a consecutive-miss stopping rule.

    ``update(epoch, value)`` returns one of:

    - ``"new_best"``  — this probe beat every previous one by > ``min_delta``;
      the caller should snapshot the current params as the run's best.
    - ``"stop"``      — ``patience`` consecutive probes have failed to set a
      new best; the caller should stop training and keep the best snapshot.
    - ``"continue"``  — neither.

    ``patience <= 0`` disables stopping (every miss returns ``"continue"``),
    but best-tracking still runs so keep-best export works on fixed-budget
    runs too.
    """

    path: str
    patience: int = 2
    min_delta: float = 0.0
    probes: list[dict] = field(default_factory=list)
    best_epoch: int | None = None
    best_value: float | None = None
    stopped_epoch: int | None = None

    def __post_init__(self) -> None:
        if os.path.exists(self.path):
            with open(self.path) as f:
                saved = json.load(f)
            self.probes = list(saved.get("probes", []))
            self.best_epoch = saved.get("best_epoch")
            self.best_value = saved.get("best_value")
            self.stopped_epoch = saved.get("stopped_epoch")

    # ------------------------------------------------------------------ core
    @property
    def misses_since_best(self) -> int:
        """Consecutive probes since (and not counting) the best one."""
        n = 0
        for p in reversed(self.probes):
            if self.best_epoch is not None and p["epoch"] == self.best_epoch:
                break
            n += 1
        return n

    def would_be_best(self, value: float) -> bool:
        """Would ``update(_, value)`` return ``"new_best"``? Exposed so a
        caller can snapshot params BEFORE committing the record (crash
        between the two then re-runs the probe instead of leaving the
        record pointing at a snapshot that was never written)."""
        return (
            self.best_value is None
            or float(value) > self.best_value + self.min_delta
        )

    def update(self, epoch: int, value: float) -> str:
        """Record one probe and return the decision (see class docstring).

        ``epoch`` is 1-based (the number of completed epochs at probe time).
        Re-recording an epoch already in the history (a resumed invocation
        re-probing its restore point) replaces the old record instead of
        double-counting a miss.
        """
        value = float(value)
        is_best = self.would_be_best(value)
        self.probes = [p for p in self.probes if p["epoch"] != epoch]
        self.probes.append({"epoch": epoch, "bleu": value})
        self.probes.sort(key=lambda p: p["epoch"])
        decision = "continue"
        if is_best:
            self.best_value = value
            self.best_epoch = epoch
            decision = "new_best"
        elif self.patience > 0 and self.misses_since_best >= self.patience:
            self.stopped_epoch = epoch
            decision = "stop"
        self._save()
        return decision

    # ----------------------------------------------------------- persistence
    def _save(self) -> None:
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "probes": self.probes,
                    "best_epoch": self.best_epoch,
                    "best_value": self.best_value,
                    "stopped_epoch": self.stopped_epoch,
                },
                f,
            )
        os.replace(tmp, self.path)  # atomic: a crash mid-write keeps the old
