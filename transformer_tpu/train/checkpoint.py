"""Checkpoint save/restore with rotation.

Counterpart of the reference's ``tf.train.Checkpoint`` +
``CheckpointManager(max_to_keep)`` + ``restore(...).expect_partial()``
(``train.py:77-80,159-164``), as a self-contained array-tree format:

    <dir>/ckpt_<step>/
        arrays.npz      flattened {path: array} of the state pytree
        meta.json       step, tree structure digest, configs (optional)

Multi-host: only process 0 writes (TPU pods are multi-process; the reference
is single-host and has no notion of this). Writes are atomic
(tmp dir + rename) so a preempted save never leaves a corrupt "latest".
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any

import jax
import numpy as np


_SEP = "/"


def _flatten(tree: Any) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _SEP.join(_path_elem(p) for p in path)
        flat[key] = np.asarray(jax.device_get(leaf))
    return flat


def _path_elem(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


class CheckpointManager:
    """Rotated checkpoints of an arbitrary pytree keyed by its ``step``."""

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 5,
        is_primary: bool | None = None,
    ) -> None:
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.is_primary = (
            is_primary if is_primary is not None else jax.process_index() == 0
        )
        if self.is_primary:
            os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------------ save
    def save(self, state: Any, step: int | None = None) -> str | None:
        step = int(state.step) if step is None else int(step)
        if not self.is_primary:
            return None
        final = os.path.join(self.directory, f"ckpt_{step:08d}")
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        flat = _flatten(state)
        np.savez(os.path.join(tmp, "arrays.npz"), **flat)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"step": step, "keys": sorted(flat)}, f)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
        self._rotate()
        return final

    def _rotate(self) -> None:
        steps = self.all_steps()
        for s in steps[: -self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, f"ckpt_{s:08d}"))

    def all_steps(self) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"ckpt_(\d{8})", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    @property
    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # --------------------------------------------------------------- restore
    def restore(self, target: Any, step: int) -> Any:
        """Restore into the structure of ``target`` (arrays replaced by saved
        values; shapes/dtypes validated). Returns a new pytree."""
        path = os.path.join(self.directory, f"ckpt_{step:08d}", "arrays.npz")
        with np.load(path) as data:
            flat = {k: data[k] for k in data.files}
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(target)
        new_leaves = []
        for p, leaf in leaves_with_path:
            key = _SEP.join(_path_elem(e) for e in p)
            if key not in flat:
                raise KeyError(f"checkpoint missing array {key!r}")
            saved = flat[key]
            leaf_arr = np.asarray(leaf)
            if saved.shape != leaf_arr.shape:
                raise ValueError(
                    f"{key}: checkpoint shape {saved.shape} != target {leaf_arr.shape}"
                )
            new_leaves.append(saved.astype(leaf_arr.dtype))
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def restore_latest(self, target: Any) -> Any | None:
        step = self.latest_step
        if step is None:
            return None
        return self.restore(target, step)


def export_params(params: Any, model_cfg, path: str) -> None:
    """Model export for serving — the counterpart of the reference's final
    ``tf.saved_model.save`` (``train.py:246``, README "Model Exporting"):
    arrays.npz + config.json, loadable without the training stack."""
    os.makedirs(path, exist_ok=True)
    np.savez(os.path.join(path, "params.npz"), **_flatten(params))
    from transformer_tpu.config import config_to_json

    with open(os.path.join(path, "config.json"), "w") as f:
        f.write(config_to_json(model_cfg))


def load_exported_params(path: str, template: Any) -> Any:
    with np.load(os.path.join(path, "params.npz")) as data:
        flat = {k: data[k] for k in data.files}
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for p, leaf in leaves_with_path:
        key = _SEP.join(_path_elem(e) for e in p)
        new_leaves.append(flat[key].astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
