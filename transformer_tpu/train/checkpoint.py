"""Checkpoint save/restore with rotation.

Counterpart of the reference's ``tf.train.Checkpoint`` +
``CheckpointManager(max_to_keep)`` + ``restore(...).expect_partial()``
(``train.py:77-80,159-164``), as a self-contained array-tree format.

Two on-disk layouts, auto-selected per save:

*Replicated* (single-host / unsharded state — the reference's scale):

    <dir>/ckpt_<step>/
        arrays.npz      flattened {path: array} of the state pytree
        meta.json       step + key list
        manifest.json   per-array crc32 + shape/dtype and a digest over the
                        entry table (the checkpoint's weight_version tag);
                        written atomically (tmp + fsync + rename) and
                        byte-verified by restore_latest before any
                        structural probe runs

*Sharded* (any leaf distributed over >1 device): no full array is ever
materialized on any host — the thing that makes >HBM models checkpointable
at all (the same rationale as sharded init, ``parallel/distributed.py``).
Each process writes only the device shards it can address (one replica of
each), with the global slice bounds encoded in the entry name:

    <dir>/ckpt_<step>/
        shards_p00000.npz   {key@d0s:d0e,d1s:d1e,...: shard array} per process
        meta.json           step, format tag, global shapes/dtypes

Restore reassembles per-device arrays with
``jax.make_array_from_single_device_arrays`` against the *target's* sharding,
so the round trip is shard-file → device, never via a host-gathered copy.
A shared filesystem across hosts is assumed (the standard TPU-pod setup).

Writes are atomic (tmp dir + rename + per-process sentinel) so a preempted
save never leaves a corrupt "latest".
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import sys
import zipfile
import zlib
from typing import Any

import jax
import numpy as np

# Fault-injection slot (``ckpt.write``): ``serve.resilience.install``
# plants the plane's hook here so chaos tests can fail a commit
# deterministically without this module dragging the serve stack into
# every train import. The injected exception subclasses OSError — it takes
# the same path a dead disk would, and must leave the previous checkpoint
# intact (the atomic tmp+rename commit guarantees it).
fault_hook = None

#: Failure shapes that mean "this checkpoint directory is torn/corrupt,
#: try an older one" in ``restore_latest`` — truncated npz members
#: (zipfile/OSError/EOFError), a half-written or garbled meta.json
#: (json's ValueError), and structural mismatches from a partial write
#: (KeyError "missing array", ValueError shape checks).
_CORRUPT_CHECKPOINT_ERRORS = (
    OSError, EOFError, KeyError, ValueError, zipfile.BadZipFile,
)

_SEP = "/"

#: Per-checkpoint integrity manifest (replicated format): one entry per
#: stored array (crc32 over the raw bytes + shape + dtype) plus a digest
#: over the sorted entry table. The digest doubles as the checkpoint's
#: ``weight_version`` tag in the live-weights control plane
#: (``serve/upgrade.py``): byte-identical weights => identical digest, so
#: mixed-version-fleet byte-consistency is assertable per tag.
MANIFEST_NAME = "manifest.json"


class CheckpointIntegrityError(ValueError):
    """The checkpoint's bytes disagree with its manifest (torn write, bit
    rot, a mixed copy) — or the manifest itself is torn. Subclasses
    ``ValueError`` so ``restore_latest``'s corrupt-checkpoint fallback
    treats it exactly like the structural probe it supersedes."""


def manifest_entries(flat: "dict[str, np.ndarray]") -> dict:
    """Per-array integrity entries for a flattened checkpoint: crc32 over
    the raw array bytes (layout-normalized), shape, dtype. Pure numpy —
    the model-free router verifies checkpoints with this too."""
    out = {}
    for key in sorted(flat):
        a = np.ascontiguousarray(flat[key])
        out[key] = {
            "crc32": zlib.crc32(a.tobytes()) & 0xFFFFFFFF,
            "shape": list(a.shape),
            "dtype": str(a.dtype),
        }
    return out


def manifest_digest(entries: dict) -> str:
    """Digest over the canonicalized entry table — the checkpoint's
    ``weight_version``. Any flipped byte, reshaped leaf, or re-dtyped leaf
    changes it; a byte-identical save reproduces it."""
    blob = json.dumps(entries, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def build_manifest(flat: "dict[str, np.ndarray]", step: "int | None") -> dict:
    entries = manifest_entries(flat)
    return {
        "format": "manifest-v1",
        "step": step,
        "arrays": entries,
        "digest": manifest_digest(entries),
    }


def write_manifest(
    dirpath: str, flat: "dict[str, np.ndarray]", step: "int | None" = None
) -> dict:
    """Commit ``dirpath``'s integrity manifest atomically: tmp file,
    fsync, rename — a crash mid-write leaves either no manifest (the
    pre-manifest structural probe still applies) or a complete one, never
    a torn one that could reject a good checkpoint."""
    manifest = build_manifest(flat, step)
    final = os.path.join(dirpath, MANIFEST_NAME)
    tmp = final + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    return manifest


def load_manifest(ckpt_dir: str) -> "dict | None":
    """The checkpoint's manifest, or None when it predates manifests.
    A torn/garbled manifest raises :class:`CheckpointIntegrityError`
    (json's ValueError is re-shaped so callers see one corruption type)."""
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            manifest = json.load(f)
    except ValueError as e:
        raise CheckpointIntegrityError(
            f"manifest at {ckpt_dir} is unparseable: {e}"
        ) from e
    if not isinstance(manifest, dict) or not isinstance(
        manifest.get("arrays"), dict
    ) or "digest" not in manifest:
        raise CheckpointIntegrityError(
            f"manifest at {ckpt_dir} is missing its arrays/digest fields"
        )
    return manifest


def verify_manifest(
    ckpt_dir: str, flat: "dict[str, np.ndarray] | None" = None
) -> str:
    """Verify ``ckpt_dir``'s stored arrays against its manifest: internal
    digest consistency, key set, then per-array shape/dtype/crc32. Returns
    the verified digest (the ``weight_version``); raises
    :class:`CheckpointIntegrityError` on ANY disagreement and
    ``FileNotFoundError``/``zipfile`` errors on unreadable files. ``flat``
    skips the npz read when the caller already loaded the arrays (the
    replica verifies and loads in one pass)."""
    manifest = load_manifest(ckpt_dir)
    if manifest is None:
        raise CheckpointIntegrityError(f"no manifest at {ckpt_dir}")
    entries = manifest["arrays"]
    if manifest_digest(entries) != manifest["digest"]:
        raise CheckpointIntegrityError(
            f"manifest at {ckpt_dir} fails its own digest (torn manifest)"
        )
    if flat is None:
        with np.load(os.path.join(ckpt_dir, "arrays.npz")) as data:
            flat = {k: data[k] for k in data.files}
    if sorted(flat) != sorted(entries):
        missing = sorted(set(entries) - set(flat))
        extra = sorted(set(flat) - set(entries))
        raise CheckpointIntegrityError(
            f"checkpoint at {ckpt_dir} disagrees with its manifest key set "
            f"(missing {missing[:3]}, extra {extra[:3]})"
        )
    for key, e in entries.items():
        a = np.ascontiguousarray(flat[key])
        if list(a.shape) != e["shape"] or str(a.dtype) != e["dtype"]:
            raise CheckpointIntegrityError(
                f"{key}: stored {a.shape}/{a.dtype} but the manifest "
                f"records {tuple(e['shape'])}/{e['dtype']}"
            )
        if (zlib.crc32(a.tobytes()) & 0xFFFFFFFF) != e["crc32"]:
            raise CheckpointIntegrityError(
                f"{key}: stored bytes fail the manifest crc32 — the "
                "checkpoint is torn or bit-rotted"
            )
    return manifest["digest"]


def checkpoint_version(ckpt_dir: str) -> "str | None":
    """The checkpoint's ``weight_version`` tag (manifest digest) WITHOUT
    byte verification — the cheap read for tagging/telemetry. None when
    the checkpoint predates manifests."""
    manifest = load_manifest(ckpt_dir)
    return None if manifest is None else manifest["digest"]


def _flatten(tree: Any) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _SEP.join(_path_elem(p) for p in path)
        flat[key] = np.asarray(jax.device_get(leaf))
    return flat


def _is_distributed(leaf: Any) -> bool:
    """True for a jax.Array laid out across more than one device."""
    return isinstance(leaf, jax.Array) and len(leaf.sharding.device_set) > 1


def _bounds(index: tuple, shape: tuple[int, ...]) -> tuple[tuple[int, int], ...]:
    """Resolve a shard's tuple-of-slices index to explicit (start, stop)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _entry_name(key: str, bounds: tuple[tuple[int, int], ...]) -> str:
    return key + "@" + ",".join(f"{a}:{b}" for a, b in bounds)


def _parse_entry(entry: str) -> tuple[str, tuple[tuple[int, int], ...]]:
    key, sep, spec = entry.rpartition("@")
    if not sep:
        return entry, ()
    if not spec:  # scalar leaf: "key@" with an empty bounds spec
        return key, ()
    bounds = tuple(
        (int(a), int(b))
        for a, b in (part.split(":") for part in spec.split(","))
    )
    return key, bounds


def _path_elem(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


class CheckpointManager:
    """Rotated checkpoints of an arbitrary pytree keyed by its ``step``."""

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 5,
        is_primary: bool | None = None,
    ) -> None:
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.is_primary = (
            is_primary if is_primary is not None else jax.process_index() == 0
        )
        if self.is_primary:
            os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------------ save
    def save(self, state: Any, step: int | None = None) -> str | None:
        step = int(state.step) if step is None else int(step)
        leaves = jax.tree_util.tree_leaves(state)
        if any(_is_distributed(l) for l in leaves):
            return self._save_sharded(state, step)
        if not self.is_primary:
            return None
        self._write_replicated(_flatten(state), step)
        return os.path.join(self.directory, f"ckpt_{step:08d}")

    def _write_replicated(self, flat: dict[str, np.ndarray], step: int) -> None:
        """Commit one replicated-format checkpoint from host arrays: tmp dir,
        arrays.npz + meta.json, atomic rename, rotation. The single writer
        both the sync path (inline) and ``AsyncCheckpointManager`` (worker
        thread) go through, so the on-disk layout cannot diverge."""
        tmp = self._fresh_tmp(step)
        np.savez(os.path.join(tmp, "arrays.npz"), **flat)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"step": step, "keys": sorted(flat)}, f)
        # Integrity manifest (atomic in its own right, and committed by the
        # directory rename below): per-array crc32 + the digest that names
        # this checkpoint's weight_version for the serving control plane.
        write_manifest(tmp, flat, step)
        self._commit(tmp, step)

    # Shared filesystem pieces — one definition each, so the sync and async
    # writers cannot drift in layout.
    def _fresh_tmp(self, step: int) -> str:
        tmp = os.path.join(self.directory, f"ckpt_{step:08d}.tmp")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        return tmp

    def _commit(self, tmp: str, step: int) -> None:
        if fault_hook is not None:
            # Pre-rename: an injected commit failure leaves the tmp dir
            # behind and the previous checkpoint untouched — exactly the
            # crash shape the atomic layout exists for.
            fault_hook("ckpt.write")
        final = os.path.join(self.directory, f"ckpt_{step:08d}")
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
        self._rotate()

    @staticmethod
    def _shard_file(tmp: str, proc: int) -> str:
        return os.path.join(tmp, f"shards_p{proc:05d}.npz")

    def _write_sharded_meta(
        self, tmp: str, meta_arrays: dict[str, dict], step: int, nproc: int
    ) -> None:
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(
                {
                    "step": step,
                    "format": "sharded-v1",
                    "n_processes": nproc,
                    "arrays": meta_arrays,
                },
                f,
            )

    def _save_sharded(self, state: Any, step: int) -> str:
        """Every process writes its addressable shards; no full-array gather.

        Cross-process protocol: device-backed barriers
        (``multihost_utils.sync_global_devices``), not filesystem handshakes —
        stale marker files from a crashed previous save of the *same* step
        cannot fake a phase transition. Phase 1: primary clears any stale
        ``.tmp`` dir; barrier; phase 2: everyone writes its shard file;
        barrier; phase 3: primary renames tmp → final. A dead peer fails the
        barrier (backend timeout) loudly instead of committing a checkpoint
        with missing shards. (Single-process: barriers are skipped.)
        """
        proc = jax.process_index()
        nproc = jax.process_count()
        final = os.path.join(self.directory, f"ckpt_{step:08d}")
        tmp = final + ".tmp"

        def barrier(tag: str) -> None:
            if nproc > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(f"ckpt_{step}_{tag}")

        if self.is_primary:
            self._fresh_tmp(step)
        barrier("tmp_ready")

        entries, meta_arrays = self._collect_shard_entries(state)
        np.savez(self._shard_file(tmp, proc), **entries)
        if self.is_primary:
            self._write_sharded_meta(tmp, meta_arrays, step, nproc)
        barrier("shards_written")
        if self.is_primary:
            self._commit(tmp, step)
        # No process may report the save durable before the rename commits —
        # otherwise a peer could see "saved step N" for a checkpoint that a
        # primary crash leaves uncommitted.
        barrier("committed")
        return final

    def _collect_shard_entries(
        self, state: Any
    ) -> tuple[dict[str, np.ndarray], dict[str, dict]]:
        """Device -> host snapshot of this process's addressable shards (one
        replica of each distinct slice) plus, on the primary, the per-array
        meta. The device-read half of a sharded save, shared by the sync path
        and ``AsyncCheckpointManager``."""
        # Kick off all device->host copies first so the blocking np.asarray
        # pass below overlaps DMA across shards instead of serializing them.
        for leaf in jax.tree_util.tree_leaves(state):
            if _is_distributed(leaf):
                for shard in leaf.addressable_shards:
                    if shard.replica_id == 0:
                        shard.data.copy_to_host_async()
            elif isinstance(leaf, jax.Array) and self.is_primary:
                leaf.copy_to_host_async()
        entries: dict[str, np.ndarray] = {}
        meta_arrays: dict[str, dict] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
            key = _SEP.join(_path_elem(p) for p in path)
            if _is_distributed(leaf):
                shape = tuple(leaf.shape)
                for shard in leaf.addressable_shards:
                    if shard.replica_id != 0:
                        continue  # one copy of each distinct slice suffices
                    b = _bounds(shard.index, shape)
                    entries[_entry_name(key, b)] = np.asarray(shard.data)
            else:
                # Replicated / host-local leaf: one copy, written by primary.
                shape = tuple(np.shape(leaf))
                if self.is_primary:
                    arr = np.asarray(jax.device_get(leaf))
                    b = tuple((0, d) for d in shape)
                    entries[_entry_name(key, b)] = arr
            if self.is_primary:
                meta_arrays[key] = {
                    "shape": list(shape),
                    "dtype": str(
                        leaf.dtype if hasattr(leaf, "dtype") else np.asarray(leaf).dtype
                    ),
                }
        return entries, meta_arrays

    def _rotate(self) -> None:
        steps = self.all_steps()
        for s in steps[: -self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, f"ckpt_{s:08d}"))

    def all_steps(self) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"ckpt_(\d{8})", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    @property
    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # --------------------------------------------------------------- restore
    def restore(self, target: Any, step: int) -> Any:
        """Restore into the structure of ``target`` (arrays replaced by saved
        values; shapes/dtypes validated). Returns a new pytree.

        If the checkpoint is in the sharded format, ``target``'s leaves must
        carry the shardings to restore into (e.g. the sharded-init state);
        each device shard is loaded directly from the shard files.
        """
        ckpt_dir = os.path.join(self.directory, f"ckpt_{step:08d}")
        meta_path = os.path.join(ckpt_dir, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("format") == "sharded-v1":
                return self._restore_sharded(target, ckpt_dir, meta)
        path = os.path.join(ckpt_dir, "arrays.npz")
        with np.load(path) as data:
            flat = {k: data[k] for k in data.files}
        return self._restore_replicated(target, flat)

    @staticmethod
    def _restore_replicated(target: Any, flat: dict) -> Any:
        """Rebuild ``target``'s tree from already-loaded flat arrays — the
        replicated-format half of :meth:`restore`, shared with
        ``restore_latest``'s verify-then-restore path so a manifest check
        never re-reads the npz it just checksummed."""
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(target)
        new_leaves = []
        for p, leaf in leaves_with_path:
            key = _SEP.join(_path_elem(e) for e in p)
            if key not in flat:
                raise KeyError(f"checkpoint missing array {key!r}")
            saved = flat[key]
            leaf_arr = np.asarray(leaf)
            if saved.shape != leaf_arr.shape:
                raise ValueError(
                    f"{key}: checkpoint shape {saved.shape} != target {leaf_arr.shape}"
                )
            new_leaves.append(saved.astype(leaf_arr.dtype))
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def _restore_sharded(self, target: Any, ckpt_dir: str, meta: dict) -> Any:
        """Shard-file → device restore; never materializes a full array."""
        shard_files = sorted(
            os.path.join(ckpt_dir, n)
            for n in os.listdir(ckpt_dir)
            if n.startswith("shards_p") and n.endswith(".npz")
        )
        # Lazily-opened npz handles + a location index built from entry names
        # (cheap: names only, no array data is read until requested).
        handles = [np.load(f) for f in shard_files]
        where: dict[tuple[str, tuple], int] = {}
        for i, h in enumerate(handles):
            for entry in h.files:
                where[_parse_entry(entry)] = i

        def read(key: str, bounds: tuple[tuple[int, int], ...]) -> np.ndarray:
            i = where.get((key, bounds))
            if i is not None:
                return handles[i][_entry_name(key, bounds)]
            # Bounds not stored verbatim (restore topology differs from save
            # topology): stitch the requested window from overlapping stored
            # chunks. Worst case this reads a leaf-sized window — still never
            # the whole tree at once.
            shape = tuple(b - a for a, b in bounds)
            out = np.empty(shape, dtype=meta["arrays"][key]["dtype"])
            filled = np.zeros(shape, dtype=bool)
            for (k, b2), i2 in where.items():
                if k != key:
                    continue
                inter = tuple(
                    (max(a1, a2), min(e1, e2))
                    for (a1, e1), (a2, e2) in zip(bounds, b2)
                )
                if any(a >= e for a, e in inter):
                    continue
                chunk = handles[i2][_entry_name(key, b2)]
                src = tuple(
                    slice(a - a2, e - a2)
                    for (a, e), (a2, _) in zip(inter, b2)
                )
                dst = tuple(
                    slice(a - a1, e - a1)
                    for (a, e), (a1, _) in zip(inter, bounds)
                )
                out[dst] = chunk[src]
                filled[dst] = True
            if not filled.all():
                raise KeyError(
                    f"checkpoint shard files do not cover {key!r} {bounds}"
                )
            return out

        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(target)
        new_leaves = []
        try:
            for p, leaf in leaves_with_path:
                key = _SEP.join(_path_elem(e) for e in p)
                if key not in meta["arrays"]:
                    raise KeyError(f"checkpoint missing array {key!r}")
                saved_shape = tuple(meta["arrays"][key]["shape"])
                if isinstance(leaf, jax.Array) and saved_shape != tuple(leaf.shape):
                    raise ValueError(
                        f"{key}: checkpoint shape {saved_shape} != target "
                        f"{tuple(leaf.shape)}"
                    )
                if _is_distributed(leaf):
                    sharding = leaf.sharding
                    dtype = leaf.dtype
                    singles = [
                        jax.device_put(
                            read(key, _bounds(sharding.addressable_devices_indices_map(saved_shape)[d], saved_shape)).astype(dtype),
                            d,
                        )
                        for d in sorted(
                            sharding.addressable_devices, key=lambda d: d.id
                        )
                    ]
                    new_leaves.append(
                        jax.make_array_from_single_device_arrays(
                            saved_shape, sharding, singles
                        )
                    )
                else:
                    full = tuple((0, d) for d in saved_shape)
                    arr = read(key, full)
                    leaf_arr = np.asarray(leaf)
                    new_leaves.append(arr.astype(leaf_arr.dtype))
        finally:
            for h in handles:
                h.close()
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def restore_latest(self, target: Any, on_fallback=None) -> Any | None:
        """Restore the newest INTACT checkpoint: a torn/corrupt latest (a
        crash mid-write on a filesystem without atomic rename, bit rot, a
        truncated copy) falls back to the next-newest step with a warning
        instead of killing the restart — the atomic commit makes older
        steps trustworthy, so a resumable run should resume. Explicit
        ``restore(target, step)`` still fails loudly: asking for a
        specific step and silently getting another would be worse.

        ``on_fallback(step, exc)`` (optional) is called per skipped
        checkpoint on top of the stderr warning — the Trainer wires it to a
        ``ckpt.fallback`` telemetry event.

        If EVERY checkpoint fails, the last failure re-raises instead of
        returning None: all-steps-unreadable is the signature of a
        target/config mismatch (changed model shape, renamed params), not
        of bit rot, and silently restarting from step 0 — then rotating
        the good checkpoints away — would be far worse than dying loudly.
        An empty directory still returns None (nothing to restore is the
        normal first-run case)."""
        steps = self.all_steps()
        last_exc: Exception | None = None
        for step in reversed(steps):
            try:
                ckpt_dir = os.path.join(self.directory, f"ckpt_{step:08d}")
                if os.path.exists(os.path.join(ckpt_dir, MANIFEST_NAME)):
                    # Manifest-bearing checkpoints (replicated format)
                    # verify BYTES before the structural probe gets a say:
                    # a flipped bit that still unpickles into the right
                    # shapes would pass the probe and silently restore
                    # garbage — the crc32 table catches it and falls back
                    # like any torn npz. The arrays are loaded ONCE and
                    # restored from the same verified dict.
                    with np.load(
                        os.path.join(ckpt_dir, "arrays.npz")
                    ) as data:
                        flat = {k: data[k] for k in data.files}
                    verify_manifest(ckpt_dir, flat)
                    return self._restore_replicated(target, flat)
                return self.restore(target, step)
            except _CORRUPT_CHECKPOINT_ERRORS as e:
                last_exc = e
                print(
                    f"checkpoint: ckpt_{step:08d} in {self.directory} is "
                    f"unreadable ({type(e).__name__}: {e}); falling back to "
                    "the previous checkpoint",
                    file=sys.stderr,
                )
                if on_fallback is not None:
                    on_fallback(step, e)
        if last_exc is not None:
            raise last_exc
        return None

    def wait(self) -> None:
        """No pending writes in the synchronous manager — see
        ``AsyncCheckpointManager.wait``."""


class AsyncCheckpointManager(CheckpointManager):
    """Checkpointing with the disk write off the training thread.

    ``save`` snapshots device arrays to host RAM *synchronously* — this part
    cannot be deferred: the trainer's donated-state step invalidates the old
    buffers on the next call — then hands the host copy to a single worker
    thread for the npz write, atomic rename, and rotation. The train loop
    resumes after the snapshot (device-to-host DMA) instead of stalling on
    disk I/O, which dominates for multi-GB states.

    Sharded states on a SINGLE process (one host, several chips — the
    common fsdp-on-one-board case) also write async: the shard reads are
    device->host copies done synchronously here, and the npz/rename/rotate
    goes to the worker. Only MULTI-process sharded states fall back to the
    fully synchronous path: their protocol runs collective barriers
    (``_save_sharded``), and collectives from a background thread would
    race the training step's own collectives for device-order and deadlock.

    ``wait()`` drains the queue; the trainer calls it before reporting a
    preemption save durable and at the end of ``fit``. A worker failure
    surfaces on the next ``save``/``wait`` call.
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-writer"
        )
        self._pending: Any | None = None

    def save(self, state: Any, step: int | None = None) -> str | None:
        step = int(state.step) if step is None else int(step)
        leaves = jax.tree_util.tree_leaves(state)
        sharded = any(_is_distributed(l) for l in leaves)
        if sharded and jax.process_count() > 1:
            return super().save(state, step)  # sync: see class docstring
        self.wait()  # one write in flight at a time; surface prior failures
        if not self.is_primary:
            # Misconfigured single-process secondary: writing would commit a
            # checkpoint whose replicated leaves/meta were skipped (and
            # rotate away good ones). The sync multi-process path is the only
            # one where non-primary saves participate.
            return None
        final = os.path.join(self.directory, f"ckpt_{step:08d}")
        if sharded:
            entries, meta_arrays = self._collect_shard_entries(state)
            self._pending = self._executor.submit(
                self._write_sharded_single, entries, meta_arrays, step
            )
            return final
        # Overlap the device->host copies across leaves, then materialize.
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                leaf.copy_to_host_async()
        flat = _flatten(state)
        self._pending = self._executor.submit(self._write_replicated, flat, step)
        return final

    def _write_sharded_single(
        self, entries: dict[str, np.ndarray], meta_arrays: dict[str, dict], step: int
    ) -> None:
        """Single-process sharded commit (worker thread): one shard file +
        meta, atomic rename, rotation — the filesystem half of
        ``_save_sharded`` (shared helpers) without the barriers."""
        tmp = self._fresh_tmp(step)
        np.savez(self._shard_file(tmp, 0), **entries)
        self._write_sharded_meta(tmp, meta_arrays, step, nproc=1)
        self._commit(tmp, step)

    def wait(self) -> None:
        """Block until the in-flight write (if any) has committed; re-raises
        a worker failure here rather than losing it."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def restore(self, target: Any, step: int) -> Any:
        self.wait()  # never read a checkpoint mid-write
        return super().restore(target, step)

    def restore_latest(self, target: Any, on_fallback=None) -> Any | None:
        self.wait()
        return super().restore_latest(target, on_fallback=on_fallback)


def average_checkpoints(
    mgr: CheckpointManager, template: Any, steps: list[int]
) -> Any:
    """Uniform PARAMETER average over the given checkpoint steps — the
    classic Transformer eval trick (Vaswani et al. averaged the last
    checkpoints before scoring BLEU; the reference keeps rotated
    checkpoints, ``max_to_keep``, but never averages them). Restores each
    step into ``template``'s structure (a TrainState) and returns only the
    averaged ``params`` subtree: fp64 accumulation, cast back to each
    leaf's dtype. Optimizer state is restored transiently (the checkpoint
    format stores the whole state) but never accumulated — averaged Adam
    moments would be meaningless and would double the accumulator."""
    if not steps:
        raise ValueError("average_checkpoints needs at least one step")
    acc = None
    for step in steps:
        params = mgr.restore(template, step).params
        if acc is None:
            acc = jax.tree.map(lambda x: np.asarray(x, np.float64), params)
        else:
            acc = jax.tree.map(
                lambda a, x: a + np.asarray(x, np.float64), acc, params
            )
    n = float(len(steps))
    return jax.tree.map(
        lambda a, t: (a / n).astype(np.asarray(t).dtype),
        acc,
        jax.tree.map(np.asarray, template.params),
    )


_Q8_SUFFIX = "::q8"
_Q8_SCALE_SUFFIX = "::q8scale"
# Leaves below this element count stay fp32 — biases/layernorms are tiny and
# numerically load-bearing; quantizing them saves nothing.
_Q8_MIN_SIZE = 1024


def _q8_group_axes(key: str, w: np.ndarray):
    """Reduction axes for one leaf's quantization groups. Embedding tables:
    one scale PER ROW (each token vector carries its own range — robust to
    outlier rows of a 32k-row table). 3-D+ kernels keep the last TWO axes
    when the reduced axes still hold >= 16 values — per-(head, slot) scales
    for the pre-split (d_model, H, head_dim) attention projections, so one
    outlier head cannot inflate every head's scale — at negligible scale
    storage. Everything else (2-D kernels; the (H, head_dim, d_model) out
    projection, where keeping two axes would cost 50% overhead): one scale
    per slot of the last axis, i.e. per output channel."""
    if key.endswith("embedding/table"):
        return -1
    if w.ndim >= 3 and int(np.prod(w.shape[:-2])) >= 16:
        return tuple(range(w.ndim - 2))
    return tuple(range(w.ndim - 1))


def _quantize_leaf(key: str, w: np.ndarray) -> dict[str, np.ndarray] | None:
    """Symmetric int8 weight quantization for one flat leaf, or None to keep
    it fp (grouping: ``_q8_group_axes``)."""
    w = np.asarray(w)
    # dtype.kind misses bfloat16 (ml_dtypes registers it as kind 'V'), so
    # match it by name; biases are additive load-bearing terms and stay fp
    # even when 2-D and large (MoE per-expert biases are (E, dff)).
    is_float = w.dtype.kind == "f" or w.dtype.name == "bfloat16"
    if (
        w.ndim < 2
        or w.size < _Q8_MIN_SIZE
        or not is_float
        or key.endswith("/bias")
    ):
        return None
    axis = _q8_group_axes(key, w)
    amax = np.max(np.abs(w.astype(np.float32)), axis=axis, keepdims=True)
    scale = (amax / 127.0).astype(np.float32)
    scale = np.where(scale == 0.0, 1.0, scale)  # all-zero groups stay zero
    q = np.clip(np.rint(w.astype(np.float32) / scale), -127, 127).astype(
        np.int8
    )
    return {key + _Q8_SUFFIX: q, key + _Q8_SCALE_SUFFIX: scale}


def export_params(
    params: Any, model_cfg, path: str, quantize: str = ""
) -> None:
    """Model export for serving — the counterpart of the reference's final
    ``tf.saved_model.save`` (``train.py:246``, README "Model Exporting"):
    arrays.npz + config.json, loadable without the training stack.

    ``quantize="int8"`` stores every large (>=2-D) weight as symmetric int8
    plus fp32 scales (~4x smaller artifact than fp32, ~2x smaller than a
    bf16 checkpoint); ``load_exported_params`` dequantizes transparently, so
    every decode/eval path works unchanged. Compression is the deliverable —
    compute still runs in the model dtype after load."""
    if quantize not in ("", "int8"):
        raise ValueError(f"quantize must be '' or 'int8', got {quantize!r}")
    os.makedirs(path, exist_ok=True)
    flat = _flatten(params)
    if quantize == "int8":
        out: dict[str, np.ndarray] = {}
        for k, w in flat.items():
            qleaf = _quantize_leaf(k, w)
            out.update(qleaf if qleaf is not None else {k: np.asarray(w)})
        flat = out
    np.savez(os.path.join(path, "params.npz"), **flat)
    from transformer_tpu.config import config_to_json

    with open(os.path.join(path, "config.json"), "w") as f:
        f.write(config_to_json(model_cfg))


def load_exported_params(path: str, template: Any) -> Any:
    """Rebuild the param tree from an export, transparently dequantizing any
    int8-quantized leaves (see ``export_params(quantize="int8")``)."""
    with np.load(os.path.join(path, "params.npz")) as data:
        flat = {k: data[k] for k in data.files}
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for p, leaf in leaves_with_path:
        key = _SEP.join(_path_elem(e) for e in p)
        ref = np.asarray(leaf)
        if key in flat:
            new = flat[key].astype(ref.dtype)
        elif key + _Q8_SUFFIX in flat:
            q = flat[key + _Q8_SUFFIX].astype(np.float32)
            new = (q * flat[key + _Q8_SCALE_SUFFIX]).astype(ref.dtype)
        else:
            raise KeyError(f"export at {path} has no leaf for {key!r}")
        if new.shape != ref.shape:
            # Silent wrong-shape insertion would only blow up (or quietly
            # mis-score) downstream — e.g. a scorer rebuilding the template
            # from the wrong --config for this export.
            raise ValueError(
                f"export at {path}: leaf {key!r} has shape {new.shape} but "
                f"the template expects {ref.shape} — was the template built "
                "from a different model config?"
            )
        new_leaves.append(new)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
