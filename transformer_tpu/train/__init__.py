"""Training engine (L4): LR schedule, loss, train/eval steps, checkpointing,
greedy decoding, metrics — counterpart of the reference's ``train.py`` engine."""

from transformer_tpu.train.schedule import noam_schedule
from transformer_tpu.train.loss import masked_cross_entropy
from transformer_tpu.train.state import TrainState, create_train_state, make_optimizer
from transformer_tpu.train.trainer import Trainer, make_eval_step, make_train_step
from transformer_tpu.train.checkpoint import (
    AsyncCheckpointManager,
    CheckpointManager,
    export_params,
    load_exported_params,
)
from transformer_tpu.train.decode import (
    beam_search_decode,
    generate,
    greedy_decode,
    lm_generate,
    translate,
)
from transformer_tpu.train.evaluate import bleu_on_pairs

__all__ = [
    "AsyncCheckpointManager",
    "CheckpointManager",
    "TrainState",
    "Trainer",
    "beam_search_decode",
    "bleu_on_pairs",
    "create_train_state",
    "export_params",
    "generate",
    "greedy_decode",
    "lm_generate",
    "load_exported_params",
    "make_eval_step",
    "make_optimizer",
    "make_train_step",
    "masked_cross_entropy",
    "noam_schedule",
    "translate",
]
