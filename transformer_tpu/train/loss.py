"""Loss and step metrics.

Counterpart of the reference's masked cross-entropy (``train.py:67-69,83-88``):
per-token ``SparseCategoricalCrossentropy(from_logits=True)`` with pad(0)
positions zeroed, summed and normalized. Two normalizations are offered
(``TrainConfig.loss_normalization``):

- ``"tokens"``: mean over non-pad tokens — the sane default;
- ``"batch"``: sum divided by global batch size — the reference's exact rule
  (``train.py:88``), which is also the correct normalization for summed
  per-replica losses under data parallelism (SURVEY.md §2.3.4).

Plus label smoothing (BASELINE.json configs[2]), absent from the reference.

Everything returns *sums* alongside the scalar loss so metric accumulation is
exact under sharding: per-device partial sums combine with a psum that XLA
inserts automatically when batches are sharded over the ``data`` mesh axis —
the TPU-native replacement for Keras streaming metrics (``train.py:70-73``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from transformer_tpu.config import PAD_ID


def masked_cross_entropy(
    logits: jax.Array,
    targets: jax.Array,
    label_smoothing: float = 0.0,
    normalization: str = "tokens",
    batch_size: int | None = None,
    pad_id: int = PAD_ID,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Returns ``(loss, metrics)`` where metrics carries exact sums:
    ``loss_sum`` (fp32 summed per-token CE), ``weight`` (non-pad token count),
    ``correct`` (argmax==target count on non-pad)."""
    vocab = logits.shape[-1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    target_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if label_smoothing > 0.0:
        confidence = 1.0 - label_smoothing
        uniform = label_smoothing / (vocab - 1)
        # CE against the smoothed distribution, minus its (constant) entropy
        # offset omitted — standard smoothed-CE used by most NMT stacks.
        smooth_sum = jnp.sum(logp, axis=-1) - target_logp
        per_token = -(confidence * target_logp + uniform * smooth_sum)
    else:
        per_token = -target_logp
    mask = (targets != pad_id).astype(jnp.float32)
    loss_sum = jnp.sum(per_token * mask)
    weight = jnp.sum(mask)
    if normalization == "tokens":
        loss = loss_sum / jnp.maximum(weight, 1.0)
    elif normalization == "batch":
        if batch_size is None:
            raise ValueError("normalization='batch' requires batch_size")
        loss = loss_sum / float(batch_size)
    else:
        raise ValueError(f"unknown normalization {normalization!r}")
    correct = jnp.sum(
        (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32) * mask
    )
    return loss, {"loss_sum": loss_sum, "weight": weight, "correct": correct}
