"""Loss and step metrics.

Counterpart of the reference's masked cross-entropy (``train.py:67-69,83-88``):
per-token ``SparseCategoricalCrossentropy(from_logits=True)`` with pad(0)
positions zeroed, summed and normalized. Two normalizations are offered
(``TrainConfig.loss_normalization``):

- ``"tokens"``: mean over non-pad tokens — the sane default;
- ``"batch"``: sum divided by global batch size — the reference's exact rule
  (``train.py:88``), which is also the correct normalization for summed
  per-replica losses under data parallelism (SURVEY.md §2.3.4).

Plus label smoothing (BASELINE.json configs[2]), absent from the reference.

Everything returns *sums* alongside the scalar loss so metric accumulation is
exact under sharding: per-device partial sums combine with a psum that XLA
inserts automatically when batches are sharded over the ``data`` mesh axis —
the TPU-native replacement for Keras streaming metrics (``train.py:70-73``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from transformer_tpu.config import PAD_ID


def _normalize(
    loss_sum: jax.Array,
    weight: jax.Array,
    normalization: str,
    batch_size: int | None,
) -> jax.Array:
    """The shared tokens/batch normalization rule (monolithic and chunked CE)."""
    if normalization == "tokens":
        return loss_sum / jnp.maximum(weight, 1.0)
    if normalization == "batch":
        if batch_size is None:
            raise ValueError("normalization='batch' requires batch_size")
        return loss_sum / float(batch_size)
    raise ValueError(f"unknown normalization {normalization!r}")


def masked_cross_entropy(
    logits: jax.Array,
    targets: jax.Array,
    label_smoothing: float = 0.0,
    normalization: str = "tokens",
    batch_size: int | None = None,
    pad_id: int = PAD_ID,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Returns ``(loss, metrics)`` where metrics carries exact sums:
    ``loss_sum`` (fp32 summed per-token CE), ``weight`` (non-pad token count),
    ``correct`` (argmax==target count on non-pad)."""
    vocab = logits.shape[-1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    target_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if label_smoothing > 0.0:
        confidence = 1.0 - label_smoothing
        uniform = label_smoothing / (vocab - 1)
        # CE against the smoothed distribution, minus its (constant) entropy
        # offset omitted — standard smoothed-CE used by most NMT stacks.
        smooth_sum = jnp.sum(logp, axis=-1) - target_logp
        per_token = -(confidence * target_logp + uniform * smooth_sum)
    else:
        per_token = -target_logp
    mask = (targets != pad_id).astype(jnp.float32)
    loss_sum = jnp.sum(per_token * mask)
    weight = jnp.sum(mask)
    loss = _normalize(loss_sum, weight, normalization, batch_size)
    correct = jnp.sum(
        (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32) * mask
    )
    return loss, {"loss_sum": loss_sum, "weight": weight, "correct": correct}


def chunked_cross_entropy_from_hidden(
    params,
    hidden: jax.Array,
    targets: jax.Array,
    cfg,
    *,
    num_chunks: int,
    label_smoothing: float = 0.0,
    normalization: str = "tokens",
    batch_size: int | None = None,
    pad_id: int = PAD_ID,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Masked CE computed WITHOUT materializing the full (B, S, V) logits.

    The (B, S, d_model) decoder hiddens (``transformer_hidden_apply``) are
    scanned in ``num_chunks`` sequence slices; each slice runs the vocab
    projection + CE under ``jax.checkpoint``, so only (B, S/num_chunks, V)
    logits are ever live and the backward pass recomputes them per slice.
    The memory lever for big-vocab models: at B=4, S=4096, V=32k the full
    logits tensor is ~1 GB bf16 (+2 GB fp32 log-softmax) per step; chunked,
    peak drops by the chunk factor for one extra projection matmul in the
    backward. Numerics are identical to ``masked_cross_entropy`` up to
    summation order (exact-sum metrics, both normalization rules).
    """
    from transformer_tpu.models.transformer import project_logits

    B, S, _ = hidden.shape
    chunk = -(-S // num_chunks)
    padded = chunk * num_chunks
    if padded != S:
        # Pad with PAD-target positions: zero loss weight, dead compute only
        # on the final slice.
        hidden = jnp.pad(hidden, ((0, 0), (0, padded - S), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, padded - S)), constant_values=pad_id)
    h = hidden.reshape(B, num_chunks, chunk, hidden.shape[-1]).transpose(1, 0, 2, 3)
    t = targets.reshape(B, num_chunks, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_sums(hc, tc):
        logits = project_logits(params, hc, cfg)
        _, m = masked_cross_entropy(
            logits, tc,
            label_smoothing=label_smoothing,
            normalization="tokens",  # only the exact sums are consumed
            pad_id=pad_id,
        )
        return m["loss_sum"], m["weight"], m["correct"]

    def body(acc, xs):
        ls, w, c = chunk_sums(*xs)
        return (acc[0] + ls, acc[1] + w, acc[2] + c), None

    zero = (jnp.float32(0.0), jnp.float32(0.0), jnp.float32(0.0))
    (loss_sum, weight, correct), _ = jax.lax.scan(body, zero, (h, t))
    loss = _normalize(loss_sum, weight, normalization, batch_size)
    return loss, {"loss_sum": loss_sum, "weight": weight, "correct": correct}
