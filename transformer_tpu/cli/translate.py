"""Serving-side entry point: load an export and translate text.

Exercises the counterpart of the reference's ``tf.saved_model.save`` output
(``train.py:246``, README "Model Exporting"): the directory written by
``export_params`` (params.npz + config.json) is loaded *without the training
stack* and driven end-to-end — tokenize → greedy decode → detokenize.

    python -m transformer_tpu.cli.translate --export_path=model \
        --src_vocab_file=src_vocab.subwords --tgt_vocab_file=tgt_vocab.subwords \
        [--sentences="he go to school"]            # or read stdin, one per line
"""

from __future__ import annotations

import sys

from absl import app, flags, logging

FLAGS = flags.FLAGS


def define_export_serving_flags() -> None:
    """The flags every export-consuming CLI shares (translate, serve) —
    one source of truth so the serving surfaces cannot drift."""
    flags.DEFINE_string("export_path", "model", "directory written by export_params")
    flags.DEFINE_string("src_vocab_file", "src_vocab.subwords", "source subword vocab")
    flags.DEFINE_string("tgt_vocab_file", "tgt_vocab.subwords", "target subword vocab")
    flags.DEFINE_integer("max_len", 64, "max generated tokens per request")
    flags.DEFINE_integer("beam", 1, "beam size (1 = greedy)")
    flags.DEFINE_string("platform", "", "force a jax platform (e.g. 'cpu') before first use")
    flags.DEFINE_boolean(
        "kv_cache_int8", False,
        "decode with an int8-quantized KV cache (~2-4x less cache HBM; "
        "serving-time choice, independent of the export)")


def define_translate_flags() -> None:
    define_export_serving_flags()
    flags.DEFINE_string("sentences", "", "';'-separated sentences (default: stdin lines)")
    flags.DEFINE_string(
        "attention_out", "",
        "dump per-layer attention maps to this .npz: a teacher-forced "
        "forward over (source, translation) saves encoder self-attention "
        "and decoder self/cross maps per sentence — the reference's "
        "attention_weights return (Transformer.py:30-32) as a servable "
        "artifact ('' = off)")


def load_export(export_path: str, kv_cache_int8: bool = False):
    """(params, model_cfg) from an export directory — no trainer needed.
    ``kv_cache_int8`` opts the loaded model's decode path into the int8 KV
    cache (a serving-time choice, so it is not baked into the export)."""
    import dataclasses
    import os

    import jax

    from transformer_tpu.config import ModelConfig, config_from_json
    from transformer_tpu.models import transformer_init
    from transformer_tpu.train.checkpoint import load_exported_params

    with open(os.path.join(export_path, "config.json")) as f:
        model_cfg = config_from_json(ModelConfig, f.read())
    if kv_cache_int8:
        model_cfg = dataclasses.replace(model_cfg, kv_cache_int8=True)
    # Template gives load_exported_params the tree structure + dtypes; its
    # (random) values are fully overwritten by the stored arrays.
    template = transformer_init(jax.random.PRNGKey(0), model_cfg)
    params = load_exported_params(export_path, template)
    return params, model_cfg


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import maybe_force_platform

    maybe_force_platform()

    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.train.decode import translate

    params, model_cfg = load_export(FLAGS.export_path, kv_cache_int8=FLAGS.kv_cache_int8)
    src_tok = SubwordTokenizer.load(FLAGS.src_vocab_file)
    tgt_tok = SubwordTokenizer.load(FLAGS.tgt_vocab_file)

    if FLAGS.sentences:
        sentences = [s.strip() for s in FLAGS.sentences.split(";") if s.strip()]
    else:
        sentences = [line.strip() for line in sys.stdin if line.strip()]
    if not sentences:
        logging.warning("no input sentences")
        return
    outputs = translate(
        params, model_cfg, src_tok, tgt_tok, sentences,
        max_len=FLAGS.max_len, beam_size=FLAGS.beam,
    )
    for out in outputs:
        print(out)
    if FLAGS.attention_out:
        from transformer_tpu.train.evaluate import dump_attention_maps

        n = dump_attention_maps(
            params, model_cfg, src_tok, tgt_tok, sentences, outputs,
            FLAGS.attention_out,
        )
        logging.info("wrote %d attention maps to %s", n, FLAGS.attention_out)


def run() -> None:
    define_translate_flags()
    app.run(main)


if __name__ == "__main__":
    run()
