"""Distributed training entry point.

Counterpart of the reference's ``python distributed_train.py --num_gpu=N``
(``distributed_train.py:124-179``), rebuilt for TPU: instead of
MirroredStrategy over a GPU list, a ``Mesh`` over all visible devices with
axes sized by ``--dp/--fsdp/--tp/--sp``. Run:

    python -m transformer_tpu.cli.distributed_train --dataset_path=data \
        --dp=0 --fsdp=1 --tp=1      # dp=0: all devices data-parallel

Multi-host (pod slices) works through the same entry point: each process
feeds its shard of every global batch (``Seq2SeqDataset.shard_index``) and
host 0 writes checkpoints/logs.
"""

from __future__ import annotations

import os

from absl import app, flags, logging

from transformer_tpu.cli.flags import (
    define_flags,
    flags_to_mesh_config,
    flags_to_model_config,
    flags_to_train_config,
    maybe_force_platform,
)

FLAGS = flags.FLAGS


def _reject_cpu_virtual_bf16(jax, dtype: str) -> None:
    """Refuse the one combination known to abort inside XLA, loudly.

    XLA:CPU's collective rendezvous aborts the whole process (not a Python
    exception) when a single-process, multi-virtual-device mesh runs the
    full fit machinery in bfloat16 (bisected in round 4; fp32 and the
    pytest/dryrun shard_map paths are unaffected — docs/ROUND4.md). The
    reference's precedent is its batch-divisibility ``ValueError``
    (``distributed_train.py:154-158``): fail with a message, never abort.
    ``TRANSFORMER_TPU_ALLOW_CPU_BF16=1`` re-enables the path for probing
    whether a newer XLA fixed it.
    """
    if os.environ.get("TRANSFORMER_TPU_ALLOW_CPU_BF16") == "1":
        return
    if (
        dtype == "bfloat16"
        and jax.default_backend() == "cpu"
        and jax.process_count() == 1
        and len(jax.devices()) > 1
    ):
        raise app.UsageError(
            "dtype=bfloat16 on a single-process multi-device CPU mesh "
            f"({len(jax.devices())} virtual devices) aborts in XLA:CPU's "
            "collective rendezvous (known backend bug, docs/ROUND4.md). "
            "Pass --dtype=float32 for CPU runs, or set "
            "TRANSFORMER_TPU_ALLOW_CPU_BF16=1 to try anyway."
        )


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import apply_preset

    apply_preset()  # before ANY direct FLAGS read (e.g. decoder_only)
    maybe_force_platform()
    import jax

    from transformer_tpu.data import load_dataset
    from transformer_tpu.parallel import DistributedTrainer, make_mesh
    from transformer_tpu.parallel.mesh import initialize_distributed
    from transformer_tpu.train import AsyncCheckpointManager, CheckpointManager
    from transformer_tpu.train.checkpoint import export_params
    from transformer_tpu.train.decode import translate

    initialize_distributed()
    _reject_cpu_virtual_bf16(jax, FLAGS.dtype)
    mesh_cfg = flags_to_mesh_config(len(jax.devices()))
    mesh = make_mesh(mesh_cfg)
    logging.info(
        "mesh: %s over %d devices (%d processes)",
        dict(zip(mesh.axis_names, mesh.devices.shape)),
        len(jax.devices()), jax.process_count(),
    )

    train_cfg = flags_to_train_config()
    buckets = tuple(
        int(x) for x in FLAGS.length_buckets.split(",") if x.strip()
    )
    # Same LM-window predicate as cli.train: shared data path and
    # perplexity (not translate/BLEU) epilogue.
    lm_mode = FLAGS.decoder_only or FLAGS.objective == "mlm"
    if lm_mode:
        if buckets:
            raise app.UsageError(
                "--length_buckets applies to the seq2seq pipeline only; LM "
                "windows are already fixed-width (drop the flag with "
                "--decoder_only / --objective=mlm)"
            )
        from transformer_tpu.data.pipeline import load_lm_splits

        train_ds, test_ds, tok = load_lm_splits(
            FLAGS.dataset_path,
            FLAGS.tgt_vocab_file,
            batch_size=train_cfg.batch_size,
            sequence_length=train_cfg.sequence_length,
            target_vocab_size=FLAGS.target_vocab_size,
            seed=train_cfg.seed,
            shard_index=jax.process_index(),
            shard_count=jax.process_count(),
        )
        src_tok = tgt_tok = tok
    else:
        train_ds, test_ds, src_tok, tgt_tok = load_dataset(
            FLAGS.dataset_path,
            FLAGS.src_vocab_file,
            FLAGS.tgt_vocab_file,
            batch_size=train_cfg.batch_size,
            sequence_length=train_cfg.sequence_length,
            target_vocab_size=FLAGS.target_vocab_size,
            seed=train_cfg.seed,
            shard_index=jax.process_index(),
            shard_count=jax.process_count(),
            prefetch=FLAGS.native_loader,  # composes with length_buckets (native bucketed plan)
            length_buckets=buckets,
        )
    model_cfg = flags_to_model_config(
        src_tok.model_vocab_size, tgt_tok.model_vocab_size
    )
    ckpt_cls = AsyncCheckpointManager if FLAGS.async_checkpoint else CheckpointManager
    ckpt = ckpt_cls(train_cfg.ckpt_path, train_cfg.max_ckpt_keep)
    import datetime

    stamp = datetime.datetime.now().strftime("%Y%m%d-%H%M%S")
    from transformer_tpu.cli.flags import flags_to_profiler, flags_to_telemetry

    # Host 0 owns telemetry, like logs/checkpoints: per-host event files
    # would interleave badly and the metrics are already globally reduced.
    telemetry = flags_to_telemetry() if jax.process_index() == 0 else None
    trainer = DistributedTrainer(
        model_cfg, train_cfg, mesh,
        log_dir=os.path.join(FLAGS.tb_log_dir, stamp)
        if jax.process_index() == 0
        else None,
        checkpoint=ckpt,
        log_fn=logging.info,
        profiler=flags_to_profiler() if jax.process_index() == 0 else None,
        telemetry=telemetry,
    )
    if FLAGS.consistency_check:
        from transformer_tpu.utils.consistency import (
            assert_cross_process_consistent,
        )

        def check_consistency(epoch, tr):
            assert_cross_process_consistent(
                tr.state.params, label=f"params after epoch {epoch + 1}"
            )

        trainer.fit(train_ds, test_ds, epoch_callback=check_consistency)
        assert_cross_process_consistent(trainer.state.params, label="final params")
    else:
        trainer.fit(train_ds, test_ds)

    # Multi-host: params are sharded across processes, but the epilogue
    # (sample decode, export, BLEU) runs on host 0 alone — device_get/jit on
    # arrays with non-addressable shards would fail or deadlock. Gather to
    # host-local numpy on EVERY process (allgather is a collective), then
    # let host 0 proceed.
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        host_params = multihost_utils.process_allgather(trainer.state.params)
    else:
        host_params = trainer.state.params

    if jax.process_index() == 0:
        if lm_mode:
            # LM quality metric: perplexity from fit()'s final-epoch full
            # eval (MLM: pseudo-perplexity over the deterministically-masked
            # eval positions) — the same epilogue cli.train prints.
            if test_ds is not None and trainer.eval_metrics.weight > 0:
                import math

                logging.info(
                    "eval loss %.4f, perplexity %.2f",
                    trainer.eval_metrics.loss,
                    math.exp(min(trainer.eval_metrics.loss, 30.0)),
                )
            elif test_ds is not None:
                logging.warning("eval split produced no tokens; no perplexity")
        else:
            sample = ["he goes to school"]
            out = translate(
                host_params, model_cfg, src_tok, tgt_tok, sample,
                max_len=train_cfg.sequence_length,
            )
            logging.info("sample translation %r -> %r", sample[0], out[0])
        export_params(host_params, model_cfg, "model")
        logging.info("exported params to ./model")

        # End-of-run BLEU on the test split (same epilogue as cli.train so
        # both entry points report the north-star metric).
        if FLAGS.eval_bleu and not lm_mode:
            from transformer_tpu.train.evaluate import bleu_on_test_files

            bleu_on_test_files(
                host_params, model_cfg, src_tok, tgt_tok,
                FLAGS.dataset_path,
                batch_size=train_cfg.batch_size,
                max_len=train_cfg.sequence_length,
                limit=FLAGS.bleu_limit,
                log_fn=logging.info,
            )
    if telemetry is not None:
        telemetry.close()


def run() -> None:
    define_flags()
    app.run(main)


if __name__ == "__main__":
    run()
