"""CLI entry points — counterparts of the reference's ``train.py`` /
``distributed_train.py`` absl entry points, preserving the reference flag
names (``utils.py:17-33``) plus TPU-native mesh knobs."""
