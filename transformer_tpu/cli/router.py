"""Multi-replica serving front end: spawn N replica workers and route.

    python -m transformer_tpu.cli.router --replicas 2 --export_path=model \\
        --tgt_vocab_file=vocab.subwords --metrics_jsonl=/tmp/router.jsonl

Same wire contract as ``cli.serve``: one JSONL request (or raw prompt
line) per stdin line, one JSONL response per line, in request order. The
router process itself never loads the model — it owns client intake, the
prefix-affinity/least-loaded dispatch policy, heartbeat-fed liveness, and
zero-loss failover (``serve/router.py``); each replica worker
(``serve/replica.py``) is a subprocess running the continuous-batching
scheduler over its own model copy. Killing a replica mid-stream loses no
accepted request: its in-flight work is re-dispatched to survivors with
original order, trace id, and deadline intact.

With ``--metrics_jsonl=PATH`` the router logs to PATH and each replica to
``PATH.rN``; merge the fleet view with::

    python -m transformer_tpu.obs summarize PATH PATH.r0 PATH.r1
    python -m transformer_tpu.obs trace PATH PATH.r0 PATH.r1 --out t.json

``--disaggregate`` marks replica 0 prefill-only and the rest decode-only:
prompts are ingested on the prefill side and their KV handed to decode
replicas as prefix-cache blocks (docs/SERVING.md "Multi-replica router").
"""

from __future__ import annotations

import json
import queue
import sys
import threading

from absl import app, flags, logging

FLAGS = flags.FLAGS


def define_router_flags() -> None:
    from transformer_tpu.cli.flags import define_metrics_flags

    define_metrics_flags()
    flags.DEFINE_integer("replicas", 2, "replica worker processes to spawn")
    flags.DEFINE_string("export_path", "model", "export directory (per replica)")
    flags.DEFINE_string("tgt_vocab_file", "tgt_vocab.subwords",
                        "target subword vocab (router affinity + replicas)")
    flags.DEFINE_string(
        "model_spec", "",
        "JSON test-model spec file (serve.replica build_model_from_spec) "
        "instead of an export — the CI/bench bootstrap")
    flags.DEFINE_boolean("kv_cache_int8", False, "int8 KV cache in replicas")
    flags.DEFINE_integer("serve_slots", 4, "KV-cache slots per replica")
    flags.DEFINE_integer("serve_max_total", 0, "per-slot KV budget")
    flags.DEFINE_integer("prefill_chunk", 0, "replica prefill chunk")
    flags.DEFINE_integer("max_len", 64, "default max_new per request")
    flags.DEFINE_integer("speculate_k", 0, "replica speculative lookahead")
    flags.DEFINE_integer("prefix_cache_mb", 64,
                         "per-replica prefix KV cache budget (0 = off)")
    flags.DEFINE_integer("prefix_block", 16, "prefix-cache block tokens")
    flags.DEFINE_integer(
        "affinity_block", 0,
        "token-block granularity for prefix-affinity hashing "
        "(0 = --prefix_block); prompts sharing their leading aligned "
        "blocks route to the replica whose PrefixCache is warm")
    flags.DEFINE_integer(
        "affinity_slack", 4,
        "load gap (in-flight + heartbeat backlog) past which an affine "
        "request falls back to the least-loaded replica")
    flags.DEFINE_integer(
        "max_redispatch", 2,
        "bounded failover: redispatches per request before answering a "
        "structured 'transient' error")
    flags.DEFINE_float("heartbeat_ms", 200.0, "replica heartbeat period")
    flags.DEFINE_float(
        "heartbeat_timeout", 5.0,
        "seconds without a heartbeat before a replica is failed over "
        "(0 = rely on pipe EOF / process exit only)")
    flags.DEFINE_boolean(
        "disaggregate", False,
        "prefill/decode disaggregation: replica 0 ingests prompts only and "
        "hands KV blocks to decode-only peers (docs/SERVING.md)")


def worker_args_from_flags(replica_jsonl: str = "") -> list[str]:
    """The replica-worker argv tail shared by every spawned process."""
    out = [
        "--serve_slots", str(FLAGS.serve_slots),
        "--serve_max_total", str(FLAGS.serve_max_total),
        "--prefill_chunk", str(FLAGS.prefill_chunk),
        "--max_len", str(FLAGS.max_len),
        "--speculate_k", str(FLAGS.speculate_k),
        "--prefix_cache_mb", str(FLAGS.prefix_cache_mb),
        "--prefix_block", str(FLAGS.prefix_block),
        "--heartbeat_ms", str(FLAGS.heartbeat_ms),
    ]
    if FLAGS.model_spec:
        out += ["--model_spec", FLAGS.model_spec]
    else:
        out += ["--export_path", FLAGS.export_path,
                "--tgt_vocab_file", FLAGS.tgt_vocab_file]
        if FLAGS.kv_cache_int8:
            out += ["--kv_cache_int8"]
    if replica_jsonl:
        out += ["--metrics_jsonl", replica_jsonl]
        if FLAGS.trace:
            out += ["--trace"]
    return out




def route_lines(q: "queue.Queue", router) -> None:
    """Drive the router from the stdin queue: parse lines (malformed/
    wrong-kind ones answer immediately at a reserved order), pump
    dispatch/answers, flush responses in arrival order — the
    ``serve_continuous`` loop shape, one tier up."""
    from transformer_tpu.serve.router import _RouterLineError, parse_router_line

    eof = False
    while not eof or router.busy:
        while not eof:
            try:
                line = q.get_nowait()
            except queue.Empty:
                break
            if line is None:
                eof = True
                break
            line = line.strip()
            if not line:
                continue
            try:
                req = parse_router_line(line)
            except _RouterLineError as e:
                # Bare message — byte-identical to the grouped path's
                # kind-mismatch answer (cli/serve.py parity).
                router.submit_done({"error": str(e), "code": "routing"})
                continue
            except Exception as e:  # noqa: BLE001 — bad line answers, never kills
                router.submit_done({
                    "error": f"{type(e).__name__}: {e}", "code": "validation",
                })
                continue
            router.submit(req)
        router.pump()
        for resp in router.drain_ready():
            print(json.dumps(resp), flush=True)


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import flags_to_telemetry
    from transformer_tpu.serve.router import ReplicaProcess, Router

    telemetry = flags_to_telemetry()
    # Affinity hashing needs only the tokenizer — the router never loads
    # the model or compiles a program, so it restarts cheaply and
    # survives replica OOMs.
    if FLAGS.model_spec:
        with open(FLAGS.model_spec) as f:
            spec = json.load(f)
        from transformer_tpu.data.tokenizer import SubwordTokenizer

        tok = SubwordTokenizer.build_from_corpus(
            list(spec["corpus"]),
            target_vocab_size=int(spec.get("target_vocab_size", 300)),
        )
    else:
        from transformer_tpu.data.tokenizer import SubwordTokenizer

        tok = SubwordTokenizer.load(FLAGS.tgt_vocab_file)

    n = max(1, FLAGS.replicas)
    links = []
    for i in range(n):
        role = "both"
        if FLAGS.disaggregate:
            role = "prefill" if i == 0 else "decode"
        replica_jsonl = (
            f"{FLAGS.metrics_jsonl}.r{i}" if FLAGS.metrics_jsonl else ""
        )
        links.append(
            ReplicaProcess.spawn(
                i, worker_args_from_flags(replica_jsonl), role=role,
            )
        )
    router = Router(
        links,
        encode=tok.encode,
        bos_id=tok.bos_id,
        affinity_block=FLAGS.affinity_block or FLAGS.prefix_block,
        affinity_slack=FLAGS.affinity_slack,
        max_redispatch=FLAGS.max_redispatch,
        heartbeat_timeout_s=FLAGS.heartbeat_timeout,
        disaggregate=FLAGS.disaggregate,
        telemetry=telemetry,
    )
    for link in links:
        link.start_reader(router.inbox)
    logging.info(
        "router up: %d replica(s) x %d slots, affinity block %d%s",
        n, FLAGS.serve_slots, FLAGS.affinity_block or FLAGS.prefix_block,
        ", disaggregated prefill/decode" if FLAGS.disaggregate else "",
    )

    from transformer_tpu.serve.replica import stdin_reader

    q: queue.Queue = queue.Queue(maxsize=max(1, FLAGS.serve_slots * n) * 8)
    threading.Thread(target=stdin_reader, args=(q,), daemon=True).start()
    try:
        route_lines(q, router)
    finally:
        router.shutdown()
        if telemetry is not None:
            telemetry.close()


def run() -> None:
    define_router_flags()
    app.run(main)


if __name__ == "__main__":
    run()
