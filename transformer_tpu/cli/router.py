"""Multi-replica serving front end: spawn N replica workers and route.

    python -m transformer_tpu.cli.router --replicas 2 --export_path=model \\
        --tgt_vocab_file=vocab.subwords --metrics_jsonl=/tmp/router.jsonl

Same wire contract as ``cli.serve``: one JSONL request (or raw prompt
line) per stdin line, one JSONL response per line, in request order. The
router process itself never loads the model — it owns client intake, the
prefix-affinity/least-loaded dispatch policy, heartbeat-fed liveness, and
zero-loss failover (``serve/router.py``); each replica worker
(``serve/replica.py``) is a subprocess running the continuous-batching
scheduler over its own model copy. Killing a replica mid-stream loses no
accepted request: its in-flight work is re-dispatched to survivors with
original order, trace id, and deadline intact.

With ``--metrics_jsonl=PATH`` the router logs to PATH and each replica to
``PATH.rN``; merge the fleet view with::

    python -m transformer_tpu.obs summarize PATH PATH.r0 PATH.r1
    python -m transformer_tpu.obs trace PATH PATH.r0 PATH.r1 --out t.json

``--disaggregate`` marks replica 0 prefill-only and the rest decode-only:
prompts are ingested on the prefill side and their KV handed to decode
replicas as prefix-cache blocks (docs/SERVING.md "Multi-replica router").

**Self-healing fleet** (PR 11, docs/SERVING.md "Self-healing fleet"):
``--supervise`` (default on) attaches a :class:`serve.supervisor.Supervisor`
— a SIGKILLed replica is re-bootstrapped from the same deterministic
recipe under its old name, its PrefixCache warmed from a survivor, with a
bounded restart budget (``--max_restarts`` per ``--restart_window``).
``--max_replicas N`` > the spawn count enables SLO-driven autoscaling:
sustained ``ttft_p95`` burn > 1 grows the fleet, sustained idleness
drains it back to ``--min_replicas``. ``--ha`` journals intake/delivery/
heartbeat events to ``--metrics_jsonl`` and puts replicas on takeover
control sockets so a warm standby::

    python -m transformer_tpu.cli.router --standby PATH.jsonl ...

can tail the log, detect primary death by heartbeat silence
(``--takeover_after``), adopt the fleet, and answer every in-flight
request exactly once (``serve/standby.py``).
"""

from __future__ import annotations

import json
import queue
import sys
import threading

from absl import app, flags, logging

FLAGS = flags.FLAGS


def define_router_flags() -> None:
    from transformer_tpu.cli.flags import define_metrics_flags

    define_metrics_flags()
    flags.DEFINE_integer("replicas", 2, "replica worker processes to spawn")
    flags.DEFINE_string("export_path", "model", "export directory (per replica)")
    flags.DEFINE_string("tgt_vocab_file", "tgt_vocab.subwords",
                        "target subword vocab (router affinity + replicas)")
    flags.DEFINE_string(
        "model_spec", "",
        "JSON test-model spec file (serve.replica build_model_from_spec) "
        "instead of an export — the CI/bench bootstrap")
    flags.DEFINE_boolean("kv_cache_int8", False, "int8 KV cache in replicas")
    flags.DEFINE_integer("serve_slots", 4, "KV-cache slots per replica")
    flags.DEFINE_integer("serve_max_total", 0, "per-slot KV budget")
    flags.DEFINE_integer("prefill_chunk", 0, "replica prefill chunk")
    flags.DEFINE_integer("max_len", 64, "default max_new per request")
    flags.DEFINE_integer("speculate_k", 0, "replica speculative lookahead")
    flags.DEFINE_integer("prefix_cache_mb", 64,
                         "per-replica prefix KV cache budget (0 = off)")
    flags.DEFINE_integer("prefix_block", 16, "prefix-cache block tokens")
    flags.DEFINE_enum(
        "kv_layout", "dense", ["dense", "paged"],
        "per-slot KV storage in each replica worker: dense buffers or the "
        "paged block pool with device-resident prefix aliasing "
        "(docs/SERVING.md)")
    flags.DEFINE_integer(
        "kv_pool_blocks", 0,
        "paged pool size per replica, in --prefix_block-token blocks "
        "(0 = full provisioning)")
    flags.DEFINE_string(
        "mesh", "",
        "serving mesh per replica ('N' or 'data=N'): each worker becomes "
        "one pjit program over N devices (docs/SERVING.md 'Sharded "
        "replicas'). Rides the deterministic spawn argv, so supervised "
        "respawns and scale-ups inherit the shape; heartbeats report it "
        "and the supervisor refuses a wrong-shape replacement. '' = "
        "single-device workers")
    flags.DEFINE_integer(
        "affinity_block", 0,
        "token-block granularity for prefix-affinity hashing "
        "(0 = --prefix_block); prompts sharing their leading aligned "
        "blocks route to the replica whose PrefixCache is warm")
    flags.DEFINE_integer(
        "affinity_slack", 4,
        "load gap (in-flight + heartbeat backlog) past which an affine "
        "request falls back to the least-loaded replica")
    flags.DEFINE_integer(
        "max_redispatch", 2,
        "bounded failover: redispatches per request before answering a "
        "structured 'transient' error")
    flags.DEFINE_float("heartbeat_ms", 200.0, "replica heartbeat period")
    flags.DEFINE_string(
        "fault_spec", "",
        "deterministic fault injection (docs/ROBUSTNESS.md grammar): "
        "installed in the ROUTER process (route.spawn/route.hb/"
        "route.upgrade/route.canary/route.takeover fire here) AND "
        "forwarded to every replica worker (serve.*/prefix.*/draft.*/"
        "ckpt.swap fire there)")
    flags.DEFINE_float(
        "heartbeat_timeout", 5.0,
        "seconds without a heartbeat before a replica is failed over "
        "(0 = rely on pipe EOF / process exit only)")
    flags.DEFINE_boolean(
        "disaggregate", False,
        "prefill/decode disaggregation: replica 0 ingests prompts only and "
        "hands KV blocks to decode-only peers (docs/SERVING.md)")
    # ---- self-healing fleet (serve/supervisor.py, serve/standby.py) ------
    flags.DEFINE_boolean(
        "supervise", True,
        "supervised respawn: re-bootstrap dead replicas from the same "
        "deterministic recipe under their old rendezvous name, warming "
        "the replacement's PrefixCache from a survivor before admission")
    flags.DEFINE_integer(
        "max_restarts", 3,
        "respawn budget per replica within --restart_window before the "
        "supervisor gives up (breaker stays open, fleet serves at N-1)")
    flags.DEFINE_float("restart_window", 120.0,
                       "seconds over which --max_restarts is counted")
    flags.DEFINE_float("spawn_backoff_ms", 200.0,
                       "base exponential backoff between respawn attempts")
    flags.DEFINE_integer(
        "warm_prefixes", 8,
        "hottest survivor PrefixCache prefixes exported to warm a "
        "respawned replica (0 = admit cold)")
    flags.DEFINE_integer(
        "max_replicas", 0,
        "SLO-driven autoscaling ceiling: > --replicas enables scale-up on "
        "sustained ttft_p95 burn > 1 and idle drain back down "
        "(0 = fixed fleet)")
    flags.DEFINE_integer("min_replicas", 1, "autoscaling floor")
    flags.DEFINE_string(
        "scale_signal", "ttft_p95",
        "the SLO whose burn rate drives scale-up (must name an objective "
        "in --slo_spec / the defaults)")
    flags.DEFINE_float("scale_sustain", 5.0,
                       "seconds of sustained burn > 1 before a scale-up")
    flags.DEFINE_float("scale_idle", 30.0,
                       "seconds of sustained idleness before a drain")
    flags.DEFINE_float("scale_cooldown", 15.0,
                       "seconds between consecutive scaling decisions")
    flags.DEFINE_string(
        "slo_spec", "",
        "SLO objectives for the router's own burn-rate engine (obs/slo.py "
        "grammar; '' = defaults when autoscaling is on; 'none' disables)")
    flags.DEFINE_boolean(
        "ha", False,
        "router HA primary: journal intake/delivery/heartbeat events to "
        "--metrics_jsonl and give replicas takeover control sockets so a "
        "warm standby (--standby) can adopt the fleet")
    # ---- live-weights rollout (serve/upgrade.py) --------------------------
    flags.DEFINE_string(
        "upgrade", "",
        "start a rolling weight swap to this manifest-verified checkpoint "
        "at startup (docs/SERVING.md 'Live-weights rollout'); at runtime "
        "a control line {\"upgrade\": \"<ckpt>\"} on stdin does the same")
    flags.DEFINE_float(
        "canary_window", 5.0,
        "seconds the first upgraded replica serves its pinned traffic "
        "slice before the rollout promotes (clean) or rolls back (burn)")
    flags.DEFINE_integer(
        "canary_every", 0,
        "pin every Nth accepted order to the canary during its window "
        "(0 = the fleet size at rollout start)")
    flags.DEFINE_string(
        "canary_slo", "",
        "SLO objectives for the per-weight-version canary verdict "
        "(obs/slo.py grammar; '' = short-window availability + ttft_p95)")
    flags.DEFINE_string(
        "standby", "",
        "run as the warm STANDBY for the primary whose --metrics_jsonl is "
        "this path: tail its journal, adopt the fleet when its heartbeat "
        "goes silent, then serve from this process's stdin")
    flags.DEFINE_float(
        "takeover_after", 2.0,
        "standby: seconds of primary heartbeat silence before takeover")


def worker_args_from_flags(replica_jsonl: str = "") -> list[str]:
    """The replica-worker argv tail shared by every spawned process."""
    out = [
        "--serve_slots", str(FLAGS.serve_slots),
        "--serve_max_total", str(FLAGS.serve_max_total),
        "--prefill_chunk", str(FLAGS.prefill_chunk),
        "--max_len", str(FLAGS.max_len),
        "--speculate_k", str(FLAGS.speculate_k),
        "--prefix_cache_mb", str(FLAGS.prefix_cache_mb),
        "--prefix_block", str(FLAGS.prefix_block),
        "--kv_layout", FLAGS.kv_layout,
        "--kv_pool_blocks", str(FLAGS.kv_pool_blocks),
        "--heartbeat_ms", str(FLAGS.heartbeat_ms),
    ]
    if FLAGS.model_spec:
        out += ["--model_spec", FLAGS.model_spec]
    else:
        out += ["--export_path", FLAGS.export_path,
                "--tgt_vocab_file", FLAGS.tgt_vocab_file]
        if FLAGS.kv_cache_int8:
            out += ["--kv_cache_int8"]
    if replica_jsonl:
        out += ["--metrics_jsonl", replica_jsonl]
        if FLAGS.trace:
            out += ["--trace"]
    if FLAGS.mesh:
        out += ["--mesh", FLAGS.mesh]
    if FLAGS.fault_spec:
        out += ["--fault_spec", FLAGS.fault_spec]
    if FLAGS.ha or FLAGS.standby:
        out += ["--ha"]
    return out




def route_lines(q: "queue.Queue", router) -> None:
    """Drive the router from the stdin queue: parse lines (malformed/
    wrong-kind ones answer immediately at a reserved order), pump
    dispatch/answers, flush responses in arrival order — the
    ``serve_continuous`` loop shape, one tier up."""
    from transformer_tpu.serve.router import _RouterLineError, parse_router_line

    eof = False
    while not eof or router.busy:
        while not eof:
            try:
                line = q.get_nowait()
            except queue.Empty:
                break
            if line is None:
                eof = True
                break
            line = line.strip()
            if not line:
                continue
            if line.startswith("{") and '"upgrade"' in line:
                # Control line: {"upgrade": "<ckpt_dir>"} starts a rolling
                # weight swap (serve/upgrade.py) and answers the
                # coordinator's status dict at a reserved order — the
                # operator sees the verified version (or the structured
                # refusal) inline with the response stream.
                try:
                    obj = json.loads(line)
                except ValueError:
                    obj = None
                if (
                    isinstance(obj, dict) and "upgrade" in obj
                    and "prompt" not in obj
                ):
                    status = router.start_upgrade(str(obj["upgrade"]))
                    router.submit_done(
                        {"upgrade": str(obj["upgrade"]), **status}
                    )
                    continue
            try:
                req = parse_router_line(line)
            except _RouterLineError as e:
                # Bare message — byte-identical to the grouped path's
                # kind-mismatch answer (cli/serve.py parity).
                router.submit_done({"error": str(e), "code": "routing"})
                continue
            except Exception as e:  # noqa: BLE001 — bad line answers, never kills
                router.submit_done({
                    "error": f"{type(e).__name__}: {e}", "code": "validation",
                })
                continue
            router.submit(req)
        router.pump()
        for resp in router.drain_ready():
            print(json.dumps(resp), flush=True)


def _load_tokenizer():
    # Affinity hashing needs only the tokenizer — the router never loads
    # the model or compiles a program, so it restarts cheaply and
    # survives replica OOMs.
    from transformer_tpu.data.tokenizer import SubwordTokenizer

    if FLAGS.model_spec:
        with open(FLAGS.model_spec) as f:
            spec = json.load(f)
        return SubwordTokenizer.build_from_corpus(
            list(spec["corpus"]),
            target_vocab_size=int(spec.get("target_vocab_size", 300)),
        )
    return SubwordTokenizer.load(FLAGS.tgt_vocab_file)


def _spawn_recipe():
    """The supervisor's deterministic re-bootstrap callable: the SAME
    worker argv the original fleet used, under the replica's old name —
    rendezvous hashing re-offers the replacement its predecessor's keys.
    When a live-weights rollout has set the fleet's target
    (``Router.weight_target``), the replacement bootstraps from that
    checkpoint (``--init_ckpt``, manifest-verified) instead of the argv
    weights — a heal mid- or post-rollout must never resurrect stale
    weights."""
    from transformer_tpu.serve.router import ReplicaProcess

    def spawn(index: int, name: str, role: str, weight_target=None):
        replica_jsonl = (
            f"{FLAGS.metrics_jsonl}.r{index}" if FLAGS.metrics_jsonl else ""
        )
        argv = worker_args_from_flags(replica_jsonl)
        if weight_target is not None:
            ckpt_dir, version = weight_target
            argv += ["--init_ckpt", ckpt_dir, "--weight_version", version]
        return ReplicaProcess.spawn(index, argv, role=role, name=name)

    return spawn


def _supervision_kwargs() -> dict:
    """Supervisor / FleetScaler / SLO kwargs shared by the primary and an
    adopting standby (the standby becomes a first-class primary)."""
    from transformer_tpu.serve.supervisor import FleetScaler, Supervisor

    from transformer_tpu.serve.upgrade import UpgradeCoordinator

    out: dict = {
        # The live-weights rollout coordinator is always attached: the
        # --upgrade flag and the control line both drive it, and an idle
        # coordinator costs one no-op poll per pump.
        "upgrader": UpgradeCoordinator(
            canary_window_s=FLAGS.canary_window,
            canary_every=FLAGS.canary_every,
            canary_slos=FLAGS.canary_slo or None,
        ),
    }
    if FLAGS.supervise:
        from transformer_tpu.serve.sharded import normalize_mesh_spec

        out["supervisor"] = Supervisor(
            _spawn_recipe(),
            max_restarts=FLAGS.max_restarts,
            restart_window_s=FLAGS.restart_window,
            backoff_ms=FLAGS.spawn_backoff_ms,
            warm_prefixes=FLAGS.warm_prefixes,
            # Canonicalized ('data=N') so the flag spelling can never
            # alias into a false wrong-shape refusal.
            expected_mesh=normalize_mesh_spec(FLAGS.mesh),
        )
    slo_spec = FLAGS.slo_spec
    autoscale = FLAGS.supervise and FLAGS.max_replicas > 0
    if slo_spec.lower() in ("none", "off"):
        slo_spec = ""
        autoscale = False
    if autoscale:
        out["scaler"] = FleetScaler(
            signal=FLAGS.scale_signal,
            sustain_s=FLAGS.scale_sustain,
            idle_s=FLAGS.scale_idle,
            max_replicas=FLAGS.max_replicas,
            min_replicas=FLAGS.min_replicas,
            cooldown_s=FLAGS.scale_cooldown,
        )
    if slo_spec:
        out["slos"] = slo_spec
    elif autoscale:
        from transformer_tpu.obs.slo import DEFAULT_SLOS

        out["slos"] = DEFAULT_SLOS
    if autoscale:
        # A watched signal missing from the objective set would pin the
        # scale-up burn to 0 forever while idle drain kept working — a
        # silently one-directional autoscaler. Fail loudly at startup.
        from transformer_tpu.obs.slo import parse_slo_spec

        specs = (
            parse_slo_spec(out["slos"])
            if isinstance(out["slos"], str) else out["slos"]
        )
        names = {s.name for s in specs}
        if FLAGS.scale_signal not in names:
            raise ValueError(
                f"--scale_signal {FLAGS.scale_signal!r} is not among the "
                f"SLO objectives {sorted(names)}; scale-up could never "
                "trigger"
            )
    return out


def _serve_stdin(router, telemetry) -> None:
    from transformer_tpu.serve.replica import stdin_reader

    q: queue.Queue = queue.Queue(
        maxsize=max(1, FLAGS.serve_slots * max(1, len(router.links))) * 8
    )
    threading.Thread(target=stdin_reader, args=(q,), daemon=True).start()
    try:
        route_lines(q, router)
    finally:
        router.shutdown()
        if telemetry is not None:
            telemetry.close()


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import flags_to_telemetry
    from transformer_tpu.serve.router import ReplicaProcess, Router

    if FLAGS.fault_spec:
        from transformer_tpu.serve import resilience

        resilience.install(resilience.FaultPlane.parse(FLAGS.fault_spec))
    telemetry = flags_to_telemetry()
    tok = _load_tokenizer()

    if FLAGS.standby:
        # Warm standby: tail the primary's journal until its heartbeat
        # goes silent, adopt the fleet, then serve from OUR stdin.
        from transformer_tpu.serve.standby import Standby

        if telemetry is None:
            logging.warning(
                "--standby without --metrics_jsonl: after adopting, this "
                "router writes no journal — the NEXT standby will have "
                "nothing to tail"
            )

        standby = Standby(
            FLAGS.standby,
            takeover_after_s=FLAGS.takeover_after,
            encode=tok.encode,
            bos_id=tok.bos_id,
            telemetry=telemetry,
            router_kwargs=dict(
                affinity_block=FLAGS.affinity_block or FLAGS.prefix_block,
                affinity_slack=FLAGS.affinity_slack,
                max_redispatch=FLAGS.max_redispatch,
                heartbeat_timeout_s=FLAGS.heartbeat_timeout,
                **_supervision_kwargs(),
            ),
        )
        logging.info(
            "standby up: tailing %s (takeover after %.1fs of silence)",
            FLAGS.standby, FLAGS.takeover_after,
        )
        router = standby.run_until_takeover()
        logging.info(
            "adopted the fleet as epoch %d: %s", router.epoch,
            standby.stats,
        )
        _serve_stdin(router, telemetry)
        return

    ha = FLAGS.ha
    if ha and telemetry is None:
        # The HA journal IS the event log — a standby cannot adopt what
        # was never written. Warn like --trace does, don't silently no-op.
        # Write the decision back into FLAGS so the worker argv agrees:
        # a worker spawned with --ha would survive this router's death as
        # a permanent orphan no standby could ever find.
        logging.warning(
            "--ha needs --metrics_jsonl for the standby journal; disabling"
        )
        ha = False
        FLAGS.ha = False

    n = max(1, FLAGS.replicas)
    links = []
    for i in range(n):
        role = "both"
        if FLAGS.disaggregate:
            role = "prefill" if i == 0 else "decode"
        replica_jsonl = (
            f"{FLAGS.metrics_jsonl}.r{i}" if FLAGS.metrics_jsonl else ""
        )
        links.append(
            ReplicaProcess.spawn(
                i, worker_args_from_flags(replica_jsonl), role=role,
            )
        )
    router = Router(
        links,
        encode=tok.encode,
        bos_id=tok.bos_id,
        affinity_block=FLAGS.affinity_block or FLAGS.prefix_block,
        affinity_slack=FLAGS.affinity_slack,
        max_redispatch=FLAGS.max_redispatch,
        heartbeat_timeout_s=FLAGS.heartbeat_timeout,
        disaggregate=FLAGS.disaggregate,
        telemetry=telemetry,
        ha=ha,
        **_supervision_kwargs(),
    )
    for link in links:
        link.start_reader(router.inbox)
    logging.info(
        "router up: %d replica(s) x %d slots, affinity block %d%s%s%s",
        n, FLAGS.serve_slots, FLAGS.affinity_block or FLAGS.prefix_block,
        ", disaggregated prefill/decode" if FLAGS.disaggregate else "",
        ", supervised" if FLAGS.supervise else "",
        ", HA journal on" if ha else "",
    )
    if FLAGS.upgrade:
        status = router.start_upgrade(FLAGS.upgrade)
        if status.get("ok"):
            logging.info(
                "rolling upgrade started: %s -> version %s",
                FLAGS.upgrade, status.get("version"),
            )
        else:
            logging.error("upgrade refused: %s", status.get("error"))
    _serve_stdin(router, telemetry)


def run() -> None:
    define_router_flags()
    app.run(main)


if __name__ == "__main__":
    run()
