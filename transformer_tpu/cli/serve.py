"""Persistent serving loop: JSONL requests on stdin, JSONL responses on stdout.

    python -m transformer_tpu.cli.serve --export_path=model \
        --src_vocab_file=src.subwords --tgt_vocab_file=tgt.subwords

Each input line is either a JSON object or a raw sentence:

    {"src": "he goes to school"}            seq2seq translation
    {"src": "...", "beam": 4}               per-request beam override
    {"prompt": "...", "max_new": 32}        decoder-only LM continuation
    {"fill": "he [MASK] to school"}         encoder-only masked-LM fill
    he goes to school                       raw line == {"src": ...}
                                            (or prompt/fill per export kind)

One response line per request: {"translation": ...} / {"continuation": ...}
/ {"filled": ..., "candidates": ...}, or {"error": ...} for malformed requests (the loop never dies on one bad
line). Responses come back in request order.

Two levels of amortization make this the right shape for a long-lived TPU
process:

- **Compile caching**: the decode program caches per (batch, width) bucket,
  so request N hits the cache request 1 paid for (vs one `cli.translate`
  process per request, which recompiles every time).
- **Request batching**: a reader thread queues stdin lines; each loop
  iteration drains up to ``--serve_batch`` ALREADY-QUEUED requests (never
  waits for stragglers — an idle queue means a batch of 1 and zero added
  latency), groups them by decode signature (kind + max_len + beam /
  sampling params), and runs ONE decode per group. Concurrent clients
  share the chip instead of serializing through batch-1 decodes.

Decoder-only (LM) exports additionally get **continuous batching**
(``--serve_slots``, default on): instead of decoding each drained batch to
completion, a step-level scheduler advances a fixed pool of KV-cache slots
one token per tick, retiring finished requests and admitting queued ones
mid-flight via single-pass chunked prefill (``--prefill_chunk``) — a
straggler with a long generation no longer holds a whole batch's chip time
hostage. ``--serve_slots=0`` restores the grouped decode-to-completion
path. ``--speculate_k`` adds speculative decoding on the same slot pool:
a drafter (``--draft_checkpoint`` model or the default n-gram
prompt-lookup, ``--draft_ngram``) proposes candidate tokens and one
multi-token verify forward scores them all — more tokens per
bandwidth-bound forward, byte-identical greedy answers.
``--prefix_cache_mb`` adds a cross-request prefix KV cache: completed
prompt KV is kept host-side in a radix trie of token-aligned blocks
(``--prefix_block``), and a new request restores its longest shared
prefix straight into its slot instead of re-forwarding it — shared
system prompts and retry storms stop paying prefill. See
docs/SERVING.md.

Telemetry: ``--metrics_jsonl`` streams structured events (per-request spans,
slot utilization) + periodic metric snapshots, and ``--metrics_port`` serves
a Prometheus ``/metrics`` scrape endpoint — docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
import queue
import sys
import threading
import time

from absl import app, flags, logging

FLAGS = flags.FLAGS


def define_serve_flags() -> None:
    from transformer_tpu.cli.flags import define_metrics_flags
    from transformer_tpu.cli.translate import define_export_serving_flags

    define_export_serving_flags()
    define_metrics_flags()
    flags.DEFINE_integer(
        "serve_batch", 8,
        "max already-queued requests aggregated into one decode (grouped by "
        "decode signature; 1 = the old request-at-a-time behavior)")
    flags.DEFINE_integer(
        "serve_slots", 8,
        "KV-cache slots for continuous (in-flight) batching of decoder-only "
        "LM requests: finished requests retire at step boundaries and queued "
        "ones are admitted mid-flight via chunked prefill. 0 = grouped "
        "decode-to-completion batching (the --serve_batch path). Ignored for "
        "seq2seq / fill-mask exports, which always use the grouped path.")
    flags.DEFINE_integer(
        "serve_max_total", 0,
        "per-slot KV budget (prompt + generated tokens) for continuous "
        "batching; 0 sizes it to the model's max_position")
    flags.DEFINE_integer(
        "prefill_chunk", 0,
        "split prompt prefill into chunks of this many tokens so activation "
        "memory stays bounded at long prompt lengths (0 = whole prompt in "
        "one forward); also used by grouped-path generate()")
    flags.DEFINE_integer(
        "speculate_k", 0,
        "speculative decoding lookahead for the continuous-batching path: "
        "a drafter proposes up to this many candidate tokens per step and "
        "one multi-token verify forward scores them all (greedy answers "
        "stay byte-identical; sampled requests use rejection-sampling "
        "acceptance). 0 = off. Incompatible with attention_window "
        "(rolling caches cannot roll back)")
    flags.DEFINE_string(
        "draft_checkpoint", "",
        "export directory of a small draft model SHARING the target "
        "tokenizer, used as the speculative drafter ('' = the model-free "
        "n-gram prompt-lookup drafter)")
    flags.DEFINE_integer(
        "draft_ngram", 3,
        "longest suffix n-gram the model-free drafter matches against "
        "earlier context (only used when --draft_checkpoint is unset)")
    flags.DEFINE_integer(
        "prefix_cache_mb", 0,
        "host-memory byte budget (MiB) for the cross-request prefix KV "
        "cache on the continuous-batching path: completed prompt KV is "
        "stored as token-aligned blocks in a radix trie and new requests "
        "restore their longest shared prefix instead of re-forwarding it "
        "(greedy answers byte-identical). 0 = off. Incompatible with "
        "attention_window (rolling caches evict absolute-position rows)")
    flags.DEFINE_integer(
        "prefix_block", 16,
        "prefix-cache block granularity in tokens: prompts share stored KV "
        "in units of this many positions (smaller = finer matching, more "
        "trie overhead)")
    flags.DEFINE_boolean(
        "prefix_verify_checksums", True,
        "re-verify each matched prefix-cache block's crc32 at admission "
        "(corrupt blocks are dropped instead of silently restored — "
        "docs/ROBUSTNESS.md). Costs O(matched KV bytes) of host CPU per "
        "hit; disable to trade integrity checking for admission latency")
    flags.DEFINE_enum(
        "kv_layout", "dense", ["dense", "paged"],
        "per-slot KV storage for the continuous-batching path: 'dense' "
        "reserves max_total rows per slot (the historical layout); "
        "'paged' backs every slot from ONE device-resident block pool "
        "through per-slot block tables (kernels/kv_pool.py) — resident KV "
        "proportional to used tokens, prefix-cache hits restored by "
        "block-table aliasing with zero host copies, byte-identical "
        "answers either way. Incompatible with attention_window")
    flags.DEFINE_integer(
        "kv_pool_blocks", 0,
        "paged KV pool size in blocks of --prefix_block tokens (0 = full "
        "provisioning: every slot can always reach --serve_max_total). "
        "Smaller pools bound resident KV by used tokens; under pressure "
        "the device-resident prefix tier spills to host and, as the last "
        "rung, the requesting slot answers a structured 'resource' error")
    flags.DEFINE_enum(
        "decode_kernel", "xla", ["xla", "paged_flash"],
        "decode/verify kernel for the paged continuous-batching path: "
        "'xla' gathers a dense view of each slot's KV through the block "
        "table (the bitwise parity reference and CPU fallback); "
        "'paged_flash' runs the fused Pallas kernels that read pool "
        "blocks in place (no gathered view) plus the fused "
        "residual+LN+FFN step — requires --kv_layout paged, a "
        "decoder-only config without attention_window; answers are "
        "byte-identical to 'xla'. Off-TPU backends run the kernels in "
        "Pallas interpret mode (a correctness path, not a fast one)")
    flags.DEFINE_integer(
        "max_backlog", 0,
        "bounded admission backpressure for the continuous-batching path: "
        "submissions beyond this many queued-but-unadmitted requests answer "
        "a structured 'backpressure' error immediately instead of growing "
        "the queue (0 = unbounded, the historical behavior)")
    flags.DEFINE_integer(
        "admission_retries", 2,
        "bounded retries (with jittered exponential backoff) for transient "
        "admission faults on the continuous-batching path; exhausted "
        "retries answer a structured 'transient' error")
    flags.DEFINE_integer(
        "breaker_threshold", 3,
        "consecutive faults before a serving circuit breaker (speculative "
        "decoding / prefix cache) fails its subsystem open to the plain "
        "byte-parity path — docs/ROBUSTNESS.md")
    flags.DEFINE_float(
        "breaker_cooldown", 30.0,
        "seconds an open circuit breaker waits before one half-open "
        "re-probe of its subsystem")
    flags.DEFINE_string(
        "fault_spec", "",
        "deterministic fault injection for chaos drills (docs/ROBUSTNESS.md "
        "grammar), e.g. 'serve.prefill:p=0.25,seed=7;obs.emit:at=5'. "
        "'' = disarmed (zero overhead)")
    flags.DEFINE_string(
        "slo_spec", "",
        "SLO objectives evaluated as multi-window burn rates over the "
        "answer stream (docs/OBSERVABILITY.md grammar), e.g. "
        "'availability:objective=0.999;ttft_p95:threshold=0.5'. '' = the "
        "default objectives when telemetry is on; 'none' = off. Surfaced "
        "as serve_slo_burn_* gauges + slo.burn events; report offline with "
        "`python -m transformer_tpu.obs slo <jsonl>`")


def _parse_line(line: str, model_cfg) -> dict:
    """One stdin line -> request dict (raises on malformed input)."""
    if line.startswith("{"):
        req = json.loads(line)
        if not isinstance(req, dict):
            raise ValueError("request must be a JSON object")
        return req
    # Raw-line convenience maps to whichever request kind this export serves.
    if model_cfg.encoder_only:
        return {"fill": line}
    return {"prompt" if model_cfg.decoder_only else "src": line}


def _signature(
    req: dict, model_cfg, default_max_len: int, default_beam: int
) -> tuple | None:
    """Batching key: requests in the same group run as ONE decode call.
    None = malformed or kind-mismatched (answered individually)."""
    if model_cfg.encoder_only:
        if "fill" not in req:
            return None
        top_k = int(req.get("top_k", 5))
        if not 1 <= top_k <= 100:
            # Raised (not returned) so the caller's except answers THIS
            # request with the message instead of a routing error.
            raise ValueError(f"top_k must be in [1, 100], got {top_k}")
        return ("fill", top_k)
    # Non-MLM exports ignore a stray 'fill' key (unknown keys never
    # changed routing before the fill kind existed).
    if "src" in req:
        if model_cfg.decoder_only:
            return None
        return (
            "src",
            int(req.get("max_len", default_max_len)),
            int(req.get("beam", default_beam)),
        )
    if "prompt" in req:
        if not model_cfg.decoder_only:
            return None
        temperature = float(req.get("temperature", 0.0))
        return (
            "prompt",
            int(req.get("max_new", default_max_len)),
            temperature,
            int(req.get("top_k", 0)),
            float(req.get("top_p", 1.0)),
            # Per-request sampling seed: part of the signature because one
            # generate() call holds ONE rng for the whole batch (the
            # continuous scheduler honors seeds per-request; grouped serving
            # must answer seeded requests identically). Greedy decode never
            # touches the rng, so a stray seed must not split its groups.
            int(req.get("seed", 0)) if temperature > 0.0 else 0,
        )
    return None


def serve_lines(
    lines: list[str], params, model_cfg, src_tok, tgt_tok,
    default_max_len: int = 64, default_beam: int = 1,
    prefill_chunk: int = 0,
) -> list[dict]:
    """Answer a batch of request lines with one decode per signature group,
    preserving input order. Pure function of its inputs — the unit the
    batching test drives directly."""
    from transformer_tpu.train.decode import fill_mask, generate, translate

    responses: list[dict | None] = [None] * len(lines)
    groups: dict[tuple, list[tuple[int, dict]]] = {}
    kind = (
        "fill-mask" if model_cfg.encoder_only
        else "LM" if model_cfg.decoder_only else "seq2seq"
    )
    served_key = {"fill-mask": "fill", "LM": "prompt", "seq2seq": "src"}[kind]
    for i, line in enumerate(lines):
        try:
            req = _parse_line(line, model_cfg)
            # int()/float() on request fields can raise too ("beam": "four"):
            # inside the try so one bad request answers, never kills the loop.
            sig = _signature(req, model_cfg, default_max_len, default_beam)
        except Exception as e:  # noqa: BLE001 — bad line answers, never kills
            responses[i] = {"error": f"{type(e).__name__}: {e}"}
            continue
        if sig is not None and sig[0] == "prompt" and sig[2] > 0.0:
            # Sampled LM requests run batch-1: one lm_generate rng serves a
            # whole batch, so a co-batched sampled request's draws would
            # depend on its neighbors — the answer to a seeded request must
            # not change with traffic (and must match the continuous
            # scheduler, which picks per-row).
            sig = (*sig, i)
        if sig is None:
            sent = next(
                (k for k in ("src", "prompt", "fill") if k in req), None
            )
            if sent:
                msg = f"{kind} export serves '{served_key}', not '{sent}'"
            else:
                msg = (
                    "request needs 'src' (seq2seq), 'prompt' (LM) or "
                    "'fill' (masked-LM)"
                )
            responses[i] = {"error": msg}
            continue
        groups.setdefault(sig, []).append((i, req))

    def run_group(sig, members) -> list[dict]:
        if sig[0] == "fill":
            _, top_k = sig
            outs = fill_mask(
                params, model_cfg, tgt_tok,
                [str(req["fill"]) for _, req in members],
                top_k=top_k,
            )
            # Tuples -> lists for clean JSON round-trips.
            return [
                {
                    "filled": o["filled"],
                    "candidates": [
                        [[t, p] for t, p in cands] for cands in o["candidates"]
                    ],
                }
                for o in outs
            ]
        if sig[0] == "src":
            _, max_len, beam = sig
            outs = translate(
                params, model_cfg, src_tok, tgt_tok,
                [str(req["src"]) for _, req in members],
                max_len=max_len, beam_size=beam,
            )
            return [{"translation": out} for out in outs]
        # Sampled signatures carry a trailing per-request discriminator
        # (batch-1 semantics above) — slice the decode params off the front.
        _, max_new, temperature, top_k, top_p, seed = sig[:6]
        outs = generate(
            params, model_cfg, tgt_tok,
            [str(req["prompt"]) for _, req in members],
            max_new=max_new, temperature=temperature,
            top_k=top_k, top_p=top_p, seed=seed, prefill_chunk=prefill_chunk,
        )
        return [{"continuation": out} for out in outs]

    for sig, members in groups.items():
        try:
            outs = run_group(sig, members)
        except Exception:  # noqa: BLE001
            # One request can poison a whole group (e.g. an over-length
            # prompt). Preserve per-request error isolation: retry each
            # member alone so innocent co-batched requests still succeed.
            outs = []
            for member in members:
                try:
                    outs.extend(run_group(sig, [member]))
                except Exception as e:  # noqa: BLE001 — answers, never kills
                    outs.append({"error": f"{type(e).__name__}: {e}"})
        for (i, _), out in zip(members, outs):
            responses[i] = out
    return [
        r if r is not None else {"error": "internal: unanswered"}
        for r in responses
    ]


class _RoutingError(ValueError):
    """Kind-mismatch the grouped path answers with the BARE message (its
    sig-is-None branch builds the response directly, no exception-type
    prefix) — serve_continuous must answer it the same way."""


def _route_lm_request(line: str, model_cfg) -> dict:
    """One stdin line -> LM request dict for the continuous scheduler
    (raises with the same message shapes ``serve_lines`` answers with)."""
    req = _parse_line(line, model_cfg)
    # Mirror _signature's key precedence exactly — 'src' rejects even when
    # 'prompt' is also present, a stray 'fill' next to 'prompt' is ignored —
    # so --serve_slots=0 and the continuous path answer any given line the
    # same way.
    if "src" in req:
        raise _RoutingError("LM export serves 'prompt', not 'src'")
    if "prompt" not in req:
        if "fill" in req:
            raise _RoutingError("LM export serves 'prompt', not 'fill'")
        raise _RoutingError(
            "request needs 'src' (seq2seq), 'prompt' (LM) or "
            "'fill' (masked-LM)"
        )
    return req


def serve_continuous(q: queue.Queue, sched, model_cfg, telemetry=None) -> None:
    """Drive the continuous-batching scheduler from the stdin queue: ingest
    whatever is already queued (malformed lines answer immediately via a
    reserved output position — ordering is preserved), admit queued requests
    into free slots, advance every occupied slot one token, flush responses
    completed in arrival order. Blocks on stdin ONLY when nothing is
    in-flight and nothing is waiting to flush — an in-flight request never
    waits on a quiet client. Ingestion stops while the scheduler's backlog
    plus its unflushed responses reach the cap, so the reader thread's
    bounded queue keeps exerting stdin backpressure (a piped multi-GB
    request file must not accumulate in the scheduler's host-side queue —
    and a flood of instantly error-answered lines must not accumulate in
    its done-buffer — either)."""
    eof = False
    backlog_cap = max(1, sched.num_slots) * 8
    while not eof or sched.busy:
        while not eof and sched.backlog + sched.ready_count < backlog_cap:
            try:
                line = q.get(block=not (sched.busy or sched.has_ready))
            except queue.Empty:
                break
            if line is None:
                eof = True
                break
            line = line.strip()
            if not line:
                continue
            try:
                req = _route_lm_request(line, model_cfg)
            except _RoutingError as e:
                # Error-taxonomy codes (docs/ROBUSTNESS.md) ride along; the
                # `error` string stays byte-identical to the grouped path's.
                sched.submit_done({"error": str(e), "code": "routing"})
                continue
            except Exception as e:  # noqa: BLE001 — bad line answers, never kills
                sched.submit_done(
                    {"error": f"{type(e).__name__}: {e}", "code": "validation"}
                )
                continue
            sched.submit(req)
        sched.admit()
        sched.step()
        sched.idle_backoff()
        for resp in sched.drain_ready():
            print(json.dumps(resp), flush=True)
    if telemetry is not None:
        telemetry.maybe_flush(force=True)




def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import flags_to_telemetry, maybe_force_platform

    maybe_force_platform()
    if FLAGS.fault_spec:
        # Arm the fault plane BEFORE any subsystem starts: injection points
        # fire deterministically per (seed, point, call-index), so a chaos
        # drill replays exactly (docs/ROBUSTNESS.md).
        from transformer_tpu.serve import resilience

        resilience.install(resilience.FaultPlane.parse(FLAGS.fault_spec))
        logging.info("fault plane armed: %s", FLAGS.fault_spec)
    telemetry = flags_to_telemetry()
    if FLAGS.slo_spec and FLAGS.slo_spec.lower() not in ("none", "off") \
            and telemetry is None:
        # The engine's whole output is gauges + slo.burn events: without a
        # telemetry sink an explicit spec would silently enforce nothing.
        logging.warning(
            "--slo_spec needs --metrics_jsonl (or --metrics_port) to "
            "surface burn rates; SLO evaluation disabled for this run"
        )

    from transformer_tpu.cli.translate import load_export
    from transformer_tpu.data.tokenizer import SubwordTokenizer

    params, model_cfg = load_export(
        FLAGS.export_path, kv_cache_int8=FLAGS.kv_cache_int8
    )
    if model_cfg.decoder_only or model_cfg.encoder_only:
        src_tok = tgt_tok = SubwordTokenizer.load(FLAGS.tgt_vocab_file)
    else:
        src_tok = SubwordTokenizer.load(FLAGS.src_vocab_file)
        tgt_tok = (
            src_tok
            if FLAGS.tgt_vocab_file == FLAGS.src_vocab_file
            else SubwordTokenizer.load(FLAGS.tgt_vocab_file)
        )
    continuous = model_cfg.decoder_only and FLAGS.serve_slots > 0
    logging.info(
        "serving %s from %s; one JSONL request per stdin line, %s",
        "fill-mask" if model_cfg.encoder_only
        else "LM" if model_cfg.decoder_only else "seq2seq",
        FLAGS.export_path,
        f"continuous batching over {FLAGS.serve_slots} cache slots"
        if continuous
        else f"batching up to {max(1, FLAGS.serve_batch)} queued requests "
        "per decode",
    )

    # Bounded queue: the reader thread blocks on put() once it is this far
    # ahead, restoring the stdin backpressure a blocking read loop has — a
    # piped multi-GB request file must not accumulate in host memory.
    from transformer_tpu.serve.replica import stdin_reader

    q: queue.Queue = queue.Queue(maxsize=max(1, FLAGS.serve_batch) * 8)
    threading.Thread(target=stdin_reader, args=(q,), daemon=True).start()
    if continuous:
        from transformer_tpu.obs.slo import DEFAULT_SLOS
        from transformer_tpu.serve import (
            ContinuousScheduler,
            PrefixCache,
            drafter_from_flags,
        )

        drafter = None
        if FLAGS.speculate_k > 0:
            drafter = drafter_from_flags(
                FLAGS.draft_checkpoint, FLAGS.draft_ngram,
                FLAGS.serve_max_total or model_cfg.max_position + 1,
                eos_id=tgt_tok.eos_id,
                target_vocab_size=model_cfg.target_vocab_size,
            )
        prefix_cache = None
        if FLAGS.prefix_cache_mb > 0:
            prefix_cache = PrefixCache(
                model_cfg,
                block_tokens=FLAGS.prefix_block,
                budget_mb=FLAGS.prefix_cache_mb,
                verify_checksums=FLAGS.prefix_verify_checksums,
            )
        # Price the pool before allocating it: the cost model's dense-KV
        # budget (analysis/costs.py — the number the paged-KV refactor is
        # measured against) in the startup log, so an operator sees the
        # device bytes a --serve_slots/--serve_max_total choice commits to.
        from transformer_tpu.analysis.costs import kv_cache_bytes

        # Same sizing as the scheduler's SlotPool: max_total plus the
        # speculative lookahead slack (verify rows write k extra rows).
        pool_tokens = (
            FLAGS.serve_max_total or model_cfg.max_position + 1
        ) + max(0, FLAGS.speculate_k)
        kv = kv_cache_bytes(model_cfg, pool_tokens)
        if FLAGS.kv_layout == "paged":
            blk = FLAGS.prefix_block
            slot_blocks = -(-pool_tokens // blk)
            n_blocks = FLAGS.kv_pool_blocks or (
                1 + FLAGS.serve_slots * slot_blocks
            )
            pool_bytes = n_blocks * blk * kv["bytes_per_token"]
            logging.info(
                "paged KV pool budget: %d blocks x %d tokens = %.1f MiB "
                "(%d bytes/token; dense layout would reserve %.1f MiB)",
                n_blocks, blk, pool_bytes / (1 << 20),
                kv["bytes_per_token"],
                FLAGS.serve_slots * kv["bytes_per_slot"] / (1 << 20),
            )
        else:
            logging.info(
                "slot pool KV budget: %d slots x %d bytes/slot = %.1f MiB "
                "(%d bytes/token, dense max_len layout)",
                FLAGS.serve_slots, kv["bytes_per_slot"],
                FLAGS.serve_slots * kv["bytes_per_slot"] / (1 << 20),
                kv["bytes_per_token"],
            )
        sched = ContinuousScheduler(
            params, model_cfg, tgt_tok,
            num_slots=FLAGS.serve_slots,
            max_total=FLAGS.serve_max_total or None,
            prefill_chunk=FLAGS.prefill_chunk,
            default_max_new=FLAGS.max_len,
            telemetry=telemetry,
            speculate_k=FLAGS.speculate_k,
            drafter=drafter,
            prefix_cache=prefix_cache,
            max_backlog=FLAGS.max_backlog,
            kv_layout=FLAGS.kv_layout,
            kv_block=FLAGS.prefix_block,
            kv_pool_blocks=FLAGS.kv_pool_blocks,
            decode_kernel=FLAGS.decode_kernel,
            admission_retries=FLAGS.admission_retries,
            breaker_threshold=FLAGS.breaker_threshold,
            breaker_cooldown_s=FLAGS.breaker_cooldown,
            # '' = the default objective set (only consulted when telemetry
            # is on — the engine's whole output is gauges + events);
            # 'none' parses to an empty tuple and disables it.
            slos=FLAGS.slo_spec or (DEFAULT_SLOS if telemetry else None),
        )
        serve_continuous(q, sched, model_cfg, telemetry=telemetry)
        if telemetry is not None:
            telemetry.close()
        return
    eof = False
    while not eof:
        first = q.get()
        if first is None:
            break
        lines = [first]
        # Drain whatever is ALREADY queued (no waiting: an idle queue means
        # a batch of one and zero added latency).
        while len(lines) < max(1, FLAGS.serve_batch):
            try:
                nxt = q.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                eof = True
                break
            lines.append(nxt)
        lines = [line.strip() for line in lines]
        lines = [line for line in lines if line]
        if not lines:
            continue
        t0 = time.perf_counter()
        responses = serve_lines(
            lines, params, model_cfg, src_tok, tgt_tok,
            default_max_len=FLAGS.max_len, default_beam=FLAGS.beam,
            prefill_chunk=FLAGS.prefill_chunk,
        )
        if telemetry is not None:
            # Grouped path: one span per drained batch (the per-request
            # breakdown is the continuous scheduler's richer contract).
            batch_s = time.perf_counter() - t0
            errors = sum(1 for r in responses if "error" in r)
            reg = telemetry.registry
            reg.counter("serve_requests_total").inc(len(responses))
            if errors:
                reg.counter("serve_errors_total").inc(errors)
            reg.histogram(
                "serve_batch_seconds", "one grouped decode batch"
            ).observe(batch_s)
            telemetry.emit(
                "serve.batch", size=len(responses), errors=errors,
                batch_s=round(batch_s, 6),
            )
            telemetry.maybe_flush()
        for resp in responses:
            print(json.dumps(resp), flush=True)
    if telemetry is not None:
        telemetry.close()


def run() -> None:
    define_serve_flags()
    app.run(main)


if __name__ == "__main__":
    run()
