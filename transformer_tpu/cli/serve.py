"""Persistent serving loop: JSONL requests on stdin, JSONL responses on stdout.

    python -m transformer_tpu.cli.serve --export_path=model \
        --src_vocab_file=src.subwords --tgt_vocab_file=tgt.subwords

Each input line is either a JSON object or a raw sentence:

    {"src": "he goes to school"}            seq2seq translation
    {"src": "...", "beam": 4}               per-request beam override
    {"prompt": "...", "max_new": 32}        decoder-only LM continuation
    he goes to school                       raw line == {"src": ...}

One response line per request: {"translation": ...} / {"continuation": ...},
or {"error": ...} for malformed requests (the loop never dies on one bad
line). The point of the loop (vs one `cli.translate` invocation per
request) is compile amortization: the decode program caches per
(batch, width) bucket, so request N hits the cache request 1 paid for —
the right shape for a long-lived TPU serving process.
"""

from __future__ import annotations

import json
import sys

from absl import app, flags, logging

FLAGS = flags.FLAGS


def define_serve_flags() -> None:
    from transformer_tpu.cli.translate import define_export_serving_flags

    define_export_serving_flags()


def _handle(req: dict, params, model_cfg, src_tok, tgt_tok) -> dict:
    from transformer_tpu.train.decode import generate, translate

    if "src" in req:
        if model_cfg.decoder_only:
            return {"error": "decoder-only export serves 'prompt', not 'src'"}
        out = translate(
            params, model_cfg, src_tok, tgt_tok, [str(req["src"])],
            max_len=int(req.get("max_len", FLAGS.max_len)),
            beam_size=int(req.get("beam", FLAGS.beam)),
        )
        return {"translation": out[0]}
    if "prompt" in req:
        if not model_cfg.decoder_only:
            return {"error": "seq2seq export serves 'src', not 'prompt'"}
        out = generate(
            params, model_cfg, tgt_tok, [str(req["prompt"])],
            max_new=int(req.get("max_new", FLAGS.max_len)),
            temperature=float(req.get("temperature", 0.0)),
            top_k=int(req.get("top_k", 0)),
            top_p=float(req.get("top_p", 1.0)),
        )
        return {"continuation": out[0]}
    return {"error": "request needs 'src' (seq2seq) or 'prompt' (LM)"}


def main(argv) -> None:
    del argv
    if FLAGS.platform:
        import jax

        jax.config.update("jax_platforms", FLAGS.platform)

    from transformer_tpu.cli.translate import load_export
    from transformer_tpu.data.tokenizer import SubwordTokenizer

    params, model_cfg = load_export(
        FLAGS.export_path, kv_cache_int8=FLAGS.kv_cache_int8
    )
    if model_cfg.decoder_only:
        src_tok = tgt_tok = SubwordTokenizer.load(FLAGS.tgt_vocab_file)
    else:
        src_tok = SubwordTokenizer.load(FLAGS.src_vocab_file)
        tgt_tok = (
            src_tok
            if FLAGS.tgt_vocab_file == FLAGS.src_vocab_file
            else SubwordTokenizer.load(FLAGS.tgt_vocab_file)
        )
    logging.info("serving %s from %s; one JSONL request per stdin line",
                 "LM" if model_cfg.decoder_only else "seq2seq",
                 FLAGS.export_path)

    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            if line.startswith("{"):
                req = json.loads(line)
            else:
                # Raw-line convenience maps to whichever request kind this
                # export actually serves.
                key = "prompt" if model_cfg.decoder_only else "src"
                req = {key: line}
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
            resp = _handle(req, params, model_cfg, src_tok, tgt_tok)
        except Exception as e:  # noqa: BLE001 — one bad line must not kill the loop
            resp = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps(resp), flush=True)


def run() -> None:
    define_serve_flags()
    app.run(main)


if __name__ == "__main__":
    run()
