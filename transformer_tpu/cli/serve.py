"""Persistent serving loop: JSONL requests on stdin, JSONL responses on stdout.

    python -m transformer_tpu.cli.serve --export_path=model \
        --src_vocab_file=src.subwords --tgt_vocab_file=tgt.subwords

Each input line is either a JSON object or a raw sentence:

    {"src": "he goes to school"}            seq2seq translation
    {"src": "...", "beam": 4}               per-request beam override
    {"prompt": "...", "max_new": 32}        decoder-only LM continuation
    {"fill": "he [MASK] to school"}         encoder-only masked-LM fill
    he goes to school                       raw line == {"src": ...}
                                            (or prompt/fill per export kind)

One response line per request: {"translation": ...} / {"continuation": ...}
/ {"filled": ..., "candidates": ...}, or {"error": ...} for malformed requests (the loop never dies on one bad
line). Responses come back in request order.

Two levels of amortization make this the right shape for a long-lived TPU
process:

- **Compile caching**: the decode program caches per (batch, width) bucket,
  so request N hits the cache request 1 paid for (vs one `cli.translate`
  process per request, which recompiles every time).
- **Request batching**: a reader thread queues stdin lines; each loop
  iteration drains up to ``--serve_batch`` ALREADY-QUEUED requests (never
  waits for stragglers — an idle queue means a batch of 1 and zero added
  latency), groups them by decode signature (kind + max_len + beam /
  sampling params), and runs ONE decode per group. Concurrent clients
  share the chip instead of serializing through batch-1 decodes.
"""

from __future__ import annotations

import json
import queue
import sys
import threading

from absl import app, flags, logging

FLAGS = flags.FLAGS


def define_serve_flags() -> None:
    from transformer_tpu.cli.translate import define_export_serving_flags

    define_export_serving_flags()
    flags.DEFINE_integer(
        "serve_batch", 8,
        "max already-queued requests aggregated into one decode (grouped by "
        "decode signature; 1 = the old request-at-a-time behavior)")


def _parse_line(line: str, model_cfg) -> dict:
    """One stdin line -> request dict (raises on malformed input)."""
    if line.startswith("{"):
        req = json.loads(line)
        if not isinstance(req, dict):
            raise ValueError("request must be a JSON object")
        return req
    # Raw-line convenience maps to whichever request kind this export serves.
    if model_cfg.encoder_only:
        return {"fill": line}
    return {"prompt" if model_cfg.decoder_only else "src": line}


def _signature(
    req: dict, model_cfg, default_max_len: int, default_beam: int
) -> tuple | None:
    """Batching key: requests in the same group run as ONE decode call.
    None = malformed or kind-mismatched (answered individually)."""
    if model_cfg.encoder_only:
        if "fill" not in req:
            return None
        top_k = int(req.get("top_k", 5))
        if not 1 <= top_k <= 100:
            # Raised (not returned) so the caller's except answers THIS
            # request with the message instead of a routing error.
            raise ValueError(f"top_k must be in [1, 100], got {top_k}")
        return ("fill", top_k)
    # Non-MLM exports ignore a stray 'fill' key (unknown keys never
    # changed routing before the fill kind existed).
    if "src" in req:
        if model_cfg.decoder_only:
            return None
        return (
            "src",
            int(req.get("max_len", default_max_len)),
            int(req.get("beam", default_beam)),
        )
    if "prompt" in req:
        if not model_cfg.decoder_only:
            return None
        return (
            "prompt",
            int(req.get("max_new", default_max_len)),
            float(req.get("temperature", 0.0)),
            int(req.get("top_k", 0)),
            float(req.get("top_p", 1.0)),
        )
    return None


def serve_lines(
    lines: list[str], params, model_cfg, src_tok, tgt_tok,
    default_max_len: int = 64, default_beam: int = 1,
) -> list[dict]:
    """Answer a batch of request lines with one decode per signature group,
    preserving input order. Pure function of its inputs — the unit the
    batching test drives directly."""
    from transformer_tpu.train.decode import fill_mask, generate, translate

    responses: list[dict | None] = [None] * len(lines)
    groups: dict[tuple, list[tuple[int, dict]]] = {}
    kind = (
        "fill-mask" if model_cfg.encoder_only
        else "LM" if model_cfg.decoder_only else "seq2seq"
    )
    served_key = {"fill-mask": "fill", "LM": "prompt", "seq2seq": "src"}[kind]
    for i, line in enumerate(lines):
        try:
            req = _parse_line(line, model_cfg)
            # int()/float() on request fields can raise too ("beam": "four"):
            # inside the try so one bad request answers, never kills the loop.
            sig = _signature(req, model_cfg, default_max_len, default_beam)
        except Exception as e:  # noqa: BLE001 — bad line answers, never kills
            responses[i] = {"error": f"{type(e).__name__}: {e}"}
            continue
        if sig is None:
            sent = next(
                (k for k in ("src", "prompt", "fill") if k in req), None
            )
            if sent:
                msg = f"{kind} export serves '{served_key}', not '{sent}'"
            else:
                msg = (
                    "request needs 'src' (seq2seq), 'prompt' (LM) or "
                    "'fill' (masked-LM)"
                )
            responses[i] = {"error": msg}
            continue
        groups.setdefault(sig, []).append((i, req))

    def run_group(sig, members) -> list[dict]:
        if sig[0] == "fill":
            _, top_k = sig
            outs = fill_mask(
                params, model_cfg, tgt_tok,
                [str(req["fill"]) for _, req in members],
                top_k=top_k,
            )
            # Tuples -> lists for clean JSON round-trips.
            return [
                {
                    "filled": o["filled"],
                    "candidates": [
                        [[t, p] for t, p in cands] for cands in o["candidates"]
                    ],
                }
                for o in outs
            ]
        if sig[0] == "src":
            _, max_len, beam = sig
            outs = translate(
                params, model_cfg, src_tok, tgt_tok,
                [str(req["src"]) for _, req in members],
                max_len=max_len, beam_size=beam,
            )
            return [{"translation": out} for out in outs]
        _, max_new, temperature, top_k, top_p = sig
        outs = generate(
            params, model_cfg, tgt_tok,
            [str(req["prompt"]) for _, req in members],
            max_new=max_new, temperature=temperature,
            top_k=top_k, top_p=top_p,
        )
        return [{"continuation": out} for out in outs]

    for sig, members in groups.items():
        try:
            outs = run_group(sig, members)
        except Exception:  # noqa: BLE001
            # One request can poison a whole group (e.g. an over-length
            # prompt). Preserve per-request error isolation: retry each
            # member alone so innocent co-batched requests still succeed.
            outs = []
            for member in members:
                try:
                    outs.extend(run_group(sig, [member]))
                except Exception as e:  # noqa: BLE001 — answers, never kills
                    outs.append({"error": f"{type(e).__name__}: {e}"})
        for (i, _), out in zip(members, outs):
            responses[i] = out
    return [
        r if r is not None else {"error": "internal: unanswered"}
        for r in responses
    ]


def _stdin_reader(q: queue.Queue) -> None:
    for line in sys.stdin:
        q.put(line)
    q.put(None)  # EOF sentinel


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import maybe_force_platform

    maybe_force_platform()

    from transformer_tpu.cli.translate import load_export
    from transformer_tpu.data.tokenizer import SubwordTokenizer

    params, model_cfg = load_export(
        FLAGS.export_path, kv_cache_int8=FLAGS.kv_cache_int8
    )
    if model_cfg.decoder_only or model_cfg.encoder_only:
        src_tok = tgt_tok = SubwordTokenizer.load(FLAGS.tgt_vocab_file)
    else:
        src_tok = SubwordTokenizer.load(FLAGS.src_vocab_file)
        tgt_tok = (
            src_tok
            if FLAGS.tgt_vocab_file == FLAGS.src_vocab_file
            else SubwordTokenizer.load(FLAGS.tgt_vocab_file)
        )
    logging.info(
        "serving %s from %s; one JSONL request per stdin line, batching up "
        "to %d queued requests per decode",
        "fill-mask" if model_cfg.encoder_only
        else "LM" if model_cfg.decoder_only else "seq2seq",
        FLAGS.export_path, max(1, FLAGS.serve_batch),
    )

    # Bounded queue: the reader thread blocks on put() once it is this far
    # ahead, restoring the stdin backpressure a blocking read loop has — a
    # piped multi-GB request file must not accumulate in host memory.
    q: queue.Queue = queue.Queue(maxsize=max(1, FLAGS.serve_batch) * 8)
    threading.Thread(target=_stdin_reader, args=(q,), daemon=True).start()
    eof = False
    while not eof:
        first = q.get()
        if first is None:
            break
        lines = [first]
        # Drain whatever is ALREADY queued (no waiting: an idle queue means
        # a batch of one and zero added latency).
        while len(lines) < max(1, FLAGS.serve_batch):
            try:
                nxt = q.get_nowait()
            except queue.Empty:
                break
            if nxt is None:
                eof = True
                break
            lines.append(nxt)
        lines = [line.strip() for line in lines]
        lines = [line for line in lines if line]
        if not lines:
            continue
        for resp in serve_lines(
            lines, params, model_cfg, src_tok, tgt_tok,
            default_max_len=FLAGS.max_len, default_beam=FLAGS.beam,
        ):
            print(json.dumps(resp), flush=True)


def run() -> None:
    define_serve_flags()
    app.run(main)


if __name__ == "__main__":
    run()
