"""Score a trained model: corpus BLEU (seq2seq) or perplexity (LM).

    python -m transformer_tpu.cli.evaluate --export_path=model \
        --src_file=data/src-test.txt --tgt_file=data/tgt-test.txt \
        --src_vocab_file=src_vocab.subwords --tgt_vocab_file=tgt_vocab.subwords

Prints one JSON line on stdout so benchmark harnesses can parse it —
``{"bleu": ..., "n": ..., "beam": ...}`` for seq2seq exports, or
``{"perplexity": ..., "n_tokens": ...}`` when the export is a
``decoder_only`` LM (scored on ``--tgt_file``; the src flags are unused).
Progress goes to logging/stderr.
"""

from __future__ import annotations

import json

from absl import app, flags, logging

FLAGS = flags.FLAGS


def define_evaluate_flags() -> None:
    flags.DEFINE_string("export_path", "model", "directory written by export_params")
    flags.DEFINE_string("src_file", "data/src-test.txt", "source sentences, one per line")
    flags.DEFINE_string("tgt_file", "data/tgt-test.txt", "reference translations")
    flags.DEFINE_string("src_vocab_file", "src_vocab.subwords", "source subword vocab")
    flags.DEFINE_string("tgt_vocab_file", "tgt_vocab.subwords", "target subword vocab")
    flags.DEFINE_integer("batch_size", 64, "decode batch size")
    flags.DEFINE_integer("max_len", 64, "max generated tokens per sentence")
    flags.DEFINE_integer("beam", 1, "beam size (1 = greedy)")
    flags.DEFINE_integer("limit", 0, "evaluate only the first N pairs (0 = all)")
    flags.DEFINE_string("platform", "", "force a jax platform (e.g. 'cpu') before first use")
    flags.DEFINE_boolean(
        "kv_cache_int8", False,
        "decode with an int8-quantized KV cache (~2-4x less cache HBM; "
        "serving-time choice, independent of the export)")


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import maybe_force_platform

    maybe_force_platform()

    from transformer_tpu.cli.translate import load_export
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.train.evaluate import (
        bleu_on_pairs,
        perplexity_on_lines,
        read_lines,
    )

    params, model_cfg = load_export(FLAGS.export_path, kv_cache_int8=FLAGS.kv_cache_int8)
    if model_cfg.decoder_only:
        # LM family: no translation to score — report token perplexity on
        # the target-side text instead.
        tok = SubwordTokenizer.load(FLAGS.tgt_vocab_file)
        lines = read_lines(FLAGS.tgt_file)
        if FLAGS.limit:
            lines = lines[: FLAGS.limit]
        ppl, n_tokens = perplexity_on_lines(
            params, model_cfg, tok, lines,
            batch_size=FLAGS.batch_size, log_fn=logging.info,
        )
        logging.info("perplexity %.2f over %d tokens", ppl, n_tokens)
        print(json.dumps({"perplexity": round(ppl, 3), "n_tokens": n_tokens}))
        return
    src_tok = SubwordTokenizer.load(FLAGS.src_vocab_file)
    tgt_tok = SubwordTokenizer.load(FLAGS.tgt_vocab_file)
    src_lines = read_lines(FLAGS.src_file)
    ref_lines = read_lines(FLAGS.tgt_file)
    if FLAGS.limit:
        src_lines = src_lines[: FLAGS.limit]
        ref_lines = ref_lines[: FLAGS.limit]
    bleu, _ = bleu_on_pairs(
        params, model_cfg, src_tok, tgt_tok, src_lines, ref_lines,
        batch_size=FLAGS.batch_size, max_len=FLAGS.max_len,
        beam_size=FLAGS.beam,
        log_fn=logging.info,
    )
    logging.info("BLEU %.2f on %d pairs (beam %d)", bleu, len(src_lines), FLAGS.beam)
    print(json.dumps({"bleu": round(bleu, 2), "n": len(src_lines), "beam": FLAGS.beam}))


def run() -> None:
    define_evaluate_flags()
    app.run(main)


if __name__ == "__main__":
    run()
