"""Single-device training entry point.

Counterpart of the reference's ``python train.py`` (``train.py:216-251``):
load data → build model → train → restore → sample greedy decode → export.
Run:

    python -m transformer_tpu.cli.train --dataset_path=data --epochs=4

Differences by design (SURVEY.md §2.3 fixes): restore happens *before*
training; the demo decode uses target-tokenizer specials, stops on EOS and
detokenizes; checkpoints save on the intended cadence.
"""

from __future__ import annotations

import os

from absl import app, flags, logging

from transformer_tpu.cli.flags import (
    define_flags,
    flags_to_mesh_config,
    flags_to_model_config,
    flags_to_train_config,
    maybe_force_platform,
)

FLAGS = flags.FLAGS


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import apply_preset

    apply_preset()  # before ANY direct FLAGS read (e.g. decoder_only)
    maybe_force_platform()
    import jax

    from transformer_tpu.data import load_dataset
    from transformer_tpu.train import (
        AsyncCheckpointManager,
        CheckpointManager,
        Trainer,
        create_train_state,
    )
    from transformer_tpu.train.checkpoint import export_params
    from transformer_tpu.train.decode import translate

    train_cfg = flags_to_train_config()
    buckets = tuple(
        int(x) for x in FLAGS.length_buckets.split(",") if x.strip()
    )
    # LM-window mode: decoder-only causal LM and encoder-only masked LM
    # share the data path and the perplexity (not translate/BLEU) epilogue.
    lm_mode = FLAGS.decoder_only or FLAGS.objective == "mlm"
    if lm_mode:
        if buckets:
            raise app.UsageError(
                "--length_buckets applies to the seq2seq pipeline only; LM "
                "windows are already fixed-width (drop the flag with "
                "--decoder_only / --objective=mlm)"
            )
        # LM-window mode (causal decoder-only AND masked-LM encoder-only):
        # the target-side corpus as one chunked token stream.
        from transformer_tpu.data.pipeline import load_lm_splits

        train_ds, test_ds, tok = load_lm_splits(
            FLAGS.dataset_path,
            FLAGS.tgt_vocab_file,
            batch_size=train_cfg.batch_size,
            sequence_length=train_cfg.sequence_length,
            target_vocab_size=FLAGS.target_vocab_size,
            seed=train_cfg.seed,
        )
        src_tok = tgt_tok = tok
    else:
        train_ds, test_ds, src_tok, tgt_tok = load_dataset(
            FLAGS.dataset_path,
            FLAGS.src_vocab_file,
            FLAGS.tgt_vocab_file,
            batch_size=train_cfg.batch_size,
            sequence_length=train_cfg.sequence_length,
            target_vocab_size=FLAGS.target_vocab_size,
            seed=train_cfg.seed,
            # streaming reads the corpus line-by-line (O(buffer_size) host
            # memory) and excludes the native loader / bucket planner, which
            # need the in-memory example table.
            prefetch=FLAGS.native_loader and not FLAGS.streaming,
            length_buckets=buckets,
            streaming=FLAGS.streaming,
            buffer_size=FLAGS.buffer_size,
        )
    if FLAGS.streaming:
        # num_examples would force a full line-count scan of the corpus
        # before training — the exact startup cost streaming exists to avoid.
        logging.info(
            "data: streaming corpus (buffer %d), vocabs %d/%d",
            FLAGS.buffer_size, src_tok.vocab_size, tgt_tok.vocab_size,
        )
    else:
        logging.info(
            "data: %d train examples, vocabs %d/%d",
            train_ds.num_examples, src_tok.vocab_size, tgt_tok.vocab_size,
        )
    model_cfg = flags_to_model_config(
        src_tok.model_vocab_size, tgt_tok.model_vocab_size
    )
    state = create_train_state(
        jax.random.PRNGKey(train_cfg.seed), model_cfg, train_cfg
    )
    ckpt_cls = AsyncCheckpointManager if FLAGS.async_checkpoint else CheckpointManager
    ckpt = ckpt_cls(train_cfg.ckpt_path, train_cfg.max_ckpt_keep)
    import datetime

    stamp = datetime.datetime.now().strftime("%Y%m%d-%H%M%S")
    from transformer_tpu.cli.flags import flags_to_profiler, flags_to_telemetry

    telemetry = flags_to_telemetry()
    trainer = Trainer(
        model_cfg, train_cfg, state,
        log_dir=os.path.join(FLAGS.tb_log_dir, stamp),
        checkpoint=ckpt,
        log_fn=logging.info,
        profiler=flags_to_profiler(),
        telemetry=telemetry,
    )
    trainer.fit(train_ds, test_ds)
    if telemetry is not None:
        telemetry.close()

    if lm_mode:
        # LM quality metric: perplexity from fit()'s final-epoch full eval
        # (for MLM: pseudo-perplexity over the deterministically-masked
        # eval positions)
        # (trainer.evaluate already ran over the whole split; re-running it
        # here would double end-of-run eval time for the same number).
        if test_ds is not None and trainer.eval_metrics.weight > 0:
            import math

            logging.info(
                "eval loss %.4f, perplexity %.2f",
                trainer.eval_metrics.loss,
                math.exp(min(trainer.eval_metrics.loss, 30.0)),
            )
        elif test_ds is not None:
            logging.warning("eval split produced no tokens; no perplexity")
    else:
        sample = "he go to school"
        out = translate(
            trainer.state.params, model_cfg, src_tok, tgt_tok, sample,
            max_len=train_cfg.sequence_length,
        )
        logging.info("sample translation %r -> %r", sample, out[0])
    export_params(trainer.state.params, model_cfg, "model")
    logging.info("exported params to ./model")

    # End-of-run quality metric (BASELINE.json north star): corpus BLEU on
    # the test split, when one exists. The reference never computes any
    # translation-quality metric (token accuracy only, train.py:140-141).
    if FLAGS.eval_bleu and not lm_mode:
        from transformer_tpu.train.evaluate import bleu_on_test_files

        bleu_on_test_files(
            trainer.state.params, model_cfg, src_tok, tgt_tok,
            FLAGS.dataset_path,
            batch_size=train_cfg.batch_size,
            max_len=train_cfg.sequence_length,
            limit=FLAGS.bleu_limit,
            log_fn=logging.info,
        )


def run() -> None:
    define_flags()
    app.run(main)


if __name__ == "__main__":
    run()
