"""Flag surface.

Preserves the reference's 16-flag namespace verbatim (``utils.py:17-33``:
dataset_path, buffer_size, src_vocab_file, tgt_vocab_file, sequence_length,
epochs, batch_size, per_replica_batch_size, num_layers, d_model, dff,
num_heads, enable_function, max_ckpt_keep, ckpt_path, dropout_rate) and adds
the TPU-native knobs (mesh axes, dtype, platform, variants). ``flags_to_*``
materialize the namespace into the framework's config dataclasses — the
counterpart of ``flags_dict()`` + ``main(**kwargs)`` splatting
(``utils.py:36-62``, ``train.py:216-220``).
"""

from __future__ import annotations

from absl import flags

from transformer_tpu.config import MeshConfig, ModelConfig, TrainConfig

FLAGS = flags.FLAGS


# Literal so flag definition stays jax-import-free (the CLIs defer `import
# jax` into main() on purpose — env/platform setup must run first);
# tests/test_flags.py pins this against ops.ffn.FFN_ACTIVATIONS.
_FFN_ACTIVATION_NAMES = ("geglu", "gelu", "reglu", "relu", "silu", "swiglu")

# One-flag reproduction of the BASELINE.json benchmark configs: values land
# on flags the user did NOT set explicitly (explicit flags always win).
_PRESETS: dict[str, dict] = {
    "tiny": dict(num_layers=2, d_model=128, num_heads=4, dff=512, batch_size=64),
    "base": dict(num_layers=6, d_model=512, num_heads=8, dff=2048, batch_size=64),
    "big": dict(
        num_layers=6, d_model=1024, num_heads=16, dff=4096,
        label_smoothing=0.1, batch_size=32,
    ),
    "tied": dict(
        num_layers=6, d_model=512, num_heads=8, dff=2048,
        tie_embeddings=True, tie_output=True, batch_size=64,
    ),
    "long4k": dict(
        num_layers=6, d_model=512, num_heads=8, dff=2048,
        decoder_only=True, attention_impl="flash", sequence_length=4096,
        remat=True, batch_size=4,
    ),
}


def apply_preset() -> None:
    """Fold ``--preset`` values into unset flags (idempotent; called by the
    flags_to_* materializers so every CLI gets it)."""
    if not FLAGS.preset:
        return
    for name, value in _PRESETS[FLAGS.preset].items():
        if not FLAGS[name].present:
            setattr(FLAGS, name, value)


def define_metrics_flags() -> None:
    """Telemetry knobs (docs/OBSERVABILITY.md) — shared by the training
    CLIs (via ``define_flags``) and the export-serving CLIs (``cli.serve``
    defines its own surface), hence the idempotence guard."""
    if "metrics_jsonl" in FLAGS:
        return
    flags.DEFINE_string(
        "metrics_jsonl", "",
        "write structured telemetry (JSONL events + periodic metric "
        "snapshots) to this file; a Prometheus text exposition is rewritten "
        "alongside it at <file>.prom. Summarize with "
        "`python -m transformer_tpu.obs summarize <file>`. '' = off")
    flags.DEFINE_integer(
        "metrics_port", 0,
        "serve a Prometheus /metrics scrape endpoint on this port "
        "(0 = off; train/distributed_train/serve). Works with or without "
        "--metrics_jsonl")
    flags.DEFINE_float(
        "metrics_interval", 10.0,
        "seconds between periodic metric-snapshot flushes (prom file + "
        "metrics.snapshot events)")
    flags.DEFINE_boolean(
        "trace", False,
        "record hierarchical trace.span events (request-scoped distributed "
        "tracing, docs/OBSERVABILITY.md) into --metrics_jsonl; export with "
        "`python -m transformer_tpu.obs trace <file> --out trace.json` and "
        "load in chrome://tracing / Perfetto. Answers and compiled programs "
        "are unaffected (contract-checked)")
    flags.DEFINE_boolean(
        "profile_programs", True,
        "per-program dispatch profiler (obs/profile.py): clock every canned "
        "jitted program into perf_seconds_* histograms and roofline/drift "
        "gauges, sentinel measured-vs-banked drift (perf.drift events). "
        "Jaxpr-inert (contract-checked); report with "
        "`python -m transformer_tpu.obs roofline <file>`")
    flags.DEFINE_boolean(
        "flight_recorder", True,
        "always-on bounded flight recorder (obs/flight.py): keep the last "
        "seconds of events/spans/snapshots in memory and dump them to "
        "<metrics_jsonl>.flight.json on signal/close plus a periodic "
        "autodump (crash durability). Needs --metrics_jsonl")


def define_flags() -> None:
    flags.DEFINE_enum(
        "preset", "", ["", *sorted(_PRESETS)],
        "start from a BASELINE benchmark config (tiny/base/big/tied/long4k); "
        "explicitly-passed flags override preset values")
    # --- reference-surface flags (utils.py:18-33 defaults) ---
    flags.DEFINE_string("dataset_path", "data", "directory with src/tgt line files")
    flags.DEFINE_integer(
        "buffer_size", 100000,
        "shuffle buffer size: with --streaming this bounds host memory (the "
        "reference's utils.py:154 semantics); the in-memory path ignores it "
        "(full permutation is free there)")
    flags.DEFINE_string("src_vocab_file", "src_vocab.subwords", "source subword vocab path")
    flags.DEFINE_string("tgt_vocab_file", "tgt_vocab.subwords", "target subword vocab path")
    flags.DEFINE_integer("sequence_length", 50, "max sequence length (tokens incl. BOS/EOS)")
    flags.DEFINE_integer("epochs", 4, "training epochs")
    flags.DEFINE_integer("batch_size", 64, "global batch size")
    flags.DEFINE_integer("per_replica_batch_size", 16, "compat flag; derived from batch_size/mesh")
    flags.DEFINE_integer("num_layers", 4, "transformer layers per stack")
    flags.DEFINE_integer("d_model", 512, "model width")
    flags.DEFINE_integer("dff", 1024, "FFN hidden width")
    flags.DEFINE_integer("num_heads", 4, "attention heads")
    flags.DEFINE_integer(
        "num_kv_heads", 0,
        "grouped-query attention: k/v heads, each serving "
        "num_heads/num_kv_heads query heads (smaller decode KV cache); "
        "0 = num_heads (standard MHA)")
    flags.DEFINE_boolean("enable_function", True, "jit the train/eval steps (False = eager debug)")
    flags.DEFINE_integer("max_ckpt_keep", 5, "checkpoints to retain")
    flags.DEFINE_string("ckpt_path", "model_dist", "checkpoint directory")
    flags.DEFINE_float("dropout_rate", 0.1, "dropout rate")
    # --- framework extensions ---
    flags.DEFINE_integer("target_vocab_size", 2**15, "subword vocab build target")
    flags.DEFINE_integer(
        "warmup_steps", 60000,
        "LR warmup steps, shared by every --lr_schedule; the 60000 default "
        "is reference-noam parity — set a small value (hundreds) for "
        "cosine/constant runs")
    flags.DEFINE_enum(
        "lr_schedule", "noam", ["noam", "cosine", "constant"],
        "LR schedule: noam (reference), or warmup + cosine-decay / constant "
        "at --peak_lr (modern-LM schedules)")
    flags.DEFINE_float("peak_lr", 0.0, "peak LR for cosine/constant schedules")
    flags.DEFINE_integer(
        "lr_decay_steps", 0, "cosine horizon (decays to peak_lr/10 here)")
    flags.DEFINE_float("label_smoothing", 0.0, "label smoothing epsilon")
    flags.DEFINE_enum("loss_normalization", "tokens", ["tokens", "batch"],
                      "CE normalization ('batch' = reference rule)")
    flags.DEFINE_float("max_grad_norm", 0.0, "global-norm gradient clip (0 = off)")
    flags.DEFINE_enum(
        "optimizer", "adam", ["adam", "adafactor", "adamw"],
        "adam = reference optimizer; adafactor = factored second moments "
        "(far less optimizer-state memory for big models); adamw = "
        "decoupled weight decay on matrices (--weight_decay)")
    flags.DEFINE_float(
        "weight_decay", 0.0,
        "adamw decoupled weight decay (vectors — biases/layernorms — exempt)")
    flags.DEFINE_boolean("tie_embeddings", False, "share src/tgt embedding tables")
    flags.DEFINE_boolean("tie_output", False, "tie output projection to embedding")
    flags.DEFINE_enum("norm_scheme", "post", ["post", "pre"], "residual LayerNorm wiring")
    flags.DEFINE_enum(
        "ffn_activation", "relu", list(_FFN_ACTIVATION_NAMES),
        "FFN activation (reference: relu); swiglu/geglu/reglu are the gated "
        "three-matmul variants")
    flags.DEFINE_enum(
        "position_scheme", "sinusoidal", ["sinusoidal", "rope"],
        "position encoding: additive sinusoidal table (reference behavior) "
        "or rotary q/k embeddings (long-context; relative positions)")
    flags.DEFINE_boolean(
        "decoder_only", False,
        "causal-LM mode (cli.train and cli.distributed_train): train a "
        "decoder-only model on the target-side corpus chunked into "
        "sequence_length windows (BASELINE configs[4]); translation-side "
        "flags are ignored")
    flags.DEFINE_enum(
        "objective", "causal", ["causal", "mlm"],
        "training objective: 'causal' (teacher-forcing seq2seq / LM) or "
        "'mlm' (BERT-style masked-LM on an encoder-only model: trains on "
        "target-side LM windows like --decoder_only, masks dynamically "
        "in-step, reserves the top input id for [MASK])")
    flags.DEFINE_float(
        "mlm_mask_rate", 0.15,
        "fraction of non-pad positions selected per MLM step (80/10/10 "
        "mask/random/keep split within the selection)")
    flags.DEFINE_enum("attention_impl", "xla", ["xla", "flash", "ring", "ulysses"],
                      "attention kernel (ring/ulysses = sequence-parallel, use with --sp>1)")
    flags.DEFINE_string("dtype", "bfloat16", "compute dtype")
    flags.DEFINE_integer(
        "moe_experts", 0,
        "Mixture-of-Experts FFN: experts per MoE layer (0 = dense FFN). "
        "Shard over devices with --ep.")
    flags.DEFINE_integer("moe_top_k", 2, "experts each token routes to")
    flags.DEFINE_float("moe_capacity_factor", 1.25,
                       "slack over the even-split expert capacity")
    flags.DEFINE_integer("moe_every", 1,
                         "MoE cadence: every k-th layer carries the MoE FFN")
    flags.DEFINE_float("moe_aux_weight", 0.01,
                       "load-balance auxiliary loss weight")
    flags.DEFINE_boolean(
        "remat", False,
        "rematerialize layer activations in backward (less HBM, ~1/3 more "
        "FLOPs) — the long-context memory lever")
    flags.DEFINE_string("tb_log_dir", "logs", "TensorBoard log root")
    flags.DEFINE_integer("seed", 0, "PRNG seed")
    flags.DEFINE_string("platform", "", "force a jax platform (e.g. 'cpu') before first use")
    flags.DEFINE_boolean("native_loader", True,
                         "prefetch batches via the C++ loader when available")
    flags.DEFINE_string(
        "length_buckets", "",
        "comma-separated ascending batch widths (e.g. '24,36,50', last <= "
        "sequence_length): batches pad to the smallest fitting bucket — "
        "one compile per bucket, far fewer padding FLOPs ('' = off)")
    flags.DEFINE_boolean(
        "streaming", False,
        "stream the train corpus from disk with a --buffer_size shuffle "
        "buffer instead of loading it into RAM (corpora larger than host "
        "memory; needs pre-built vocab files; seq2seq pipeline only)")
    flags.DEFINE_string("profile_dir", "", "capture a jax.profiler trace into this dir")
    flags.DEFINE_integer("profile_start_step", 2, "first step of the profile window")
    flags.DEFINE_integer("profile_num_steps", 3, "profile window length in steps")
    define_metrics_flags()
    # --- mesh knobs (distributed) ---
    flags.DEFINE_integer("dp", 0, "data-parallel mesh size (0 = all devices)")
    flags.DEFINE_integer("fsdp", 1, "fsdp (param-shard) mesh size")
    flags.DEFINE_integer("tp", 1, "tensor-parallel mesh size")
    flags.DEFINE_integer("sp", 1, "sequence-parallel mesh size")
    flags.DEFINE_integer(
        "pp", 1,
        "pipeline-parallel mesh size (GPipe stages). Note: pipe partitions "
        "compute only; combine with --fsdp to shard stage params/optimizer "
        "state, else each device holds a full param replica.")
    flags.DEFINE_integer(
        "ep", 1,
        "expert-parallel mesh size (MoE expert weights sharded; tokens reach "
        "their experts via an ICI all-to-all). The expert axis also splits "
        "the batch, so it contributes to the data-parallel divisibility check.")
    flags.DEFINE_integer(
        "pp_microbatches", 0,
        "GPipe microbatches per step (0 = one per stage); more microbatches "
        "shrink the pipeline bubble at the cost of smaller per-shard matmuls")
    flags.DEFINE_enum(
        "pp_schedule", "gpipe", ["gpipe", "1f1b"],
        "pipeline schedule: 'gpipe' (autodiff backward, activation stash "
        "grows with pp_microbatches) or '1f1b' (interleaved manual backward, "
        "stash bounded at 2*stages-1 microbatches — raise pp_microbatches "
        "freely; decoder-only dense models on data x pipe meshes)")
    flags.DEFINE_integer(
        "dcn_data", 1,
        "multi-slice: how many DCN-connected slices (processes off-TPU) the "
        "data axis spans; must divide --dp. Slow DCN hops then carry only "
        "the data-parallel gradient all-reduce — every other axis stays on "
        "intra-slice ICI.")
    flags.DEFINE_integer(
        "eval_max_batches", 8,
        "cap on in-loop eval batches (0 = full test set each eval)")
    flags.DEFINE_integer(
        "early_stop_patience", 0,
        "stop after this many consecutive epochs without eval-loss "
        "improvement (0 = run all epochs, the reference behavior)")
    flags.DEFINE_integer(
        "grad_accum", 1,
        "gradient-accumulation micro-steps per optimizer update (1 = off)")
    flags.DEFINE_integer(
        "loss_chunks", 1,
        "compute the vocab projection + CE over this many sequence slices so "
        "the full (B,S,V) logits tensor is never materialized (1 = off) — "
        "the memory lever for big-vocab/long-context configs")
    flags.DEFINE_enum(
        "remat_policy", "full", ["full", "dots"],
        "what remat may keep: 'full' recomputes everything (min memory); "
        "'dots' saves matmul outputs, recomputes only elementwise ops "
        "(most of the memory win at a fraction of the recompute)")
    flags.DEFINE_integer(
        "attention_window", 0,
        "sliding-window causal self-attention: each position attends only "
        "the last N positions (0 = full attention); structural tile-skip "
        "in the flash kernel, banded mask under xla, honored by decode")
    flags.DEFINE_integer(
        "steps_per_dispatch", 1,
        "optimizer steps per host dispatch, run inside one jitted lax.scan "
        "(1 = off) — amortizes per-step dispatch overhead when step times "
        "are small; log/eval/preemption granularity becomes this many steps")
    flags.DEFINE_boolean(
        "consistency_check", False,
        "after every epoch (and at end of run), assert that all processes "
        "hold bit-identical replicated state (catches silent per-host "
        "RNG/data-order divergence; utils/consistency.py)")
    flags.DEFINE_boolean(
        "async_checkpoint", False,
        "write checkpoints from a background thread (device snapshot stays "
        "synchronous); multi-process sharded states fall back to sync saves")
    flags.DEFINE_boolean(
        "eval_bleu", True,
        "compute corpus BLEU on the test split after training")
    flags.DEFINE_integer(
        "bleu_limit", 200,
        "cap on test pairs scored for end-of-run BLEU (0 = all)")


def flags_to_model_config(input_vocab_size: int, target_vocab_size: int) -> ModelConfig:
    apply_preset()
    if FLAGS.objective == "mlm":
        # Reserve the top input id for [MASK] (train/mlm.py): the model
        # vocab is one larger than the tokenizer's; head and embedding
        # share the single (extended) id space.
        input_vocab_size += 1
        target_vocab_size = input_vocab_size
    return ModelConfig(
        num_layers=FLAGS.num_layers,
        d_model=FLAGS.d_model,
        num_heads=FLAGS.num_heads,
        num_kv_heads=FLAGS.num_kv_heads,
        dff=FLAGS.dff,
        input_vocab_size=input_vocab_size,
        target_vocab_size=target_vocab_size,
        dropout_rate=FLAGS.dropout_rate,
        max_position=max(FLAGS.sequence_length, 64),
        norm_scheme=FLAGS.norm_scheme,
        position_scheme=FLAGS.position_scheme,
        decoder_only=FLAGS.decoder_only,
        encoder_only=FLAGS.objective == "mlm",
        tie_embeddings=FLAGS.tie_embeddings,
        tie_output=FLAGS.tie_output,
        ffn_activation=FLAGS.ffn_activation,
        dtype=FLAGS.dtype,
        attention_impl=FLAGS.attention_impl,
        attention_window=FLAGS.attention_window,
        remat=FLAGS.remat,
        remat_policy=FLAGS.remat_policy,
        moe_experts=FLAGS.moe_experts,
        moe_top_k=FLAGS.moe_top_k,
        moe_capacity_factor=FLAGS.moe_capacity_factor,
        moe_every=FLAGS.moe_every,
        moe_aux_weight=FLAGS.moe_aux_weight,
    )


def flags_to_train_config() -> TrainConfig:
    apply_preset()
    return TrainConfig(
        batch_size=FLAGS.batch_size,
        sequence_length=FLAGS.sequence_length,
        epochs=FLAGS.epochs,
        warmup_steps=FLAGS.warmup_steps,
        lr_schedule=FLAGS.lr_schedule,
        peak_lr=FLAGS.peak_lr,
        lr_decay_steps=FLAGS.lr_decay_steps,
        label_smoothing=FLAGS.label_smoothing,
        loss_normalization=FLAGS.loss_normalization,
        max_grad_norm=FLAGS.max_grad_norm,
        optimizer=FLAGS.optimizer,
        weight_decay=FLAGS.weight_decay,
        buffer_size=FLAGS.buffer_size,
        max_ckpt_keep=FLAGS.max_ckpt_keep,
        ckpt_path=FLAGS.ckpt_path,
        enable_function=FLAGS.enable_function,
        seed=FLAGS.seed,
        pp_microbatches=FLAGS.pp_microbatches,
        pp_schedule=FLAGS.pp_schedule,
        eval_max_batches=FLAGS.eval_max_batches,
        early_stop_patience=FLAGS.early_stop_patience,
        grad_accum_steps=FLAGS.grad_accum,
        loss_chunks=FLAGS.loss_chunks,
        steps_per_dispatch=FLAGS.steps_per_dispatch,
        objective=FLAGS.objective,
        mlm_mask_rate=FLAGS.mlm_mask_rate,
    )


def flags_to_profiler():
    """Profiler from --profile_* flags, or None when profiling is off."""
    if not FLAGS.profile_dir:
        return None
    from transformer_tpu.utils.profiling import Profiler

    return Profiler(
        FLAGS.profile_dir,
        start_step=FLAGS.profile_start_step,
        num_steps=FLAGS.profile_num_steps,
    )


def flags_to_telemetry():
    """obs.Telemetry from --metrics_* flags, or None when telemetry is off
    (--metrics_jsonl unset and --metrics_port 0 — the zero-overhead
    default). Owns the whole --metrics_* interpretation, including starting
    the /metrics scrape endpoint, so every CLI wires telemetry identically.
    The jax-free obs import keeps flag materialization safe to run before
    platform setup, like the rest of this module."""
    if not FLAGS.metrics_jsonl and not FLAGS.metrics_port:
        return None
    from absl import logging

    from transformer_tpu.obs import EventLog, Telemetry
    from transformer_tpu.obs.breaker import CircuitBreaker

    events = None
    if FLAGS.metrics_jsonl:
        # Sink circuit breaker (docs/ROBUSTNESS.md): a transiently full
        # disk costs an outage window with a half-open re-probe every 30s,
        # not the rest of the process's telemetry. Direct EventLog
        # construction (no breaker) keeps the historical
        # first-failure-disables contract.
        events = EventLog(
            FLAGS.metrics_jsonl,
            breaker=CircuitBreaker("event_sink", threshold=3, cooldown_s=30.0),
        )
    if FLAGS.trace and events is None:
        # A tracer without an event sink would pay full span bookkeeping
        # and silently drop every trace.span — tell the operator instead.
        logging.warning(
            "--trace needs --metrics_jsonl to record trace.span events; "
            "tracing disabled for this run"
        )
    telemetry = Telemetry(
        events=events,
        prom_path=f"{FLAGS.metrics_jsonl}.prom" if FLAGS.metrics_jsonl else None,
        interval=FLAGS.metrics_interval,
        trace=FLAGS.trace and events is not None,
    )
    if FLAGS.profile_programs:
        telemetry.arm_profiler()
    if FLAGS.flight_recorder and FLAGS.metrics_jsonl:
        from transformer_tpu.obs.flight import flight_path_for

        recorder = telemetry.arm_flight(
            flight_path_for(FLAGS.metrics_jsonl), autodump_s=2.0
        )
        recorder.install_signal_handlers()
    if FLAGS.metrics_port:
        port = telemetry.start_prometheus_server(FLAGS.metrics_port)
        logging.info("Prometheus /metrics (+ /healthz) on port %d", port)
    return telemetry


def flags_to_mesh_config(n_devices: int) -> MeshConfig:
    non_dp = FLAGS.fsdp * FLAGS.tp * FLAGS.sp * FLAGS.pp * FLAGS.ep
    dp = FLAGS.dp or max(1, n_devices // non_dp)
    return MeshConfig(
        data=dp, fsdp=FLAGS.fsdp, model=FLAGS.tp, seq=FLAGS.sp, pipe=FLAGS.pp,
        expert=FLAGS.ep, dcn_data=FLAGS.dcn_data,
    )


def maybe_force_platform() -> None:
    """``--platform`` override, plus the persistent compilation cache
    (every CLI process re-pays full XLA compiles otherwise; opt out or
    relocate via ``$TRANSFORMER_TPU_JAX_CACHE``, see
    ``utils.enable_compilation_cache``)."""
    if FLAGS.platform:
        import jax

        jax.config.update("jax_platforms", FLAGS.platform)
    from transformer_tpu.utils.profiling import enable_compilation_cache

    enable_compilation_cache()
