"""Convert a training checkpoint into a serving export.

    python -m transformer_tpu.cli.export --ckpt_path=model_dist \
        --export_path=model --num_layers=6 --d_model=512 ... [--step=N]

Training already exports at end-of-run (the reference's
``tf.saved_model.save`` moment, ``train.py:246``); this tool covers the
other case — exporting from a mid-run or crashed run's rotated checkpoints.
Model-shape flags must match the training run (the checkpoint stores arrays
keyed by the parameter tree, which the flags reconstruct); vocabulary sizes
are recovered from the saved vocab files.
"""

from __future__ import annotations

from absl import app, flags, logging

from transformer_tpu.cli.flags import define_flags, flags_to_model_config, flags_to_train_config

FLAGS = flags.FLAGS


def define_export_flags() -> None:
    define_flags()
    flags.DEFINE_string("export_path", "model", "output directory")
    flags.DEFINE_integer("step", 0, "checkpoint step to export (0 = latest)")
    flags.DEFINE_integer(
        "average_last", 1,
        "average the params of the last N rotated checkpoints before export "
        "(the classic Transformer BLEU trick; 1 = just the chosen step)")
    flags.DEFINE_string(
        "quantize", "",
        "'int8': store large weights as symmetric int8 + fp32 scales "
        "(~4x smaller artifact; dequantized transparently on load)")


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import apply_preset

    apply_preset()  # before ANY direct FLAGS read (e.g. decoder_only)
    if FLAGS.quantize not in ("", "int8"):
        # Fail in milliseconds, not after restoring/averaging N checkpoints.
        raise app.UsageError(
            f"--quantize must be '' or 'int8', got {FLAGS.quantize!r}"
        )
    import jax

    jax.config.update("jax_platforms", FLAGS.platform or "cpu")
    from transformer_tpu.utils.profiling import enable_compilation_cache

    enable_compilation_cache()

    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.train import CheckpointManager, create_train_state
    from transformer_tpu.train.checkpoint import export_params

    if FLAGS.decoder_only:
        # LM training builds only the target-side vocab (load_lm_splits);
        # a decoder-only model has no encoder, so the src size is unused.
        src_tok = tgt_tok = SubwordTokenizer.load(FLAGS.tgt_vocab_file)
    else:
        src_tok = SubwordTokenizer.load(FLAGS.src_vocab_file)
        tgt_tok = (
            src_tok
            if FLAGS.tgt_vocab_file == FLAGS.src_vocab_file
            else SubwordTokenizer.load(FLAGS.tgt_vocab_file)
        )
    model_cfg = flags_to_model_config(
        src_tok.model_vocab_size, tgt_tok.model_vocab_size
    )
    template = create_train_state(
        jax.random.PRNGKey(0), model_cfg, flags_to_train_config()
    )
    mgr = CheckpointManager(FLAGS.ckpt_path, FLAGS.max_ckpt_keep)
    step = FLAGS.step or mgr.latest_step
    if step is None:
        raise app.UsageError(f"no checkpoints under {FLAGS.ckpt_path!r}")
    if FLAGS.step and FLAGS.step not in mgr.all_steps():
        # Fail loudly for both the single-step and averaged paths (the
        # averaged filter would otherwise silently tolerate a typo'd step).
        raise app.UsageError(
            f"no checkpoint at step {FLAGS.step} under {FLAGS.ckpt_path!r} "
            f"(available: {mgr.all_steps()})"
        )
    if FLAGS.average_last < 1:
        raise app.UsageError(
            f"--average_last must be >= 1, got {FLAGS.average_last}"
        )
    if FLAGS.average_last > 1:
        from transformer_tpu.train.checkpoint import average_checkpoints

        steps = [s for s in mgr.all_steps() if s <= step][-FLAGS.average_last:]
        if len(steps) < FLAGS.average_last:
            logging.warning(
                "only %d checkpoint(s) retained (<= step %d); averaging "
                "those instead of the requested %d",
                len(steps), step, FLAGS.average_last,
            )
        avg_params = average_checkpoints(mgr, template, steps)
        export_params(
            avg_params, model_cfg, FLAGS.export_path, quantize=FLAGS.quantize
        )
        logging.info(
            "exported average of steps %s from %s to %s",
            steps, FLAGS.ckpt_path, FLAGS.export_path,
        )
        return
    state = mgr.restore(template, step)
    export_params(
        state.params, model_cfg, FLAGS.export_path, quantize=FLAGS.quantize
    )
    logging.info(
        "exported step %d from %s to %s", step, FLAGS.ckpt_path, FLAGS.export_path
    )


def run() -> None:
    define_export_flags()
    app.run(main)


if __name__ == "__main__":
    run()
