"""LM serving entry point: load a decoder-only export and continue prompts.

    python -m transformer_tpu.cli.generate --export_path=model \
        --vocab_file=tgt_vocab.subwords [--prompts="der Mann"] \
        [--temperature=0.8 --top_k=40 --top_p=0.95]  # or stdin, one per line

Counterpart of cli.translate for the causal-LM model family (BASELINE
configs[4]); greedy by default, temperature/top-k sampling optional.
"""

from __future__ import annotations

import sys

from absl import app, flags, logging

FLAGS = flags.FLAGS


def define_generate_flags() -> None:
    flags.DEFINE_string("export_path", "model", "directory written by export_params")
    flags.DEFINE_string("vocab_file", "tgt_vocab.subwords", "subword vocab path")
    flags.DEFINE_string("prompts", "", "';'-separated prompts (default: stdin lines)")
    flags.DEFINE_integer("max_new", 64, "max generated tokens per prompt")
    flags.DEFINE_float("temperature", 0.0, "sampling temperature (0 = greedy)")
    flags.DEFINE_integer("top_k", 0, "top-k truncation for sampling (0 = off)")
    flags.DEFINE_float("top_p", 1.0, "nucleus (top-p) truncation for sampling (1 = off)")
    flags.DEFINE_integer("seed", 0, "sampling seed")
    flags.DEFINE_string("platform", "", "force a jax platform (e.g. 'cpu') before first use")
    flags.DEFINE_boolean(
        "kv_cache_int8", False,
        "decode with an int8-quantized KV cache (~2-4x less cache HBM; "
        "serving-time choice, independent of the export)")


def main(argv) -> None:
    del argv
    from transformer_tpu.cli.flags import maybe_force_platform

    maybe_force_platform()

    from transformer_tpu.cli.translate import load_export
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.train.decode import generate

    params, model_cfg = load_export(FLAGS.export_path, kv_cache_int8=FLAGS.kv_cache_int8)
    if not model_cfg.decoder_only:
        raise app.UsageError(
            "the export is a seq2seq model; use cli.translate instead"
        )
    tok = SubwordTokenizer.load(FLAGS.vocab_file)

    if FLAGS.prompts:
        prompts = [p.strip() for p in FLAGS.prompts.split(";") if p.strip()]
    else:
        prompts = [line.strip() for line in sys.stdin if line.strip()]
    if not prompts:
        logging.warning("no input prompts")
        return
    outputs = generate(
        params, model_cfg, tok, prompts,
        max_new=FLAGS.max_new, temperature=FLAGS.temperature,
        top_k=FLAGS.top_k, top_p=FLAGS.top_p, seed=FLAGS.seed,
    )
    for out in outputs:
        print(out)


def run() -> None:
    define_generate_flags()
    app.run(main)


if __name__ == "__main__":
    run()
