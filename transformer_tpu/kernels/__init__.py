"""Pallas TPU kernels for the hot ops.

The one genuinely hot kernel in the reference is scaled dot-product attention
(``Attention.py:20-32``, invoked 3×num_layers times per step); its blockwise
TPU-native replacement lives here. Everything else (layernorm, FFN, masking)
fuses well under plain XLA and deliberately stays out of Pallas.
"""

from transformer_tpu.kernels.flash_attention import flash_attention

__all__ = ["flash_attention"]
