"""Paged KV memory: one device-resident block pool for slots and prefixes.

The serving tier's dense layout reserves ``max_total`` KV rows per slot
whether or not a token ever lands there — the cost model
(``analysis/costs.py``) prices that as the repo's largest memory waste
(bf16 128 B/token, GQA 64, int8 96, per slot, per layer). This module is
the vLLM-style alternative: ONE pool of fixed-size token-aligned blocks
per layer, shared by every slot, addressed through per-slot block tables.
Resident KV becomes proportional to *used* tokens, a prefix-cache hit
becomes block-table aliasing (no host round trip), and speculative
rollback becomes a table truncation that returns blocks to the free list.

Split of responsibilities:

- :class:`KVPool` — the HOST-side allocator: free-list alloc/free,
  per-block refcounts (a block may be shared by several slot tables plus
  the prefix cache's device tier), copy-on-write splits for shared blocks
  about to be written, per-slot table rows, and the cached device upload
  of the table. Pure numpy + lists under ONE lock (the TPA1xx concurrency
  rules lint this module; ``analysis/schedules.py kv_pool_contention``
  explores two-thread interleavings against exactly this guard, and a
  real-thread hammer test rides tier-1).
- Device-side pure functions (``gather_block_views`` here,
  ``paged_attention`` in ``kernels/flash_attention.py``, the jitted
  ``_pool_*_paged`` programs in ``serve/scheduler.py``) — functional jax
  code that threads the pool buffers through jit like any other cache
  pytree. The allocator never touches device memory; the jitted programs
  never see the free list.

Block 0 is the SINK: permanently pinned, never allocated, never aliased.
Unmapped table entries point at it (gathered sink rows land at positions
the offset causal mask hides) and free slots' steps write into it (their
writes must land somewhere fixed that no live slot can own — the paged
twin of the dense pool's "free slots step too" invariant).

Byte parity with the dense layout is structural: the paged decode step
gathers each slot's blocks into a dense-ordered view, runs the SAME
vmapped model forward the dense pool runs (same shapes, same mask, same
storage-layout round trip), and scatters the newly written rows back —
so greedy AND seeded-sampled answers are bit-identical paged vs dense
(tests/test_kv_pool.py pins this across bf16/int8/GQA, composed with
chunked prefill, speculative decoding, and prefix reuse).
"""

from __future__ import annotations

import threading

import numpy as np


class KVPoolExhausted(RuntimeError):
    """The free list cannot satisfy an allocation. Admission-time callers
    degrade this to a transient (retryable) error after asking the prefix
    cache's device tier to spill; decode-time callers preempt the slot
    with a structured ``resource`` answer."""


class KVPool:
    """Host-side allocator for a ``num_blocks`` x ``block_tokens`` pool.

    Owns the per-slot block tables (``num_slots`` rows of
    ``slot_blocks`` entries each): ``table[s, j]`` is the pool block
    holding slot ``s``'s positions ``[j*B, (j+1)*B)``; entries at or past
    the slot's allocated count point at the sink. Every live table entry
    holds one reference on its block; the prefix cache's device tier takes
    additional references via :meth:`retain`. A block returns to the free
    list exactly when its refcount reaches zero — refcounts never go
    negative and a block is never double-freed (``check_consistency``
    re-derives the whole accounting; the schedule checker and the hammer
    test assert it under contention).

    Threading contract: ONE ``threading.Lock`` guards the free list, the
    refcounts, the tables, and the stats. The device-table upload cache
    (:meth:`table_device`) is refreshed under the same lock.
    """

    SINK = 0

    def __init__(
        self, num_blocks: int, block_tokens: int,
        num_slots: int, slot_blocks: int,
    ):
        if num_blocks < 2:
            raise ValueError(
                f"kv pool needs >= 2 blocks (sink + 1), got {num_blocks}"
            )
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self.num_slots = num_slots
        self.slot_blocks = slot_blocks
        self._lock = threading.Lock()
        self._refs = np.zeros((num_blocks,), np.int32)
        self._refs[self.SINK] = 1  # permanently pinned
        # LIFO free list (ids 1..num_blocks-1): recently freed blocks are
        # reused first, keeping the working set hot.
        self._free = list(range(num_blocks - 1, 0, -1))
        self.table = np.zeros((num_slots, slot_blocks), np.int32)
        self._owned = np.zeros((num_slots,), np.int32)
        self._dirty = True
        self._table_dev = None
        self.stats = {
            "allocated_blocks": 0, "freed_blocks": 0, "cow_splits": 0,
            "alias_blocks": 0,
        }

    # ---- accounting --------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        with self._lock:
            return self.num_blocks - 1 - len(self._free)

    def refs(self, bid: int) -> int:
        with self._lock:
            return int(self._refs[bid])

    def slot_tokens(self, slot: int) -> int:
        """Token capacity currently backed by real blocks for ``slot``."""
        with self._lock:
            return int(self._owned[slot]) * self.block_tokens

    # ---- alloc / free ------------------------------------------------------

    def _pop_free(self) -> int:
        # caller holds the lock
        if not self._free:
            raise KVPoolExhausted(
                f"kv pool exhausted: {self.num_blocks - 1} blocks all "
                "referenced (live slots + device-resident prefixes)"
            )
        bid = self._free.pop()
        self._refs[bid] = 1
        self.stats["allocated_blocks"] += 1
        return bid

    def _release(self, bid: int) -> bool:
        # caller holds the lock; returns True when the block was freed
        if bid == self.SINK:
            return False
        self._refs[bid] -= 1
        if self._refs[bid] < 0:  # pragma: no cover - guarded by tests
            raise AssertionError(f"negative refcount on block {bid}")
        if self._refs[bid] == 0:
            self._free.append(bid)
            self.stats["freed_blocks"] += 1
            return True
        return False

    def ensure(self, slot: int, tokens: int) -> bool:
        """Grow ``slot``'s table to cover ``tokens`` positions with OWNED
        (refcount-1) blocks appended past the current end. Returns True
        when the table changed. Raises :class:`KVPoolExhausted` (leaving
        already-appended blocks in place — the caller's free_slot/truncate
        rolls back) when the free list runs dry."""
        need = min(-(-tokens // self.block_tokens), self.slot_blocks)
        changed = False
        with self._lock:
            while self._owned[slot] < need:
                bid = self._pop_free()
                self.table[slot, self._owned[slot]] = bid
                self._owned[slot] += 1
                changed = True
            if changed:
                self._dirty = True
        return changed

    def extend(self, slot: int, bid: int | None = None) -> tuple[int, int]:
        """Append ONE block at the slot's next table position: alias an
        existing block (``bid`` given — takes a reference; the prefix
        cache's device-resident hit path) or allocate a fresh one.
        Returns ``(position, block_id)``."""
        with self._lock:
            j = int(self._owned[slot])
            if j >= self.slot_blocks:
                raise ValueError(
                    f"slot {slot} table full ({self.slot_blocks} blocks)"
                )
            if bid is None:
                bid = self._pop_free()
            else:
                if bid == self.SINK or self._refs[bid] <= 0:
                    raise ValueError(f"cannot alias dead block {bid}")
                self._refs[bid] += 1
                self.stats["alias_blocks"] += 1
            self.table[slot, j] = bid
            self._owned[slot] += 1
            self._dirty = True
            return j, int(bid)

    def truncate(self, slot: int, tokens: int) -> int:
        """Shrink ``slot``'s table to the blocks covering ``tokens``
        positions, releasing the rest (speculative rollback = table
        truncation; freed blocks return to the pool unless the device
        tier still references them). Returns blocks released from the
        table."""
        keep = -(-tokens // self.block_tokens) if tokens > 0 else 0
        released = 0
        with self._lock:
            while self._owned[slot] > keep:
                j = int(self._owned[slot]) - 1
                self._release(int(self.table[slot, j]))
                self.table[slot, j] = self.SINK
                self._owned[slot] = j
                released += 1
            if released:
                self._dirty = True
        return released

    def free_slot(self, slot: int) -> int:
        """Retire ``slot``: drop every table reference (aliased prefix
        blocks survive under the device tier's refs) and reset the row to
        the sink."""
        return self.truncate(slot, 0)

    # ---- sharing -----------------------------------------------------------

    def retain(self, bid: int) -> None:
        """External pin (the prefix cache's device tier adopting a
        retiring slot's block)."""
        with self._lock:
            if bid == self.SINK or self._refs[bid] <= 0:
                raise ValueError(f"cannot retain dead block {bid}")
            self._refs[bid] += 1

    def release(self, bid: int) -> bool:
        """Drop an external pin; True when the block returned to the
        free list."""
        with self._lock:
            return self._release(bid)

    def make_writable(
        self, slot: int, start_token: int, end_token: int
    ) -> list[tuple[int, int]]:
        """Copy-on-write guard for a write into positions ``[start_token,
        end_token)``: any touched block shared with another owner
        (refcount > 1) is split — a fresh block takes its table entry, the
        old block keeps its other owners. Returns ``(src, dst)`` block-id
        pairs the caller must copy ON DEVICE (``_pool_copy_blocks``)
        before dispatching the write. Normal serving flows write only past
        the aliased (block-aligned) prefix, so this usually returns [] —
        it is the guard that makes aliasing safe by construction rather
        than by call-site discipline."""
        if end_token <= start_token:
            return []
        B = self.block_tokens
        pairs: list[tuple[int, int]] = []
        with self._lock:
            j0 = start_token // B
            j1 = -(-end_token // B)
            for j in range(j0, min(j1, int(self._owned[slot]))):
                bid = int(self.table[slot, j])
                if bid == self.SINK or self._refs[bid] <= 1:
                    continue
                new = self._pop_free()
                self._refs[bid] -= 1  # > 1 before, so never frees here
                self.table[slot, j] = new
                self.stats["cow_splits"] += 1
                pairs.append((bid, new))
            if pairs:
                self._dirty = True
        return pairs

    # ---- device table ------------------------------------------------------

    def table_device(self):
        """The (num_slots, slot_blocks) int32 table as a device array,
        re-uploaded only when the host table changed since the last call
        (a few hundred bytes — negligible next to a decode step, and the
        block DATA never moves through the host on the aliased path)."""
        import jax.numpy as jnp

        with self._lock:
            if self._dirty or self._table_dev is None:
                self._table_dev = jnp.asarray(self.table)
                self._dirty = False
            return self._table_dev

    # ---- invariants --------------------------------------------------------

    def check_consistency(self) -> None:
        """Re-derive the whole accounting from first principles: refcounts
        never negative, free list duplicate-free and disjoint from every
        table, every live table entry referenced, freed blocks hold zero
        references, block-count conservation. The schedule checker and the
        hammer test call this after every operation."""
        with self._lock:
            free = list(self._free)
            assert len(set(free)) == len(free), "double-free: dup in free list"
            assert self.SINK not in free, "sink leaked into the free list"
            assert (self._refs >= 0).all(), (
                f"negative refcount: {self._refs.tolist()}"
            )
            for bid in free:
                assert self._refs[bid] == 0, (
                    f"free block {bid} still referenced ({self._refs[bid]})"
                )
            table_refs = np.zeros_like(self._refs)
            for s in range(self.num_slots):
                owned = int(self._owned[s])
                for j in range(self.slot_blocks):
                    bid = int(self.table[s, j])
                    if j < owned:
                        assert bid != self.SINK, (
                            f"slot {s} owned entry {j} points at the sink"
                        )
                        assert bid not in free, (
                            f"slot {s} references freed block {bid}"
                        )
                        table_refs[bid] += 1
                    else:
                        assert bid == self.SINK, (
                            f"slot {s} stale entry {j} -> {bid}"
                        )
            # refs = table occurrences + external pins (>= 0 each)
            extra = self._refs - table_refs
            extra[self.SINK] -= 1  # the permanent sink pin
            assert (extra >= 0).all(), (
                f"refcount below table occupancy: {extra.tolist()}"
            )
            live = self.num_blocks - 1 - len(free)
            assert live == int((self._refs[1:] > 0).sum()), (
                "block-count conservation violated"
            )


# ==========================================================================
# device-side pure helpers (used inside jitted programs)


def gather_block_views(buf, table, width: int | None = None):
    """Gather per-sequence dense-ordered KV views through block tables:
    ``buf`` (num_blocks, B, ...) x ``table`` (N, nmax) -> (N, L, ...) where
    ``L = width`` (sliced from nmax*B; ``None`` keeps the full nmax*B).
    Slicing to the dense buffer length keeps the attention reduction the
    SAME shape as the dense layout — a precondition of bitwise parity.
    Unmapped entries gather the sink block; its rows land at positions the
    offset causal mask hides."""
    import jax.numpy as jnp

    n, nmax = table.shape
    view = jnp.take(buf, table, axis=0)  # (N, nmax, B, ...)
    view = view.reshape(n, nmax * buf.shape[1], *buf.shape[2:])
    if width is not None and width < view.shape[1]:
        view = view[:, :width]
    return view


def scatter_rows(buf, row_ids, rows):
    """Write flat pool rows: ``buf`` (num_blocks, B, ...), ``row_ids``
    (M,) flat row indices (block*B + offset), ``rows`` (M, ...). Row ids
    may repeat ONLY on sink rows (free slots all write there); the sink's
    content is never read unmasked, so the scatter's pick order is
    irrelevant."""
    nb, bt = buf.shape[0], buf.shape[1]
    flat = buf.reshape(nb * bt, *buf.shape[2:])
    return flat.at[row_ids].set(rows).reshape(buf.shape)


def block_row_ids(table, index, s_q: int, block_tokens: int):
    """Flat pool row ids for per-sequence writes at positions
    ``[index[s], index[s] + s_q)``: (N, s_q) int32. Positions past the
    table's mapped range clamp into the slot's last entry — free slots
    (index 0, all-sink rows) land in the sink."""
    import jax.numpy as jnp

    nmax = table.shape[1]
    pos = index[:, None] + jnp.arange(s_q)[None, :]
    blk = jnp.take_along_axis(
        table, jnp.clip(pos // block_tokens, 0, nmax - 1), axis=1
    )
    return blk * block_tokens + pos % block_tokens
