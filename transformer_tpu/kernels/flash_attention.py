"""Blockwise (flash) attention as a Pallas TPU kernel, forward + backward.

This is the TPU-native replacement for the reference's
``scaled_dot_product_attention`` (``Attention.py:3-34``) at long sequence
length: instead of materializing the full (B, H, S, S) score tensor in HBM
(reference ``Attention.py:20``), scores are computed tile-by-tile in VMEM with
an online softmax, so memory is O(S·D) and the two matmuls per tile stay on
the MXU. The (B·H, q-block, k-block) grid walks the k-axis sequentially,
carrying the running max / normalizer / output accumulator in VMEM scratch —
the canonical TPU flash-attention schedule.

Semantics match ``ops.attention.dot_product_attention``:

- softmax in fp32 regardless of input dtype;
- optional key-padding mask (True = "may attend"), same polarity as
  ``ops.masks``;
- optional causal masking, passed *structurally* (a static flag, not a dense
  (S, S) mask) so fully-above-diagonal tiles are skipped outright.

The backward pass is the standard two-kernel split: one accumulates dQ over
k-blocks, the other dK/dV over q-blocks, both recomputing the tile of
attention probabilities from the saved per-row logsumexp rather than storing
the (S, S) probability matrix.

On non-TPU backends the kernels run in Pallas interpret mode, which is how the
CPU test suite exercises them bit-for-bit against the XLA oracle.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

# Finite stand-in for -inf: keeps fully-masked rows NaN-free (same approach as
# the reference's additive -1e9, ``Attention.py:26``) while staying far below
# any reachable logit so the exp-guard below can recognize masked entries.
_MASKED = -1e30
_MASK_GUARD = -1e29


@dataclasses.dataclass(frozen=True)
class _FlashConfig:
    """Static kernel configuration (hashable: used as a nondiff custom-vjp arg)."""

    causal: bool
    has_mask: bool
    block_q: int
    block_k: int
    num_heads: int  # for the kv-mask index map: grid axis 0 runs over B*H
    scale: float
    interpret: bool
    # Grouped-query attention: k/v arrive folded as (B*H_kv, S_k, D) and each
    # kv head serves num_heads/num_kv_heads query heads VIA THE BLOCKSPEC
    # INDEX MAPS — kv is never materialized at the full head count, so HBM kv
    # traffic stays at the H_kv rate (the whole point of GQA).
    num_kv_heads: int = 0  # 0 = same as num_heads (plain MHA)
    # Sliding-window band (Mistral-style local attention): LOCAL row r may
    # attend LOCAL col c only when c > r - band. None = unbounded. For plain
    # flash attention band == window (> 0); for ring hops the band is the
    # window shifted by the hop's static chunk offset (band = W - t·C, any
    # sign — ring_attention). Structural like causality: tiles fully below
    # the band are skipped by _visible, so compute per q-block is O(window),
    # not O(S).
    band: int | None = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def group(self) -> int:
        return self.num_heads // self.kv_heads

    def kv_row(self, b):
        """Grid row over B*H -> row of the folded (B*H_kv, S, D) kv array."""
        if self.group == 1:
            return b
        return (b // self.num_heads) * self.kv_heads + (b % self.num_heads) // self.group


def _largest_divisor_block(seq_len: int, requested: int) -> int:
    block = min(requested, seq_len)
    while seq_len % block:
        block -= 1
    return block


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _block_and_padded_len(seq_len: int, requested: int) -> tuple[int, int]:
    """Pick a TPU-legal block size and the (possibly padded) sequence length.

    The Mosaic lowering requires the block's sublane dim to be divisible by 8
    or equal to the full array dim. A divisor block satisfying that is used
    as-is (no padding); otherwise the sequence is padded up to a multiple of
    an 8-aligned block (e.g. S=4095 -> block 128, padded to 4096 — the
    teacher-forcing shift makes off-by-one lengths the common case)."""
    block = _largest_divisor_block(seq_len, requested)
    if block == seq_len or block % 8 == 0:
        return block, seq_len
    block = max(8, min(requested, _round_up(seq_len, 8)) // 8 * 8)
    return block, _round_up(seq_len, block)


def _compiler_params(dimension_semantics: tuple[str, ...]):
    # jax renamed TPUCompilerParams -> CompilerParams; accept either spelling
    # so the kernel compiles across the jax versions the repo meets.
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is None:  # pragma: no cover - exotic pallas build
        return None
    try:
        return cls(dimension_semantics=dimension_semantics)
    except TypeError:  # pragma: no cover - older/newer field spellings
        return None


def _gated(cfg: _FlashConfig) -> bool:
    """Whether any structural tile-skip condition applies."""
    return cfg.causal or cfg.band is not None


def _visible(cfg: _FlashConfig, i, j):
    """Whether k-block j has any position visible to q-block i under
    causality and/or the sliding-window band (call only when ``_gated``)."""
    conds = []
    if cfg.causal:
        conds.append(j * cfg.block_k <= i * cfg.block_q + cfg.block_q - 1)
    if cfg.band is not None:
        # Band lower edge, conservatively from the q-block's FIRST row
        # (i*bq): its band start (row - band + 1) is the leftmost in the
        # tile, so any tile whose last col reaches it may still hold
        # in-band entries for some row. Using the last row here would skip
        # tiles that earlier rows still need when band < block_q.
        conds.append(
            j * cfg.block_k + cfg.block_k - 1 >= i * cfg.block_q - cfg.band + 1
        )
    vis = conds[0]
    for extra in conds[1:]:
        vis = jnp.logical_and(vis, extra)
    return vis


def _tile_bias(cfg: _FlashConfig, s, i, j, mask_ref):
    """Apply key-padding and intra-tile causal masking to a (bq, bk) score tile."""
    if cfg.has_mask:
        # Mask arrives pre-tiled as (B, nk, 1, block_k) so each grid step maps
        # its (1, block_k) tile as a full block — TPU lane tiling forbids a
        # blocked lane dim that is neither 128-aligned nor the whole array.
        valid = mask_ref[0, 0] != 0  # (1, block_k)
        s = jnp.where(valid, s, _MASKED)
    if _gated(cfg):
        rows = i * cfg.block_q + jax.lax.broadcasted_iota(
            jnp.int32, (cfg.block_q, cfg.block_k), 0
        )
        cols = j * cfg.block_k + jax.lax.broadcasted_iota(
            jnp.int32, (cfg.block_q, cfg.block_k), 1
        )
        allowed = None
        if cfg.causal:
            allowed = cols <= rows
        if cfg.band is not None:
            in_band = cols > rows - cfg.band
            allowed = in_band if allowed is None else jnp.logical_and(allowed, in_band)
        s = jnp.where(allowed, s, _MASKED)
    return s


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(cfg: _FlashConfig, *refs):
    if cfg.has_mask:
        mask_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        mask_ref = None
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _MASKED)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        # Matmul inputs stay in the model dtype (bf16 runs the MXU at full
        # rate; fp32 inputs don't) with fp32 accumulation; scale applies to
        # the fp32 scores. For fp32 models every cast below is a no-op.
        q = q_ref[0]  # (bq, D)
        k = k_ref[0]  # (bk, D)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * cfg.scale
        )  # (bq, bk) fp32
        s = _tile_bias(cfg, s, i, j, mask_ref)

        m_prev = m_scr[:, 0:1]  # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # exp(_MASKED - _MASKED) would be 1, silently attending to masked
        # positions in all-masked tiles — zero those entries explicitly.
        p = jnp.where(s > _MASK_GUARD, jnp.exp(s - m_new), 0.0)  # (bq, bk)
        correction = jnp.exp(m_prev - m_new)  # (bq, 1)
        l_new = correction * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]  # (bk, D)
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if _gated(cfg):
        pl.when(_visible(cfg, i, j))(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, 0:1] + jnp.log(l_safe)


def _fwd(cfg: _FlashConfig, q, k, v, kv_mask):
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    nq = s_q // cfg.block_q
    nk = s_k // cfg.block_k

    in_specs = []
    inputs = []
    if cfg.has_mask:
        in_specs.append(
            pl.BlockSpec(
                (1, 1, 1, cfg.block_k), lambda b, i, j: (b // cfg.num_heads, j, 0, 0)
            )
        )
        inputs.append(kv_mask)
    in_specs += [
        pl.BlockSpec((1, cfg.block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (cfg.kv_row(b), j, 0)),
        pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (cfg.kv_row(b), j, 0)),
    ]
    inputs += [q, k, v]

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, cfg),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, cfg.block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, cfg.block_q, 1), lambda b, i, j: (b, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            # Per-row logsumexp, stored column-shaped (bq, 1) per tile so the
            # backward pass broadcasts it along lanes with no relayout.
            jax.ShapeDtypeStruct((bh, nq, cfg.block_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, 128), jnp.float32),
            pltpu.VMEM((cfg.block_q, 128), jnp.float32),
            pltpu.VMEM((cfg.block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=cfg.interpret,
    )(*inputs)
    return out, lse


# ---------------------------------------------------------------------------
# Ring-step forward: the same blockwise inner loop, but the online-softmax
# carry (running max m, normalizer l, unnormalized accumulator acc) is an
# HBM-resident input/output instead of kernel-local scratch, so sequence-
# parallel ring attention (parallel/ring_attention.py) can fold one KV chunk
# per ring hop without ever materializing a (C, C) score tensor.
# ---------------------------------------------------------------------------


def _ring_step_kernel(cfg: _FlashConfig, *refs):
    if cfg.has_mask:
        (mask_ref, q_ref, k_ref, v_ref, m_in, l_in, acc_in,
         m_out, l_out, acc_out, m_scr, l_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, m_in, l_in, acc_in,
         m_out, l_out, acc_out, m_scr, l_scr, acc_scr) = refs
        mask_ref = None
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.broadcast_to(m_in[0, 0], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_in[0, 0], l_scr.shape)
        acc_scr[:] = acc_in[0]

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * cfg.scale
        )
        s = _tile_bias(cfg, s, i, j, mask_ref)
        m_prev = m_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(s > _MASK_GUARD, jnp.exp(s - m_new), 0.0)
        correction = jnp.exp(m_prev - m_new)
        l_new = correction * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if _gated(cfg):
        pl.when(_visible(cfg, i, j))(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _write():
        m_out[0, 0] = m_scr[:, 0:1]
        l_out[0, 0] = l_scr[:, 0:1]
        acc_out[0] = acc_scr[:]


def flash_ring_step(
    cfg: _FlashConfig,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None,
    m: jax.Array,
    l: jax.Array,
    acc: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fold one KV chunk into the online-softmax carry.

    Args (all folded to grid layout):
      q:    (BH, S_q, D) local query chunk (model dtype).
      k, v: (BH, C, D) the KV chunk visiting this ring step.
      kv_mask: pre-tiled (B, C // block_k, 1, block_k) int32 or None
        (must match ``cfg.has_mask``).
      m, l: (BH, nq, block_q, 1) fp32 running max / normalizer.
      acc:  (BH, S_q, D) fp32 unnormalized output accumulator.

    Returns the updated ``(m, l, acc)``. ``cfg.causal`` here means "this is
    the diagonal chunk pair" — intra-tile causality applies; fully-below-
    diagonal pairs use a non-causal cfg and fully-above pairs are skipped by
    the caller.
    """
    bh, s_q, d = q.shape
    c = k.shape[1]
    nq = s_q // cfg.block_q
    nk = c // cfg.block_k

    in_specs = []
    inputs = []
    if cfg.has_mask:
        in_specs.append(
            pl.BlockSpec(
                (1, 1, 1, cfg.block_k), lambda b, i, j: (b // cfg.num_heads, j, 0, 0)
            )
        )
        inputs.append(kv_mask)
    carry_specs = [
        pl.BlockSpec((1, 1, cfg.block_q, 1), lambda b, i, j: (b, i, 0, 0)),
        pl.BlockSpec((1, 1, cfg.block_q, 1), lambda b, i, j: (b, i, 0, 0)),
        pl.BlockSpec((1, cfg.block_q, d), lambda b, i, j: (b, i, 0)),
    ]
    in_specs += [
        pl.BlockSpec((1, cfg.block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (cfg.kv_row(b), j, 0)),
        pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (cfg.kv_row(b), j, 0)),
    ] + carry_specs
    inputs += [q, k, v, m, l, acc]

    n_fixed = (1 if cfg.has_mask else 0) + 3
    return pl.pallas_call(
        functools.partial(_ring_step_kernel, cfg),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=list(carry_specs),
        out_shape=[
            jax.ShapeDtypeStruct(m.shape, jnp.float32),
            jax.ShapeDtypeStruct(l.shape, jnp.float32),
            jax.ShapeDtypeStruct(acc.shape, jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_q, 128), jnp.float32),
            pltpu.VMEM((cfg.block_q, 128), jnp.float32),
            pltpu.VMEM((cfg.block_q, d), jnp.float32),
        ],
        # The carries are read once (j == 0) and written once (j == nk - 1):
        # alias them through so XLA updates in place instead of copying.
        input_output_aliases={n_fixed: 0, n_fixed + 1: 1, n_fixed + 2: 2},
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=cfg.interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _recompute_p(cfg: _FlashConfig, q_ref, k_ref, lse_ref, mask_ref, i, j):
    """Recompute the (bq, bk) probability tile from the saved logsumexp.
    q/k are returned in their stored (model) dtype; scale is folded into the
    fp32 score tensor, so callers contracting against q must scale ds."""
    q = q_ref[0]
    k = k_ref[0]
    s = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * cfg.scale
    )
    s = _tile_bias(cfg, s, i, j, mask_ref)
    lse = lse_ref[0, 0]  # (bq, 1) column — broadcasts along lanes
    p = jnp.where(s > _MASK_GUARD, jnp.exp(s - lse), 0.0)
    return q, k, p


def _dq_kernel(cfg: _FlashConfig, *refs):
    if cfg.has_mask:
        (mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr = refs
        mask_ref = None
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        _, k, p = _recompute_p(cfg, q_ref, k_ref, lse_ref, mask_ref, i, j)
        do = do_ref[0]  # (bq, D)
        v = v_ref[0]  # (bk, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk)
        ds = p * (dp - delta_ref[0, 0])  # delta: (bq, 1) column
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if _gated(cfg):
        pl.when(_visible(cfg, i, j))(_compute)
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finalize():
        # s = (q·scale)·kᵀ, so dq picks up one more factor of scale.
        dq_ref[0] = (dq_scr[:] * cfg.scale).astype(dq_ref.dtype)


def _dkdv_kernel(cfg: _FlashConfig, *refs):
    if cfg.has_mask:
        (mask_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        mask_ref = None
    j = pl.program_id(1)  # k-block: parallel axis
    # Sequential accumulation axis walks (group, q-block) pairs: with grouped
    # kv heads (GQA), grid axis 0 runs over B*H_kv and the q-heads sharing
    # each kv head are folded in here, so dk/dv accumulate across the whole
    # group in VMEM scratch with no cross-grid-row write race.
    t = pl.program_id(2)
    nt = pl.num_programs(2)
    nq = nt // cfg.group
    i = t % nq  # q-block within the current group member

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q, _, p = _recompute_p(cfg, q_ref, k_ref, lse_ref, mask_ref, i, j)
        do = do_ref[0]  # (bq, D)
        v = v_ref[0]  # (bk, D)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # pᵀ·do -> (bk, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0])
        # s = scale·(q·kᵀ): the scale that used to ride on q folds into ds.
        dk_scr[:] += jax.lax.dot_general(
            (ds * cfg.scale).astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (ds·scale)ᵀ·q -> (bk, D)

    if _gated(cfg):
        pl.when(_visible(cfg, i, j))(_compute)
    else:
        _compute()

    @pl.when(t == nt - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(cfg: _FlashConfig, q, k, v, kv_mask, out, lse, do):
    bh, s_q, d = q.shape
    nq = s_q // cfg.block_q

    # Per-row rowsum(do * out) — tiny elementwise op, left to XLA to fuse.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).reshape(bh, nq, cfg.block_q, 1)
    return flash_chunk_bwd(cfg, q, k, v, kv_mask, lse, delta, do)


def flash_chunk_bwd(cfg: _FlashConfig, q, k, v, kv_mask, lse, delta, do):
    """dq/dk/dv for one (q, KV-chunk) pair given the GLOBAL per-row softmax
    statistics (lse) and delta = rowsum(do·out). For plain flash attention the
    chunk is the whole sequence; ring attention calls this once per ring hop
    (with its local chunk pair) and accumulates — the decomposition is exact
    because p recomputed from the global lse is the true probability tile."""
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    nq = s_q // cfg.block_q
    nk = s_k // cfg.block_k

    q_spec_i = lambda b, i, j: (b, i, 0)  # noqa: E731
    lse_spec_i = lambda b, i, j: (b, i, 0, 0)  # noqa: E731

    in_specs = []
    inputs = []
    if cfg.has_mask:
        in_specs.append(
            pl.BlockSpec(
                (1, 1, 1, cfg.block_k), lambda b, i, j: (b // cfg.num_heads, j, 0, 0)
            )
        )
        inputs.append(kv_mask)
    in_specs += [
        pl.BlockSpec((1, cfg.block_q, d), q_spec_i),
        pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (cfg.kv_row(b), j, 0)),
        pl.BlockSpec((1, cfg.block_k, d), lambda b, i, j: (cfg.kv_row(b), j, 0)),
        pl.BlockSpec((1, cfg.block_q, d), q_spec_i),
        pl.BlockSpec((1, 1, cfg.block_q, 1), lse_spec_i),
        pl.BlockSpec((1, 1, cfg.block_q, 1), lse_spec_i),
    ]
    inputs += [q, k, v, do, lse, delta]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, cfg.block_q, d), q_spec_i),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((cfg.block_q, d), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=cfg.interpret,
    )(*inputs)

    # dk/dv: k-blocks parallel; (group member, q-block) pairs sequential.
    # Grid axis 0 runs over the FOLDED kv rows (B*H_kv): with grouped kv
    # heads every q-head sharing a kv head lands on the same grid row, so
    # its contribution accumulates in the same VMEM scratch.
    bkv = k.shape[0]
    group = cfg.group

    def q_row(b, t):
        # kv grid row b + group member t//nq -> row of the (B*H, ...) arrays.
        if group == 1:
            return b
        return (b // cfg.kv_heads) * cfg.num_heads + (b % cfg.kv_heads) * group + t // nq

    in_specs_kv = []
    inputs_kv = []
    if cfg.has_mask:
        in_specs_kv.append(
            pl.BlockSpec(
                (1, 1, 1, cfg.block_k), lambda b, j, t: (b // cfg.kv_heads, j, 0, 0)
            )
        )
        inputs_kv.append(kv_mask)
    in_specs_kv += [
        pl.BlockSpec((1, cfg.block_q, d), lambda b, j, t: (q_row(b, t), t % nq, 0)),
        pl.BlockSpec((1, cfg.block_k, d), lambda b, j, t: (b, j, 0)),
        pl.BlockSpec((1, cfg.block_k, d), lambda b, j, t: (b, j, 0)),
        pl.BlockSpec((1, cfg.block_q, d), lambda b, j, t: (q_row(b, t), t % nq, 0)),
        pl.BlockSpec((1, 1, cfg.block_q, 1), lambda b, j, t: (q_row(b, t), t % nq, 0, 0)),
        pl.BlockSpec((1, 1, cfg.block_q, 1), lambda b, j, t: (q_row(b, t), t % nq, 0, 0)),
    ]
    inputs_kv += [q, k, v, do, lse, delta]

    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, cfg),
        grid=(bkv, nk, nq * group),
        in_specs=in_specs_kv,
        out_specs=[
            pl.BlockSpec((1, cfg.block_k, d), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, cfg.block_k, d), lambda b, j, t: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkv, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((bkv, s_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((cfg.block_k, d), jnp.float32),
            pltpu.VMEM((cfg.block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=cfg.interpret,
    )(*inputs_kv)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing + public entry point
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _FlashConfig, q, k, v, kv_mask):
    out, _ = _fwd(cfg, q, k, v, kv_mask)
    return out


def _flash_fwd_rule(cfg, q, k, v, kv_mask):
    out, lse = _fwd(cfg, q, k, v, kv_mask)
    return out, (q, k, v, kv_mask, out, lse)


def _flash_bwd_rule(cfg, residuals, do):
    q, k, v, kv_mask, out, lse = residuals
    dq, dk, dv = _bwd(cfg, q, k, v, kv_mask, out, lse, do)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    kv_mask: jax.Array | None = None,
    causal: bool = False,
    window: int = 0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Blockwise attention over (B, S, H, D) activations.

    Args:
      q, k, v: (B, S_q|S_k, H, D). Cross-attention (S_q != S_k) is supported.
        Grouped-query attention: k/v may carry FEWER heads (B, S_k, H_kv, D)
        with H % H_kv == 0 — kv stays folded at H_kv rows and the kernel's
        BlockSpec index maps assign each q-head its kv group, so kv HBM
        traffic stays at the H_kv rate (no materialized repeat).
      kv_mask: optional (B, S_k) bool/int, True where the key is a real token
        (the padding mask of ``ops.masks.make_padding_mask`` squeezed to 2D).
      causal: structural causal masking (requires S_q == S_k positions to be
        aligned, as in self-attention).
      window: causal sliding window (requires ``causal``): row r attends
        cols in [r - window + 1, r]. Structural like causality — tiles
        outside the band are skipped, so per-row compute is O(window).
      block_q, block_k: tile sizes; shrunk to the largest divisor of the
        sequence length at or below the request.
      interpret: run in Pallas interpret mode. Default: True off-TPU, so the
        same code path is testable on CPU.

    Returns the (B, S_q, H, D) attention output in q's dtype.
    """
    if q.ndim != 4:
        raise ValueError(f"expected (B, S, H, D) inputs, got shape {q.shape}")
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    h_kv = k.shape[2]
    if v.shape[2] != h_kv:
        raise ValueError(f"k has {h_kv} heads but v has {v.shape[2]}")
    if h % h_kv:
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {h_kv}"
        )
    if causal and s_q != s_k:
        raise ValueError("causal flash attention requires S_q == S_k")
    if window and not causal:
        raise ValueError("window requires causal=True (causal sliding window)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bq, s_q_pad = _block_and_padded_len(s_q, block_q)
    bk, s_k_pad = _block_and_padded_len(s_k, block_k)
    pad_q, pad_k = s_q_pad - s_q, s_k_pad - s_k
    if pad_k and kv_mask is None and not causal:
        # Padded keys must not receive attention; under causality they sit
        # above the diagonal for every real query row, so no mask is needed.
        kv_mask = jnp.ones((b, s_k), dtype=jnp.int32)
    if kv_mask is not None:
        kv_mask = jnp.broadcast_to(kv_mask, (b, s_k))
        if pad_k:
            kv_mask = jnp.pad(kv_mask.astype(jnp.int32), ((0, 0), (0, pad_k)))
    if pad_q or pad_k:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))

    cfg = _FlashConfig(
        causal=causal,
        has_mask=kv_mask is not None,
        block_q=bq,
        block_k=bk,
        num_heads=h,
        scale=d**-0.5,
        interpret=bool(interpret),
        num_kv_heads=h_kv,
        band=int(window) if window else None,
    )

    # (B, S, H, D) -> (B*H, S, D): heads become independent grid rows (kv
    # folds at its own, possibly smaller, head count).
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * x.shape[2], x.shape[1], d)

    # Pre-tile the mask to (B, nk, 1, block_k): each (1, block_k) tile is a
    # full block under the TPU lane-tiling rules.
    mask_i32 = (
        None
        if kv_mask is None
        else kv_mask.astype(jnp.int32).reshape(b, s_k_pad // bk, 1, bk)
    )
    out = _flash(cfg, fold(q), fold(k), fold(v), mask_i32)
    out = out.reshape(b, h, s_q_pad, d).transpose(0, 2, 1, 3)
    return out[:, :s_q] if pad_q else out


# ---------------------------------------------------------------------------
# Paged (block-table) attention: the kernel-facing entry of the paged KV
# pool (kernels/kv_pool.py). K/V live in ONE (num_blocks, B, H_kv, D) pool
# per layer; each sequence addresses its blocks through a table row, so
# resident KV is proportional to used tokens and a shared prefix is the
# same physical blocks in two tables. This function gathers K/V through
# the table and attends the valid prefix — the dense path stays available
# behind the same serving interface (--kv_layout dense), and the fused
# Pallas decode kernel that reads blocks in place (no gathered view) is
# the ROADMAP's next kernel item.
# ---------------------------------------------------------------------------


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    table: jax.Array,
    lengths: jax.Array,
    *,
    impl: str = "xla",
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    width: int | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Attention over a paged KV pool through per-sequence block tables.

    Args:
      q: (N, S_q, H, D) queries; row ``s`` sits at absolute positions
        ``lengths[s] - S_q .. lengths[s] - 1`` (decode: S_q = 1 at the
        newest position, already written into the pool).
      k_pool, v_pool: (num_blocks, B, H_kv, D) pool buffers — bf16/fp32
        values, or int8 codes paired with ``k_scale``/``v_scale``.
      table: (N, nmax) int32 block table (``kernels/kv_pool.KVPool``).
      lengths: (N,) int32 valid KV length per sequence — positions
        ``>= lengths[s]`` (stale rows, sink gathers) are masked out.
      impl: "xla" — bitwise-identical math to the dense cache path
        (gather + fp32-softmax ``dot_product_attention``); "flash" — the
        Pallas blockwise kernel over the gathered view (decode S_q=1
        only: its key-padding mask carries no per-row causality);
        "paged_flash" — the fused Pallas kernel reading pool blocks in
        place through the table, no gathered view (any S_q, per-row
        offset causality, int8 dequant and GQA grouping fused).
      k_scale, v_scale: (num_blocks, B, H_kv, 1) fp32 dequant scales for
        int8 pools. "xla"/"flash" dequantize the gathered view (same
        round trip as the serving path); "paged_flash" consumes
        codes + scales inside the kernel.
      width: gather width in TOKENS (a multiple of the block size,
        typically ``ceil(max lengths / B) * B``). Clamps the gathered
        view so short slots don't pay an nmax-wide gather; positions
        beyond every slot's length carry softmax weight exactly 0.0 in
        fp32, so the clamp is bitwise-invisible. Ignored by
        "paged_flash" (the kernel skips out-of-length blocks instead).

    Returns (N, S_q, H, D) attention outputs in q's dtype.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("int8 pools need BOTH k_scale and v_scale")
    n, s_q = q.shape[:2]
    if impl == "paged_flash":
        from transformer_tpu.kernels.paged_flash import paged_flash_attention

        return paged_flash_attention(
            q, k_pool, v_pool, table, lengths,
            k_scale=k_scale, v_scale=v_scale, interpret=interpret,
        )
    from transformer_tpu.kernels.kv_pool import gather_block_views

    k = gather_block_views(k_pool, table, width=width)  # (N, L, H_kv, D)
    v = gather_block_views(v_pool, table, width=width)
    if k_scale is not None:
        k = k.astype(q.dtype) * gather_block_views(
            k_scale, table, width=width
        ).astype(q.dtype)
        v = v.astype(q.dtype) * gather_block_views(
            v_scale, table, width=width
        ).astype(q.dtype)
    L = k.shape[1]
    if impl == "flash":
        if s_q != 1:
            raise ValueError(
                "paged_attention impl='flash' serves decode (S_q = 1): its "
                "key-padding mask cannot express per-row offset causality"
            )
        kv_mask = jnp.arange(L)[None, :] < lengths[:, None]
        return flash_attention(
            q, k, v, kv_mask=kv_mask, causal=False,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )
    if impl != "xla":
        raise ValueError(f"unknown paged_attention impl {impl!r}")
    from transformer_tpu.ops.attention import dot_product_attention

    # The offset causal mask of make_cache_prefix_mask, batched per
    # sequence: query i (absolute position lengths - s_q + i) attends
    # pool position j iff j <= that position.
    positions = jnp.arange(L)[None, None, None, :]
    q_pos = (lengths[:, None, None, None] - s_q) + jnp.arange(s_q)[
        None, None, :, None
    ]
    out, _ = dot_product_attention(q, k, v, positions <= q_pos)
    return out
