"""Fused paged-decode attention: a Pallas kernel over the KVPool block table.

The gather-path twins (``serve/scheduler.py`` ``_pool_step_paged`` /
``paged_attention(impl="xla")``) first materialize a dense-ordered view of
every slot's whole KV working set through ``gather_block_views`` — one extra
full HBM pass per decode step on a path that is already KV-bandwidth bound
(decode arithmetic intensity ~0.18 vs prefill's ~0.34, ``analysis costs``).
This kernel removes that pass: the grid iterates the block TABLE, the
BlockSpec index map of the K/V pool inputs resolves ``table[s, j]`` through a
scalar-prefetched table (the classic paged-attention schedule), and each
(block_tokens, H_kv, D) block is consumed straight from the pool buffer it
lives in. Fused into the block read:

- online-softmax accumulation across table entries (running max / normalizer
  / fp32 output accumulator in VMEM scratch, exactly like
  ``flash_attention``'s k-axis walk);
- GQA head grouping: queries arrive folded as (N, H_kv, G*S_q, D) so one
  block read serves all ``G = H/H_kv`` query heads of its kv head — kv HBM
  traffic stays at the H_kv rate with no materialized repeat;
- int8 dequantization: quantized pools pass codes AND scales as separate
  inputs and the kernel dequantizes per block tile in VMEM — no bf16 pool
  copy is ever materialized in HBM;
- stale-row / sink masking from ``lengths``: per-row offset causality
  (query row i of sequence s sits at absolute position
  ``lengths[s] - S_q + i``) masks rejected-speculation leftovers, unwritten
  sink gathers, and lookahead rows in one predicate — which is also what
  lifts the gather-flash path's S_q = 1 restriction (verify rows S_q = k+1
  attend causally inside the row).

Numerics: scores are computed per (q-row, key) pair exactly like the XLA
oracle (dot in the compute dtype, cast to fp32, scaled), so masked positions
contribute exactly 0.0 either way; only the softmax normalizer/PV summation
ORDER differs (online vs full-row), which perturbs low fp32 bits — the
serving tests pin answer-level byte identity, the kernel tests pin per-dtype
tolerances.

On non-TPU backends the kernel runs in Pallas interpret mode (the CPU suite's
path); ``interpret=None`` auto-detects, same convention as
``flash_attention``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from transformer_tpu.kernels.flash_attention import (
    _MASK_GUARD,
    _MASKED,
    _compiler_params,
)

# Lane width of the m/l scratch rows (replicate-to-lanes layout, same as the
# flash kernel's (block_q, 128) running-max/normalizer scratch).
_LANES = 128


def _paged_kernel(
    # scalar-prefetch refs
    table_ref,    # (N, nmax) int32 — SMEM
    lengths_ref,  # (N,) int32 — SMEM
    # inputs
    q_ref,        # (1, H_kv, G*S_q, D) — queries folded by kv group
    k_ref,        # (1, B, H_kv, D) — pool block, resolved via table[s, j]
    v_ref,        # (1, B, H_kv, D)
    *rest,        # [k_scale_ref, v_scale_ref,] out_ref, m_scr, l_scr, acc_scr
    s_q: int,
    block_tokens: int,
    scale: float,
    quantized: bool,
):
    if quantized:
        k_scale_ref, v_scale_ref, out_ref, m_scr, l_scr, acc_scr = rest
    else:
        out_ref, m_scr, l_scr, acc_scr = rest
        k_scale_ref = v_scale_ref = None
    s, j = pl.program_id(0), pl.program_id(1)
    nmax = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _MASKED)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lengths_ref[s]

    # Blocks that start at or past this sequence's valid length hold no
    # visible position (stale table tails point at the pinned sink block):
    # skip their compute outright. The DMA still lands — table-width HBM
    # traffic is bounded by the allocator keeping tables trimmed.
    @pl.when(j * block_tokens < length)
    def _block():
        dtype = q_ref.dtype
        k = k_ref[0]  # (B, H_kv, D)
        v = v_ref[0]
        if quantized:
            # Dequant fused into the block read: codes * per-(position, head)
            # scale, in the compute dtype — the same round trip the dense
            # cache's read path applies, so values match it bit-for-bit.
            k = k.astype(dtype) * k_scale_ref[0].astype(dtype)
            v = v.astype(dtype) * v_scale_ref[0].astype(dtype)
        kt = jnp.swapaxes(k, 0, 1)  # (H_kv, B, D)
        vt = jnp.swapaxes(v, 0, 1)
        q = q_ref[0]  # (H_kv, GS, D)
        # Scores exactly as the XLA oracle computes them: dot in the compute
        # dtype, cast to fp32, then scale — per (row, key) values are
        # independent of blocking, so they match the gather path bitwise.
        scores = jax.lax.dot_general(
            q, kt, (((2,), (2,)), ((0,), (0,)))
        ).astype(jnp.float32) * scale  # (H_kv, GS, B)

        # Per-row offset causality: folded row r = g * S_q + i holds query
        # index i = r % S_q at absolute position length - S_q + i; pool
        # position j*B + b is visible iff <= that. This one predicate hides
        # stale rows (positions >= length), sink reads, and — for verify
        # rows — each lookahead token's future.
        gs, b = scores.shape[1], scores.shape[2]
        row = jax.lax.broadcasted_iota(jnp.int32, (gs, b), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (gs, b), 1)
        q_pos = (length - s_q) + row % s_q
        visible = (j * block_tokens + col) <= q_pos
        scores = jnp.where(visible[None], scores, _MASKED)

        m_prev = m_scr[...][:, :, :1]  # (H_kv, GS, 1)
        l_prev = l_scr[...][:, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # Exp-guard: fully-masked entries must contribute exactly 0 (not
        # exp(_MASKED - m) underflow noise) so masked-column parity with the
        # XLA softmax holds exactly.
        p = jnp.where(scores > _MASK_GUARD, jnp.exp(scores - m_new), 0.0)
        l_scr[...] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_scr.shape
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(dtype), vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nmax - 1)
    def _finalize():
        out_ref[0] = (
            acc_scr[...] / l_scr[...][:, :, :1]
        ).astype(out_ref.dtype)


def paged_flash_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    table: jax.Array,
    lengths: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused attention over a paged KV pool, blocks read in place.

    Args:
      q: (N, S_q, H, D) queries; row ``s`` sits at absolute positions
        ``lengths[s] - S_q .. lengths[s] - 1`` (decode S_q = 1; speculative
        verify S_q = k + 1, causal inside the row).
      k_pool, v_pool: (num_blocks, B, H_kv, D) pool buffers — bf16/fp32
        values, or int8 codes when ``k_scale``/``v_scale`` are given.
      table: (N, nmax) int32 block table (``kernels/kv_pool.KVPool``);
        entries past a slot's owned count point at the pinned sink block 0.
      lengths: (N,) int32 valid KV length per sequence (including the S_q
        rows just written for this forward).
      k_scale, v_scale: (num_blocks, B, H_kv, 1) fp32 dequant scales for
        int8 pools (``init_block_pool(quantize=True)`` storage layout); the
        kernel consumes codes + scales directly.
      interpret: Pallas interpret mode; default True off-TPU (same
        convention as ``flash_attention``).

    Returns (N, S_q, H, D) attention outputs in q's dtype.
    """
    n, s_q, h, d = q.shape
    num_blocks, block_tokens, h_kv, d_k = k_pool.shape
    if d_k != d:
        raise ValueError(f"head_dim mismatch: q {d} vs pool {d_k}")
    if h % h_kv:
        raise ValueError(f"query heads {h} must be a multiple of kv heads {h_kv}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("int8 pools need BOTH k_scale and v_scale")
    # Mosaic packs the pool's token axis into (sublane, lane) vregs whose
    # sublane count depends on the element width: 8 rows for fp32, 16 for
    # bf16, 32 for int8. A block_tokens that neither divides nor is a
    # multiple of that count forces a mid-vreg block boundary the lowering
    # rejects with an opaque shape error — fail loudly at call time instead.
    sublane = {4: 8, 2: 16, 1: 32}.get(jnp.dtype(k_pool.dtype).itemsize, 8)
    if block_tokens % sublane and sublane % block_tokens:
        raise ValueError(
            f"block_tokens {block_tokens} is incompatible with the "
            f"{jnp.dtype(k_pool.dtype).name} pool's native sublane tiling "
            f"({sublane}): it must divide {sublane} or be a multiple of it"
        )
    quantized = k_scale is not None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    group = h // h_kv
    gs = group * s_q
    nmax = table.shape[1]
    table = table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    # Fold queries by kv group: (N, S_q, H, D) -> (N, H_kv, G*S_q, D) with
    # folded row r = g*S_q + i (head h = kv_head*G + g, query index i) — one
    # pool block read serves every query head of its kv head.
    qf = (
        q.transpose(0, 2, 1, 3)
        .reshape(n, h_kv, group, s_q, d)
        .reshape(n, h_kv, gs, d)
    )

    def _at_table(s, j, table_ref, lengths_ref):
        return (table_ref[s, j], 0, 0, 0)

    def _at_seq(s, j, table_ref, lengths_ref):
        return (s, 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, h_kv, gs, d), _at_seq),
        pl.BlockSpec((1, block_tokens, h_kv, d), _at_table),
        pl.BlockSpec((1, block_tokens, h_kv, d), _at_table),
    ]
    inputs = [qf, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_tokens, h_kv, 1), _at_table),
            pl.BlockSpec((1, block_tokens, h_kv, 1), _at_table),
        ]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n, nmax),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h_kv, gs, d), _at_seq),
        scratch_shapes=[
            pltpu.VMEM((h_kv, gs, _LANES), jnp.float32),  # running max
            pltpu.VMEM((h_kv, gs, _LANES), jnp.float32),  # normalizer
            pltpu.VMEM((h_kv, gs, d), jnp.float32),       # output accumulator
        ],
    )
    kernel = functools.partial(
        _paged_kernel,
        s_q=s_q,
        block_tokens=block_tokens,
        scale=d**-0.5,
        quantized=quantized,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, h_kv, gs, d), q.dtype),
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=bool(interpret),
    )(table, lengths, *inputs)
    # Unfold (N, H_kv, G*S_q, D) -> (N, S_q, H, D).
    return (
        out.reshape(n, h_kv, group, s_q, d)
        .reshape(n, h, s_q, d)
        .transpose(0, 2, 1, 3)
    )
