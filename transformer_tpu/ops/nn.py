"""Primitive neural-net building blocks: dense, embedding, layernorm, dropout.

Functional style: ``*_init(key, ...) -> params`` (a dict pytree of jnp arrays)
and ``*_apply(params, x, ...) -> y``. Parameters live in ``param_dtype``
(fp32 by default); compute casts to the caller's ``dtype`` (bf16 on TPU so the
MXU runs at full rate).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Params = dict


def glorot_uniform(key: jax.Array, shape: tuple[int, ...], dtype, fan_in: int, fan_out: int):
    """Glorot/Xavier uniform — the initializer the reference inherits from
    ``tf.keras.layers.Dense`` defaults (reference ``Attention.py:46-50``,
    ``point_ffn.py:4-6``)."""
    limit = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(key, shape, dtype=dtype, minval=-limit, maxval=limit)


def dense_init(key: jax.Array, d_in: int, d_out: int, dtype=jnp.float32) -> Params:
    return {
        "kernel": glorot_uniform(key, (d_in, d_out), dtype, d_in, d_out),
        "bias": jnp.zeros((d_out,), dtype=dtype),
    }


def dense_apply(params: Params, x: jax.Array, dtype=None) -> jax.Array:
    dtype = dtype or x.dtype
    kernel = params["kernel"].astype(dtype)
    bias = params["bias"].astype(dtype)
    return jnp.matmul(x.astype(dtype), kernel) + bias


def embedding_init(key: jax.Array, vocab_size: int, d_model: int, dtype=jnp.float32) -> Params:
    # Normal(0, 1) scaled down — standard for transformer embeddings that are
    # multiplied by sqrt(d_model) in the stack prologue (reference ``Encoder.py:52``).
    table = jax.random.normal(key, (vocab_size, d_model), dtype=dtype) * (d_model**-0.5)
    return {"table": table}


def embedding_lookup(params: Params, ids: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    return jnp.take(params["table"].astype(dtype), ids, axis=0)


def embedding_attend(params: Params, x: jax.Array) -> jax.Array:
    """Tied output projection: logits = x @ table.T (BASELINE.json configs[3])."""
    table = params["table"].astype(x.dtype)
    return jnp.matmul(x, table.T)


def layernorm_init(d: int, dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((d,), dtype=dtype), "bias": jnp.zeros((d,), dtype=dtype)}


def layernorm_apply(params: Params, x: jax.Array, epsilon: float = 1e-6) -> jax.Array:
    """LayerNorm with the reference's epsilon=1e-6 (``Encoder.py:13-14``).

    Statistics are computed in fp32 regardless of the compute dtype — bf16
    variance is numerically unsafe — then the result is cast back.
    """
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    normed = (x32 - mean) * jax.lax.rsqrt(var + epsilon)
    out = normed * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
    return out.astype(orig_dtype)


def dropout(key: jax.Array | None, x: jax.Array, rate: float, deterministic: bool) -> jax.Array:
    """Inverted dropout. ``deterministic=True`` (eval) or rate==0 is identity —
    and both must be decided at trace time (static), never via data-dependent
    control flow inside jit."""
    if deterministic or rate == 0.0:
        return x
    if key is None:
        raise ValueError("dropout in training mode requires an rng key")
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, p=keep, shape=x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def remat_layer(fn, cfg):
    """Wrap a per-layer apply in ``jax.checkpoint`` under the configured
    policy (``ModelConfig.remat_policy``): "full" recomputes everything;
    "dots" saves matmul outputs and recomputes only the elementwise/
    bandwidth-bound ops (``dots_with_no_batch_dims_saveable``) — the same
    gradients either way, different memory/recompute point."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    return jax.checkpoint(fn)
