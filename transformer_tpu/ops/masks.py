"""Attention-mask construction.

Capability parity with the reference's ``positionalencoding.py:25-52``
(``create_padding_mask`` / ``create_look_ahead_mask`` / ``create_masks``) with
one deliberate semantic flip: here a mask is **boolean with True = "may
attend"** (the JAX-ecosystem convention), converted to an additive bias right
at the attention op. The reference instead uses float masks where 1.0 means
"blocked" and adds ``mask * -1e9`` (``Attention.py:26``). The resulting
attention patterns are identical; the boolean form fuses cleanly under XLA and
feeds block-granular masking in the Pallas kernels.

Masks are built from raw token ids inside the forward pass, exactly like the
reference (``Transformer.py:23``) — they are not part of the data pipeline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from transformer_tpu.config import PAD_ID

# Large-negative constant used for additive masking. Finite (not -inf) so that
# fully-masked rows produce a uniform softmax instead of NaNs — same approach
# as the reference's -1e9 (``Attention.py:26``).
NEG_INF = -1e9


def make_padding_mask(ids: jax.Array, pad_id: int = PAD_ID) -> jax.Array:
    """(B, S) int ids -> (B, 1, 1, S) bool, True where the key position is a
    real token (reference ``create_padding_mask``, ``positionalencoding.py:25-30``,
    with the blocked/allowed polarity flipped as documented above)."""
    allowed = ids != pad_id
    return allowed[:, None, None, :]


def make_causal_mask(seq_len: int, window: int = 0) -> jax.Array:
    """(1, 1, S, S) bool, True where query position i may attend key position
    j<=i (reference ``create_look_ahead_mask``, ``positionalencoding.py:32-34``).
    ``window > 0`` additionally bounds attention to the last ``window``
    positions (banded/sliding-window causal mask, Mistral-style)."""
    mask = jnp.tril(jnp.ones((seq_len, seq_len), dtype=jnp.bool_))
    if window:
        mask = jnp.logical_and(
            mask, jnp.triu(jnp.ones_like(mask), k=-(window - 1))
        )
    return mask[None, None, :, :]


def make_cache_prefix_mask(
    index: jax.Array, s_q: int, buf_len: int, window: int = 0
) -> jax.Array:
    """(1, 1, s_q, buf_len) bool: the offset causal mask of a prefill chunk
    attending into a partially-filled full-length decode cache. Query i sits
    at absolute position ``index + i`` and may attend buffer position j iff
    ``j <= index + i`` — so a chunk of S_q prompt tokens stays causal against
    both the already-cached prefix and itself. ``window > 0`` additionally
    bounds each query to the last ``window`` positions (the banded form used
    when ``attention_window`` is set on a full-length cache)."""
    positions = jnp.arange(buf_len)[None, None, None, :]
    q_pos = index + jnp.arange(s_q)[None, None, :, None]
    valid = positions <= q_pos
    if window:
        valid = jnp.logical_and(valid, positions > q_pos - window)
    return valid


def make_rolling_prefill_mask(
    index: jax.Array, s_q: int, buf_len: int
) -> jax.Array:
    """(1, 1, s_q, buf_len + s_q) bool mask for a prefill chunk attending a
    ROLLING window cache: the first ``buf_len`` key columns are the buffer's
    pre-chunk slots, the last ``s_q`` columns are the chunk's own keys.

    Buffer slot s last held absolute position ``p_old(s)`` — the largest
    ``p < index`` with ``p % buf_len == s`` (negative = never written). Query
    i (absolute position ``index + i``) may attend slot s iff ``p_old(s)``
    is real and inside its band ``(index + i - buf_len, index + i]``; chunk
    key j (absolute position ``index + j``) iff ``j <= i`` (``j > i -
    buf_len`` holds by construction since chunks are capped at ``buf_len``).
    This reproduces, position for position, what the one-token-per-step
    rolling path would have attended at each tick."""
    slots = jnp.arange(buf_len)[None, :]
    p_old = (index - 1) - ((index - 1 - slots) % buf_len)
    q_pos = index + jnp.arange(s_q)[:, None]
    old_ok = jnp.logical_and(p_old >= 0, p_old > q_pos - buf_len)
    chunk_ok = jnp.arange(s_q)[None, :] <= jnp.arange(s_q)[:, None]
    return jnp.concatenate([old_ok, chunk_ok], axis=1)[None, None]


def make_seq2seq_masks(
    inp: jax.Array, tar: jax.Array, pad_id: int = PAD_ID
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The three masks of an encoder-decoder step (reference ``create_masks``,
    ``positionalencoding.py:37-52``):

    - ``enc_mask``    (B,1,1,S_src): encoder self-attention padding mask.
    - ``combined``    (B,1,S_tgt,S_tgt): decoder self-attention — causal AND
      target-padding (the reference's ``tf.maximum`` of blocked-masks is a
      logical-AND of allowed-masks).
    - ``cross_mask``  (B,1,1,S_src): decoder cross-attention mask over the
      *encoder* keys (source padding).
    """
    enc_mask = make_padding_mask(inp, pad_id)
    causal = make_causal_mask(tar.shape[1])
    tgt_pad = make_padding_mask(tar, pad_id)
    combined = jnp.logical_and(causal, tgt_pad)
    cross_mask = make_padding_mask(inp, pad_id)
    return enc_mask, combined, cross_mask


def attention_bias(mask: jax.Array | None, dtype=jnp.float32) -> jax.Array | None:
    """Boolean allowed-mask -> additive bias (0 where allowed, NEG_INF where
    blocked), in the requested compute dtype."""
    if mask is None:
        return None
    return jnp.where(mask, jnp.zeros((), dtype=dtype), jnp.asarray(NEG_INF, dtype=dtype))
