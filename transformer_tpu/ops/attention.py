"""Scaled dot-product attention and multi-head attention.

The TPU-native counterpart of the reference's ``Attention.py``:

- ``scaled_dot_product_attention`` (``Attention.py:3-34``) becomes
  ``dot_product_attention``: two einsums around an fp32 softmax, with the mask
  applied as an additive bias. XLA fuses the scale/bias/softmax chain; the
  matmuls land on the MXU.
- ``MultiHeadAttention`` (``Attention.py:36-78``) becomes ``mha_init`` /
  ``mha_apply`` over a parameter pytree. Instead of the reference's four
  ``d_model -> d_model`` Dense layers plus reshape/transpose
  (``Attention.py:46-57``), projections map directly ``d_model -> (heads,
  head_dim)`` via one einsum — no transposes in the hot path, and the ``heads``
  axis is a real array axis that tensor parallelism shards on the ``model``
  mesh axis.

Activation layout is (batch, seq, heads, head_dim) throughout.

Call convention: ``mha_apply(params, x_q, x_kv, mask)`` — query input first.
(The reference's positional order is ``(v, k, q, mask)``, ``Attention.py:59``;
self-attention calls are unaffected, cross-attention callers must pass
query=decoder state, kv=encoder output.)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from transformer_tpu.ops.masks import attention_bias
from transformer_tpu.ops.nn import Params, glorot_uniform


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    return_weights: bool = False,
) -> tuple[jax.Array, jax.Array | None]:
    """softmax(q·kᵀ/√d + bias)·v for (B, S, H, D) queries.

    Matches the math of reference ``Attention.py:20-32``. The softmax runs in
    fp32 even when inputs are bf16 — exp/sum in bf16 loses enough precision to
    move BLEU. Returns ``(output, weights)`` where ``weights`` is the
    (B, H, S_q, S_k) attention map when ``return_weights`` else None (the
    reference always returns it, ``Attention.py:32-34``; here it is opt-in so
    training never materializes the (B,H,S,S) tensor twice).

    Grouped-query / multi-query attention (Shazeer 2019, "One Write-Head is
    All You Need"): ``k``/``v`` may carry FEWER heads (B, S_k, H_kv, D) with
    ``H % H_kv == 0`` — each kv head serves a group of ``H/H_kv`` query
    heads. The contraction runs grouped (no materialized kv repeat).
    """
    head_dim = q.shape[-1]
    scale = head_dim**-0.5
    H, Hkv = q.shape[2], k.shape[2]
    if H == Hkv:
        # (B, S_q, H, D) x (B, S_k, H, D) -> (B, H, S_q, S_k)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        if mask is not None:
            logits = logits + attention_bias(mask, dtype=jnp.float32)
        weights = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(q.dtype), v)
        return out, (weights if return_weights else None)

    if H % Hkv:
        raise ValueError(f"query heads {H} must be a multiple of kv heads {Hkv}")
    G = H // Hkv
    B, Sq = q.shape[:2]
    qg = q.reshape(B, Sq, Hkv, G, head_dim)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32) * scale
    if mask is not None:
        bias = attention_bias(mask, dtype=jnp.float32)  # (B|1, H|1, S_q|1, S_k)
        if bias.shape[1] != 1:
            raise ValueError(
                "per-head masks are unsupported with grouped kv heads"
            )
        logits = logits + bias[:, :, None]  # broadcast over (kv-head, group)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", weights.astype(q.dtype), v)
    out = out.reshape(B, Sq, H, head_dim)
    full_w = (
        weights.reshape(B, H, *weights.shape[3:]) if return_weights else None
    )
    return out, full_w


def mha_init(
    key: jax.Array,
    d_model: int,
    num_heads: int,
    param_dtype=jnp.float32,
    num_kv_heads: int | None = None,
) -> Params:
    """Parameters for multi-head attention: q/k/v projections shaped
    (d_model, heads, head_dim) and an output projection (heads, head_dim,
    d_model). Same parameter count as the reference's four Dense layers
    (``Attention.py:46-50``) — just pre-split by head.

    ``num_kv_heads < num_heads`` gives grouped-query/multi-query attention:
    k/v kernels carry only (d_model, kv_heads, head_dim) — fewer parameters
    and an ``H/H_kv``-times smaller decode KV cache."""
    head_dim = d_model // num_heads
    kv_heads = num_kv_heads or num_heads
    kq, kk, kv, ko = jax.random.split(key, 4)

    def proj(k, heads):
        fan_out = heads * head_dim
        w = glorot_uniform(k, (d_model, fan_out), param_dtype, d_model, fan_out)
        return w.reshape(d_model, heads, head_dim)

    return {
        "query": {"kernel": proj(kq, num_heads), "bias": jnp.zeros((num_heads, head_dim), param_dtype)},
        "key": {"kernel": proj(kk, kv_heads), "bias": jnp.zeros((kv_heads, head_dim), param_dtype)},
        "value": {"kernel": proj(kv, kv_heads), "bias": jnp.zeros((kv_heads, head_dim), param_dtype)},
        "out": {
            "kernel": glorot_uniform(ko, (d_model, d_model), param_dtype, d_model, d_model)
            .reshape(d_model, num_heads, head_dim)
            .transpose(1, 2, 0),
            "bias": jnp.zeros((d_model,), param_dtype),
        },
    }


def _project(p: Params, x: jax.Array, dtype) -> jax.Array:
    # (B, S, M) @ (M, H, D) -> (B, S, H, D)
    return jnp.einsum("bsm,mhd->bshd", x.astype(dtype), p["kernel"].astype(dtype)) + p[
        "bias"
    ].astype(dtype)


def project_kv(params: Params, x_kv: jax.Array, dtype=None) -> tuple[jax.Array, jax.Array]:
    """Project key/value inputs once, for reuse across decode steps via
    ``mha_apply(..., precomputed_kv=...)``."""
    dtype = dtype or x_kv.dtype
    return _project(params["key"], x_kv, dtype), _project(params["value"], x_kv, dtype)


def _kv_padding_mask(mask: jax.Array | None, impl: str) -> jax.Array | None:
    """Blockwise kernels (flash/ring/ulysses) take key-padding only: squeeze a
    broadcastable (B|1, 1, 1, S_k) allowed-mask to (B|1, S_k), or reject."""
    if mask is None:
        return None
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[-2] == 1:
        return mask[:, 0, 0, :]
    raise ValueError(
        f"attention_impl={impl!r} takes a key-padding mask (B, 1, 1, S_k) "
        f"plus the structural causal flag; got a mask of shape {mask.shape}. "
        "Per-head masks are unsupported, and causality must be passed as "
        "causal=True, not folded into the mask."
    )


def mha_apply(
    params: Params,
    x_q: jax.Array,
    x_kv: jax.Array,
    mask: jax.Array | None = None,
    *,
    impl: str = "xla",
    causal: bool = False,
    window: int = 0,
    return_weights: bool = False,
    cache: dict[str, Any] | None = None,
    precomputed_kv: tuple[jax.Array, jax.Array] | None = None,
    flash_block_q: int = 128,
    flash_block_k: int = 128,
    rope: bool = False,
) -> tuple[jax.Array, jax.Array | None, dict[str, Any] | None]:
    """Multi-head attention forward.

    Args:
      params: pytree from ``mha_init``.
      x_q: (B, S_q, d_model) query-side input.
      x_kv: (B, S_k, d_model) key/value-side input (same as ``x_q`` for
        self-attention; encoder output for cross-attention).
      mask: broadcastable bool allowed-mask (B|1, 1|H, S_q|1, S_k).
      impl: "xla" | "flash" (Pallas blockwise kernel; no attention-weight
        output).
      causal: enforce causality; ANDed with any provided ``mask``.
      window: causal sliding window (needs ``causal`` — or a cache, whose
        prefix mask is causal by construction): each position attends only
        the last ``window`` positions. 0 = unbounded. Supported on every
        impl: banded mask under "xla", static band-tile skip under "flash",
        per-hop band with early ring stop under "ring", and a band in the
        per-device flash call under "ulysses"
        (tests/test_sequence_parallel.py::test_window pins the parallel
        impls against the single-device oracle).
      cache: optional decode KV cache ``{"k","v","index"}`` from
        ``init_cache``. Full-length cache (k/v shaped (B, max_len, H, D)):
        S_q is the number of new positions (1 for greedy decode, >1 for
        prefill), new k/v are written at ``index`` and attention runs
        causally over the filled prefix. Rolling cache
        (``init_cache(window=...)``, k/v shaped (B, min(window, max_len),
        H, D)): one token per step only, slot ``index % buf_len`` is
        overwritten, the slot mask is built internally (caller masks are
        rejected). Returns the updated cache.
      precomputed_kv: optional (k, v) already projected to (B, S_k, H, D) —
        used by cross-attention during decode so the static encoder output is
        projected once, not once per generated token.
      rope: rotate q and the NEWLY-projected k by their absolute positions
        (``ops.positional.apply_rope``) — self-attention only (cross-attention
        callers must leave this False; cached keys are stored rotated, so the
        decode path composes for free). Positions come from ``cache["index"]``
        when decoding, else ``arange(S_q)``.

    Returns ``(out, weights|None, cache|None)``.
    """
    if window and not causal and cache is None:
        # Same contract as flash_attention and the ring/ulysses branch:
        # a window without causality (or a cache, whose prefix mask is
        # causal by construction) would otherwise be silently ignored.
        raise ValueError(
            "window requires causal=True (or a decode cache); bidirectional "
            "local attention is not implemented"
        )
    dtype = x_q.dtype
    q = _project(params["query"], x_q, dtype)
    if precomputed_kv is not None:
        k, v = (t.astype(dtype) for t in precomputed_kv)
    else:
        k = _project(params["key"], x_kv, dtype)
        v = _project(params["value"], x_kv, dtype)

    if rope:
        from transformer_tpu.ops.positional import apply_rope

        offset = cache["index"] if cache is not None else 0
        positions = offset + jnp.arange(x_q.shape[1])
        q = apply_rope(q, positions)
        if precomputed_kv is None:
            k = apply_rope(k, positions)

    if cache is not None:
        idx = cache["index"]
        buf_len = cache["k"].shape[1]
        s_q = x_q.shape[1]
        # Rolling window buffer (init_cache(window=...)): the buffer holds
        # only the last `buf_len <= window` positions and each step writes
        # slot idx % buf_len — decode HBM and score compute are O(window),
        # not O(max_len). Attention is permutation-invariant over kv slots,
        # so slot ORDER never matters, only which slots are valid; RoPE
        # composes because keys are cached already rotated by their
        # absolute position. Rolling-ness is carried EXPLICITLY by the
        # cache (the "rolling" key init_cache stores when built with a
        # window) — key presence is static pytree structure, so the branch
        # stays trace-time. Inferring it from buffer size would misclassify
        # a full-length cache as rolling whenever max_len <= window.
        rolling = "rolling" in cache
        if rolling and s_q > 1:
            # Chunked PREFILL into a rolling buffer. Writing the chunk first
            # and then attending the buffer (the one-token flow) would be
            # wrong here: a later chunk token's write can evict a position
            # that is still inside an earlier chunk token's band. So attend
            # FIRST — against the buffer's pre-chunk contents plus the
            # chunk's own keys — then write. Chunks are capped at buf_len so
            # the write slots are distinct (no intra-chunk eviction).
            if s_q > buf_len:
                raise ValueError(
                    f"rolling-window prefill chunks must fit the window "
                    f"buffer: got s_q={s_q} > buf_len={buf_len} (split the "
                    "prefill into chunks of at most the window size)"
                )
            if mask is not None:
                raise ValueError(
                    "rolling-window cache builds its own slot mask; a "
                    "caller mask is indexed by absolute position and "
                    "cannot compose with rotated slots"
                )
            from transformer_tpu.ops.masks import make_rolling_prefill_mask

            if "k_scale" in cache:
                k_old = cache["k"].astype(dtype) * cache["k_scale"].astype(dtype)
                v_old = cache["v"].astype(dtype) * cache["v_scale"].astype(dtype)
            else:
                k_old = cache["k"].astype(dtype)
                v_old = cache["v"].astype(dtype)
            mask = make_rolling_prefill_mask(idx, s_q, buf_len)
            slots_w = (idx + jnp.arange(s_q)) % buf_len
            new_cache, k, v = _store_kv(
                cache, k, v, lambda buf, val: buf.at[:, slots_w].set(val)
            )
            new_cache["index"] = idx + s_q
            new_cache["rolling"] = cache["rolling"]
            cache = new_cache
            k = jnp.concatenate([k_old, k], axis=1)
            v = jnp.concatenate([v_old, v], axis=1)
        else:
            if rolling:
                if mask is not None:
                    raise ValueError(
                        "rolling-window cache builds its own slot mask; a "
                        "caller mask is indexed by absolute position and "
                        "cannot compose with rotated slots"
                    )
                write_pos = idx % buf_len
            else:
                write_pos = idx
            # int8 caches (init_cache(quantize=True)) store each new
            # (position, head) row as int8 with its own fp32 scale — the
            # cache is the decode-side HBM bottleneck at long contexts, and
            # int8 reads cost 2x (vs bf16) to 4x (vs fp32) less bandwidth.
            # Dequantize below for the attention math (compute stays in the
            # model dtype; the win is memory, not FLOPs).
            new_cache, _, _ = _store_kv(
                cache, k, v,
                lambda buf, val: jax.lax.dynamic_update_slice(
                    buf, val, (0, write_pos, 0, 0)
                ),
            )
            new_cache["index"] = idx + s_q
            if "k_scale" in cache:
                k = new_cache["k"].astype(dtype) * new_cache["k_scale"].astype(dtype)
                v = new_cache["v"].astype(dtype) * new_cache["v_scale"].astype(dtype)
            else:
                k = new_cache["k"]
                v = new_cache["v"]
            if rolling:
                new_cache["rolling"] = cache["rolling"]
            cache = new_cache
            if rolling:
                # Which slots hold a REAL (already-written) position: all of
                # them once idx wraps, else slots <= idx. Every held position
                # is inside the band by construction (the newest write evicted
                # the only out-of-band one).
                slots = jnp.arange(buf_len)[None, None, None, :]
                mask = jnp.logical_or(slots <= idx, idx >= buf_len)
            else:
                # Causal decode mask over the cache buffer: new query at
                # absolute position idx+i may attend keys at positions <= idx+i
                # (prefill with s_q > 1 stays causal), combined with any
                # caller-provided mask. `window` masks the band when a sliding
                # window runs over a FULL-LENGTH (non-rolling) cache.
                from transformer_tpu.ops.masks import make_cache_prefix_mask

                valid = make_cache_prefix_mask(idx, s_q, buf_len, window=window)
                mask = valid if mask is None else jnp.logical_and(mask, valid)
        k = k.astype(dtype)
        v = v.astype(dtype)

    # Grouped-query kv heads need NO materialized repeat on any blockwise
    # path: flash and ring map each q-head to its kv group in the kernels'
    # BlockSpec index maps (kv HBM reads — and the ring's per-hop ppermute
    # payload — stay at the H_kv rate), and ulysses all-to-alls kv at its
    # own head count when divisible (seq_context.seq_parallel_attention
    # repeats only in the two documented misalignment corners).
    if impl == "flash" and cache is None:
        # Causality stays structural (a static kernel flag) so the Pallas
        # kernel can skip above-diagonal tiles instead of masking them.
        from transformer_tpu.kernels.flash_attention import flash_attention

        kv_mask = _kv_padding_mask(mask, impl)
        out = flash_attention(
            q, k, v,
            kv_mask=kv_mask,
            causal=causal,
            # The top-of-function guard rejects window without causal on
            # this (cache-free) path, so window>0 implies causal here.
            window=window,
            block_q=flash_block_q,
            block_k=flash_block_k,
        )
        weights = None
    elif impl in ("ring", "ulysses") and cache is None:
        # Stack-level sequence parallelism: the distributed engine activates a
        # SeqParallelContext around the jitted forward
        # (parallel/distributed.make_sharded_steps), and the attention core
        # runs under shard_map on the context's mesh with S split over the
        # 'seq' axis (KV chunks ride ICI via ppermute / all_to_all —
        # parallel/ring_attention.py).
        from transformer_tpu.parallel.seq_context import (
            current_seq_context,
            seq_parallel_attention,
        )

        ctx = current_seq_context()
        if ctx is None:
            raise RuntimeError(
                f"attention_impl={impl!r} needs an active sequence-parallel "
                "context: train through DistributedTrainer with "
                "MeshConfig(seq>1) (or wrap the forward in "
                "parallel.seq_context.sequence_parallel)"
            )
        kv_mask = _kv_padding_mask(mask, impl)
        if kv_mask is not None and kv_mask.shape[0] == 1 and q.shape[0] != 1:
            kv_mask = jnp.broadcast_to(kv_mask, (q.shape[0], kv_mask.shape[1]))
        out = seq_parallel_attention(
            ctx, impl, q, k, v, kv_mask, causal, window=window
        )
        weights = None
    else:
        if causal and cache is None:
            # Causality is enforced whether or not a padding mask was provided.
            from transformer_tpu.ops.masks import make_causal_mask

            cmask = make_causal_mask(x_q.shape[1], window=window)
            mask = cmask if mask is None else jnp.logical_and(mask, cmask)
        out, weights = dot_product_attention(q, k, v, mask, return_weights=return_weights)

    merged = jnp.einsum(
        "bshd,hdm->bsm", out, params["out"]["kernel"].astype(dtype)
    ) + params["out"]["bias"].astype(dtype)
    return merged, weights, cache


def _quantize_kv(t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-(position, head) quantization of a (B, S, H, D)
    projection: one fp32 scale per row of ``D`` values."""
    amax = jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(t.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def kv_buffer_keys(cache: dict[str, Any]) -> tuple[str, ...]:
    """The cache keys that hold per-position KV rows, in the cache's own
    storage layout: ``("k", "v")`` for plain caches, plus the fp32
    ``k_scale``/``v_scale`` rows for int8-quantized ones. The ONE listing of
    the layout's buffer names — ``_store_kv``, ``slice_kv_blocks``, and
    ``insert_kv_blocks`` all iterate it, so a future layout (new buffer key)
    cannot desynchronize the write, export, and restore paths."""
    if "k_scale" in cache:
        return ("k", "k_scale", "v", "v_scale")
    return ("k", "v")


def _require_positional_buffers(cache: dict[str, Any], op: str) -> None:
    """Reject rolling-window caches from operations that address buffer rows
    by absolute position. A rolling buffer stores position ``p`` at slot
    ``p % buf_len`` and EVICTS on wrap — row ranges are neither stable nor
    complete, so block export/restore (prefix cache) and index rollback
    (speculation) are structurally unsound there. Shared by
    ``rollback_cache`` / ``slice_kv_blocks`` / ``insert_kv_blocks`` so every
    random-access path refuses with the same policy."""
    if "rolling" in cache:
        raise ValueError(
            f"{op} cannot address a rolling-window cache by position: the "
            "window buffer evicts rows on wrap (slot p % buf_len), so "
            "absolute-position rows are neither stable nor complete — serve "
            "this config without attention_window"
        )


def slice_kv_blocks(cache: dict[str, Any], start, n: int) -> dict[str, Any]:
    """Read buffer rows ``[start, start + n)`` of every KV buffer — the
    block-granular EXPORT half of the prefix cache's round trip. Rows come
    out in the cache's own storage layout (int8 codes and their fp32 scales
    slice as stored, bf16 slices as bf16), so an exported block re-inserted
    by ``insert_kv_blocks`` is bit-identical to the original write — the
    invariant that makes cross-request KV reuse byte-transparent. ``n`` must
    be static (it is a shape); ``start`` may be traced."""
    _require_positional_buffers(cache, "slice_kv_blocks")
    return {
        key: jax.lax.dynamic_slice_in_dim(cache[key], start, n, axis=1)
        for key in kv_buffer_keys(cache)
    }


def insert_kv_blocks(
    cache: dict[str, Any], blocks: dict[str, Any], start
) -> dict[str, Any]:
    """Write exported KV rows back at buffer rows ``[start, start +
    blocks_len)`` — the RESTORE half of ``slice_kv_blocks``. Blocks are
    already in storage layout, so this is a pure ``dynamic_update_slice``
    per buffer: no re-quantization, no dtype conversion, bit-identical to
    the rows the donor cache held. ``index`` (and any other bookkeeping) is
    left untouched — callers own it, same contract as ``_store_kv``."""
    _require_positional_buffers(cache, "insert_kv_blocks")
    new = dict(cache)
    for key in kv_buffer_keys(cache):
        new[key] = jax.lax.dynamic_update_slice_in_dim(
            cache[key], blocks[key], start, axis=1
        )
    return new


def _store_kv(cache, k, v, write):
    """Write new (B, S_q, H, D) k/v into a decode cache's buffers via
    ``write(buf, val) -> buf`` (the caller picks the scatter: rolling slots
    or a contiguous dynamic_update_slice). The ONE place that knows the int8
    layout — quantizing into the four k/k_scale/v/v_scale buffers — so the
    prefill and one-token write paths can never desynchronize numerics.

    Returns ``(new_cache_bufs, k_rt, v_rt)``: the updated buffers (no
    "index"/"rolling" bookkeeping — callers own that) plus the new entries
    as the read path will see them — the quantize->dequantize round trip for
    int8 caches, the inputs unchanged otherwise. Attending the chunk's own
    keys through ``k_rt`` keeps int8 decode numerics independent of whether
    a position arrived via prefill or step."""
    if "k_scale" in cache:
        kq, ks = _quantize_kv(k)
        vq, vs = _quantize_kv(v)
        vals = {"k": kq, "k_scale": ks, "v": vq, "v_scale": vs}
        new = {key: write(cache[key], vals[key]) for key in kv_buffer_keys(cache)}
        dtype = k.dtype
        return (
            new,
            kq.astype(dtype) * ks.astype(dtype),
            vq.astype(dtype) * vs.astype(dtype),
        )
    new = {
        "k": write(cache["k"], k.astype(cache["k"].dtype)),
        "v": write(cache["v"], v.astype(cache["v"].dtype)),
    }
    return new, k, v


def rollback_cache(cache: dict[str, Any], index) -> dict[str, Any]:
    """O(1) KV rollback: keep the buffers, reset ``index`` to an earlier
    position. The speculative-decoding verify step writes K/V for every
    candidate token it scores; rejected candidates are "erased" by moving
    the index back — their stale rows stay in the buffer but the offset
    causal mask (``make_cache_prefix_mask``) already hides every position
    ``>= index`` from all later reads, and the next real write overwrites
    them in place (the int8 variant re-quantizes the row, so stale scales
    can never pair with fresh codes).

    Rolling-window caches are REJECTED (``_require_positional_buffers``, the
    same policy gate the prefix cache's block slice/insert uses): a
    speculative write at position ``p`` evicts slot ``p % buf_len`` — a
    position that may still be inside the window after rollback — so index
    reset cannot restore their state. Gate speculation off for
    ``attention_window`` configs instead.
    """
    _require_positional_buffers(cache, "rollback_cache")
    return dict(cache, index=jnp.asarray(index, jnp.int32))


def init_block_pool(
    num_blocks: int,
    block_tokens: int,
    num_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quantize: bool = False,
) -> dict[str, Any]:
    """One layer's PAGED KV pool: the per-position buffers of
    ``init_cache``, re-shaped from one (B, max_len, H, D) run per slot
    into a single (num_blocks, block_tokens, H, D) pool every slot
    addresses through a block table (``kernels/kv_pool.py``). Buffer KEYS
    and storage layouts are identical to the dense cache's — int8 codes
    with fp32 scales, GQA kv-head counts — so ``kv_buffer_keys`` iterates
    both, a pool block read IS a host-format prefix-cache block, and the
    dense <-> paged round trip is bit-transparent. No ``index`` (per-slot
    position bookkeeping lives with the table) and no rolling variant
    (rolling windows evict absolute-position rows — the same refusal the
    prefix cache and speculative rollback enforce)."""
    shape = (num_blocks, block_tokens, num_heads, head_dim)
    if quantize:
        return {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(shape[:3] + (1,), dtype=jnp.float32),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "v_scale": jnp.zeros(shape[:3] + (1,), dtype=jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, dtype=dtype),
        "v": jnp.zeros(shape, dtype=dtype),
    }


def init_cache(
    batch_size: int,
    max_len: int,
    num_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quantize: bool = False,
    window: int = 0,
) -> dict[str, Any]:
    """Fresh decode cache. The reference instead re-runs the full decoder over
    a concat-grown buffer every step (``train.py:109-118``) — a recompile bomb
    under XLA; a fixed-size cache plus ``dynamic_update_slice`` keeps decode a
    single compiled program.

    ``quantize=True`` stores k/v as int8 with one fp32 scale per
    (position, head) row (``ModelConfig.kv_cache_int8``): the cache — the
    HBM bottleneck of long-context serving — shrinks ~2x vs bf16 storage
    (~4x vs fp32) plus D/4 scale overhead; attention dequantizes on read.

    ``window > 0`` (``ModelConfig.attention_window``) allocates a ROLLING
    buffer of only min(window, max_len) slots: each decode step overwrites
    slot ``index % buf_len``, so windowed decode pays O(window) HBM and
    score compute regardless of context length. Composes with ``quantize``.
    Rolling caches carry a ``"rolling"`` sentinel key — its PRESENCE (static
    pytree structure) is what marks the cache as rolling; the stored value
    records the requested window for debugging only (the effective band is
    the buffer length, min(window, max_len))."""
    buf_len = min(window, max_len) if window else max_len
    shape = (batch_size, buf_len, num_heads, head_dim)
    if quantize:
        cache = {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(shape[:3] + (1,), dtype=jnp.float32),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "v_scale": jnp.zeros(shape[:3] + (1,), dtype=jnp.float32),
            "index": jnp.array(0, dtype=jnp.int32),
        }
    else:
        cache = {
            "k": jnp.zeros(shape, dtype=dtype),
            "v": jnp.zeros(shape, dtype=dtype),
            "index": jnp.array(0, dtype=jnp.int32),
        }
    if window:
        cache["rolling"] = jnp.array(window, dtype=jnp.int32)
    return cache
