"""Position-wise feed-forward network.

Counterpart of the reference's ``point_wise_feed_forward_network``
(``point_ffn.py:3-7``): Dense(dff, act) -> Dense(d_model), relu by default.
Two MXU matmuls with the activation fused between them by XLA. The ``dff``
axis is the tensor-parallel shard axis (column-parallel first matmul,
row-parallel second).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from transformer_tpu.ops.nn import Params, dense_apply, dense_init

_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
}


def ffn_init(key: jax.Array, d_model: int, dff: int, param_dtype=jnp.float32) -> Params:
    k1, k2 = jax.random.split(key)
    return {
        "in": dense_init(k1, d_model, dff, param_dtype),
        "out": dense_init(k2, dff, d_model, param_dtype),
    }


def ffn_apply(params: Params, x: jax.Array, activation: str = "relu") -> jax.Array:
    act = _ACTIVATIONS[activation]
    h = act(dense_apply(params["in"], x))
    return dense_apply(params["out"], h)
