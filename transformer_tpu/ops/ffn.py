"""Position-wise feed-forward network.

Counterpart of the reference's ``point_wise_feed_forward_network``
(``point_ffn.py:3-7``): Dense(dff, act) -> Dense(d_model), relu by default.
Two MXU matmuls with the activation fused between them by XLA. The ``dff``
axis is the tensor-parallel shard axis (column-parallel first matmul,
row-parallel second).

Gated variants (Shazeer 2020, "GLU Variants Improve Transformer"):
``swiglu``/``geglu``/``reglu`` add a third (gate) projection —
``act(x W_gate) * (x W_in) W_out`` — the FFN used by most modern LLMs.
Three matmuls instead of two; all still column/row-parallel on ``dff``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from transformer_tpu.ops.nn import Params, dense_apply, dense_init

_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
}

# Gated variants: activation applied to the GATE branch.
_GATED_ACTIVATIONS = {
    "swiglu": jax.nn.silu,
    "geglu": jax.nn.gelu,
    "reglu": jax.nn.relu,
}

# Public name list: config validation derives from this; the CLI keeps a
# jax-import-free literal copy pinned to it by tests/test_flags.py.
FFN_ACTIVATIONS = tuple(sorted({**_ACTIVATIONS, **_GATED_ACTIVATIONS}))


def is_gated(activation: str) -> bool:
    return activation in _GATED_ACTIVATIONS


def ffn_init(
    key: jax.Array,
    d_model: int,
    dff: int,
    param_dtype=jnp.float32,
    activation: str = "relu",
) -> Params:
    # Ungated configs split exactly as before the gated variants existed, so
    # seeded inits stay byte-identical regardless of JAX's split semantics.
    k1, k2 = jax.random.split(key)
    params = {
        "in": dense_init(k1, d_model, dff, param_dtype),
        "out": dense_init(k2, dff, d_model, param_dtype),
    }
    if is_gated(activation):
        params["gate"] = dense_init(
            jax.random.fold_in(key, 2), d_model, dff, param_dtype
        )
    return params


def ffn_apply(params: Params, x: jax.Array, activation: str = "relu") -> jax.Array:
    if is_gated(activation):
        act = _GATED_ACTIVATIONS[activation]
        h = act(dense_apply(params["gate"], x)) * dense_apply(params["in"], x)
        return dense_apply(params["out"], h)
    act = _ACTIVATIONS[activation]
    h = act(dense_apply(params["in"], x))
    return dense_apply(params["out"], h)
