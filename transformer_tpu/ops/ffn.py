"""Position-wise feed-forward network.

Counterpart of the reference's ``point_wise_feed_forward_network``
(``point_ffn.py:3-7``): Dense(dff, act) -> Dense(d_model), relu by default.
Two MXU matmuls with the activation fused between them by XLA. The ``dff``
axis is the tensor-parallel shard axis (column-parallel first matmul,
row-parallel second).

Gated variants (Shazeer 2020, "GLU Variants Improve Transformer"):
``swiglu``/``geglu``/``reglu`` add a third (gate) projection —
``act(x W_gate) * (x W_in) W_out`` — the FFN used by most modern LLMs.
Three matmuls instead of two; all still column/row-parallel on ``dff``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from transformer_tpu.ops.nn import Params, dense_apply, dense_init

_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
}

# Gated variants: activation applied to the GATE branch.
_GATED_ACTIVATIONS = {
    "swiglu": jax.nn.silu,
    "geglu": jax.nn.gelu,
    "reglu": jax.nn.relu,
}

# Public name list: config validation derives from this; the CLI keeps a
# jax-import-free literal copy pinned to it by tests/test_flags.py.
FFN_ACTIVATIONS = tuple(sorted({**_ACTIVATIONS, **_GATED_ACTIVATIONS}))


def is_gated(activation: str) -> bool:
    return activation in _GATED_ACTIVATIONS


def ffn_init(
    key: jax.Array,
    d_model: int,
    dff: int,
    param_dtype=jnp.float32,
    activation: str = "relu",
) -> Params:
    # Ungated configs split exactly as before the gated variants existed, so
    # seeded inits stay byte-identical regardless of JAX's split semantics.
    k1, k2 = jax.random.split(key)
    params = {
        "in": dense_init(k1, d_model, dff, param_dtype),
        "out": dense_init(k2, dff, d_model, param_dtype),
    }
    if is_gated(activation):
        params["gate"] = dense_init(
            jax.random.fold_in(key, 2), d_model, dff, param_dtype
        )
    return params


def ffn_apply(params: Params, x: jax.Array, activation: str = "relu") -> jax.Array:
    if is_gated(activation):
        act = _GATED_ACTIVATIONS[activation]
        h = act(dense_apply(params["gate"], x)) * dense_apply(params["in"], x)
        return dense_apply(params["out"], h)
    act = _ACTIVATIONS[activation]
    h = act(dense_apply(params["in"], x))
    return dense_apply(params["out"], h)


# ---------------------------------------------------------------------------
# Fused residual+LN+FFN decode kernel (Flash Multi-Head FFN shape).
#
# The XLA decode path runs the FFN sublayer as LN -> matmul -> activation ->
# matmul -> residual(+LN), each stage writing its result to HBM — including
# the (M, dff) intermediate, the widest tensor in the layer. For decode M is
# tiny (num_slots * S_q rows), so every stage is bandwidth-bound and the
# round trips dominate. This kernel walks the dff axis in tiles: each grid
# step loads one (d, bdff) column slab of W_in (plus the gate slab when the
# activation is gated), produces its (M, bdff) slice of the intermediate IN
# VMEM, multiplies into the (bdff, d) row slab of W_out, and accumulates
# into an (M, d) fp32 scratch. The dff-wide intermediate never exists in
# HBM; weight traffic is the unavoidable one pass over W_in/W_gate/W_out.
#
# Numerics track the XLA stage chain: LN statistics in fp32 exactly as
# ``layernorm_apply``; both matmuls accumulate fp32 and cast to the compute
# dtype like ``dense_apply``'s bf16 matmuls; only the second matmul's
# dff-contraction ORDER differs (tile partial sums vs one reduction), a
# low-bit fp32 effect that the cast to bf16 usually rounds away. MoE layers
# keep the XLA path (dispatch is data-dependent; fusing it is its own
# kernel) — ``models/paged_decode.py`` routes per layer.
# ---------------------------------------------------------------------------


def _ffn_tile(dff: int, requested: int = 512) -> int:
    """Largest divisor of ``dff`` at or below ``requested`` that is a legal
    TPU lane tile (a multiple of 128, or the full axis)."""
    for t in range(min(requested, dff), 0, -1):
        if dff % t == 0 and (t % 128 == 0 or t == dff):
            return t
    return dff


def _fused_kernel(
    x_ref,       # (M, d) sublayer input
    w_in_ref,    # (d, bdff) column slab of W_in
    b_in_ref,    # (1, bdff)
    *rest,       # [w_gate_ref, b_gate_ref,] w_out_ref, b_out_ref,
                 # ln_scale_ref, ln_bias_ref, out_ref, h_scr, acc_scr
    activation: str,
    pre_ln: bool,
    epsilon: float,
):
    if is_gated(activation):
        w_gate_ref, b_gate_ref = rest[0], rest[1]
        rest = rest[2:]
    else:
        w_gate_ref = b_gate_ref = None
    w_out_ref, b_out_ref, ln_scale_ref, ln_bias_ref, out_ref, h_scr, acc_scr = rest
    j = pl.program_id(0)
    dtype = x_ref.dtype

    def _ln(t):
        # layernorm_apply verbatim: fp32 stats, affine in fp32, cast back.
        t32 = t.astype(jnp.float32)
        mean = jnp.mean(t32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(t32 - mean), axis=-1, keepdims=True)
        normed = (t32 - mean) * jax.lax.rsqrt(var + epsilon)
        out = normed * ln_scale_ref[0].astype(jnp.float32) + ln_bias_ref[
            0
        ].astype(jnp.float32)
        return out.astype(dtype)

    @pl.when(j == 0)
    def _init():
        # Pre-LN feeds LN(x) to the FFN; post-LN feeds x itself (the LN in
        # that scheme wraps the residual sum at the end).
        h_scr[...] = _ln(x_ref[...]) if pre_ln else x_ref[...]
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _dense(w_ref, b_ref):
        # dense_apply's bf16 matmul accumulates fp32 on the MXU; mirror it.
        z = jax.lax.dot_general(
            h_scr[...], w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return z.astype(dtype) + b_ref[0]

    if is_gated(activation):
        t = _GATED_ACTIVATIONS[activation](_dense(w_gate_ref, b_gate_ref)) * _dense(
            w_in_ref, b_in_ref
        )
    else:
        t = _ACTIVATIONS[activation](_dense(w_in_ref, b_in_ref))
    acc_scr[...] += jax.lax.dot_general(
        t, w_out_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == pl.num_programs(0) - 1)
    def _finalize():
        y = acc_scr[...].astype(dtype) + b_out_ref[0]
        res = x_ref[...] + y
        out_ref[...] = res if pre_ln else _ln(res)


def fused_ln_ffn(
    ln_params: Params,
    ffn_params: Params,
    x: jax.Array,
    *,
    activation: str = "relu",
    norm_scheme: str = "pre",
    epsilon: float = 1e-6,
    block_dff: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """The whole FFN sublayer — residual, LayerNorm, and both matmuls — as
    one Pallas kernel, dff tiled so the wide intermediate stays in VMEM.

    Computes ``x + ffn(LN(x))`` (pre-LN) or ``LN(x + ffn(x))`` (post-LN)
    for deterministic decode (dropout is identity there). ``x`` is
    (..., d_model); leading axes fold into rows.
    """
    if norm_scheme not in ("pre", "post"):
        raise ValueError(f"unknown norm_scheme {norm_scheme!r}")
    from transformer_tpu.kernels.flash_attention import _compiler_params

    lead, d = x.shape[:-1], x.shape[-1]
    m = 1
    for a in lead:
        m *= a
    xf = x.reshape(m, d)
    dff = ffn_params["in"]["kernel"].shape[1]
    bdff = _ffn_tile(dff, block_dff)
    dtype = x.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def _cast2(t):
        return t.astype(dtype).reshape(1, -1) if t.ndim == 1 else t.astype(dtype)

    inputs = [
        xf,
        ffn_params["in"]["kernel"].astype(dtype),
        _cast2(ffn_params["in"]["bias"]),
    ]
    in_specs = [
        pl.BlockSpec((m, d), lambda j: (0, 0)),
        pl.BlockSpec((d, bdff), lambda j: (0, j)),
        pl.BlockSpec((1, bdff), lambda j: (0, j)),
    ]
    if is_gated(activation):
        inputs += [
            ffn_params["gate"]["kernel"].astype(dtype),
            _cast2(ffn_params["gate"]["bias"]),
        ]
        in_specs += [
            pl.BlockSpec((d, bdff), lambda j: (0, j)),
            pl.BlockSpec((1, bdff), lambda j: (0, j)),
        ]
    inputs += [
        ffn_params["out"]["kernel"].astype(dtype),
        _cast2(ffn_params["out"]["bias"]),
        _cast2(ln_params["scale"]),
        _cast2(ln_params["bias"]),
    ]
    in_specs += [
        pl.BlockSpec((bdff, d), lambda j: (j, 0)),
        pl.BlockSpec((1, d), lambda j: (0, 0)),
        pl.BlockSpec((1, d), lambda j: (0, 0)),
        pl.BlockSpec((1, d), lambda j: (0, 0)),
    ]

    kernel = functools.partial(
        _fused_kernel,
        activation=activation,
        pre_ln=norm_scheme == "pre",
        epsilon=epsilon,
    )
    out = pl.pallas_call(
        kernel,
        grid=(dff // bdff,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((m, d), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((m, d), dtype),
        scratch_shapes=[
            pltpu.VMEM((m, d), dtype),        # FFN input rows (LN'd or raw)
            pltpu.VMEM((m, d), jnp.float32),  # fp32 output accumulator
        ],
        compiler_params=_compiler_params(("arbitrary",)),
        interpret=bool(interpret),
    )(*inputs)
    return out.reshape(*lead, d)
