"""Mixture-of-Experts feed-forward layer with expert parallelism.

No reference counterpart: the reference's FFN is a dense two-matmul block
(``point_ffn.py:3-7``) — this is a capability extension (SURVEY.md §2.4 lists
expert parallelism as out of reference scope), built TPU-first:

- **Static shapes.** Routing uses the classic capacity-factor dispatch
  (Shazeer-style top-k gating): every (batch-row, expert) pair gets a fixed
  number of token slots ``C``, and dispatch/combine are dense one-hot
  tensors contracted with einsums. No sort, no gather/scatter with
  data-dependent shapes — everything XLA sees is a fixed-shape matmul, so
  the MXU stays fed and nothing recompiles.
- **Expert parallelism as sharding.** Expert weights are stacked on a leading
  ``E`` axis — ``in/kernel (E, M, F)`` — and sharded over the ``expert`` mesh
  axis (``parallel/sharding.py``). The all-to-all that moves token slots to
  their experts is inserted by GSPMD from the sharding annotations, riding
  ICI; there is no hand-written collective. EP composes with tp ('model'
  shards F) and fsdp exactly like the dense FFN.
- **Remat-safe aux loss.** The load-balance loss is a real function output
  threaded through the layer stack (``models/encoder.py``), not a side
  channel, so it survives ``jax.checkpoint``.

Routing math (fp32 throughout; expert matmuls in the compute dtype):
top-k gates renormalized over the selected experts, earlier choices get
capacity priority, tokens overflowing an expert's capacity are dropped (the
residual connection around the FFN sublayer carries them through unchanged).
The auxiliary load-balancing loss is the standard Switch/GShard form
``E * sum_e f_e * p_e`` (f_e: fraction of tokens whose first choice is e;
p_e: mean router probability), which is 1.0 at perfect balance.
"""

from __future__ import annotations

import contextlib
import math

import jax
import jax.numpy as jnp

from transformer_tpu.ops.ffn import _ACTIVATIONS
from transformer_tpu.ops.nn import Params, glorot_uniform

# Active mesh for expert-sharding constraints (see ``expert_mesh`` below).
_EXPERT_MESH: list = []


@contextlib.contextmanager
def expert_mesh(mesh):
    """Activate sharding hints inside ``moe_apply``: the distributed engine
    wraps its forward in this context (``parallel/distributed.py``) so the
    dispatch/combine einsums are annotated with the exact resharding points —
    tokens move from batch-sharded (data×fsdp×expert) to expert-sharded via
    ONE GSPMD all-to-all instead of the partitioner's replicate-then-slice
    fallback. Without the context (single chip, plain jit) the hints vanish."""
    _EXPERT_MESH.append(mesh)
    try:
        yield
    finally:
        _EXPERT_MESH.pop()


def _constrain(x: jax.Array, *spec) -> jax.Array:
    if not _EXPERT_MESH:
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _EXPERT_MESH[-1]

    def present(a):
        axes = a if isinstance(a, tuple) else (a,)
        return all(ax in mesh.shape for ax in axes)

    cleaned = P(*[(a if a is None or present(a) else None) for a in spec])
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, cleaned))


def moe_init(
    key: jax.Array,
    d_model: int,
    dff: int,
    num_experts: int,
    param_dtype=jnp.float32,
) -> Params:
    """Router plus ``num_experts`` independent FFNs stacked on a leading E
    axis. Per-expert fan-in/fan-out matches ``ffn_init`` so a 1-expert MoE is
    parameter-for-parameter the dense FFN."""
    k_router, k_in, k_out = jax.random.split(key, 3)
    E = num_experts

    def stacked(k, d_in, d_out):
        keys = jax.random.split(k, E)
        return jnp.stack(
            [glorot_uniform(keys[e], (d_in, d_out), param_dtype, d_in, d_out) for e in range(E)]
        )

    return {
        "router": {"kernel": glorot_uniform(k_router, (d_model, E), param_dtype, d_model, E)},
        "in": {
            "kernel": stacked(k_in, d_model, dff),
            "bias": jnp.zeros((E, dff), param_dtype),
        },
        "out": {
            "kernel": stacked(k_out, dff, d_model),
            "bias": jnp.zeros((E, d_model), param_dtype),
        },
    }


def expert_capacity(
    seq_len: int, num_experts: int, top_k: int, capacity_factor: float
) -> int:
    """Token slots per (batch-row, expert): the even-split share
    ``S * k / E`` scaled by the capacity factor, at least 1, at most S."""
    even = seq_len * top_k / num_experts
    return max(1, min(seq_len, math.ceil(even * capacity_factor)))


def moe_apply(
    params: Params,
    x: jax.Array,
    *,
    num_experts: int,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    activation: str = "relu",
    token_mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(B, S, M) -> ((B, S, M), aux_loss).

    Each batch row is a routing group: capacity is budgeted per row, so the
    dispatch tensors stay (B, S, E, C) and the whole layer is four einsums.
    Dropped tokens (capacity overflow) produce zero output here; the caller's
    residual connection passes their activations through unchanged.

    ``token_mask`` (B, S) bool, True = real token: PAD positions are neither
    dispatched (they'd steal capacity slots from real tokens' choices) nor
    counted in the load-balance statistics (a mostly-PAD batch would
    otherwise train the router to balance padding).
    """
    B, S, M = x.shape
    E, k = num_experts, min(top_k, num_experts)
    C = expert_capacity(S, E, k, capacity_factor)
    act = _ACTIVATIONS[activation]
    dtype = x.dtype

    # --- routing (fp32: softmax over experts + cumsum bookkeeping) ---------
    router_logits = jnp.einsum(
        "bsm,me->bse", x.astype(jnp.float32), params["router"]["kernel"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # (B, S, E)
    live = (
        None
        if token_mask is None
        else jnp.broadcast_to(token_mask.astype(jnp.float32), (B, S))
    )

    gates, indices = jax.lax.top_k(probs, k)  # (B, S, k)
    # Renormalize over the selected experts (GShard top-2 convention).
    gates = gates / jnp.maximum(jnp.sum(gates, axis=-1, keepdims=True), 1e-9)

    combine = jnp.zeros((B, S, E, C), jnp.float32)
    counts = jnp.zeros((B, E), jnp.float32)  # slots used so far, per expert
    for j in range(k):
        oh = jax.nn.one_hot(indices[..., j], E, dtype=jnp.float32)  # (B, S, E)
        if live is not None:
            oh = oh * live[..., None]  # PADs claim no slot
        # Position of each token within its chosen expert's capacity buffer:
        # tokens earlier in the sequence (and earlier choice ranks j) first.
        pos = jnp.cumsum(oh, axis=1) - oh + counts[:, None, :]  # (B, S, E)
        pos_j = jnp.sum(pos * oh, axis=-1)  # (B, S)
        fits = (pos_j < C).astype(jnp.float32) * jnp.sum(oh, axis=-1)
        counts = counts + jnp.sum(oh * fits[..., None], axis=1)
        slot = jax.nn.one_hot(pos_j.astype(jnp.int32), C, dtype=jnp.float32)  # (B, S, C)
        dispatch_j = oh[..., None] * slot[..., None, :] * fits[..., None, None]
        combine = combine + gates[..., j, None, None] * dispatch_j

    dispatch = (combine > 0).astype(dtype)  # (B, S, E, C)

    # --- expert computation (MXU matmuls in the compute dtype) -------------
    # The B dim of the slot tensors drops the 'expert' axis (tokens now live
    # on it via the E dim): that boundary is the token->expert all-to-all.
    xe = jnp.einsum("bsec,bsm->becm", dispatch, x)  # (B, E, C, M)
    xe = _constrain(xe, ("data", "fsdp"), "expert", None, None)
    h = act(
        jnp.einsum("becm,emf->becf", xe, params["in"]["kernel"].astype(dtype))
        + params["in"]["bias"].astype(dtype)[None, :, None, :]
    )
    h = _constrain(h, ("data", "fsdp"), "expert", None, "model")
    ye = (
        jnp.einsum("becf,efm->becm", h, params["out"]["kernel"].astype(dtype))
        + params["out"]["bias"].astype(dtype)[None, :, None, :]
    )
    ye = _constrain(ye, ("data", "fsdp"), "expert", None, None)
    y = jnp.einsum("bsec,becm->bsm", combine.astype(dtype), ye)
    y = _constrain(y, ("data", "fsdp", "expert"), None, None)

    # --- load-balance auxiliary loss (Switch: E * sum_e f_e * p_e) ---------
    # Statistics over REAL tokens only when a token_mask is given.
    first_choice = jax.nn.one_hot(indices[..., 0], E, dtype=jnp.float32)
    if live is None:
        f = jnp.mean(first_choice, axis=(0, 1))  # fraction routed to e
        p = jnp.mean(probs, axis=(0, 1))  # mean router prob for e
    else:
        n = jnp.maximum(jnp.sum(live), 1.0)
        f = jnp.sum(first_choice * live[..., None], axis=(0, 1)) / n
        p = jnp.sum(probs * live[..., None], axis=(0, 1)) / n
    aux = jnp.float32(E) * jnp.sum(f * p)
    return y, aux
