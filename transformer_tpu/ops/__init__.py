"""Core ops (L1): attention math, FFN, positional encoding, masks, primitives.

The TPU-native counterpart of the reference's ``Attention.py`` /
``point_ffn.py`` / ``positionalencoding.py``: pure functions over parameter
pytrees, traced once under jit and fused by XLA.
"""

from transformer_tpu.ops.attention import (
    dot_product_attention,
    mha_apply,
    mha_init,
)
from transformer_tpu.ops.ffn import ffn_apply, ffn_init
from transformer_tpu.ops.moe import expert_capacity, moe_apply, moe_init
from transformer_tpu.ops.masks import (
    attention_bias,
    make_causal_mask,
    make_padding_mask,
    make_seq2seq_masks,
)
from transformer_tpu.ops.positional import apply_rope, sinusoidal_positional_encoding

__all__ = [
    "apply_rope",
    "attention_bias",
    "dot_product_attention",
    "expert_capacity",
    "ffn_apply",
    "ffn_init",
    "moe_apply",
    "moe_init",
    "make_causal_mask",
    "make_padding_mask",
    "make_seq2seq_masks",
    "mha_apply",
    "mha_init",
    "sinusoidal_positional_encoding",
]
