"""Sinusoidal positional encoding.

Counterpart of the reference's ``positionalencoding.py:4-23``, computed with
jnp closed-form (traceable, constant-folded by XLA) instead of eager NumPy at
module-construction time. The table is sized by **max positions**, fixing the
reference's quirk of sizing it by vocab size (~32k rows; ``Encoder.py:40``,
SURVEY.md §2.3.5).

Layout matches the reference: the first d_model/2 channels carry sin of the
even-index angle frequencies and the last d_model/2 carry cos of the odd-index
frequencies, concatenated block-wise (``positionalencoding.py:19``) rather than
interleaved. Any self-consistent layout trains identically; the block layout is
also the friendlier one for rotary-style slicing later.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sinusoidal_positional_encoding(
    max_position: int, d_model: int, dtype=jnp.float32
) -> jax.Array:
    """Return (max_position, d_model) table: pe[p] = [sin(p/10000^(2i/d)) for
    even i] ++ [cos(p/10000^(2i/d)) for odd i] (reference ``get_angles``,
    ``positionalencoding.py:4-6``)."""
    positions = jnp.arange(max_position, dtype=jnp.float32)[:, None]  # (P, 1)
    channels = jnp.arange(d_model, dtype=jnp.float32)[None, :]  # (1, D)
    angle_rates = jnp.power(10000.0, -(2.0 * jnp.floor(channels / 2.0)) / d_model)
    angles = positions * angle_rates  # (P, D)
    evens = angles[:, 0::2]
    odds = angles[:, 1::2]
    table = jnp.concatenate([jnp.sin(evens), jnp.cos(odds)], axis=-1)
    return table.astype(dtype)
