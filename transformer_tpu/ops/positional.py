"""Sinusoidal positional encoding.

Counterpart of the reference's ``positionalencoding.py:4-23``, computed with
jnp closed-form (traceable, constant-folded by XLA) instead of eager NumPy at
module-construction time. The table is sized by **max positions**, fixing the
reference's quirk of sizing it by vocab size (~32k rows; ``Encoder.py:40``,
SURVEY.md §2.3.5).

Layout matches the reference: the first d_model/2 channels carry sin of the
even-index angle frequencies and the last d_model/2 carry cos of the odd-index
frequencies, concatenated block-wise (``positionalencoding.py:19``) rather than
interleaved. Any self-consistent layout trains identically; the block layout is
also the friendlier one for rotary-style slicing later.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sinusoidal_positional_encoding(
    max_position: int, d_model: int, dtype=jnp.float32
) -> jax.Array:
    """Return (max_position, d_model) table: pe[p] = [sin(p/10000^(2i/d)) for
    even i] ++ [cos(p/10000^(2i/d)) for odd i] (reference ``get_angles``,
    ``positionalencoding.py:4-6``)."""
    positions = jnp.arange(max_position, dtype=jnp.float32)[:, None]  # (P, 1)
    channels = jnp.arange(d_model, dtype=jnp.float32)[None, :]  # (1, D)
    angle_rates = jnp.power(10000.0, -(2.0 * jnp.floor(channels / 2.0)) / d_model)
    angles = positions * angle_rates  # (P, D)
    evens = angles[:, 0::2]
    odds = angles[:, 1::2]
    table = jnp.concatenate([jnp.sin(evens), jnp.cos(odds)], axis=-1)
    return table.astype(dtype)


def apply_rope(
    x: jax.Array,
    positions: jax.Array,
    base: float = 10000.0,
) -> jax.Array:
    """Rotary position embedding (no reference counterpart — the reference is
    additive-sinusoidal only; RoPE is the long-context extension for the
    decoder-only 4096-token config, ``ModelConfig.position_scheme="rope"``).

    Rotates each (even, odd-half) channel pair of ``x`` (B, S, H, D) by an
    angle proportional to its absolute position, which makes q·k depend only
    on the RELATIVE distance between query and key. Half-split layout
    (first D/2 channels pair with the last D/2) — contiguous slices, no
    interleaved gather, TPU-lane friendly. ``positions`` is (S,) absolute
    token positions (pass ``offset + arange(S)`` during KV-cache decode).
    Angles in fp32; output in x.dtype.
    """
    head_dim = x.shape[-1]
    half = head_dim // 2
    inv_freq = jnp.power(
        jnp.float32(base), -jnp.arange(0, half, dtype=jnp.float32) / half
    )  # (D/2,)
    angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]  # (S, D/2)
    cos = jnp.cos(angles)[None, :, None, :]  # (1, S, 1, D/2)
    sin = jnp.sin(angles)[None, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)
