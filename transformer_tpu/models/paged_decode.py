"""Fused paged decode forward: the batched LM step over pool slots.

The gather twins (``serve/scheduler.py`` ``_pool_step_paged`` /
``_pool_verify_paged``) run decode as ``vmap`` over per-slot batch-1
``transformer_decode_step`` calls against dense VIEWS of the pool — which
forces ``gather_block_views`` to materialize every slot's whole KV working
set in dense order before attention even starts, and leaves each sublayer's
intermediates round-tripping HBM between XLA fusions. This module is the
same step built on the fused kernels instead:

- attention consumes the pool buffers in place through the block table
  (``kernels/paged_flash.paged_flash_attention`` — no gathered view, GQA
  grouping and int8 dequant inside the kernel);
- the dense FFN sublayer runs as one residual+LN+FFN kernel
  (``ops/ffn.fused_ln_ffn`` — the dff-wide intermediate never leaves VMEM);
- everything else (embedding prologue, q/k/v/out projections, RoPE,
  LayerNorms, pool scatter) reuses the exact ops the gather path reaches
  through ``transformer_decode_step``, so the two paths share numerics
  wherever fusion doesn't force a different reduction order.

Write-then-attend: each layer scatters its freshly projected (and, for int8
pools, freshly quantized) K/V rows into the pool FIRST, then attends through
the table — the kernel's pool read hands back exactly the
quantize->dequantize round trip ``_store_kv`` returns on the dense path, so
stored rows and attended values stay bit-identical between paths. The S_q
rows just written are visible to the attention (lengths = index + S_q) with
per-row offset causality inside the kernel, which is what serves both
one-token decode (S_q = 1) and speculative verify (S_q = k + 1).

Scope guards (the gather path remains the general fallback): decoder-only
LM configs, no attention window (the paged-flash kernel has no band mask —
windowed configs keep the gather path, whose prefix mask carries the band),
deterministic (dropout-free) decode. MoE FFN layers fall back to the XLA
sublayer per layer; their attention still runs fused.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from transformer_tpu.config import ModelConfig
from transformer_tpu.kernels.flash_attention import paged_attention
from transformer_tpu.kernels.kv_pool import block_row_ids, scatter_rows
from transformer_tpu.models.encoder import (
    _ffn_sublayer_apply,
    _sublayer,
    embed_prologue,
    layer_uses_moe,
)
from transformer_tpu.models.transformer import project_logits
from transformer_tpu.ops.attention import _project, _quantize_kv, kv_buffer_keys
from transformer_tpu.ops.ffn import fused_ln_ffn
from transformer_tpu.ops.nn import Params, layernorm_apply
from transformer_tpu.ops.positional import apply_rope


def check_paged_flash_config(cfg: ModelConfig) -> None:
    """Reject configs the fused path cannot serve (they keep the gather
    path): the guards are static, so the scheduler validates once at init."""
    if not cfg.decoder_only:
        raise ValueError("paged_flash decode serves decoder-only LM configs")
    if cfg.attention_window:
        raise ValueError(
            "paged_flash decode has no sliding-window band mask; serve "
            "attention_window configs with --decode_kernel xla"
        )


def _scatter_layer_kv(
    pool: dict[str, Any],
    k: jax.Array,
    v: jax.Array,
    rids: jax.Array,
) -> dict[str, Any]:
    """Write (N, S_q, H_kv, D) projections into the pool at flat rows
    ``rids`` — ``_store_kv``'s int8 layout decisions, re-aimed at pool
    scatter (codes AND their fp32 scales land together, so stale scales can
    never pair with fresh codes)."""
    n, s_q = k.shape[:2]

    def flat(t):
        return t.reshape(n * s_q, *t.shape[2:])

    if "k_scale" in pool:
        kq, ks = _quantize_kv(k)
        vq, vs = _quantize_kv(v)
        vals = {"k": flat(kq), "k_scale": flat(ks), "v": flat(vq), "v_scale": flat(vs)}
    else:
        vals = {"k": flat(k.astype(pool["k"].dtype)), "v": flat(v.astype(pool["v"].dtype))}
    return {key: scatter_rows(pool[key], rids, vals[key]) for key in kv_buffer_keys(pool)}


def paged_decode_forward(
    params: Params,
    toks: jax.Array,
    pool_caches: list[dict[str, Any]],
    table: jax.Array,
    index: jax.Array,
    cfg: ModelConfig,
    *,
    block_tokens: int,
    interpret: bool | None = None,
) -> tuple[jax.Array, list[dict[str, Any]]]:
    """One fused decode/verify forward over every pool slot.

    Args:
      params: full transformer params (decoder-only config).
      toks: (N, S_q) int32 token ids — S_q = 1 for plain decode, k + 1 for
        speculative verify (scored causally inside the row).
      pool_caches: per-layer ``init_block_pool`` buffers.
      table: (N, nmax) int32 device block table.
      index: (N,) int32 per-slot positions BEFORE this forward; slot s's
        tokens sit at absolute positions ``index[s] .. index[s] + S_q - 1``.
      block_tokens: pool block size (static).
      interpret: Pallas interpret mode for both kernels (default: off-TPU).

    Returns ((N, S_q, vocab) logits for every fed position, updated pools).
    Free slots (index 0, all-sink tables) produce garbage logits into rows
    the host discards and write only sink rows — same contract as the
    gather twins.
    """
    dec = params["decoder"]
    n, s_q = toks.shape
    index = index.astype(jnp.int32)
    lengths = index + s_q
    rids = block_row_ids(table, index, s_q, block_tokens).reshape(-1)

    # Per-slot batch-1 embed, vmapped — the same call shape the gather path
    # reaches through vmap(transformer_decode_step), so traced-offset
    # handling (sinusoidal slack rows) and numerics line up exactly.
    def embed_one(ids, pos):
        return embed_prologue(dec["embedding"], ids[None], cfg, None, True, pos)[0]

    x = jax.vmap(embed_one)(toks, index)  # (N, S_q, d_model)
    dtype = x.dtype
    rope = cfg.position_scheme == "rope"

    new_pools: list[dict[str, Any]] = []
    for i, layer in enumerate(dec["layers"]):
        pool = pool_caches[i]
        pool_box = [pool]

        def self_attn(h, layer=layer, pool_box=pool_box):
            mp = layer["self_mha"]
            q = _project(mp["query"], h, dtype)
            k = _project(mp["key"], h, dtype)
            v = _project(mp["value"], h, dtype)
            if rope:
                rot = jax.vmap(
                    lambda t, off: apply_rope(t[None], off + jnp.arange(s_q))[0]
                )
                q = rot(q, index)
                k = rot(k, index)
            pool = _scatter_layer_kv(pool_box[0], k, v, rids)
            pool_box[0] = pool
            quant = {"k_scale": pool["k_scale"], "v_scale": pool["v_scale"]} if "k_scale" in pool else {}
            out = paged_attention(
                q, pool["k"], pool["v"], table, lengths,
                impl="paged_flash", interpret=interpret, **quant,
            )
            return jnp.einsum(
                "bshd,hdm->bsm", out, mp["out"]["kernel"].astype(dtype)
            ) + mp["out"]["bias"].astype(dtype)

        x = _sublayer(cfg, layer["ln1"], x, self_attn, None, True)
        new_pools.append(pool_box[0])

        if layer_uses_moe(cfg, i):
            # MoE dispatch is data-dependent routing — its fusion is a
            # separate kernel. Keep the XLA sublayer; attention above
            # already ran fused.
            aux_box: list = [None]
            x = _sublayer(
                cfg, layer["ln_ffn"], x,
                lambda h, layer=layer, aux_box=aux_box: _ffn_sublayer_apply(
                    layer, h, cfg, aux_box, None
                ),
                None, True,
            )
        else:
            x = fused_ln_ffn(
                layer["ln_ffn"], layer["ffn"], x,
                activation=cfg.ffn_activation,
                norm_scheme=cfg.norm_scheme,
                epsilon=cfg.layernorm_epsilon,
                interpret=interpret,
            )

    if cfg.norm_scheme == "pre":
        x = layernorm_apply(dec["final_ln"], x, cfg.layernorm_epsilon)
    return project_logits(params, x, cfg), new_pools
