"""Decoder layer and stack (also serves as the decoder-only causal LM trunk).

Counterpart of the reference's ``Decoder.py``: three post-LN sublayers — masked
self-attention, cross-attention with v=k=encoder output and q=decoder state
(``Decoder.py:29-36``), and FFN — behind the shared embed prologue. Extensions
beyond the reference:

- ``cfg.decoder_only`` drops the cross-attention sublayer entirely
  (BASELINE.json configs[4], the 4096-token causal LM);
- per-layer KV caches make autoregressive decode O(S) instead of the
  reference's O(S²) full re-run per step (``train.py:109-118``);
- causality is passed structurally (``causal=True``) so the flash/ring
  kernels can skip above-diagonal blocks.
"""

from __future__ import annotations

from typing import Any

import jax

from transformer_tpu.config import ModelConfig
from transformer_tpu.ops.attention import init_cache, mha_apply, mha_init
from transformer_tpu.ops.nn import (
    Params,
    embedding_init,
    layernorm_apply,
    layernorm_init,
    remat_layer,
)
from transformer_tpu.models.encoder import (
    _ffn_sublayer_apply,
    _ffn_sublayer_init,
    _sublayer,
    _token_mask_from,
    embed_prologue,
    layer_uses_moe,
)


def decoder_layer_init(
    key: jax.Array, cfg: ModelConfig, layer_index: int = 0
) -> Params:
    k1, k2, k3 = jax.random.split(key, 3)
    params: Params = {
        "self_mha": mha_init(
            k1, cfg.d_model, cfg.num_heads, cfg.params_dtype,
            num_kv_heads=cfg.kv_heads,
        ),
        **_ffn_sublayer_init(k3, cfg, layer_uses_moe(cfg, layer_index)),
        "ln1": layernorm_init(cfg.d_model, cfg.params_dtype),
        "ln_ffn": layernorm_init(cfg.d_model, cfg.params_dtype),
    }
    if not cfg.decoder_only:
        params["cross_mha"] = mha_init(
            k2, cfg.d_model, cfg.num_heads, cfg.params_dtype,
            num_kv_heads=cfg.kv_heads,
        )
        params["ln2"] = layernorm_init(cfg.d_model, cfg.params_dtype)
    return params


def decoder_layer_apply(
    params: Params,
    x: jax.Array,
    enc_out: jax.Array | None,
    self_mask: jax.Array | None,
    cross_mask: jax.Array | None,
    cfg: ModelConfig,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    return_weights: bool = False,
    cache: dict[str, Any] | None = None,
    cross_kv: tuple[jax.Array, jax.Array] | None = None,
) -> tuple[
    jax.Array, jax.Array | None, jax.Array | None, dict[str, Any] | None, jax.Array | None
]:
    """Returns (x, self_attn_weights, cross_attn_weights, updated_cache,
    moe_aux_loss) — the aux loss is None for dense-FFN layers (see
    ``encoder_layer_apply``).

    ``cross_kv`` optionally carries this layer's pre-projected encoder K/V so
    decode steps don't re-project the static encoder output every token.
    """
    r1, r2, r3 = (None, None, None) if rng is None else jax.random.split(rng, 3)
    boxes: list[Any] = [None, None, None]
    aux_box: list = [None]

    def self_attn(h):
        out, w, new_cache = mha_apply(
            params["self_mha"], h, h, self_mask,
            impl=cfg.attention_impl,
            causal=cache is None,  # cache path builds its own prefix mask
            window=cfg.attention_window,
            return_weights=return_weights,
            cache=cache,
            flash_block_q=cfg.flash_block_q,
            flash_block_k=cfg.flash_block_k,
            rope=cfg.position_scheme == "rope",
        )
        boxes[0], boxes[2] = w, new_cache
        return out

    x = _sublayer(cfg, params["ln1"], x, self_attn, r1, deterministic)

    if not cfg.decoder_only:
        if enc_out is None:
            raise ValueError("encoder output required unless cfg.decoder_only")

        def cross_attn(h):
            # q = decoder state, k = v = encoder output (reference ``Decoder.py:33-36``).
            out, w, _ = mha_apply(
                params["cross_mha"], h, enc_out, cross_mask,
                return_weights=return_weights,
                precomputed_kv=cross_kv,
            )
            boxes[1] = w
            return out

        x = _sublayer(cfg, params["ln2"], x, cross_attn, r2, deterministic)

    x = _sublayer(
        cfg, params["ln_ffn"], x,
        lambda h: _ffn_sublayer_apply(
            params, h, cfg, aux_box, _token_mask_from(self_mask)
        ),
        r3, deterministic,
    )
    return x, boxes[0], boxes[1], boxes[2], aux_box[0]


def decoder_init(key: jax.Array, cfg: ModelConfig, embedding: Params | None = None) -> Params:
    """``embedding`` may be a shared table (``cfg.tie_embeddings``) — the pytree
    then simply references the same arrays; jit dedups the constant."""
    keys = jax.random.split(key, cfg.num_layers + 1)
    params: Params = {
        "embedding": embedding
        if embedding is not None
        else embedding_init(keys[0], cfg.target_vocab_size, cfg.d_model, cfg.params_dtype),
        "layers": [decoder_layer_init(keys[i + 1], cfg, i) for i in range(cfg.num_layers)],
    }
    if cfg.norm_scheme == "pre":
        params["final_ln"] = layernorm_init(cfg.d_model, cfg.params_dtype)
    return params


def decoder_apply(
    params: Params,
    ids: jax.Array,
    enc_out: jax.Array | None,
    self_mask: jax.Array | None,
    cross_mask: jax.Array | None,
    cfg: ModelConfig,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    return_weights: bool = False,
    caches: list[dict[str, Any]] | None = None,
    cross_kvs: list[tuple[jax.Array, jax.Array]] | None = None,
    position_offset: jax.Array | int = 0,
) -> tuple[jax.Array, dict[str, jax.Array], list[dict[str, Any]] | None]:
    """(B, S) ids -> (B, S, d_model). Attention maps are keyed
    ``decoder_layer{i}_block{1,2}`` for parity with the reference's dict
    (``Decoder.py:75-76``)."""
    rngs = (
        [None] * (cfg.num_layers + 1)
        if rng is None
        else list(jax.random.split(rng, cfg.num_layers + 1))
    )
    x = embed_prologue(
        params["embedding"], ids, cfg, rngs[0], deterministic, position_offset
    )
    attn_weights: dict[str, jax.Array] = {}
    new_caches: list[dict[str, Any]] | None = [] if caches is not None else None
    aux_total = None

    def layer_call(layer, x, enc_out, self_mask, cross_mask, r, cache, cross_kv):
        return decoder_layer_apply(
            layer, x, enc_out, self_mask, cross_mask, cfg,
            r, deterministic, return_weights, cache=cache, cross_kv=cross_kv,
        )

    if cfg.remat and caches is None:
        # Training-time only (decode's KV-cache path gains nothing from
        # recomputation); see cfg.remat docstring.
        layer_call = remat_layer(layer_call, cfg)
    for i, layer in enumerate(params["layers"]):
        x, w1, w2, new_cache, aux = layer_call(
            layer, x, enc_out, self_mask, cross_mask, rngs[i + 1],
            None if caches is None else caches[i],
            None if cross_kvs is None else cross_kvs[i],
        )
        if w1 is not None:
            attn_weights[f"decoder_layer{i + 1}_block1"] = w1
        if w2 is not None:
            attn_weights[f"decoder_layer{i + 1}_block2"] = w2
        if aux is not None:
            aux_total = aux if aux_total is None else aux_total + aux
        if new_caches is not None:
            new_caches.append(new_cache)
    if aux_total is not None:
        attn_weights["moe_aux_decoder"] = aux_total
    if cfg.norm_scheme == "pre":
        x = layernorm_apply(params["final_ln"], x, cfg.layernorm_epsilon)
    return x, attn_weights, new_caches


def decoder_prefill(
    params: Params,
    tokens: jax.Array,
    enc_out: jax.Array | None,
    cross_mask: jax.Array | None,
    caches: list[dict[str, Any]],
    cfg: ModelConfig,
    cross_kvs: list[tuple[jax.Array, jax.Array]] | None = None,
    start: jax.Array | int = 0,
    chunk: int = 0,
) -> tuple[jax.Array, list[dict[str, Any]]]:
    """Single-pass teacher-forced prefill: run ``tokens`` (B, n) — sitting at
    absolute positions ``start .. start + n - 1`` — through the full decoder
    forward, writing every position's K/V into ``caches`` (the cache write
    API accepts S_q > 1; ``ops/attention.py`` builds the offset causal mask
    of a chunk attending into the cached prefix). Returns ((B, d_model)
    hidden state of the LAST position, updated caches).

    ``chunk > 0`` splits the pass into ceil(n / chunk) forward calls so
    activation memory stays bounded at long prompt lengths — the compiled
    program is O(n / chunk) matmul-rich forwards, never O(n) sequential
    decode steps. Rolling-window caches cap the chunk at the window buffer
    length (an attention-layer invariant — see ``mha_apply``)."""
    n = tokens.shape[1]
    if n < 1:
        raise ValueError(f"prefill needs at least one token, got {n}")
    chunk = chunk if chunk > 0 else n  # <= 0 = whole pass in one chunk
    if caches and "rolling" in caches[0]:
        chunk = min(chunk, caches[0]["k"].shape[1])
    x_last = None
    for off in range(0, n, chunk):
        width = min(chunk, n - off)
        x, _, caches = decoder_apply(
            params, jax.lax.slice_in_dim(tokens, off, off + width, axis=1),
            enc_out, None, cross_mask, cfg,
            rng=None, deterministic=True, caches=caches, cross_kvs=cross_kvs,
            position_offset=start + off,
        )
        x_last = x[:, -1, :]
    return x_last, caches


def init_decoder_caches(
    cfg: ModelConfig, batch_size: int, max_len: int
) -> list[dict[str, Any]]:
    """One self-attention KV cache per decoder layer (int8-quantized when
    ``cfg.kv_cache_int8``; a rolling O(window) buffer when
    ``cfg.attention_window``). Caches start at position 0; fill the prompt
    in one pass with ``decoder_prefill`` and decode incrementally from
    there (``transformer_decode_step``)."""
    return [
        init_cache(
            batch_size, max_len, cfg.kv_heads, cfg.head_dim,
            cfg.compute_dtype, quantize=cfg.kv_cache_int8,
            window=cfg.attention_window,
        )
        for _ in range(cfg.num_layers)
    ]


def precompute_cross_kvs(
    params: Params, enc_out: jax.Array, cfg: ModelConfig
) -> list[tuple[jax.Array, jax.Array]]:
    """Project the (static) encoder output through every layer's cross-attention
    K/V kernels once, so autoregressive decode attends against cached tensors
    instead of re-projecting per generated token."""
    from transformer_tpu.ops.attention import project_kv

    return [
        project_kv(layer["cross_mha"], enc_out, cfg.compute_dtype)
        for layer in params["layers"]
    ]
