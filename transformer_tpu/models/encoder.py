"""Encoder layer and stack.

Counterpart of the reference's ``Encoder.py``: a post-LN residual block
(``LN(x + Drop(MHA(x)))`` then ``LN(h + Drop(FFN(h)))``, ``Encoder.py:19-29``)
stacked N deep behind an embed/scale/posenc/dropout prologue
(``Encoder.py:48-60``). Differences by design:

- optional pre-LN wiring (``norm_scheme="pre"``) for deep/long-context configs;
- the positional table is sized by ``max_position``, not vocab size
  (fixes SURVEY.md §2.3.5);
- dropout threads an explicit rng and a static ``deterministic`` flag instead
  of Keras's stateful ``training=`` mode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from transformer_tpu.config import ModelConfig
from transformer_tpu.ops.attention import mha_apply, mha_init
from transformer_tpu.ops.ffn import ffn_apply, ffn_init
from transformer_tpu.ops.moe import moe_apply, moe_init
from transformer_tpu.ops.nn import (
    Params,
    dropout,
    embedding_init,
    embedding_lookup,
    layernorm_apply,
    layernorm_init,
    remat_layer,
)
from transformer_tpu.ops.positional import sinusoidal_positional_encoding


def layer_uses_moe(cfg: ModelConfig, layer_index: int) -> bool:
    """Whether layer ``layer_index`` (0-based) carries a MoE FFN: every
    ``moe_every``-th layer counting from the top of the cadence (GShard
    alternates, Switch uses every layer — ``cfg.moe_every`` choses)."""
    return cfg.moe_experts > 0 and (layer_index + 1) % cfg.moe_every == 0


def _ffn_sublayer_init(key: jax.Array, cfg: ModelConfig, use_moe: bool) -> dict:
    if use_moe:
        return {
            "moe": moe_init(
                key, cfg.d_model, cfg.dff, cfg.moe_experts, cfg.params_dtype
            )
        }
    return {
        "ffn": ffn_init(
            key, cfg.d_model, cfg.dff, cfg.params_dtype,
            activation=cfg.ffn_activation,
        )
    }


def _token_mask_from(mask: jax.Array | None) -> jax.Array | None:
    """(B|1, 1, 1, S) key-padding attention mask -> (B|1, S) token mask for
    MoE routing; any other mask shape (combined/causal) carries no usable
    per-token padding info, so routing treats all tokens as real."""
    if mask is not None and mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[-2] == 1:
        return mask[:, 0, 0, :]
    return None


def _ffn_sublayer_apply(
    params: Params,
    h: jax.Array,
    cfg: ModelConfig,
    aux_box: list,
    token_mask: jax.Array | None = None,
):
    """Dense or MoE FFN, depending on which key the layer params carry; a MoE
    layer's load-balance loss lands in ``aux_box[0]``."""
    if "moe" in params:
        y, aux = moe_apply(
            params["moe"], h,
            num_experts=cfg.moe_experts,
            top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor,
            activation=cfg.ffn_activation,
            token_mask=token_mask,
        )
        aux_box[0] = aux
        return y
    return ffn_apply(params["ffn"], h, cfg.ffn_activation)


def encoder_layer_init(
    key: jax.Array, cfg: ModelConfig, layer_index: int = 0
) -> Params:
    k_mha, k_ffn = jax.random.split(key)
    return {
        "mha": mha_init(
            k_mha, cfg.d_model, cfg.num_heads, cfg.params_dtype,
            num_kv_heads=cfg.kv_heads,
        ),
        **_ffn_sublayer_init(k_ffn, cfg, layer_uses_moe(cfg, layer_index)),
        "ln1": layernorm_init(cfg.d_model, cfg.params_dtype),
        "ln2": layernorm_init(cfg.d_model, cfg.params_dtype),
    }


def _sublayer(cfg: ModelConfig, params_ln, x, fn, rng, deterministic):
    """Residual sublayer in post-LN (reference wiring) or pre-LN form."""
    if cfg.norm_scheme == "pre":
        y = fn(layernorm_apply(params_ln, x, cfg.layernorm_epsilon))
        y = dropout(rng, y, cfg.dropout_rate, deterministic)
        return x + y
    y = fn(x)
    y = dropout(rng, y, cfg.dropout_rate, deterministic)
    return layernorm_apply(params_ln, x + y, cfg.layernorm_epsilon)


def encoder_layer_apply(
    params: Params,
    x: jax.Array,
    mask: jax.Array | None,
    cfg: ModelConfig,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    return_weights: bool = False,
) -> tuple[jax.Array, jax.Array | None, jax.Array | None]:
    """Returns (x, attn_weights, moe_aux_loss) — the aux loss is None for
    dense-FFN layers and a scalar for MoE layers; returning it (rather than
    side-channeling) keeps it correct under ``jax.checkpoint``."""
    r1, r2 = (None, None) if rng is None else jax.random.split(rng)
    weights_box = [None]
    aux_box: list = [None]

    def attn(h):
        out, w, _ = mha_apply(
            params["mha"], h, h, mask,
            impl=cfg.attention_impl,
            return_weights=return_weights,
            flash_block_q=cfg.flash_block_q,
            flash_block_k=cfg.flash_block_k,
            rope=cfg.position_scheme == "rope",
        )
        weights_box[0] = w
        return out

    x = _sublayer(cfg, params["ln1"], x, attn, r1, deterministic)
    x = _sublayer(
        cfg, params["ln2"], x,
        lambda h: _ffn_sublayer_apply(params, h, cfg, aux_box, _token_mask_from(mask)),
        r2, deterministic,
    )
    return x, weights_box[0], aux_box[0]


def encoder_init(key: jax.Array, cfg: ModelConfig) -> Params:
    keys = jax.random.split(key, cfg.num_layers + 1)
    params: Params = {
        "embedding": embedding_init(keys[0], cfg.input_vocab_size, cfg.d_model, cfg.params_dtype),
        "layers": [encoder_layer_init(keys[i + 1], cfg, i) for i in range(cfg.num_layers)],
    }
    if cfg.norm_scheme == "pre":
        params["final_ln"] = layernorm_init(cfg.d_model, cfg.params_dtype)
    return params


def embed_prologue(
    embedding: Params,
    ids: jax.Array,
    cfg: ModelConfig,
    rng: jax.Array | None,
    deterministic: bool,
    position_offset: jax.Array | int = 0,
) -> jax.Array:
    """Shared embed → ×√d_model → +posenc → dropout prologue
    (reference ``Encoder.py:51-55`` / ``Decoder.py:65-69``). ``position_offset``
    supports KV-cache decode, where the current token sits at a nonzero
    absolute position."""
    seq_len = ids.shape[1]
    if seq_len > cfg.max_position:
        raise ValueError(
            f"sequence length {seq_len} exceeds cfg.max_position "
            f"{cfg.max_position}; raise max_position to size the positional table"
        )
    x = embedding_lookup(embedding, ids, cfg.compute_dtype)
    x = x * jnp.asarray(cfg.d_model**0.5, dtype=cfg.compute_dtype)
    if cfg.position_scheme == "sinusoidal":
        # TRACED offsets (KV-cache decode, incl. speculative verify) get
        # seq_len rows of slack beyond max_position: a verify row whose
        # lookahead tokens straddle the position budget must NOT trigger
        # dynamic_slice's start-clamping, which would silently shift the
        # positions of the row's in-budget tokens (whose picks ARE
        # consumed). Static offsets (training and prefill forwards — the
        # wide, constant-heavy programs) provably stay in-bounds, so they
        # keep the exact max_position table instead of constant-folding an
        # up-to-2x-larger one into every compiled program. The sinusoid is
        # computed, so in-range rows are identical either way.
        slack = 0 if isinstance(position_offset, (int, np.integer)) else seq_len
        table = sinusoidal_positional_encoding(
            cfg.max_position + slack, cfg.d_model, cfg.compute_dtype
        )
        pos = jax.lax.dynamic_slice_in_dim(table, position_offset, seq_len, axis=0)
        x = x + pos[None, :, :]
    # "rope": nothing additive here — positions enter via q/k rotation inside
    # self-attention (ops/attention.py mha_apply).
    return dropout(rng, x, cfg.dropout_rate, deterministic)


def encoder_apply(
    params: Params,
    ids: jax.Array,
    mask: jax.Array | None,
    cfg: ModelConfig,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    return_weights: bool = False,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """(B, S) ids -> (B, S, d_model) encodings plus (optionally) per-layer
    attention maps keyed like the reference's dict (``Decoder.py:75-76`` style).
    MoE configs additionally report the summed load-balance loss under the
    reserved key ``"moe_aux_encoder"`` in the weights dict."""
    rngs = (
        [None] * (cfg.num_layers + 1)
        if rng is None
        else list(jax.random.split(rng, cfg.num_layers + 1))
    )
    x = embed_prologue(params["embedding"], ids, cfg, rngs[0], deterministic)
    attn_weights: dict[str, jax.Array] = {}
    aux_total = None

    def layer_call(layer, x, mask, r):
        return encoder_layer_apply(
            layer, x, mask, cfg, r, deterministic, return_weights
        )

    if cfg.remat:
        # Long-context lever: recompute each layer's activations in the
        # backward pass instead of keeping them live (cfg.remat docstring).
        layer_call = remat_layer(layer_call, cfg)
    for i, layer in enumerate(params["layers"]):
        x, w, aux = layer_call(layer, x, mask, rngs[i + 1])
        if w is not None:
            attn_weights[f"encoder_layer{i + 1}"] = w
        if aux is not None:
            aux_total = aux if aux_total is None else aux_total + aux
    if aux_total is not None:
        attn_weights["moe_aux_encoder"] = aux_total
    if cfg.norm_scheme == "pre":
        x = layernorm_apply(params["final_ln"], x, cfg.layernorm_epsilon)
    return x, attn_weights
