"""Transformer assembly.

Counterpart of the reference's ``Transformer.py``: encoder + decoder + final
vocab projection, with masks rebuilt from raw token ids inside the forward pass
every call (``Transformer.py:21-23``). Extensions beyond the reference:

- ``cfg.tie_embeddings``: one shared embedding table for source and target
  (requires equal vocab sizes) — BASELINE.json configs[3];
- ``cfg.tie_output``: logits via the transposed embedding table instead of the
  reference's untied Dense (``Transformer.py:16,30``);
- ``cfg.decoder_only``: a causal LM with no encoder at all — forward takes the
  token sequence alone (BASELINE.json configs[4]);
- ``cfg.encoder_only``: a bidirectional encoder with the vocab head (BERT
  family) — trained with the masked-LM objective
  (``TrainConfig.objective="mlm"``, ``train/mlm.py``).
"""

from __future__ import annotations

from typing import Any

import jax

from transformer_tpu.config import PAD_ID, ModelConfig
from transformer_tpu.models.decoder import decoder_apply, decoder_init
from transformer_tpu.models.encoder import encoder_apply, encoder_init
from transformer_tpu.ops.masks import make_padding_mask
from transformer_tpu.ops.nn import Params, dense_apply, dense_init, embedding_attend


def transformer_init(key: jax.Array, cfg: ModelConfig) -> Params:
    k_enc, k_dec, k_final = jax.random.split(key, 3)
    if cfg.encoder_only:
        params = {"encoder": encoder_init(k_enc, cfg)}
    elif cfg.decoder_only:
        params: Params = {"decoder": decoder_init(k_dec, cfg)}
    else:
        encoder = encoder_init(k_enc, cfg)
        shared = None
        if cfg.tie_embeddings:
            if cfg.input_vocab_size != cfg.target_vocab_size:
                raise ValueError(
                    "tie_embeddings requires input_vocab_size == target_vocab_size "
                    f"({cfg.input_vocab_size} != {cfg.target_vocab_size})"
                )
            shared = encoder["embedding"]
        params = {"encoder": encoder, "decoder": decoder_init(k_dec, cfg, embedding=shared)}
    if not cfg.tie_output:
        params["final"] = dense_init(
            k_final, cfg.d_model, cfg.target_vocab_size, cfg.params_dtype
        )
    return params


def _logits(params: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    if cfg.tie_output:
        tower = "encoder" if cfg.encoder_only else "decoder"
        return embedding_attend(params[tower]["embedding"], x)
    return dense_apply(params["final"], x)


def project_logits(params: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Final vocab projection: (..., d_model) hiddens -> (..., V) raw logits
    (tied or untied per ``cfg.tie_output``). Public counterpart of the
    projection inside ``transformer_apply`` for callers that project slices
    (chunked loss, decode)."""
    return _logits(params, x, cfg)


def transformer_hidden_apply(
    params: Params,
    inp: jax.Array | None,
    tar: jax.Array,
    cfg: ModelConfig,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    return_weights: bool = False,
    pad_id: int = PAD_ID,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Forward pass up to (but not including) the final vocab projection:
    returns ((B, S_tgt, d_model) decoder hiddens, attention_weights).

    Split out of ``transformer_apply`` so the chunked-loss path
    (``train/loss.py chunked_cross_entropy_from_hidden``) can project and
    score the (huge) vocab logits a sequence slice at a time instead of
    materializing the full (B, S, V) tensor.
    """
    if cfg.encoder_only:
        # BERT family: the bidirectional encoder stack, padding mask only
        # (no causality — every position attends to the full sequence).
        mask = make_padding_mask(tar, pad_id)
        x, attn = encoder_apply(
            params["encoder"], tar, mask, cfg, rng, deterministic,
            return_weights,
        )
        return x, attn

    if cfg.decoder_only:
        self_mask = make_padding_mask(tar, pad_id)  # ANDed with causal inside MHA
        x, attn, _ = decoder_apply(
            params["decoder"], tar, None, self_mask, None, cfg,
            rng, deterministic, return_weights,
        )
        return x, attn

    # Encoder self-attention and decoder cross-attention both mask source
    # padding; decoder self-attention masks target padding, with causality
    # applied structurally inside MHA (``causal=True`` in decoder_layer_apply)
    # so the flash/ring kernels can skip above-diagonal blocks. Together these
    # equal the reference's three ``create_masks`` outputs
    # (``positionalencoding.py:37-52``) — see ``ops.masks.make_seq2seq_masks``
    # for the dense-mask form.
    enc_mask = make_padding_mask(inp, pad_id)
    cross_mask = enc_mask
    self_mask = make_padding_mask(tar, pad_id)
    r_enc, r_dec = (None, None) if rng is None else jax.random.split(rng)
    enc_out, enc_attn = encoder_apply(
        params["encoder"], inp, enc_mask, cfg, r_enc, deterministic, return_weights
    )
    x, dec_attn, _ = decoder_apply(
        params["decoder"], tar, enc_out, self_mask, cross_mask, cfg,
        r_dec, deterministic, return_weights,
    )
    return x, {**enc_attn, **dec_attn}


def transformer_apply(
    params: Params,
    inp: jax.Array | None,
    tar: jax.Array,
    cfg: ModelConfig,
    rng: jax.Array | None = None,
    deterministic: bool = True,
    return_weights: bool = False,
    pad_id: int = PAD_ID,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Forward pass: (inp, tar) token ids -> (logits, attention_weights).

    ``inp`` is ignored (may be None) when ``cfg.decoder_only``; ``tar`` is then
    the causal-LM token sequence. Logits are raw (no softmax), shaped
    (B, S_tgt, target_vocab_size) — same contract as reference
    ``Transformer.py:30-32``.
    """
    x, attn = transformer_hidden_apply(
        params, inp, tar, cfg, rng, deterministic, return_weights, pad_id
    )
    return _logits(params, x, cfg), attn


def transformer_prefill(
    params: Params,
    tokens: jax.Array,
    enc_out: jax.Array | None,
    cross_mask: jax.Array | None,
    caches: list[dict[str, Any]],
    position: jax.Array | int,
    cfg: ModelConfig,
    cross_kvs: list[tuple[jax.Array, jax.Array]] | None = None,
    chunk: int = 0,
) -> tuple[jax.Array, list[dict[str, Any]]]:
    """Single-pass prompt ingestion: (B, n) tokens at absolute positions
    ``position .. position + n - 1`` -> ((B, vocab) logits for the NEXT
    position, caches holding every prompt position's K/V).

    The serving-side counterpart of ``transformer_decode_step``: where the
    step consumes ONE token per bandwidth-bound call, prefill consumes the
    whole prompt (in ``chunk``-sized pieces when ``chunk > 0``) through the
    teacher-forcing forward — O(n / chunk) MXU-saturating matmuls instead of
    O(n) sequential steps. Only the last position is projected to the vocab,
    so the (B, n, V) logits tensor is never materialized."""
    from transformer_tpu.models.decoder import decoder_prefill

    x_last, new_caches = decoder_prefill(
        params["decoder"], tokens, enc_out, cross_mask, caches, cfg,
        cross_kvs=cross_kvs, start=position, chunk=chunk,
    )
    return _logits(params, x_last[:, None, :], cfg)[:, -1, :], new_caches


def transformer_verify(
    params: Params,
    tokens: jax.Array,
    caches: list[dict[str, Any]],
    position: jax.Array | int,
    cfg: ModelConfig,
) -> tuple[jax.Array, list[dict[str, Any]]]:
    """Speculative-decoding verify forward: (B, W) candidate tokens at
    absolute positions ``position .. position + W - 1`` -> ((B, W, vocab)
    logits for EVERY fed position, updated caches).

    The multi-token sibling of ``transformer_decode_step`` built on the same
    S_q > 1 cache-write path ``transformer_prefill`` uses (offset causal
    mask from ``ops/masks.py``): one matmul-rich forward scores a drafter's
    ``k`` proposals plus the bonus position, instead of ``k + 1``
    bandwidth-bound single-token steps. Where prefill projects only the
    last position (prompt logits are never needed), verify projects ALL
    positions — ``logits[:, j]`` is the next-token distribution after the
    prefix extended by ``tokens[:, :j+1]``, which is exactly what the
    acceptance rule compares against ``tokens[:, j+1]``. W stays small
    (k + 1), so the (B, W, V) tensor never approaches the (B, S, V)
    materialization the chunked-loss path avoids. Rejected candidates roll
    back with ``ops.attention.rollback_cache`` (decoder-only: speculation
    targets the LM serving path)."""
    x, _, new_caches = decoder_apply(
        params["decoder"], tokens, None, None, None, cfg,
        rng=None, deterministic=True, caches=caches,
        position_offset=position,
    )
    return _logits(params, x, cfg), new_caches


def transformer_decode_step(
    params: Params,
    token: jax.Array,
    enc_out: jax.Array | None,
    cross_mask: jax.Array | None,
    caches: list[dict[str, Any]],
    position: jax.Array,
    cfg: ModelConfig,
    cross_kvs: list[tuple[jax.Array, jax.Array]] | None = None,
) -> tuple[jax.Array, list[dict[str, Any]]]:
    """One KV-cached autoregressive step: (B, 1) token -> (B, vocab) next-token
    logits plus updated caches. This replaces the reference's full re-encode +
    re-decode per generated token (``train.py:110``). Pass ``cross_kvs`` from
    ``precompute_cross_kvs`` to avoid re-projecting the encoder output."""
    x, _, new_caches = decoder_apply(
        params["decoder"], token, enc_out, None, cross_mask, cfg,
        rng=None, deterministic=True, caches=caches, cross_kvs=cross_kvs,
        position_offset=position,
    )
    logits = _logits(params, x, cfg)
    return logits[:, -1, :], new_caches
