"""Model layers and assembly (L2/L3): encoder/decoder stacks and the
Transformer — counterpart of the reference's ``Encoder.py`` / ``Decoder.py`` /
``Transformer.py``, as pure init/apply functions over parameter pytrees."""

from transformer_tpu.models.decoder import (
    decoder_apply,
    decoder_init,
    decoder_layer_apply,
    decoder_layer_init,
)
from transformer_tpu.models.encoder import (
    encoder_apply,
    encoder_init,
    encoder_layer_apply,
    encoder_layer_init,
)
from transformer_tpu.models.transformer import (
    project_logits,
    transformer_apply,
    transformer_hidden_apply,
    transformer_init,
)

__all__ = [
    "decoder_apply",
    "decoder_init",
    "decoder_layer_apply",
    "decoder_layer_init",
    "encoder_apply",
    "encoder_init",
    "encoder_layer_apply",
    "encoder_layer_init",
    "project_logits",
    "transformer_apply",
    "transformer_hidden_apply",
    "transformer_init",
]
